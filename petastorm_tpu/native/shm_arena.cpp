// Process-shared memory arena allocator: the native data plane between reader
// worker processes and the consumer.
//
// Reference parity: petastorm's ProcessPool moves results over a ZeroMQ TCP
// data plane (petastorm/workers_pool/process_pool.py:52-74,180-199).  On a TPU
// host VM every worker and the consumer share one machine, so the idiomatic
// replacement is a shared-memory arena: producers copy column payloads in once,
// the consumer wraps them as numpy arrays with zero further copies.
//
// Layout: the Python side maps one POSIX shared-memory segment into every
// process (multiprocessing.shared_memory) and hands this library the base
// pointer.  The arena header holds a process-shared robust pthread mutex; the
// body is a first-fit free list with 64-byte aligned block headers, split on
// alloc and coalesced on free.  Frees may arrive out of allocation order
// (workers complete rowgroups out of order), which is why this is a free-list
// allocator and not a ring.
//
// C ABI (ctypes): psa_init / psa_alloc / psa_free / psa_free_bytes /
// psa_largest_free / psa_check.

#include <errno.h>
#include <pthread.h>
#include <stdint.h>
#include <string.h>

namespace {

constexpr uint64_t kMagic = 0x70736130617265ULL;  // "psa0are"
constexpr uint64_t kAlign = 64;                   // cacheline; keeps numpy views aligned

struct ArenaHeader {
  uint64_t magic;
  uint64_t size;             // total mapped bytes, header included
  uint64_t first_block;      // offset of the first block header
  pthread_mutex_t mutex;
};

struct BlockHeader {
  uint64_t size;             // payload bytes (excluding this header)
  uint64_t next;             // offset of next block header, 0 = end
  uint32_t free_flag;        // 1 = free, 0 = allocated
  uint32_t pad;
  char align_pad[40];        // header = 64B, so payloads stay 64B-aligned
};
static_assert(sizeof(BlockHeader) == kAlign, "payload alignment broken");

inline uint64_t align_up(uint64_t v) { return (v + kAlign - 1) & ~(kAlign - 1); }

inline ArenaHeader* header(void* mem) { return static_cast<ArenaHeader*>(mem); }

inline BlockHeader* block_at(void* mem, uint64_t off) {
  return reinterpret_cast<BlockHeader*>(static_cast<char*>(mem) + off);
}

// Robust lock: if a worker died holding the mutex, recover its state and
// continue (the dead worker's allocation leaks until the arena is destroyed,
// which is the safe failure mode).
int lock(ArenaHeader* h) {
  int rc = pthread_mutex_lock(&h->mutex);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&h->mutex);
    rc = 0;
  }
  return rc;
}

}  // namespace

extern "C" {

// Initialize an arena over `size` bytes of zeroed shared memory at `mem`.
// Called exactly once, by the consumer, before any worker attaches.
int psa_init(void* mem, uint64_t size) {
  if (size < sizeof(ArenaHeader) + sizeof(BlockHeader) + kAlign) return -1;
  ArenaHeader* h = header(mem);
  h->size = size;
  h->first_block = align_up(sizeof(ArenaHeader));

  pthread_mutexattr_t attr;
  if (pthread_mutexattr_init(&attr) != 0) return -2;
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  if (pthread_mutex_init(&h->mutex, &attr) != 0) {
    pthread_mutexattr_destroy(&attr);
    return -2;
  }
  pthread_mutexattr_destroy(&attr);

  BlockHeader* first = block_at(mem, h->first_block);
  first->size = size - h->first_block - sizeof(BlockHeader);
  first->next = 0;
  first->free_flag = 1;
  first->pad = 0;
  h->magic = kMagic;  // last: attaching processes spin on magic
  return 0;
}

// True once psa_init completed (workers poll this after mapping).
int psa_check(void* mem) { return header(mem)->magic == kMagic ? 1 : 0; }

// Allocate `size` payload bytes; returns the payload offset (64-byte aligned),
// or -1 when no block fits (caller retries / falls back), or -2 on corruption.
int64_t psa_alloc(void* mem, uint64_t size) {
  ArenaHeader* h = header(mem);
  if (h->magic != kMagic) return -2;
  uint64_t need = align_up(size ? size : 1);
  if (lock(h) != 0) return -2;

  int64_t result = -1;
  for (uint64_t off = h->first_block; off != 0;) {
    BlockHeader* b = block_at(mem, off);
    if (b->free_flag && b->size >= need) {
      uint64_t remainder = b->size - need;
      if (remainder > sizeof(BlockHeader) + kAlign) {
        // split: tail of this block becomes a new free block
        uint64_t new_off = off + sizeof(BlockHeader) + need;
        BlockHeader* nb = block_at(mem, new_off);
        nb->size = remainder - sizeof(BlockHeader);
        nb->next = b->next;
        nb->free_flag = 1;
        nb->pad = 0;
        b->size = need;
        b->next = new_off;
      }
      b->free_flag = 0;
      result = static_cast<int64_t>(off + sizeof(BlockHeader));
      break;
    }
    off = b->next;
  }
  pthread_mutex_unlock(&h->mutex);
  return result;
}

// Free the allocation whose *payload* starts at `payload_off`.
// Coalesces with free neighbours (prev found by list walk: block counts stay
// small because batches are large and short-lived).
int psa_free(void* mem, uint64_t payload_off) {
  ArenaHeader* h = header(mem);
  if (h->magic != kMagic) return -2;
  uint64_t off = payload_off - sizeof(BlockHeader);
  if (lock(h) != 0) return -2;

  BlockHeader* target = nullptr;
  BlockHeader* prev = nullptr;
  for (uint64_t cur = h->first_block; cur != 0;) {
    BlockHeader* b = block_at(mem, cur);
    if (cur == off) { target = b; break; }
    prev = b;
    cur = b->next;
  }
  if (target == nullptr || target->free_flag) {
    pthread_mutex_unlock(&h->mutex);
    return -1;  // not an allocated block (double free / bad offset)
  }
  target->free_flag = 1;
  // coalesce with next
  if (target->next != 0) {
    BlockHeader* nb = block_at(mem, target->next);
    if (nb->free_flag) {
      target->size += sizeof(BlockHeader) + nb->size;
      target->next = nb->next;
    }
  }
  // coalesce with prev
  if (prev != nullptr && prev->free_flag) {
    prev->size += sizeof(BlockHeader) + target->size;
    prev->next = target->next;
  }
  pthread_mutex_unlock(&h->mutex);
  return 0;
}

uint64_t psa_free_bytes(void* mem) {
  ArenaHeader* h = header(mem);
  if (h->magic != kMagic) return 0;
  if (lock(h) != 0) return 0;
  uint64_t total = 0;
  for (uint64_t off = h->first_block; off != 0;) {
    BlockHeader* b = block_at(mem, off);
    if (b->free_flag) total += b->size;
    off = b->next;
  }
  pthread_mutex_unlock(&h->mutex);
  return total;
}

uint64_t psa_largest_free(void* mem) {
  ArenaHeader* h = header(mem);
  if (h->magic != kMagic) return 0;
  if (lock(h) != 0) return 0;
  uint64_t largest = 0;
  for (uint64_t off = h->first_block; off != 0;) {
    BlockHeader* b = block_at(mem, off);
    if (b->free_flag && b->size > largest) largest = b->size;
    off = b->next;
  }
  pthread_mutex_unlock(&h->mutex);
  return largest;
}

}  // extern "C"
