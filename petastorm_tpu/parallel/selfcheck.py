"""Real multi-process validation of the multi-host data plane.

Every multi-host claim in this framework ultimately rests on three JAX
primitives: the ``jax.distributed.initialize`` process topology,
``jax.make_array_from_process_local_data`` global-batch assembly, and the
``multihost_utils.process_allgather`` drain alignment.  On a single machine
those paths are normally only *simulated* - one process pretending to be many
hosts, which is exactly how the reference simulates sharding too
(petastorm/tests/test_end_to_end.py:454 runs every "worker" in-process).
This module executes them for REAL: it launches N separate OS processes on
the CPU backend (Gloo collectives over localhost), each owning a disjoint
subset of one shared device mesh, and drives

* sharded reading    - ``shard_options_from_jax()`` resolved per process
* global assembly    - every batch built with ``jax.process_count() > 1``
* collective steps   - a jitted masked global mean per step (replicated
                       output realized on every host)
* drain alignment    - the REAL ``process_allgather`` branch of
                       ``JaxDataLoader.drain`` (no injected counts), with
                       hosts configured to buffer deliberately unequal
                       amounts so the zero-pad path must fire
* valid-mask safety  - pads carry a zero ``valid_mask_field`` column and the
                       collective runs on EVERY drained step (the no-hang
                       contract; see JaxDataLoader.drain docs)
* elastic resume     - a second launch under a DIFFERENT process count
                       resumes from ``elastic_resume()`` of the saved cursors
* dp x tp meshes     - ``run_mesh2d_check``: 2-D mesh delivery with the data
                       axis crossing processes and tensor parallelism inside
                       each, one jitted reduction over both axes
* coordinated writes - ``run_distributed_write_check``: the default
                       ``sync_global_devices`` barrier path of
                       ``distributed_write_dataset`` (never reachable from
                       single-process tests), geometry sidecar merge, and
                       exact all-host readback
* context parallel   - ``run_context_parallel_check``: sequence-sharded
                       delivery plus ring attention (ppermute) and Ulysses
                       (all_to_all) over a mesh SPANNING the processes,
                       checked against a full-attention reference
* shuffled + stacked - ``run_shuffled_check``: SEEDED shuffled sharded
                       reading with ``stack_batches=2`` delivery and stacked
                       drain, at 4 processes: all hosts must realize the
                       identical permutation (replicated all-gather of every
                       unit's ids), the masked multiset must equal the
                       dataset, the order must match the locally recomputed
                       seeded plan, and the pod shuffle-quality
                       rank-correlation bound runs on the REAL-process rows
* mixed decode       - ``run_mixed_check``: ``device-mixed`` jpeg decode on
                       a mesh spanning processes - host-local bucket decode,
                       global-array scatter, pixels all-gathered and checked
                       bit-identical across hosts and against a host decode

and verifies, in the launching process, that the rows every process observed
reconstruct the single-process ground truth row for row, and that phase-1
consumption plus phase-2 resume cover the dataset exactly once.

Usage (also wired into the driver dry-run and the test suite)::

    from petastorm_tpu.parallel.selfcheck import run_selfcheck
    report = run_selfcheck(num_processes=2, devices_per_process=2)
    assert report["ok"], report["failures"]

or from a shell::

    python -m petastorm_tpu.parallel.selfcheck --num-processes 2
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import re
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional

import numpy as np

MASK_FIELD = "mask"
#: head count of the context-parallel check's attention (Ulysses runs only
#: when this divides the device count; ring has no such constraint)
_CP_HEADS = 4
#: vocab/hidden of the 2-D mesh check's embedding computation
_M2D_VOCAB = 32
_M2D_HIDDEN = 16
_ID = "id"
_VALUE = "value"
_VALUE_DIM = 4


def _value_for_ids(ids):
    import numpy as np

    ids = np.asarray(ids, dtype=np.float32)
    return np.stack([ids * 0.5, ids - 3.0, ids % 7.0, ids * 0.25],
                    axis=-1).astype(np.float32)


# ---------------------------------------------------------------------------
# worker side (runs in each spawned process)
# ---------------------------------------------------------------------------

def _worker_main(args) -> None:
    # sitecustomize may have imported jax already (axon plugin); the backend is
    # lazy, so re-asserting the CPU platform before distributed init still works
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=args.coordinator,
                               num_processes=args.num_processes,
                               process_id=args.process_id)
    if args.phase == "pipeline":
        _worker_pipeline(args)
    elif args.phase == "resume":
        _worker_resume(args)
    elif args.phase == "cp":
        _worker_cp(args)
    elif args.phase == "write":
        _worker_write(args)
    elif args.phase == "mesh2d":
        _worker_mesh2d(args)
    elif args.phase == "shuffled":
        _worker_shuffled(args)
    elif args.phase == "mixed":
        _worker_mixed(args)
    else:
        raise ValueError(f"unknown phase {args.phase!r}")


def _worker_pipeline(args) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import multihost_utils
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.parallel.mesh import shard_options_from_jax
    from petastorm_tpu.reader import make_reader

    pid = jax.process_index()
    assert jax.process_count() == args.num_processes, (
        jax.process_count(), args.num_processes)
    devices = jax.devices()
    mesh = Mesh(np.asarray(devices), ("data",))
    cur, count = shard_options_from_jax()
    local_rows = args.global_batch * len(jax.local_devices()) // len(devices)

    # DELIBERATELY asymmetric buffering: the higher-ranked process holds a much
    # deeper in-flight window, so at drain time the hosts have unequal batch
    # counts and the alignment pad path MUST fire on the shallow host(s)
    # workers_count=1 pins delivery to plan order (multi-worker pools deliver
    # in completion order, legitimately nondeterministic) so the launcher can
    # assert row-for-row equality against its own single-process read
    reader = make_reader(args.dataset, cur_shard=cur, shard_count=count,
                         shuffle_row_groups=False, num_epochs=1,
                         workers_count=1, results_queue_size=2 + 8 * pid)
    rep = NamedSharding(mesh, P())
    masked_mean = jax.jit(
        lambda v, m: (v.sum(axis=1) * m).sum() / jnp.maximum(m.sum(), 1.0),
        out_shardings=rep)

    batches: List[Dict] = []

    def record(batch, kind):
        entry = {"kind": kind, "shards": [], "mask": [],
                 "valid_rows": int(batch.get("_valid_rows", -1)),
                 "values_match": True}
        for sh in batch[_ID].addressable_shards:
            sl = sh.index[0]
            ids = np.asarray(sh.data).ravel()
            entry["shards"].append({"start": int(sl.start or 0),
                                    "stop": int(sl.stop),
                                    "ids": ids.astype(int).tolist()})
        for sh in batch[_VALUE].addressable_shards:
            sl = sh.index[0]
            ids = next(s["ids"] for s in entry["shards"]
                       if s["start"] == int(sl.start or 0))
            vals = np.asarray(sh.data)
            if entry["valid_rows"] != 0 and not np.allclose(
                    vals, _value_for_ids(ids)):
                entry["values_match"] = False
        for sh in batch[MASK_FIELD].addressable_shards:
            sl = sh.index[0]
            entry["mask"].append({"start": int(sl.start or 0),
                                  "vals": np.asarray(sh.data).ravel().tolist()})
        batches.append(entry)

    steps = 0
    means: List[float] = []
    with JaxDataLoader(reader, batch_size=args.global_batch, mesh=mesh,
                       shardings={_ID: P("data"), _VALUE: P("data")},
                       drop_last=False, prefetch=2 + 6 * pid,
                       valid_mask_field=MASK_FIELD) as loader:
        it = iter(loader)
        first = next(it)
        means.append(float(masked_mean(first[_VALUE], first[MASK_FIELD])))
        steps += 1
        record(first, "consumed")
        time.sleep(args.settle)  # let every host's pipeline buffer to capacity

        drained_real = pad_count = 0
        for b in loader.drain():  # REAL process_allgather alignment
            # the contract under pod collectives: run EVERY drained step (the
            # mask zeroes pad rows out of the loss); branching on the
            # host-local '_valid_rows' here would hang the other process
            means.append(float(masked_mean(b[_VALUE], b[MASK_FIELD])))
            steps += 1
            if b.get("_valid_rows", local_rows) == 0:
                pad_count += 1
                record(b, "drain_pad")
            else:
                drained_real += 1
                record(b, "drain_real")
        state = loader.state_dict()["reader"]

    real_all = multihost_utils.process_allgather(
        np.asarray([drained_real], np.int32)).ravel()
    drain_steps_all = multihost_utils.process_allgather(
        np.asarray([drained_real + pad_count], np.int32)).ravel()
    steps_all = multihost_utils.process_allgather(
        np.asarray([steps], np.int32)).ravel()
    assert len(set(drain_steps_all.tolist())) == 1, (
        f"drain alignment broken: per-host drain step counts {drain_steps_all}")
    assert len(set(steps_all.tolist())) == 1, (
        f"collective step counts diverged: {steps_all}")
    assert state.get("ordinal_exact"), state

    with open(os.path.join(args.out, f"state_{pid}.pkl"), "wb") as f:
        pickle.dump(state, f)
    report = {
        "process_id": pid,
        "process_count": jax.process_count(),
        "n_devices": len(devices),
        "n_local_devices": len(jax.local_devices()),
        "local_rows": local_rows,
        "cur_shard": cur,
        "shard_count": count,
        "drained_real": int(drained_real),
        "pad_count": int(pad_count),
        "real_all": real_all.tolist(),
        "drain_steps_all": drain_steps_all.tolist(),
        "steps_all": steps_all.tolist(),
        "means": means,
        "batches": batches,
    }
    with open(os.path.join(args.out, f"worker_{pid}.json"), "w") as f:
        json.dump(report, f)


def _worker_cp(args) -> None:
    """Context-parallel data plane + attention collectives across REAL
    process boundaries: sequence-sharded loader delivery (every host reads
    every row, materializes only its sequence slice), then ring attention
    (ppermute K/V rotation) and Ulysses (all_to_all head/sequence reshard)
    run over a mesh spanning both processes and must match a local
    full-attention reference on the replicated data."""
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.ops.ring_attention import ring_attention
    from petastorm_tpu.ops.ulysses import ulysses_attention
    from petastorm_tpu.reader import make_reader

    pid = jax.process_index()
    devices = jax.devices()
    n_dev = len(devices)
    mesh = Mesh(np.asarray(devices).reshape(1, n_dev), ("data", "seq"))
    rep = NamedSharding(mesh, P())

    reader = make_reader(args.dataset, shuffle_row_groups=False, num_epochs=1,
                         workers_count=1)
    with JaxDataLoader(reader, batch_size=args.global_batch, mesh=mesh,
                       shardings={_ID: P("data"),
                                  "x": P("data", "seq")}) as loader:
        batch = next(iter(loader))
        x = batch["x"]  # (B, S, D) global; sequence sharded across processes
    B, S, D = x.shape
    H = _CP_HEADS
    dh = D // H

    to_bhsd = jax.jit(
        lambda t: t.reshape(B, S, H, dh).transpose(0, 2, 1, 3),
        out_shardings=NamedSharding(mesh, P(None, None, "seq", None)))
    qkv = to_bhsd(x)
    out_ring = ring_attention(qkv, qkv, qkv, mesh=mesh, causal=True)
    replicate = jax.jit(lambda t: t, out_shardings=rep)
    ring_rep = np.asarray(replicate(out_ring))

    # local reference from the REPLICATED input (float64 softmax)
    x_rep = np.asarray(replicate(x)).astype(np.float64)
    q = x_rep.reshape(B, S, H, dh).transpose(0, 2, 1, 3)
    scores = np.einsum("bhqd,bhkd->bhqk", q, q) / (dh ** 0.5)
    mask = np.tril(np.ones((S, S), bool))
    scores = np.where(mask, scores, -np.inf)
    w = np.exp(scores - scores.max(axis=-1, keepdims=True))
    w /= w.sum(axis=-1, keepdims=True)
    ref = np.einsum("bhqk,bhkd->bhqd", w, q)

    err_ring = float(np.max(np.abs(ring_rep - ref)))
    assert err_ring < 2e-4, f"ring attention diverged: max err {err_ring}"
    err_uly = None
    if H % n_dev == 0:
        uly_rep = np.asarray(replicate(
            ulysses_attention(qkv, qkv, qkv, mesh=mesh, causal=True)))
        err_uly = float(np.max(np.abs(uly_rep - ref)))
        assert err_uly < 2e-4, f"ulysses diverged: max err {err_uly}"

    with open(os.path.join(args.out, f"cp_{pid}.json"), "w") as f:
        json.dump({"process_id": pid, "process_count": jax.process_count(),
                   "err_ring": err_ring, "err_uly": err_uly,
                   "ring_sum": float(ring_rep.sum()),
                   "shape": [int(B), int(S), int(D)]}, f)


def run_context_parallel_check(num_processes: int = 2,
                               devices_per_process: int = 2,
                               seq: int = 32, dim: int = 32,
                               global_batch: int = 2,
                               timeout: float = 240.0,
                               workdir: Optional[str] = None) -> Dict:
    """Ring + Ulysses attention over sequence-sharded delivery in REAL
    separate processes; see ``_worker_cp``.  Returns {"ok", "failures", ...}.
    """
    import tempfile

    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.schema import Field, Schema

    n_dev = num_processes * devices_per_process
    assert seq % n_dev == 0, (
        f"seq ({seq}) must divide over the {n_dev}-device mesh")
    assert dim % _CP_HEADS == 0, (
        f"dim ({dim}) must be divisible by the head count ({_CP_HEADS})")
    workdir = workdir or tempfile.mkdtemp(prefix="petastorm_tpu_cpcheck_")
    os.makedirs(workdir, exist_ok=True)
    dataset = os.path.join(workdir, f"cp_s{seq}_d{dim}_b{global_batch}")
    if not os.path.exists(dataset):
        rng = np.random.default_rng(11)
        schema = Schema("CpCheck", [
            Field(_ID, np.int32),
            Field("x", np.float32, (seq, dim)),
        ])
        write_dataset(dataset, schema,
                      [{_ID: np.int32(i),
                        "x": rng.standard_normal((seq, dim)).astype(np.float32)}
                       for i in range(global_batch)],
                      row_group_size_rows=global_batch)
    report, workers = _launch_and_collect(
        "cp", num_processes, devices_per_process, dataset, workdir, timeout,
        ["--global-batch", str(global_batch)])
    if workers is None:
        return report
    sums = {w["ring_sum"] for w in workers}
    if len(sums) != 1:
        report["failures"].append(
            f"hosts realized different ring outputs: {sums}")
    report["err_ring"] = max(w["err_ring"] for w in workers)
    uly = [w["err_uly"] for w in workers if w["err_uly"] is not None]
    # Ulysses runs only when the head count divides the device count; ring
    # alone still proves the cross-process collective path
    report["err_uly"] = max(uly) if uly else None
    report["ok"] = not report["failures"]
    return report


def _worker_shuffled(args) -> None:
    """SHUFFLED sharded reading + STACKED delivery + stacked drain across
    real process boundaries (VERDICT r4 item 3a + the stack-mode drain of
    item 1): every host reads its shard with the same seeded rowgroup
    permutation, units arrive as (K, G, ...) stacks, and the per-unit global
    id/mask arrays are REPLICATED (a real cross-process all-gather) so the
    launcher can assert all hosts realized the identical permutation."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.parallel.mesh import shard_options_from_jax
    from petastorm_tpu.reader import make_reader

    pid = jax.process_index()
    devices = jax.devices()
    mesh = Mesh(np.asarray(devices), ("data",))
    rep = NamedSharding(mesh, P())
    cur, count = shard_options_from_jax()
    reader = make_reader(args.dataset, cur_shard=cur, shard_count=count,
                         shuffle_row_groups=True, shuffle_seed=args.seed,
                         num_epochs=1, workers_count=1)
    replicate = jax.jit(lambda t: t, out_shardings=rep)
    masked_mean = jax.jit(
        lambda v, m: (v.sum(axis=-1) * m).sum() / jnp.maximum(m.sum(), 1.0),
        out_shardings=rep)
    units: List[Dict] = []
    means: List[float] = []

    def record(u):
        # the collective runs on EVERY unit (incl. drain pads) - the stacked
        # no-hang contract - and the replicated ids ARE the cross-host proof
        means.append(float(masked_mean(u[_VALUE], u[MASK_FIELD])))
        units.append({
            "ids": np.asarray(replicate(u[_ID])).astype(int).tolist(),
            "mask": np.asarray(replicate(u[MASK_FIELD])).tolist()})

    with JaxDataLoader(reader, batch_size=args.global_batch, mesh=mesh,
                       stack_batches=2, drop_last=False,
                       shardings={_ID: P("data"), _VALUE: P("data")},
                       valid_mask_field=MASK_FIELD) as loader:
        it = iter(loader)
        record(next(it))
        for u in loader.drain():  # stacked drain over real process_allgather
            record(u)
    with open(os.path.join(args.out, f"shuffled_{pid}.json"), "w") as f:
        json.dump({"process_id": pid, "process_count": jax.process_count(),
                   "units": units, "means": means}, f)


def run_shuffled_check(num_processes: int = 4, devices_per_process: int = 2,
                       global_batch: int = 8, n_batches: int = 16,
                       seed: int = 9, timeout: float = 300.0,
                       workdir: Optional[str] = None) -> Dict:
    """Seeded SHUFFLED reading with stacked delivery over real processes;
    see ``_worker_shuffled``.  Asserts (a) every host realized the identical
    global unit sequence (permutation agreement), (b) the masked multiset
    equals the dataset exactly, (c) the delivered order matches the seeded
    plan's lockstep interleave recomputed locally, (d) the shuffle-quality
    rank-correlation bound holds on rows collected from REAL processes
    (previously only simulated in-process,
    tests/test_weighted_and_shuffle_quality.py)."""
    import tempfile

    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.reader import make_reader
    from petastorm_tpu.schema import Field, Schema
    from petastorm_tpu.test_util.shuffling_analysis import rank_correlation

    assert global_batch % num_processes == 0
    assert n_batches % 2 == 0, "stack_batches=2 needs an even batch count"
    local_rows = global_batch // num_processes
    total = n_batches * global_batch
    workdir = workdir or tempfile.mkdtemp(prefix="petastorm_tpu_shufcheck_")
    os.makedirs(workdir, exist_ok=True)
    dataset = os.path.join(
        workdir, f"shuf_gb{global_batch}_nb{n_batches}_np{num_processes}")
    if not os.path.exists(dataset):
        schema = Schema("ShufCheck", [
            Field(_ID, np.int32),
            Field(_VALUE, np.float32, (_VALUE_DIM,)),
        ])
        write_dataset(dataset, schema,
                      [{_ID: np.int32(i), _VALUE: _value_for_ids([i])[0]}
                       for i in range(total)],
                      row_group_size_rows=local_rows)
    report, workers = _launch_and_collect(
        "shuffled", num_processes, devices_per_process, dataset, workdir,
        timeout, ["--global-batch", str(global_batch), "--seed", str(seed)])
    if workers is None:
        return report
    failures = report["failures"]
    seqs = {json.dumps(w["units"]) for w in workers}
    if len(seqs) != 1:
        failures.append("hosts realized DIFFERENT global unit sequences -"
                        " the seeded permutation diverged across processes")
    if len({tuple(w["means"]) for w in workers}) != 1:
        failures.append("hosts realized different collective results")
    flat: List[int] = []
    for u in workers[0]["units"]:
        for ids_step, mask_step in zip(u["ids"], u["mask"]):
            flat.extend(i for i, m in zip(ids_step, mask_step) if m > 0)
    if sorted(flat) != list(range(total)):
        dup = len(flat) - len(set(flat))
        failures.append(f"shuffled multiset broken: {len(flat)} rows,"
                        f" {dup} duplicated (want each of {total} once)")
    if flat == sorted(flat):
        failures.append("delivered order is the written order - nothing"
                        " shuffled")
    rho = abs(rank_correlation(np.asarray(flat))) if flat else 1.0
    report["rho_global"] = round(float(rho), 4)
    if rho > 0.5:
        failures.append(f"pod shuffle quality: global |rho|={rho:.3f} > 0.5")

    # the seeded plan is a pure function of (seed, shard): recompute each
    # shard's stream locally and interleave in lockstep - the pod's delivered
    # order must match it exactly (determinism across real processes)
    per_shard: List[List[int]] = []
    for p in range(num_processes):
        r = make_reader(dataset, cur_shard=p, shard_count=num_processes,
                        shuffle_row_groups=True, shuffle_seed=seed,
                        num_epochs=1, workers_count=1)
        ids: List[int] = []
        try:
            for cb in r.iter_batches():
                ids.extend(np.asarray(cb.columns[_ID]).astype(int).tolist())
        finally:
            r.stop()
            r.join()
        per_shard.append(ids)
    expect: List[int] = []
    for t in range(n_batches):
        for p in range(num_processes):
            expect.extend(per_shard[p][t * local_rows:(t + 1) * local_rows])
    if flat and flat != expect:
        failures.append("delivered order != seeded plan interleave (plan"
                        " determinism broken across real processes)")
    report["units"] = len(workers[0]["units"])
    report["ok"] = not failures
    return report


_MIXED_GEOMS = ((16, 24), (24, 16))
_MIXED_TARGET = (24, 24, 3)


def _worker_mixed(args) -> None:
    """'device-mixed' jpeg decode over a mesh SPANNING real processes
    (VERDICT r4 item 3b): each host entropy+bucket-decodes only ITS batch
    rows (host-local, per-geometry compiles), delivery declares one global
    array, and the REPLICATING all-gather proves rows decoded on host A
    arrive bit-identical on host B."""
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.parallel.mesh import shard_options_from_jax
    from petastorm_tpu.reader import make_batch_reader

    pid = jax.process_index()
    devices = jax.devices()
    mesh = Mesh(np.asarray(devices), ("data",))
    rep = NamedSharding(mesh, P())
    cur, count = shard_options_from_jax()
    reader = make_batch_reader(args.dataset, cur_shard=cur, shard_count=count,
                               shuffle_row_groups=False, num_epochs=1,
                               workers_count=1,
                               decode_placement={"image": "device-mixed"})
    replicate = jax.jit(lambda t: t, out_shardings=rep)
    got: Dict[int, np.ndarray] = {}
    with JaxDataLoader(reader, batch_size=args.global_batch, mesh=mesh,
                       fields=[_ID, "image"],
                       pad_shapes={"image": _MIXED_TARGET},
                       shardings={_ID: P("data"), "image": P("data")}) as loader:
        for b in loader:
            ids = np.asarray(replicate(b[_ID])).astype(int)
            imgs = np.asarray(replicate(b["image"]))
            for k, i in enumerate(ids):
                got[int(i)] = imgs[k]
        diag = loader.diagnostics
    order = sorted(got)
    np.save(os.path.join(args.out, f"mixed_{pid}.npy"),
            np.stack([got[i] for i in order]))
    with open(os.path.join(args.out, f"mixed_{pid}.json"), "w") as f:
        json.dump({"process_id": pid, "process_count": jax.process_count(),
                   "ids": order,
                   "geometries": diag.get("mixed_decode_geometries", {}),
                   "declared": diag.get("declared_geometries", {})}, f)


def run_mixed_check(num_processes: int = 2, devices_per_process: int = 4,
                    global_batch: int = 8, n_rows: int = 16,
                    timeout: float = 300.0,
                    workdir: Optional[str] = None) -> Dict:
    """'device-mixed' decode across real process boundaries; see
    ``_worker_mixed``.  The launcher generates a 2-geometry jpeg dataset,
    every worker decodes only its shard's rows on its own host, and the
    check compares (a) all hosts' replicated pixel arrays bit-for-bit and
    (b) every image against the launcher's own HOST decode of the stored
    bytes within the hybrid-decode tolerance."""
    import tempfile

    from petastorm_tpu.codecs import CompressedImageCodec
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.reader import make_batch_reader
    from petastorm_tpu.schema import Field, Schema
    from petastorm_tpu.test_util.synthetic import synthetic_rgb_image

    workdir = workdir or tempfile.mkdtemp(prefix="petastorm_tpu_mixcheck_")
    os.makedirs(workdir, exist_ok=True)
    dataset = os.path.join(workdir, f"mixed_n{n_rows}_gb{global_batch}")
    if not os.path.exists(dataset):
        schema = Schema("MixCheck", [
            Field(_ID, np.int32),
            Field("image", np.uint8, (None, None, 3),
                  CompressedImageCodec("jpeg", quality=92)),
        ])
        write_dataset(dataset, schema,
                      [{_ID: np.int32(i),
                        "image": synthetic_rgb_image(
                            i, *_MIXED_GEOMS[i % len(_MIXED_GEOMS)], noise=0)}
                       for i in range(n_rows)],
                      row_group_size_rows=global_batch // num_processes)
    report, workers = _launch_and_collect(
        "mixed", num_processes, devices_per_process, dataset, workdir,
        timeout, ["--global-batch", str(global_batch)])
    if workers is None:
        return report
    failures = report["failures"]
    arrays = [np.load(os.path.join(workdir, f"mixed_{w['process_id']}.npy"))
              for w in workers]
    if any(w["ids"] != list(range(n_rows)) for w in workers):
        failures.append("a host did not observe every row id")
    for w, a in zip(workers[1:], arrays[1:]):
        if not np.array_equal(arrays[0], a):
            failures.append(
                f"host {w['process_id']}: replicated decoded pixels differ"
                " from host 0 (cross-process scatter broke)")
            break
    geom_counts = {json.dumps(w["geometries"]) for w in workers}
    report["geometries_per_host"] = [w["geometries"] for w in workers]
    if any(w["geometries"].get("image", 0) > len(_MIXED_GEOMS)
           for w in workers):
        failures.append("a host compiled more decode geometries than the"
                        f" dataset holds: {sorted(geom_counts)}")
    if failures:
        # a host missed rows or pixels diverged: arrays[0] may be short, so
        # the per-row reference comparison below would IndexError instead of
        # returning the structured report
        report["ok"] = False
        return report
    # reference: the launcher's own host decode of the stored bytes
    ref: Dict[int, np.ndarray] = {}
    with make_batch_reader(dataset, shuffle_row_groups=False,
                           num_epochs=1, workers_count=1) as r:
        for cb in r.iter_batches():
            for i, img in zip(np.asarray(cb.columns[_ID]).astype(int),
                              cb.columns["image"]):
                ref[int(i)] = np.asarray(img)
    max_err, mean_err = 0.0, 0.0
    for i in range(n_rows):
        h, w_ = ref[i].shape[:2]
        dev = arrays[0][i]
        diff = np.abs(ref[i].astype(int) - dev[:h, :w_].astype(int))
        max_err = max(max_err, float(diff.max()))
        mean_err = max(mean_err, float(diff.mean()))
        if diff.max() > 6 or diff.mean() >= 1.0:
            failures.append(f"row {i}: device-mixed pixels off by"
                            f" max {diff.max()} / mean {diff.mean():.2f}"
                            " vs host decode")
            break
        if dev[h:].any() or dev[:, w_:].any():
            failures.append(f"row {i}: pad region not zero")
            break
    report["max_pixel_err"] = max_err
    report["mean_pixel_err"] = round(mean_err, 3)
    report["rows"] = n_rows
    report["ok"] = not failures
    return report


def _worker_mesh2d(args) -> None:
    """2-D mesh delivery with the DATA axis crossing the process boundary
    and the MODEL axis inside each process (dp x tp, the standard pod
    layout): sequence axis of 'tokens' sharded over 'model', batch over
    'data', then one jitted computation with a tp-sharded weight whose mean
    reduces over BOTH axes - psum inside each process, cross-process data
    reduction over Gloo - must equal a local numpy reference and agree
    bit-for-bit across hosts."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.parallel.mesh import shard_options_from_jax
    from petastorm_tpu.reader import make_reader

    pid = jax.process_index()
    devices = jax.devices()
    per = len(jax.local_devices())
    mesh = Mesh(np.asarray(devices).reshape(len(devices) // per, per),
                ("data", "model"))
    rep = NamedSharding(mesh, P())
    cur, count = shard_options_from_jax()
    reader = make_reader(args.dataset, cur_shard=cur, shard_count=count,
                         shuffle_row_groups=False, num_epochs=1,
                         workers_count=1)
    with JaxDataLoader(reader, batch_size=args.global_batch, mesh=mesh,
                       shardings={_ID: P("data"),
                                  "tokens": P("data", "model")}) as loader:
        batch = next(iter(loader))
        ids_g, tokens_g = batch[_ID], batch["tokens"]

    rng = np.random.default_rng(5)
    emb_np = rng.standard_normal((_M2D_VOCAB, _M2D_HIDDEN)).astype(np.float32)
    w_np = rng.standard_normal((_M2D_HIDDEN, _M2D_HIDDEN)).astype(np.float32)
    w_sharding = NamedSharding(mesh, P(None, "model"))  # tp-sharded weight
    W = jax.make_array_from_callback(w_np.shape, w_sharding,
                                     lambda idx: w_np[idx])
    emb = jax.make_array_from_callback(emb_np.shape, rep,
                                       lambda idx: emb_np[idx])
    loss_fn = jax.jit(
        lambda t, w, e: jnp.mean(jnp.einsum("bsh,hk->bsk", e[t], w)),
        out_shardings=rep)
    loss = float(loss_fn(tokens_g, W, emb))

    replicate = jax.jit(lambda t: t, out_shardings=rep)
    ids = np.asarray(replicate(ids_g)).astype(int)
    tokens = np.asarray(replicate(tokens_g))
    S = tokens.shape[1]
    expected = (ids[:, None] * 7 + np.arange(S)[None, :]) % _M2D_VOCAB
    assert np.array_equal(tokens, expected), "2-D delivery scrambled rows"
    ref = float(np.mean(np.einsum("bsh,hk->bsk", emb_np[tokens], w_np)))
    err = abs(loss - ref) / max(abs(ref), 1e-9)
    assert err < 1e-5, f"dp x tp collective diverged: {loss} vs {ref}"

    # every addressable token shard must live inside this process's data row
    lo = pid * (args.global_batch // jax.process_count())
    hi = lo + args.global_batch // jax.process_count()
    for sh in tokens_g.addressable_shards:
        b_sl = sh.index[0]
        # a replicated-delivery regression shows up as slice(None) bounds -
        # which a coalescing check would wave through on process 0
        assert b_sl.start is not None and b_sl.stop is not None, sh.index
        assert lo <= b_sl.start and b_sl.stop <= hi, sh.index
    with open(os.path.join(args.out, f"mesh2d_{pid}.json"), "w") as f:
        json.dump({"process_id": pid, "loss": loss, "ref": ref,
                   "mesh": {k: int(v) for k, v in mesh.shape.items()}}, f)


def _launch_and_collect(phase: str, num_processes: int,
                        devices_per_process: int, dataset: str, workdir: str,
                        timeout: float, extra: Optional[List[str]] = None,
                        result_prefix: Optional[str] = None):
    """Shared launcher boilerplate: spawn the workers, wait, load their
    result JSONs.  Returns ``(report, workers)``; ``workers`` is None when
    the launch failed (``report['failures']``/``'timeout'`` say why)."""
    report: Dict = {"ok": False, "timeout": False, "environment": False,
                    "failures": [], "workdir": workdir}
    logs: List[str] = []
    report["logs"] = logs
    error = _launch(phase, num_processes, devices_per_process, dataset,
                    workdir, timeout, logs, extra)
    if error:
        report["failures"].append(error)
        report["timeout"] = "timed out" in error
        report["environment"] = "environment-bound" in error
        return report, None
    workers = []
    prefix = result_prefix or phase
    for pid in range(num_processes):
        with open(os.path.join(workdir, f"{prefix}_{pid}.json")) as f:
            workers.append(json.load(f))
    return report, workers


def run_mesh2d_check(num_processes: int = 2, devices_per_process: int = 2,
                     global_batch: int = 8, seq: int = 8,
                     timeout: float = 240.0,
                     workdir: Optional[str] = None) -> Dict:
    """dp x tp delivery + collectives over a 2-D mesh whose data axis crosses
    real process boundaries; see ``_worker_mesh2d``."""
    import tempfile

    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.schema import Field, Schema

    assert global_batch % num_processes == 0 and \
        global_batch >= num_processes, (
        f"global_batch ({global_batch}) must divide over the data axis"
        f" ({num_processes} processes)")
    assert seq % devices_per_process == 0, (
        f"seq ({seq}) must divide over the model axis"
        f" ({devices_per_process} devices/process)")
    workdir = workdir or tempfile.mkdtemp(prefix="petastorm_tpu_m2dcheck_")
    os.makedirs(workdir, exist_ok=True)
    # every parameter that shapes the written file is in the cache name, so
    # a reused workdir can never serve a stale-geometry dataset
    dataset = os.path.join(
        workdir, f"m2d_b{global_batch}_s{seq}_np{num_processes}")
    if not os.path.exists(dataset):
        schema = Schema("Mesh2d", [
            Field(_ID, np.int32),
            Field("tokens", np.int32, (seq,)),
        ])
        total = global_batch * 4
        write_dataset(dataset, schema,
                      [{_ID: np.int32(i),
                        "tokens": ((i * 7 + np.arange(seq)) % _M2D_VOCAB
                                   ).astype(np.int32)}
                       for i in range(total)],
                      row_group_size_rows=global_batch // num_processes)
    report, workers = _launch_and_collect(
        "mesh2d", num_processes, devices_per_process, dataset, workdir,
        timeout, ["--global-batch", str(global_batch)])
    if workers is None:
        return report
    losses = {w["loss"] for w in workers}
    if len(losses) != 1:
        report["failures"].append(f"hosts realized different losses: {losses}")
    report["loss"] = workers[0]["loss"]
    report["mesh"] = workers[0]["mesh"]
    report["ok"] = not report["failures"]
    return report


def _worker_write(args) -> None:
    """Coordinated multi-host dataset write with the DEFAULT sync path: real
    ``multihost_utils.sync_global_devices`` barriers over Gloo (the in-repo
    tests simulate hosts with a threading.Barrier; this executes the actual
    collective), host-0 metadata stamp incl. merged geometry sidecars, then
    every host reads the stamped dataset back and checksums it."""
    import jax
    import numpy as np

    from petastorm_tpu.codecs import CompressedImageCodec
    from petastorm_tpu.parallel.write import distributed_write_dataset
    from petastorm_tpu.reader import make_batch_reader
    from petastorm_tpu.schema import Field, Schema

    pid = jax.process_index()
    count = jax.process_count()
    schema = Schema("MpWrite", [
        Field(_ID, np.int32),
        Field("image", np.uint8, (None, None, 3),
              CompressedImageCodec("png")),  # lossless: exact readback
    ])
    total = args.global_batch * 8
    rng = np.random.default_rng(7)
    all_rows = [{_ID: np.int32(i),
                 "image": rng.integers(0, 255, ((16, 24) if i % 2 else (24, 16))
                                       + (3,), dtype=np.uint8)}
                for i in range(total)]
    url = os.path.join(args.out, "mp_written_ds")
    # DEFAULT coordination: process_index/count and sync_fn come from the JAX
    # distributed runtime - the code path single-process tests cannot reach
    files = distributed_write_dataset(url, schema, all_rows[pid::count],
                                      row_group_size_rows=4)
    ids = []
    with make_batch_reader(url, num_epochs=1, workers_count=1) as r:
        declared = r.declared_geometries
        for cb in r.iter_batches():
            ids.extend(np.asarray(cb.columns[_ID]).astype(int).tolist())
    assert sorted(ids) == list(range(total)), (len(ids), total)
    assert sorted(declared["image"]) == [(16, 24, 3), (24, 16, 3)], declared
    with open(os.path.join(args.out, f"write_{pid}.json"), "w") as f:
        json.dump({"process_id": pid, "files": len(files),
                   "rows_read": len(ids),
                   "geometries": sorted(declared["image"])}, f)


def run_distributed_write_check(num_processes: int = 2,
                                global_batch: int = 8,
                                timeout: float = 240.0,
                                workdir: Optional[str] = None) -> Dict:
    """Multi-host coordinated write through the REAL sync_global_devices
    barriers; see ``_worker_write``.  Returns {"ok", "failures", ...}."""
    import tempfile

    workdir = workdir or tempfile.mkdtemp(prefix="petastorm_tpu_wrcheck_")
    os.makedirs(workdir, exist_ok=True)
    report, workers = _launch_and_collect(
        "write", num_processes, 1, "unused", workdir, timeout,
        ["--global-batch", str(global_batch)])
    if workers is None:
        return report
    report["rows_read"] = workers[0]["rows_read"]
    report["files_per_host"] = [w["files"] for w in workers]
    if any(w["rows_read"] != workers[0]["rows_read"] for w in workers):
        report["failures"].append("hosts read back different row counts")
    if any(w["files"] == 0 for w in workers):
        report["failures"].append("a host wrote no part files")
    report["ok"] = not report["failures"]
    return report


def _worker_resume(args) -> None:
    import jax

    from petastorm_tpu.parallel.mesh import shard_options_from_jax
    from petastorm_tpu.reader import elastic_resume, make_reader

    pid = jax.process_index()
    with open(args.resume_states, "rb") as f:
        states = pickle.load(f)
    token = elastic_resume(states)
    cur, count = shard_options_from_jax()
    reader = make_reader(args.dataset, cur_shard=cur, shard_count=count,
                         shuffle_row_groups=False, num_epochs=1,
                         workers_count=1, resume_from=token)
    ids: List[int] = []
    try:
        for cb in reader.iter_batches():
            ids.extend(np.asarray(cb.columns[_ID]).astype(int).tolist())
    finally:
        reader.stop()
        reader.join()
    with open(os.path.join(args.out, f"resume_{pid}.json"), "w") as f:
        json.dump({"process_id": pid, "process_count": jax.process_count(),
                   "ids": ids}, f)


# ---------------------------------------------------------------------------
# launcher side
# ---------------------------------------------------------------------------

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env(devices_per_process: int) -> Dict[str, str]:
    import petastorm_tpu

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count="
                        f"{devices_per_process}").strip()
    root = os.path.dirname(os.path.dirname(
        os.path.abspath(petastorm_tpu.__file__)))
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    return env


#: worker-log markers of failures that are properties of the RUNTIME, not
#: the data plane: this jax build simply cannot run the check here.  A
#: worker exit matching one is reported "environment-bound" so callers
#: (tests) can skip rather than fail - same contract as launcher timeouts.
_ENV_BOUND_MARKERS = (
    # jax 0.4.x CPU backend has no cross-process collective implementation
    "Multiprocess computations aren't implemented on the CPU backend",
    "Unable to initialize backend",
)


def _environment_bound_reason(log_path: str) -> Optional[str]:
    """The matching environment-bound marker line from a failed worker's
    log, or None (a real failure)."""
    try:
        with open(log_path, errors="replace") as f:
            tail = f.read()[-20000:]
    except OSError:
        return None
    for marker in _ENV_BOUND_MARKERS:
        if marker in tail:
            return marker
    return None


def _launch(phase: str, num_processes: int, devices_per_process: int,
            dataset: str, out: str, timeout: float, logs: List[str],
            extra: Optional[List[str]] = None) -> Optional[str]:
    """Spawn one worker per process, wait, return an error string or None."""
    port = _free_port()
    env = _worker_env(devices_per_process)
    procs = []
    for pid in range(num_processes):
        log_path = os.path.join(out, f"{phase}_{pid}.log")
        logs.append(log_path)
        log = open(log_path, "w")
        cmd = [sys.executable, "-m", "petastorm_tpu.parallel.selfcheck",
               "--worker", "--phase", phase,
               "--process-id", str(pid),
               "--num-processes", str(num_processes),
               "--coordinator", f"127.0.0.1:{port}",
               "--dataset", dataset, "--out", out] + (extra or [])
        procs.append((subprocess.Popen(cmd, env=env, stdout=log, stderr=log),
                      log))
    deadline = time.monotonic() + timeout
    error = None
    try:
        for proc, _ in procs:
            left = max(0.1, deadline - time.monotonic())
            try:
                code = proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                error = (f"{phase}: timed out after {timeout:.0f}s"
                         " (collective hang or machine too slow)")
                break
            if code != 0 and error is None:
                error = f"{phase}: worker exited with code {code}"
    finally:
        for proc, log in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
            log.close()
    if error and "timed out" not in error:
        # classify runtime-capability exits (e.g. a jax build whose CPU
        # backend has no cross-process collectives) so callers can skip
        for pid in range(num_processes):
            reason = _environment_bound_reason(
                os.path.join(out, f"{phase}_{pid}.log"))
            if reason is not None:
                return (f"{phase}: environment-bound: {reason}"
                        f" (worker {pid})")
    return error


def run_selfcheck(num_processes: int = 2,
                  devices_per_process: int = 2,
                  global_batch: int = 8,
                  n_batches: int = 28,
                  resume_processes: Optional[int] = 3,
                  settle: float = 1.5,
                  timeout: float = 240.0,
                  workdir: Optional[str] = None) -> Dict:
    """Run the multi-process data-plane check; return a report dict.

    ``report["ok"]`` is True when every invariant held; ``report["failures"]``
    lists what broke (``report["timeout"]`` marks an environment-style failure
    the caller may choose to skip on rather than fail).
    """
    import tempfile

    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.reader import make_reader
    from petastorm_tpu.schema import Field, Schema

    assert global_batch % (num_processes * devices_per_process) == 0, (
        "global_batch must divide evenly over the device mesh")
    local_rows = global_batch // num_processes
    total_rows = n_batches * global_batch

    workdir = workdir or tempfile.mkdtemp(prefix="petastorm_tpu_selfcheck_")
    os.makedirs(workdir, exist_ok=True)
    # params-keyed name: a reused workdir with different batch/process
    # geometry must regenerate, not misverify against a stale dataset
    dataset = os.path.join(
        workdir, f"ds_gb{global_batch}_nb{n_batches}_np{num_processes}")
    schema = Schema("SelfCheck", [
        Field(_ID, np.int32),
        Field(_VALUE, np.float32, (_VALUE_DIM,)),
    ])
    if not os.path.exists(dataset):
        write_dataset(dataset, schema,
                      [{_ID: np.int32(i), _VALUE: _value_for_ids([i])[0]}
                       for i in range(total_rows)],
                      row_group_size_rows=local_rows)

    report: Dict = {"ok": False, "timeout": False, "environment": False,
                    "failures": [],
                    "workdir": workdir, "num_processes": num_processes,
                    "devices_per_process": devices_per_process,
                    "global_batch": global_batch, "n_batches": n_batches}
    failures = report["failures"]
    logs: List[str] = []
    report["logs"] = logs

    # up to two pipeline attempts: drained-count inequality (which forces the
    # pad path) comes from engineered buffering asymmetry plus a settle
    # sleep, and a slow/contended box can still even the counts out - that
    # is a property of the box, not a data-plane failure, so retry once with
    # a longer settle and report `pad_exercised` rather than failing
    report["notes"] = notes = []
    workers: List[Dict] = []
    attempt_settle = settle
    for attempt in range(2):
        error = _launch("pipeline", num_processes, devices_per_process,
                        dataset, workdir, timeout, logs,
                        ["--global-batch", str(global_batch),
                         "--settle", str(attempt_settle)])
        if error:
            failures.append(error)
            report["timeout"] = "timed out" in error
            report["environment"] = "environment-bound" in error
            return report
        workers = []
        for pid in range(num_processes):
            with open(os.path.join(workdir, f"worker_{pid}.json")) as f:
                workers.append(json.load(f))
        if len(set(workers[0]["real_all"])) > 1 or attempt == 1:
            break
        notes.append(f"attempt {attempt + 1}: hosts drained equal counts"
                     f" {workers[0]['real_all']}; retrying with settle"
                     f" {attempt_settle * 2}")
        attempt_settle *= 2
    report["pad_exercised"] = len(set(workers[0]["real_all"])) > 1

    # ground truth: what each shard yields when read in THIS process
    def shard_ids(shard: int, count: int) -> List[int]:
        r = make_reader(dataset, cur_shard=shard, shard_count=count,
                        shuffle_row_groups=False, num_epochs=1,
                        workers_count=1)
        out: List[int] = []
        try:
            for cb in r.iter_batches():
                out.extend(np.asarray(cb.columns[_ID]).astype(int).tolist())
        finally:
            r.stop()
            r.join()
        return out

    expected_shards = [shard_ids(p, num_processes)
                       for p in range(num_processes)]

    # -- per-worker checks ---------------------------------------------------
    consumed: List[int] = []
    for w in workers:
        pid = w["process_id"]
        if w["process_count"] != num_processes:
            failures.append(f"worker {pid}: process_count {w['process_count']}")
        if w["n_devices"] != num_processes * devices_per_process:
            failures.append(f"worker {pid}: saw {w['n_devices']} devices")
        exp = expected_shards[pid]
        real = [b for b in w["batches"] if b["kind"] != "drain_pad"]
        pads = [b for b in w["batches"] if b["kind"] == "drain_pad"]
        lo = pid * local_rows
        for k, b in enumerate(real):
            shards = sorted(b["shards"], key=lambda s: s["start"])
            got = [i for s in shards for i in s["ids"]]
            want = exp[k * local_rows:(k + 1) * local_rows]
            if got != want:
                failures.append(
                    f"worker {pid} batch {k}: rows {got} != expected {want}"
                    " (global assembly placed the wrong data)")
                break
            starts = [s["start"] for s in shards]
            if starts[0] != lo or shards[-1]["stop"] != lo + local_rows:
                failures.append(
                    f"worker {pid} batch {k}: local shards cover"
                    f" [{starts[0]}, {shards[-1]['stop']}) but this process"
                    f" owns [{lo}, {lo + local_rows})")
                break
            if not b["values_match"]:
                failures.append(f"worker {pid} batch {k}: value column does"
                                " not match f(id)")
                break
            mask_vals = [v for m in sorted(b["mask"], key=lambda s: s["start"])
                         for v in m["vals"]]
            if mask_vals != [1.0] * local_rows:
                failures.append(f"worker {pid} batch {k}: real batch mask"
                                f" {mask_vals}")
                break
        for b in pads:
            mask_vals = [v for m in b["mask"] for v in m["vals"]]
            if any(v != 0.0 for v in mask_vals):
                failures.append(f"worker {pid}: pad batch has nonzero mask")
            if b["valid_rows"] != 0:
                failures.append(f"worker {pid}: pad batch valid_rows"
                                f" {b['valid_rows']}")
        consumed.extend(exp[:len(real) * local_rows])
        if len(set(w["drain_steps_all"])) != 1:
            failures.append(f"worker {pid}: unaligned drain steps"
                            f" {w['drain_steps_all']}")
        if any(not np.isfinite(m) for m in w["means"]):
            failures.append(f"worker {pid}: non-finite collective result")

    # -- cross-worker checks -------------------------------------------------
    real_counts = workers[0]["real_all"]
    report["drained_real_per_process"] = real_counts
    report["pad_counts"] = [w["pad_count"] for w in workers]
    if not report["pad_exercised"]:
        notes.append(
            "hosts drained equal counts on both attempts - the pad path was"
            " not exercised this run (slow box, not a data-plane failure)")
    elif sum(report["pad_counts"]) == 0:
        failures.append("hosts drained unequal counts but no alignment pads"
                        " were emitted")
    means = [tuple(w["means"]) for w in workers]
    if len(set(means)) != 1:
        failures.append("hosts realized different collective results:"
                        f" {means} (replicated output must agree)")

    # -- phase 2: elastic resume under a different process count -------------
    if resume_processes:
        states = []
        for pid in range(num_processes):
            with open(os.path.join(workdir, f"state_{pid}.pkl"), "rb") as f:
                states.append(pickle.load(f))
        with open(os.path.join(workdir, "states.pkl"), "wb") as f:
            pickle.dump(states, f)
        error = _launch("resume", resume_processes, 1, dataset, workdir,
                        timeout, logs,
                        ["--resume-states",
                         os.path.join(workdir, "states.pkl")])
        if error:
            failures.append(error)
            report["timeout"] = report["timeout"] or "timed out" in error
            report["environment"] = (report.get("environment", False)
                                     or "environment-bound" in error)
            return report
        resumed: List[int] = []
        for pid in range(resume_processes):
            with open(os.path.join(workdir, f"resume_{pid}.json")) as f:
                resumed.extend(json.load(f)["ids"])
        report["consumed_rows"] = len(consumed)
        report["resumed_rows"] = len(resumed)
        both = sorted(consumed + resumed)
        if both != list(range(total_rows)):
            dup = len(both) - len(set(both))
            missing = sorted(set(range(total_rows)) - set(both))[:10]
            failures.append(
                f"resume not exact: {dup} duplicated rows, first missing"
                f" {missing} ({len(consumed)} consumed + {len(resumed)}"
                f" resumed of {total_rows})")

    report["ok"] = not failures
    return report


def _main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--worker", action="store_true",
                        help="internal: run as a spawned worker process")
    parser.add_argument("--phase", default="pipeline",
                        choices=["pipeline", "resume", "cp", "write",
                                 "mesh2d", "shuffled", "mixed"])
    parser.add_argument("--process-id", type=int, default=0)
    parser.add_argument("--num-processes", type=int, default=2)
    parser.add_argument("--coordinator", default=None)
    parser.add_argument("--dataset", default=None)
    parser.add_argument("--out", default=None)
    parser.add_argument("--global-batch", type=int, default=8)
    parser.add_argument("--seed", type=int, default=9)
    parser.add_argument("--settle", type=float, default=1.5)
    parser.add_argument("--resume-states", default=None)
    parser.add_argument("--devices-per-process", type=int, default=2)
    parser.add_argument("--resume-processes", type=int, default=3)
    parser.add_argument("--timeout", type=float, default=240.0)
    args = parser.parse_args()
    if args.worker:
        _worker_main(args)
        return 0
    # launcher mode: --phase picks which check to run (the 'resume' phase is
    # part of the pipeline check, not standalone)
    if args.phase == "pipeline":
        report = run_selfcheck(num_processes=args.num_processes,
                               devices_per_process=args.devices_per_process,
                               global_batch=args.global_batch,
                               resume_processes=args.resume_processes,
                               settle=args.settle, timeout=args.timeout)
    elif args.phase == "cp":
        report = run_context_parallel_check(
            num_processes=args.num_processes,
            devices_per_process=args.devices_per_process,
            timeout=args.timeout)
    elif args.phase == "write":
        report = run_distributed_write_check(
            num_processes=args.num_processes, timeout=args.timeout)
    elif args.phase == "mesh2d":
        report = run_mesh2d_check(
            num_processes=args.num_processes,
            devices_per_process=args.devices_per_process,
            timeout=args.timeout)
    elif args.phase == "shuffled":
        report = run_shuffled_check(
            num_processes=args.num_processes,
            devices_per_process=args.devices_per_process,
            seed=args.seed, timeout=args.timeout)
    elif args.phase == "mixed":
        report = run_mixed_check(
            num_processes=args.num_processes,
            devices_per_process=args.devices_per_process,
            timeout=args.timeout)
    else:
        print(f"--phase {args.phase} is not a standalone check (it runs"
              " inside the pipeline check)")
        return 2
    print(json.dumps(report, indent=2))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(_main())
