"""Stdlib-only line coverage for the test suite (PEP 669 sys.monitoring).

coverage.py / pytest-cov have no installable distribution in the zero-egress
build environment (docs/operations.md), so this uses Python 3.12's
monitoring API directly: a LINE callback that returns
``sys.monitoring.DISABLE`` after the first hit of each code location -
steady-state overhead is near zero (the same mechanism coverage.py's
``sysmon`` core uses).

Usage::

    python tools/run_coverage.py                # full suite + report
    python tools/run_coverage.py tests/test_schema.py   # subset
    COV=1 ./ci.sh                               # CI entry

Reference analog: the reference tracks line coverage via codecov
(/root/reference/README.rst:4-12); the recorded figure lives in RESULTS.md.

Caveats (stated in the report): subprocess children (spawn-based process
pools, the multi-process selfcheck workers, bench train children) execute
outside this process, so lines only they reach count as uncovered here.
"""

from __future__ import annotations

import os
import sys
from typing import Dict, Set


class LineCoverage:
    """Record executed lines of files under ``root`` via sys.monitoring."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root) + os.sep
        self.hits: Dict[str, Set[int]] = {}
        self._tool = sys.monitoring.COVERAGE_ID

    def _on_line(self, code, line):
        fn = code.co_filename
        if fn.startswith(self.root):
            self.hits.setdefault(fn, set()).add(line)
        # one hit per location is all line coverage needs: disabling the
        # event at this location makes the steady state almost free
        return sys.monitoring.DISABLE

    def start(self) -> None:
        sys.monitoring.use_tool_id(self._tool, "petastorm-tpu-linecov")
        sys.monitoring.register_callback(
            self._tool, sys.monitoring.events.LINE, self._on_line)
        sys.monitoring.set_events(self._tool, sys.monitoring.events.LINE)

    def stop(self) -> None:
        sys.monitoring.set_events(self._tool, 0)
        sys.monitoring.register_callback(
            self._tool, sys.monitoring.events.LINE, None)
        sys.monitoring.free_tool_id(self._tool)


def executable_lines(path: str) -> Set[int]:
    """The interpreter's own notion of executable lines: compile the file
    and walk every code object's ``co_lines`` - the honest denominator
    (comments/blank lines never appear; docstring loads do)."""
    with open(path, encoding="utf-8") as f:
        source = f.read()
    try:
        top = compile(source, path, "exec")
    except SyntaxError:
        return set()
    lines: Set[int] = set()
    stack = [top]
    while stack:
        code = stack.pop()
        for _, _, line in code.co_lines():
            if line is not None:
                lines.add(line)
        for const in code.co_consts:
            if hasattr(const, "co_lines"):
                stack.append(const)
    return lines


def report(cov: LineCoverage, pkg_root: str) -> float:
    rows = []
    total_exec = total_hit = 0
    for dirpath, _, files in os.walk(pkg_root):
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            exe = executable_lines(path)
            if not exe:
                continue
            hit = len(cov.hits.get(path, set()) & exe)
            total_exec += len(exe)
            total_hit += hit
            rows.append((os.path.relpath(path, pkg_root), hit, len(exe)))
    rows.sort(key=lambda r: r[1] / r[2])
    print("\n== line coverage (sys.monitoring; in-process only - spawn-pool"
          " workers, selfcheck processes and bench children run elsewhere) ==")
    for rel, hit, exe in rows:
        print(f"  {100.0 * hit / exe:5.1f}%  {hit:5d}/{exe:<5d}  {rel}")
    pct = 100.0 * total_hit / max(total_exec, 1)
    print(f"COVERAGE_TOTAL {pct:.1f}% ({total_hit}/{total_exec} lines)")
    return pct


def main() -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    import pytest

    # resolve the package by PATH, not import: importing it here would run
    # every module-level line BEFORE the monitor starts, permanently
    # undercounting them (import happens once per process)
    pkg_root = os.path.join(repo, "petastorm_tpu")
    cov = LineCoverage(pkg_root)
    cov.start()
    try:
        rc = pytest.main(["tests/", "-q"] + sys.argv[1:])
    finally:
        cov.stop()
        report(cov, pkg_root)
    return int(rc)


if __name__ == "__main__":
    sys.exit(main())
