"""Token/sequence pipeline: tokenized-text datasets, deterministic sequence
packing, ragged delivery, and seeded multi-corpus mixture scheduling -
the LLM ingest workload on the same plan/executor/service machinery the
image pipeline runs on (ROADMAP item 4; docs/operations.md "Token
pipelines").

Layers (each usable alone):

* :mod:`~petastorm_tpu.sequence.dataset` - token corpora as
  variable-length list columns; validated readers; the document stream.
* :mod:`~petastorm_tpu.sequence.packing` - first-fit-shrinking packing
  into dense ``(batch, seq_len)`` blocks with segment IDs / positions /
  loss masks, ragged delivery, and the packed-stream digest.
* :mod:`~petastorm_tpu.sequence.mixing` - N corpora mixed by weight, the
  whole mixture a pure function of one seed, draw sequence certified.
* :mod:`~petastorm_tpu.sequence.loader` - JaxDataLoader integration
  delivering ``(tokens, segment_ids, positions, loss_mask)`` device
  arrays.
"""

from petastorm_tpu.sequence.dataset import (is_sequence_field,
                                            iter_documents,
                                            make_sequence_reader,
                                            token_field)
from petastorm_tpu.sequence.loader import (PackedSequenceReader,
                                           make_packed_sequence_loader)
from petastorm_tpu.sequence.mixing import (corpus_seed,
                                           make_mixed_sequence_reader)
from petastorm_tpu.sequence.packing import (PACKED_FIELDS, SequencePacker,
                                            iter_packed_blocks,
                                            iter_packed_rows,
                                            iter_ragged_batches,
                                            packed_stream_digest)

__all__ = [
    "token_field", "is_sequence_field", "make_sequence_reader",
    "iter_documents",
    "SequencePacker", "iter_packed_rows", "iter_packed_blocks",
    "iter_ragged_batches", "packed_stream_digest", "PACKED_FIELDS",
    "make_mixed_sequence_reader", "corpus_seed",
    "PackedSequenceReader", "make_packed_sequence_loader",
]
