"""Spark-free dataset writer.

Reference parity: ``materialize_dataset`` (petastorm/etl/dataset_metadata.py:53-133)
which (a) pre-configures the writer (rowgroup size), (b) lets the user write parquet,
(c) stamps schema + per-file rowgroup counts into ``_common_metadata`` and validates
readability (dataset_metadata.py:118-131).  The reference's row encoding is
``dict_to_spark_row`` on Spark executors (unischema.py:356-403).

Here the default writer is pyarrow-native (no JVM): ``write_dataset`` encodes rows
columnar-batch-at-a-time and writes parquet directly; ``materialize_dataset`` is kept
as a context manager for interop flows (user writes parquet by any means - pandas,
polars, Spark-over-parquet - and we stamp metadata on exit).  Distributed writes on a
TPU pod: every host calls ``write_dataset`` with a distinct ``file_prefix`` (e.g.
``f"part-{jax.process_index()}"``) into the same directory, then exactly one host
calls ``stamp_dataset_metadata`` - coordination is the caller's (or
petastorm_tpu.parallel's) job, not a JVM's.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import logging
import posixpath
import uuid
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Union

import numpy as np
import pyarrow as pa
import pyarrow.fs as pafs
import pyarrow.parquet as pq

from petastorm_tpu.errors import MetadataError, SchemaError
from petastorm_tpu.etl.metadata import (GEOMETRIES_METADATA_KEY,
                                        ROW_GROUPS_METADATA_KEY, _is_data_file,
                                        _read_kv_metadata,
                                        collect_row_group_counts, hive_partition_segment,
                                        open_dataset, write_metadata_file)
from petastorm_tpu.fs import get_filesystem_and_path
from petastorm_tpu.schema import SCHEMA_METADATA_KEY, Schema, insert_explicit_nulls

logger = logging.getLogger(__name__)

DEFAULT_ROW_GROUP_SIZE_MB = 32  # reference default: row_group_size_mb (dataset_metadata.py:62)


def default_compression(schema: Schema, exclude: Optional[set] = None
                        ) -> Dict[str, str]:
    """Per-column parquet codecs: snappy, but UNCOMPRESSED for fields whose
    codec already emits entropy-coded bytes (``Codec.precompressed``)."""
    exclude = exclude or set()
    return {f.name: ("NONE" if getattr(f.codec, "precompressed", False)
                     else "SNAPPY")
            for f in schema if f.name not in exclude}


def _encode_chunk(schema: Schema, file_schema: pa.Schema,
                  rows: List[dict],
                  encode_pool=None) -> pa.RecordBatch:
    """Encode a chunk of row dicts into one arrow RecordBatch (storage types).

    With ``encode_pool`` (a ThreadPoolExecutor), rows encode in parallel: the
    expensive codecs (jpeg/png encode via cv2/libpng, np.savez deflate)
    release the GIL, so write-side materialization scales with host cores.
    Output order is the input order either way.
    """
    prepared = (insert_explicit_nulls(schema, r) for r in rows)
    if encode_pool is not None:
        encoded_rows = list(encode_pool.map(schema.encode_row, prepared))
    else:
        encoded_rows = [schema.encode_row(r) for r in prepared]
    arrays = [pa.array([r[name] for r in encoded_rows], type=file_schema.field(name).type)
              for name in file_schema.names]
    return pa.RecordBatch.from_arrays(arrays, schema=file_schema)


def _estimate_rows_per_group(batch: pa.RecordBatch, target_mb: float) -> int:
    nbytes = max(batch.nbytes, 1)
    per_row = nbytes / max(batch.num_rows, 1)
    return max(1, int(target_mb * 1024 * 1024 / per_row))


def write_dataset(url: str,
                  schema: Schema,
                  rows: Iterable[dict],
                  row_group_size_mb: Optional[float] = None,
                  row_group_size_rows: Optional[int] = None,
                  rows_per_file: Optional[int] = None,
                  partition_by: Sequence[str] = (),
                  file_prefix: str = "part",
                  filesystem: Optional[pafs.FileSystem] = None,
                  storage_options: Optional[dict] = None,
                  stamp_metadata: bool = True,
                  mode: str = "error",
                  compression: Optional[Union[str, Dict[str, str]]] = None,
                  encode_workers: int = 1,
                  geometry_sink: Optional[Dict[str, set]] = None) -> List[str]:
    """Encode + write rows as a petastorm_tpu parquet dataset; returns file paths.

    ``partition_by`` names scalar fields materialized as hive ``key=value``
    directories (values must be str/int/bool-convertible); partitioned fields are
    not duplicated inside the files, matching parquet convention.

    ``mode``: what to do when ``url`` already holds data files - ``"error"``
    (default; silently mixing old and new rows is almost never intended),
    ``"overwrite"`` (delete existing contents first), or ``"append"`` (add new
    part files; the metadata stamp is refreshed to cover old + new).

    ``compression``: parquet codec name, or {column: codec} dict.  Default:
    snappy, except columns whose field codec is ``precompressed`` (PNG/JPEG
    images, compressed ndarrays) are stored UNCOMPRESSED - re-compressing
    entropy-coded bytes saves nothing and costs a decompress pass per read.

    ``encode_workers`` > 1 encodes rows through the codecs on a thread pool
    (jpeg/png/deflate encoding releases the GIL); row and rowgroup order are
    unchanged, so the written dataset is byte-identical either way.

    ``geometry_sink``: coordination hook for multi-writer flows
    (``parallel.distributed_write_dataset``) - the distinct image shapes this
    call observed are ADDED to the given dict ({field: set of shape tuples})
    so a coordinator can merge every writer's set and stamp the combined
    geometry contract; with ``stamp_metadata=True`` the shapes are also
    stamped directly.
    """
    if mode not in ("error", "overwrite", "append"):
        raise ValueError(f"mode must be 'error', 'overwrite' or 'append',"
                         f" got {mode!r}")
    if row_group_size_mb is None and row_group_size_rows is None:
        row_group_size_mb = DEFAULT_ROW_GROUP_SIZE_MB
    for pcol in partition_by:
        if pcol not in schema:
            raise SchemaError(f"partition_by field {pcol!r} not in schema")
        if schema[pcol].shape != ():
            raise SchemaError(f"partition_by field {pcol!r} must be scalar")

    fs, root = get_filesystem_and_path(url, storage_options, filesystem)
    if mode != "append" and fs.get_file_info(root).type == pafs.FileType.Directory:
        existing = [f.path for f in fs.get_file_info(
                        pafs.FileSelector(root, recursive=True))
                    if f.type == pafs.FileType.File and _is_data_file(f.path)]
        if existing and mode == "error":
            raise SchemaError(
                f"Dataset path {url!r} already contains {len(existing)} data"
                " file(s); pass mode='overwrite' to replace or mode='append'"
                " to add to it")
        if existing:
            fs.delete_dir_contents(root)
    fs.create_dir(root, recursive=True)

    storage = schema.as_arrow_schema()
    file_schema = pa.schema([storage.field(f.name) for f in schema
                             if f.name not in set(partition_by)],
                            metadata={SCHEMA_METADATA_KEY: schema.to_json()})
    if compression is None:
        compression = default_compression(schema, exclude=set(partition_by))

    writers: Dict[str, pq.ParquetWriter] = {}
    files: List[str] = []
    rows_written: Dict[str, int] = {}
    rows_per_group = row_group_size_rows

    def _writer_for(partition_values: tuple) -> pq.ParquetWriter:
        key = "/".join(hive_partition_segment(k, v) for k, v in partition_values)
        if key not in writers:
            subdir = posixpath.join(root, key) if key else root
            fs.create_dir(subdir, recursive=True)
            fname = f"{file_prefix}-{len(files):05d}-{uuid.uuid4().hex[:8]}.parquet"
            path = posixpath.join(subdir, fname)
            # page checksums are the storage-integrity layer: the image codec's
            # native decoder skips in-stream PNG CRCs, so corruption detection
            # belongs here (verified on read via verify_checksums=True)
            writers[key] = pq.ParquetWriter(path, file_schema, filesystem=fs,
                                            compression=compression,
                                            write_page_checksum=True)
            files.append(path)
            rows_written[key] = 0
        return writers[key]

    def _delete_files_best_effort(fs_, paths):
        for path in paths:
            try:
                fs_.delete_file(path)
            except Exception:  # noqa: BLE001 - already failing
                logger.warning("could not delete partial file %s after failed"
                               " write", path, exc_info=True)

    # dataset-level geometry contract: record the distinct image shapes of
    # variable-shape CompressedImageCodec fields while the rows stream by, so
    # readers know EVERY geometry up front (bounds the on-device mixed-decode
    # compile count; jax loader 'device-mixed')
    from petastorm_tpu.codecs import CompressedImageCodec

    geom_fields = [f.name for f in schema
                   if isinstance(f.codec, CompressedImageCodec)
                   and any(d is None for d in f.shape)]
    geom_seen: Dict[str, set] = (geometry_sink if geometry_sink is not None
                                 else {})
    for name in geom_fields:
        geom_seen.setdefault(name, set())

    _ESTIMATE_CHUNK = 1024  # rows encoded to estimate bytes/row for MB-based sizing
    pending: Dict[tuple, List[dict]] = {}

    def _flush(pv: tuple, final: bool) -> None:
        """Write full rowgroups from the partition buffer; keep the remainder.

        Buffering per partition (not per encode-chunk) is what prevents runt
        rowgroups when rows interleave across partitions.
        """
        nonlocal rows_per_group
        buf = pending.get(pv, [])
        threshold = rows_per_group if rows_per_group is not None else _ESTIMATE_CHUNK
        while buf and (final or len(buf) >= threshold):
            chunk, buf = buf[:threshold], buf[threshold:]
            batch = _encode_chunk(schema, file_schema, chunk,
                                  encode_pool=encode_pool)
            if rows_per_group is None:
                rows_per_group = _estimate_rows_per_group(batch, row_group_size_mb)
                threshold = rows_per_group
            writer = _writer_for(pv)
            # write_table splits into ceil(n/rows_per_group) rowgroups itself,
            # which only matters for the estimate chunk exceeding the target
            writer.write_table(pa.Table.from_batches([batch]),
                               row_group_size=rows_per_group)
            key = "/".join(hive_partition_segment(k, v) for k, v in pv)
            rows_written[key] += batch.num_rows
            if rows_per_file and rows_written[key] >= rows_per_file:
                writers.pop(key).close()
                rows_written[key] = 0
        pending[pv] = buf

    encode_pool = None
    if encode_workers > 1:
        from concurrent.futures import ThreadPoolExecutor

        encode_pool = ThreadPoolExecutor(max_workers=encode_workers,
                                         thread_name_prefix="pst-encode")
    failed = False
    try:
        for r in rows:
            for k in partition_by:
                if r.get(k) is None:
                    raise SchemaError(f"Row is missing a value for partition field {k!r}"
                                      " (partition values must be non-null)")
            pv = tuple((k, str(r[k])) for k in partition_by)
            for name in geom_fields:
                v = r.get(name)
                if v is not None:
                    geom_seen[name].add(tuple(np.asarray(v).shape))
            pending.setdefault(pv, []).append(r)
            if len(pending[pv]) >= (rows_per_group or _ESTIMATE_CHUNK):
                _flush(pv, final=False)
        for pv in list(pending):
            _flush(pv, final=True)
    except BaseException:
        failed = True
        raise
    finally:
        if encode_pool is not None:
            encode_pool.shutdown(wait=True)
        if failed:
            # best-effort close so output streams/multipart uploads are not
            # leaked when encoding or the caller's row generator raised (the
            # happy path closes below, where a footer-write failure must
            # still raise loudly)
            for w in writers.values():
                try:
                    w.close()
                except Exception:  # noqa: BLE001 - already failing
                    logger.warning("could not close parquet writer after"
                                   " failed write", exc_info=True)
            # close() wrote footers, so the debris now parses as VALID parquet
            # that a later mode='append' run or metadata stamp would silently
            # adopt as complete data - delete what this failed call produced
            _delete_files_best_effort(fs, files)

    close_exc = None
    for w in writers.values():
        try:
            w.close()
        except BaseException as exc:  # noqa: BLE001 - re-raised below
            # keep closing the REST: an unclosed writer's stream could flush
            # its footer later (GC/multipart commit) and resurrect a valid
            # parquet file after the cleanup below deletes it
            if close_exc is None:
                close_exc = exc
    if close_exc is not None:
        # a footer flush failed (ENOSPC, upload error): earlier writers in
        # this loop closed fine, so their files parse as complete parquet -
        # the whole call failed, none of its output may survive to be adopted
        _delete_files_best_effort(fs, files)
        raise close_exc
    if not files:
        logger.warning("write_dataset(%s): no rows were written; dataset left empty",
                       url)
        return []
    if stamp_metadata:
        stamp_dataset_metadata(url, schema, filesystem=fs,
                               geometries={n: s for n, s in geom_seen.items()
                                           if s} or None)
    return files


def stamp_dataset_metadata(url: str, schema: Optional[Schema] = None,
                           filesystem: Optional[pafs.FileSystem] = None,
                           storage_options: Optional[dict] = None,
                           validate: bool = True,
                           geometries: Optional[Dict[str, Iterable]] = None,
                           merge_geometries: bool = True) -> None:
    """Write/refresh ``_common_metadata``: schema JSON + per-file rowgroup counts.

    ``geometries``: {field: iterable of image shape tuples} to stamp as the
    dataset-level geometry contract (see ``etl.metadata.declared_geometries``).
    With ``merge_geometries=True`` (default) they are unioned with any
    already-stamped shapes - right for ``mode='append'`` writes, which see
    only their own rows.  Pass ``merge_geometries=False`` when the given set
    is authoritative for the WHOLE dataset (a full rescan:
    ``petastorm-tpu-generate-metadata --scan-geometries``), so stale
    geometries from rewritten files actually disappear.

    Reference: the post-write half of ``materialize_dataset``
    (dataset_metadata.py:113-131) and the standalone regenerator CLI
    (etl/petastorm_generate_metadata.py).
    """
    fs, root = get_filesystem_and_path(url, storage_options, filesystem)
    selector = pafs.FileSelector(root, recursive=True)
    files = sorted(f.path for f in fs.get_file_info(selector)
                   if f.type == pafs.FileType.File and _is_data_file(f.path))
    if not files:
        raise MetadataError(f"No data files under {url!r} to stamp metadata for")
    counts = collect_row_group_counts(fs, root, files)
    with fs.open_input_file(files[0]) as f:
        arrow_schema = pq.ParquetFile(f).schema_arrow
    if schema is None:
        file_kv = arrow_schema.metadata or {}
        if SCHEMA_METADATA_KEY not in file_kv:
            raise MetadataError(
                "No schema given and data files carry no petastorm-tpu schema;"
                " pass schema= explicitly")
        schema = Schema.from_json(file_kv[SCHEMA_METADATA_KEY])
    kv = {
        SCHEMA_METADATA_KEY: schema.to_json().encode(),
        ROW_GROUPS_METADATA_KEY: json.dumps({"files": counts}).encode(),
    }
    # an EMPTY dict with merge_geometries=False is meaningful: an authoritative
    # rescan found no image geometries, so the stamped contract must become
    # empty (write_metadata_file's KV merge would otherwise preserve the stale
    # key and the "REPLACE" semantics of --scan-geometries would silently fail)
    if geometries or (geometries is not None and not merge_geometries):
        merged: Dict[str, set] = {n: {tuple(int(d) for d in s) for s in shapes}
                                  for n, shapes in geometries.items()}
        existing_raw = (_read_kv_metadata(fs, root).get(GEOMETRIES_METADATA_KEY)
                        if merge_geometries else None)
        if existing_raw:
            try:
                for n, shapes in json.loads(existing_raw).items():
                    merged.setdefault(n, set()).update(
                        tuple(int(d) for d in s) for s in shapes)
            except (ValueError, TypeError):
                logger.warning("discarding unparseable stamped geometry"
                               " metadata during re-stamp")
        kv[GEOMETRIES_METADATA_KEY] = json.dumps(
            {n: sorted(list(s) for s in shapes)
             for n, shapes in merged.items()}).encode()
    write_metadata_file(fs, root, arrow_schema, kv)
    if validate:
        info = open_dataset(url, filesystem=fs, require_stored_schema=True)
        if not info.row_groups:
            raise MetadataError(f"Validation failed: no rowgroups visible at {url!r}")


@contextlib.contextmanager
def materialize_dataset(url: str, schema: Schema,
                        filesystem: Optional[pafs.FileSystem] = None,
                        storage_options: Optional[dict] = None) -> Iterator[None]:
    """Context manager: user writes parquet under ``url`` inside the block (by any
    engine), metadata is stamped + validated on exit.

    Reference: ``materialize_dataset`` (dataset_metadata.py:53-133), minus the JVM.
    Encoded cell values must follow the schema's storage types - use
    ``schema.encode_row`` (the ``dict_to_spark_row`` equivalent) on each row.
    """
    yield
    stamp_dataset_metadata(url, schema, filesystem=filesystem,
                           storage_options=storage_options)
