"""Benchmark harness (L7).

Reference parity: petastorm/benchmark/ - ``reader_throughput`` warmup+measure
cycles reporting samples/sec, RSS, CPU% (throughput.py:113-174), fresh-process
re-exec for accurate RSS (throughput.py:69-91), argparse CLI (cli.py), and a
loader-only microbench without parquet (dummy_reader.py:25-85).
"""

from petastorm_tpu.benchmark.throughput import (BenchmarkResult, WorkerPoolType,
                                                jax_loader_throughput,
                                                reader_throughput)

__all__ = ["BenchmarkResult", "WorkerPoolType", "reader_throughput",
           "jax_loader_throughput"]
