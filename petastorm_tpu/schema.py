"""Typed tensor schema stored alongside Parquet data.

Reference parity: petastorm/unischema.py (497 LoC) - UnischemaField namedtuple with
codec-invariant eq/hash (unischema.py:40-85), Unischema with views/regex matching/
cached namedtuples (unischema.py:88-240,434-461), arrow-schema inference
(unischema.py:302-353), write-side row encoding ``dict_to_spark_row``
(unischema.py:356-403) and ``insert_explicit_nulls`` (unischema.py:406-421).

Design differences (TPU-first):

* ``Schema`` serializes to **JSON** stored in parquet key-value metadata - never
  pickle (the reference's worst fragility: etl/dataset_metadata.py:202-206 pickles
  class instances, so refactors break stored datasets).
* Fields carry a ``jax_feed`` view (promoted dtype + static-shape policy) so the
  device-delivery layer is a pure function of the schema; XLA needs static shapes,
  so variable dims (None) must resolve through a pad-to-bucket policy declared here.
* Row encoding targets pyarrow (``encode_row``), not Spark Rows; Spark interop is an
  adapter on top (petastorm_tpu/spark/).
"""

from __future__ import annotations

import dataclasses
import json
import re
from collections import OrderedDict, namedtuple
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np
import pyarrow as pa

from petastorm_tpu import dtypes
from petastorm_tpu.codecs import (Codec, NdarrayCodec, ScalarCodec, ScalarListCodec,
                                  codec_from_json)
from petastorm_tpu.errors import SchemaError

#: Parquet key-value metadata key holding the JSON-serialized Schema.
SCHEMA_METADATA_KEY = b"petastorm-tpu.schema.v1"


@dataclasses.dataclass(frozen=True)
class Field:
    """One logical field: a named tensor with dtype, shape, codec, nullability.

    ``shape`` dims of ``None`` are variable (reference: unischema.py:56-57).
    Equality and hash ignore the codec, matching the reference's codec-invariant
    field identity (unischema.py:40-85) so schema views from different sources
    compare equal.
    """

    name: str
    dtype: np.dtype
    shape: Tuple[Optional[int], ...] = ()
    codec: Optional[Codec] = None
    nullable: bool = False

    def __post_init__(self):
        object.__setattr__(self, "dtype", np.dtype(self.dtype))
        object.__setattr__(self, "shape", tuple(self.shape))
        if self.codec is None:
            default = ScalarCodec() if self.shape == () else NdarrayCodec()
            object.__setattr__(self, "codec", default)

    @property
    def is_fixed_shape(self) -> bool:
        """True when every dim is concrete (no None wildcards) - such columns decode to one contiguous (n, *shape) array."""
        return all(d is not None for d in self.shape)

    def __eq__(self, other):
        if not isinstance(other, Field):
            return NotImplemented
        return (self.name, self.dtype, self.shape, self.nullable) == (
            other.name, other.dtype, other.shape, other.nullable)

    def __hash__(self):
        return hash((self.name, self.dtype, self.shape, self.nullable))

    # -- serialization --------------------------------------------------------

    def to_json(self) -> Dict[str, Any]:
        """JSON-native dict for the stored schema document (dtype as numpy str, shape with null wildcards, codec by registered name)."""
        # dtype.str ('<U10', '|S5', '<f4') roundtrips through np.dtype() exactly,
        # unlike dtype.name which is lossy for unicode and invalid for bytes
        return {
            "name": self.name,
            "dtype": "object" if self.dtype.kind == "O" else self.dtype.str,
            "shape": list(self.shape),
            "codec": self.codec.to_json(),
            "nullable": self.nullable,
        }

    @classmethod
    def from_json(cls, obj: Dict[str, Any]) -> "Field":
        dtype = np.dtype("object") if obj["dtype"] in ("str", "object") else np.dtype(obj["dtype"])
        return cls(
            name=obj["name"],
            dtype=dtype,
            shape=tuple(obj["shape"]),
            codec=codec_from_json(obj["codec"]),
            nullable=bool(obj.get("nullable", False)),
        )


_SelectorT = Union[str, Field, "re.Pattern"]


class Schema:
    """Ordered collection of Fields with views, namedtuple emission, and IO forms."""

    def __init__(self, name: str, fields: Sequence[Field]):
        self._name = name
        self._fields: "OrderedDict[str, Field]" = OrderedDict()
        for f in fields:
            if f.name in self._fields:
                raise SchemaError(f"Duplicate field {f.name!r} in schema {name!r}")
            self._fields[f.name] = f
        self._namedtuple = None

    def __getstate__(self):
        # the cached namedtuple type is created dynamically and cannot be
        # pickled (process-pool workers receive schemas by pickle); rebuild
        # it lazily on the other side
        state = self.__dict__.copy()
        state["_namedtuple"] = None
        return state

    # -- basic access ---------------------------------------------------------

    @property
    def name(self) -> str:
        """The schema's name (stored with the dataset; informational)."""
        return self._name

    @property
    def fields(self) -> "OrderedDict[str, Field]":
        """name -> Field mapping, in declaration order."""
        return self._fields

    def __iter__(self):
        return iter(self._fields.values())

    def __len__(self):
        return len(self._fields)

    def __contains__(self, name: str) -> bool:
        return name in self._fields

    def __getattr__(self, name: str) -> Field:
        # attribute sugar: schema.field_name (reference: unischema.py:179-197)
        fields = object.__getattribute__(self, "_fields")
        if name in fields:
            return fields[name]
        raise AttributeError(f"Schema {self._name!r} has no field {name!r}")

    def __getitem__(self, name: str) -> Field:
        return self._fields[name]

    def __eq__(self, other):
        return isinstance(other, Schema) and list(self) == list(other)

    def __repr__(self):
        lines = ",\n  ".join(
            f"Field({f.name!r}, {f.dtype.name}, {f.shape}, {f.codec!r}, nullable={f.nullable})"
            for f in self)
        return f"Schema({self._name!r}, [\n  {lines}\n])"

    # -- views ----------------------------------------------------------------

    def view(self, selectors: Iterable[_SelectorT]) -> "Schema":
        """Sub-schema by field instances, exact names, or regex patterns.

        Regexes use fullmatch semantics as in the reference (unischema.py:434-461);
        an unmatched selector raises (unischema.py:199-240 behavior).
        """
        selected = self.resolve_fields(selectors)
        return Schema(self._name, [f for f in self if f.name in selected])

    def resolve_fields(self, selectors: Iterable[_SelectorT]) -> List[str]:
        """Expand name/regex/Field selectors into concrete field names, in schema order (reference unischema field-selection semantics)."""
        selected: "OrderedDict[str, None]" = OrderedDict()
        for sel in selectors:
            if isinstance(sel, Field):
                if sel.name not in self._fields or self._fields[sel.name] != sel:
                    raise SchemaError(f"Field {sel.name!r} is not part of schema {self._name!r}")
                selected[sel.name] = None
                continue
            if isinstance(sel, str) and sel in self._fields:
                # exact name wins over regex interpretation, so metachar names
                # ('a+b') stay selectable and 'a.b' doesn't over-match 'axb'
                selected[sel] = None
                continue
            pattern = sel.pattern if isinstance(sel, re.Pattern) else sel
            matches = [n for n in self._fields if re.fullmatch(pattern, n)]
            if not matches:
                raise SchemaError(
                    f"Selector {pattern!r} matched no field of schema {self._name!r};"
                    f" fields: {list(self._fields)}")
            for n in matches:
                selected[n] = None
        return list(selected)

    # -- namedtuple emission --------------------------------------------------

    def make_namedtuple_type(self):
        """Cached namedtuple type for this schema's field set.

        Cached per instance so dataset element types compare equal across batches
        (reference caches per (schema, fieldset): unischema.py:88-111).  Python 3.7+
        has no 255-field limit, so the reference's >255-field workaround
        (namedtuple_gt_255_fields.py) is unnecessary.
        """
        if self._namedtuple is None:
            self._namedtuple = namedtuple(f"{self._name}_view", list(self._fields))
        return self._namedtuple

    def make_namedtuple(self, **kwargs):
        """One row as this schema's namedtuple (fields passed by keyword)."""
        missing = set(self._fields) - set(kwargs)
        if missing:
            raise SchemaError(f"Missing fields {sorted(missing)} building row of {self._name!r}")
        return self.make_namedtuple_type()(**{k: kwargs[k] for k in self._fields})

    # -- serialization --------------------------------------------------------

    def to_json(self) -> str:
        """Schema as the JSON document stored under the parquet KV key (never pickle - stable across class renames); inverted by ``from_json``."""
        return json.dumps({
            "version": 1,
            "name": self._name,
            "fields": [f.to_json() for f in self],
        })

    @classmethod
    def from_json(cls, payload: Union[str, bytes]) -> "Schema":
        obj = json.loads(payload)
        if obj.get("version") != 1:
            raise SchemaError(f"Unsupported schema version {obj.get('version')!r}")
        return cls(obj["name"], [Field.from_json(f) for f in obj["fields"]])

    # -- arrow interop --------------------------------------------------------

    def as_arrow_schema(self) -> pa.Schema:
        """Arrow *storage* schema (codec storage types, not logical types)."""
        return pa.schema([
            pa.field(f.name, f.codec.storage_type(f), nullable=f.nullable) for f in self
        ])

    @classmethod
    def from_arrow_schema(cls, arrow_schema: pa.Schema, name: str = "inferred",
                          partition_columns: Sequence[str] = ()) -> "Schema":
        """Infer a Schema from plain Parquet (non-petastorm) storage.

        Mirrors reference inference incl. partition columns (unischema.py:302-353):
        scalar columns -> ScalarCodec fields; list-of-scalar columns -> 1-D variable
        fields; nested types are rejected.
        """
        fields = []
        for af in arrow_schema:
            atype = af.type
            if dtypes.is_list_of_scalars(atype):
                fields.append(Field(af.name, dtypes.arrow_to_numpy(atype.value_type),
                                    shape=(None,), codec=ScalarListCodec(),
                                    nullable=af.nullable))
            elif pa.types.is_nested(atype):
                raise SchemaError(
                    f"Column {af.name!r}: nested arrow type {atype} is not supported;"
                    " select it out with schema_fields")
            else:
                fields.append(Field(af.name, dtypes.arrow_to_numpy(atype), shape=(),
                                    codec=ScalarCodec(), nullable=af.nullable))
        for pcol in partition_columns:
            if pcol not in {f.name for f in fields}:
                fields.append(Field(pcol, np.dtype("object"), shape=(), codec=ScalarCodec(),
                                    nullable=False))
        return cls(name, fields)

    # -- write-side row encoding ---------------------------------------------

    def encode_row(self, row: Dict[str, Any]) -> Dict[str, Any]:
        """Validate + codec-encode one row dict for pyarrow ingestion.

        Reference: ``dict_to_spark_row`` (unischema.py:356-403) including explicit
        null insertion for missing nullable fields (unischema.py:406-421).
        """
        if not isinstance(row, dict):
            raise SchemaError(f"encode_row expects a dict, got {type(row)}")
        unknown = set(row) - set(self._fields)
        if unknown:
            raise SchemaError(f"Unknown fields {sorted(unknown)} for schema {self._name!r}")
        out = {}
        for f in self:
            value = row.get(f.name)
            if value is None:
                if not f.nullable:
                    raise SchemaError(f"Field {f.name!r} is not nullable but got None")
                out[f.name] = None
            else:
                out[f.name] = f.codec.encode(f, value)
        return out


def insert_explicit_nulls(schema: Schema, row: Dict[str, Any]) -> Dict[str, Any]:
    """Add explicit None for missing nullable fields (reference: unischema.py:406-421)."""
    out = dict(row)
    for f in schema:
        if f.name not in out:
            if not f.nullable:
                raise SchemaError(f"Field {f.name!r} missing and not nullable")
            out[f.name] = None
    return out
