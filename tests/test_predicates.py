"""Predicate combinator unit tests.

Reference analog: petastorm/tests/test_predicates.py (combinators at
petastorm/predicates.py:44-182).  End-to-end predicate behavior (pushdown,
split-read) lives in tests/test_end_to_end.py; this file covers each
combinator's vectorized mask and per-row fallback in isolation.
"""

import numpy as np
import pytest

from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.predicates import (in_intersection, in_lambda, in_negate,
                                      in_pseudorandom_split, in_reduce, in_set)

COLS = {
    "a": np.array([1, 2, 3, 4, 5]),
    "b": np.array([2, 2, 9, 4, 9]),
    "name": np.array(["x", "y", "x", "z", "y"], dtype=object),
}


def test_in_set_mask_and_row():
    p = in_set({2, 4}, "a")
    assert p.get_fields() == ["a"]
    assert p.do_include_vectorized(COLS).tolist() == [False, True, False, True, False]
    assert p.do_include({"a": 4}) and not p.do_include({"a": 3})


def test_in_set_strings():
    p = in_set({"x"}, "name")
    assert p.do_include_vectorized(COLS).tolist() == [True, False, True, False, False]


def test_in_intersection():
    p = in_intersection({2, 4}, ["a", "b"])
    assert sorted(p.get_fields()) == ["a", "b"]
    # both a AND b must be in {2, 4}
    assert p.do_include_vectorized(COLS).tolist() == [False, True, False, True, False]


def test_in_negate():
    p = in_negate(in_set({2, 4}, "a"))
    assert p.get_fields() == ["a"]
    assert p.do_include_vectorized(COLS).tolist() == [True, False, True, False, True]
    assert p.do_include({"a": 3})


def test_in_reduce_all_any_custom():
    evens = in_lambda(["a"], lambda c: c["a"] % 2 == 0, vectorized=True)
    small = in_lambda(["a"], lambda c: c["a"] < 4, vectorized=True)
    assert in_reduce([evens, small], np.all).do_include_vectorized(
        COLS).tolist() == [False, True, False, False, False]
    assert in_reduce([evens, small], np.any).do_include_vectorized(
        COLS).tolist() == [True, True, True, True, False]
    # custom reduce: exactly-one-of
    xor = in_reduce([evens, small], lambda m, axis: np.sum(m, axis=axis) == 1)
    assert xor.do_include_vectorized(COLS).tolist() == [True, False, True, True, False]
    # field union is deduplicated, order-preserving
    assert in_reduce([evens, small]).get_fields() == ["a"]


def test_in_lambda_row_and_state():
    seen = []
    p = in_lambda(["a"], lambda row, state: state.append(row["a"]) or row["a"] > 2,
                  state=seen)
    assert p.do_include_vectorized(COLS).tolist() == [False, False, True, True, True]
    assert seen == [1, 2, 3, 4, 5]  # state threaded through (reference contract)


def test_in_pseudorandom_split_properties():
    names = np.array([f"sample_{i}" for i in range(2000)], dtype=object)
    fractions = [0.5, 0.3, 0.2]
    masks = [in_pseudorandom_split(fractions, i, "k").do_include_vectorized(
        {"k": names}) for i in range(3)]
    total = np.stack(masks).sum(axis=0)
    assert (total == 1).all()  # partition: every row in exactly one subset
    sizes = [m.mean() for m in masks]
    for got, want in zip(sizes, fractions):
        assert abs(got - want) < 0.05, (got, want)
    # deterministic across instances
    again = in_pseudorandom_split(fractions, 0, "k").do_include_vectorized(
        {"k": names})
    assert (again == masks[0]).all()


def test_in_pseudorandom_split_validation():
    with pytest.raises(PetastormTpuError, match="out of range"):
        in_pseudorandom_split([0.5, 0.5], 2, "k")
    with pytest.raises(PetastormTpuError, match="sum"):
        in_pseudorandom_split([0.9, 0.9], 0, "k")


def test_row_fallback_matches_vectorized():
    preds = [in_set({2, 4}, "a"),
             in_intersection({2, 4}, ["a", "b"]),
             in_negate(in_set({2}, "a")),
             in_reduce([in_set({2, 4}, "a"), in_set({2, 4}, "b")])]
    for p in preds:
        vec = p.do_include_vectorized(COLS)
        rows = [p.do_include({k: COLS[k][i] for k in p.get_fields()})
                for i in range(5)]
        assert vec.tolist() == rows, type(p).__name__
