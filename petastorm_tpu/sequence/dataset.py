"""Tokenized-text dataset surface: variable-length token columns as a
first-class reader workload.

A token corpus here is an ordinary petastorm_tpu parquet dataset whose
document column is a **variable-length 1-D list field**
(:func:`token_field`: arrow ``list<int>`` storage via
:class:`~petastorm_tpu.codecs.ScalarListCodec` - no per-cell npy framing,
readable by any arrow tool).  Everything the image pipeline built - the
deterministic plan, executors, the warm tier, the service hop, the chaos
matrix - applies unchanged; this module adds the token-aware entry points:

* :func:`make_sequence_reader` - a validated ``make_batch_reader`` over a
  token corpus.  Predicates push down into the worker's split-read exactly
  as for images: predicate columns decode first and the surviving-row mask
  filters the arrow table *before* the token column decodes, so filtered
  documents never cost decode or transform (the ``sequence.rows_filtered``
  counter vs ``worker.rows_decoded`` is the observable proof).
* :func:`iter_documents` - the delivered batch stream flattened to one
  document (1-D token array) at a time, in delivered order - the input the
  packer (:mod:`petastorm_tpu.sequence.packing`) consumes.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from petastorm_tpu.codecs import ScalarListCodec
from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.schema import Field


def token_field(name: str = "tokens", dtype=np.int32,
                nullable: bool = False) -> Field:
    """A variable-length token-sequence field: 1-D ``dtype`` tokens stored
    as an arrow list column (:class:`ScalarListCodec` - no binary framing,
    so plain-parquet tools read the corpus too)."""
    return Field(name, np.dtype(dtype), shape=(None,),
                 codec=ScalarListCodec(), nullable=nullable)


def is_sequence_field(field: Field) -> bool:
    """True when ``field`` is a variable-length 1-D sequence column (the
    shape a token document has) - either declared via :func:`token_field`
    (ScalarListCodec) or an inferred plain-parquet list column."""
    return (isinstance(field.codec, ScalarListCodec)
            or (len(field.shape) == 1 and field.shape[0] is None))


def make_sequence_reader(dataset_url, tokens_field: str = "tokens",
                         **reader_kwargs):
    """A columnar reader over a token corpus, validated for sequence use.

    Thin wrapper over :func:`petastorm_tpu.reader.make_batch_reader` that
    checks ``tokens_field`` exists and is a variable-length sequence column
    (see :func:`token_field`) - catching the classic mistakes (typo'd field
    name, fixed-shape column, image field) at construction instead of as a
    packer shape error mid-epoch.  All ``make_batch_reader`` knobs pass
    through: seeded shuffles, ``deterministic='seed'`` delivery, predicates
    (worker-side pushdown - dropped documents never decode), the warm
    cache, and ``service_address``.

    Returns the reader; consume via :func:`iter_documents` + the packer, or
    :class:`petastorm_tpu.sequence.loader.PackedSequenceReader` for the jax
    delivery path.
    """
    from petastorm_tpu.reader import make_batch_reader

    reader = make_batch_reader(dataset_url, **reader_kwargs)
    try:
        schema = reader.schema
        if tokens_field not in schema:
            raise PetastormTpuError(
                f"tokens_field {tokens_field!r} is not in the dataset schema"
                f" {[f.name for f in schema]} (or was excluded by"
                " schema_fields)")
        field = schema[tokens_field]
        if not is_sequence_field(field):
            raise PetastormTpuError(
                f"tokens_field {tokens_field!r} is not a variable-length"
                f" sequence column (shape {field.shape}, codec"
                f" {field.codec!r}); declare it with"
                " petastorm_tpu.sequence.token_field(...) or point"
                " tokens_field at the list column")
    except BaseException:
        reader.stop()
        reader.join()
        raise
    return reader


def iter_documents(reader, tokens_field: str = "tokens",
                   tokens_dtype=np.int32,
                   max_documents: Optional[int] = None
                   ) -> Iterator[np.ndarray]:
    """Flatten a reader's delivered batches into one document at a time.

    Yields 1-D ``tokens_dtype`` arrays in delivered order (plan order under
    ``deterministic='seed'``) - the stream the packer consumes.  Handles
    both wire forms of a variable-length column: the uniform-length 2-D
    fast path and the ragged object-array path.  ``None`` cells (nullable
    fields) are skipped.  ``max_documents`` bounds the iteration (the
    reader is left running; stop it via its context manager).
    """
    tokens_dtype = np.dtype(tokens_dtype)
    n = 0
    for batch in reader.iter_batches():
        col = batch.columns[tokens_field]
        if getattr(col, "dtype", None) is not None and col.dtype != object:
            rows = np.asarray(col).astype(tokens_dtype, copy=False)
            for i in range(len(rows)):
                yield rows[i]
                n += 1
                if max_documents is not None and n >= max_documents:
                    return
        else:
            for cell in col:
                if cell is None:
                    continue
                yield np.asarray(cell).ravel().astype(tokens_dtype,
                                                      copy=False)
                n += 1
                if max_documents is not None and n >= max_documents:
                    return
