"""Small MLP (mirrors the reference's examples/mnist consumer)."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    features: Sequence[int] = (128, 64)
    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(jnp.float32)
        for f in self.features:
            x = nn.relu(nn.Dense(f)(x))
        return nn.Dense(self.num_classes)(x)
