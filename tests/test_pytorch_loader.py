"""Torch delivery layer tests (reference: tests/test_pytorch_dataloader.py)."""

import decimal

import numpy as np
import pytest
import torch

from petastorm_tpu.codecs import NdarrayCodec
from petastorm_tpu.etl.writer import write_dataset
from petastorm_tpu.pytorch import (BatchedDataLoader, DataLoader,
                                   decimal_friendly_collate)
from petastorm_tpu.reader import make_reader
from petastorm_tpu.schema import Field, Schema

NUM_ROWS = 40


@pytest.fixture(scope="module")
def torch_dataset(tmp_path_factory):
    url = str(tmp_path_factory.mktemp("torch_ds") / "ds")
    schema = Schema("TorchSchema", [
        Field("id", np.int64),
        Field("val_u16", np.uint16),
        Field("val_u32", np.uint32),
        Field("vec", np.float32, (3,), NdarrayCodec()),
    ])
    rows = [{"id": i, "val_u16": i * 2, "val_u32": i * 3,
             "vec": np.full(3, i, np.float32)} for i in range(NUM_ROWS)]
    write_dataset(url, schema, rows, row_group_size_rows=8)
    return url


def _collect(loader):
    batches = list(loader)
    ids = torch.cat([b["id"] for b in batches]).tolist()
    return batches, ids


def test_round_trip_values_and_batching(torch_dataset):
    with make_reader(torch_dataset, shuffle_row_groups=False,
                     reader_pool_type="serial", num_epochs=1) as r:
        with DataLoader(r, batch_size=8) as loader:
            batches, ids = _collect(loader)
    assert ids == list(range(NUM_ROWS))
    assert all(b["id"].shape[0] == 8 for b in batches)
    first = batches[0]
    assert first["vec"].shape == (8, 3)
    assert torch.equal(first["vec"][3], torch.full((3,), 3.0))


def test_dtype_promotions(torch_dataset):
    with make_reader(torch_dataset, num_epochs=1) as r:
        with DataLoader(r, batch_size=4) as loader:
            batch = next(iter(loader))
    assert batch["val_u16"].dtype == torch.int32
    assert batch["val_u32"].dtype == torch.int64


def test_partial_final_batch(torch_dataset):
    with make_reader(torch_dataset, shuffle_row_groups=False,
                     reader_pool_type="serial", num_epochs=1) as r:
        with DataLoader(r, batch_size=7) as loader:
            batches, ids = _collect(loader)
    assert sorted(ids) == list(range(NUM_ROWS))
    assert [len(b["id"]) for b in batches] == [7, 7, 7, 7, 7, 5]


def test_shuffling_changes_order_and_is_seeded(torch_dataset):
    def read(seed):
        with make_reader(torch_dataset, shuffle_row_groups=False,
                         reader_pool_type="serial", num_epochs=1) as r:
            with DataLoader(r, batch_size=8, shuffling_queue_capacity=20,
                            seed=seed) as loader:
                return _collect(loader)[1]

    a, b, c = read(7), read(7), read(8)
    assert sorted(a) == list(range(NUM_ROWS))
    assert a != list(range(NUM_ROWS))
    assert a == b
    assert a != c


def test_batched_loader_transform_fn(torch_dataset):
    with make_reader(torch_dataset, num_epochs=1) as r:
        with BatchedDataLoader(
                r, batch_size=8,
                transform_fn=lambda b: {"id_f": b["id"].float() * 2}) as loader:
            batch = next(iter(loader))
    assert batch["id_f"].dtype == torch.float32


def test_error_latch_and_reiteration_guard(torch_dataset):
    with make_reader(torch_dataset, num_epochs=1) as r:
        loader = DataLoader(r, batch_size=4,
                            collate_fn=lambda b: 1 / 0)  # raises in emit
        with pytest.raises(ZeroDivisionError):
            next(iter(loader))
        with pytest.raises(RuntimeError, match="previous iteration failed"):
            iter(loader).__next__()
        r.stop(), r.join()


def test_string_fields_rejected(tmp_path):
    url = str(tmp_path / "str_ds")
    schema = Schema("S", [Field("id", np.int64),
                          Field("name", np.dtype("object"))])
    write_dataset(url, schema,
                  [{"id": i, "name": f"n{i}"} for i in range(10)],
                  row_group_size_rows=5)
    with make_reader(url, num_epochs=1) as r:
        with DataLoader(r, batch_size=2) as loader:
            with pytest.raises(TypeError, match="string"):
                next(iter(loader))


def test_variable_shape_becomes_list(tmp_path):
    url = str(tmp_path / "var_ds")
    schema = Schema("V", [Field("id", np.int64),
                          Field("pts", np.float32, (None, 2), NdarrayCodec())])
    rows = [{"id": i, "pts": np.ones((i + 1, 2), np.float32)}
            for i in range(6)]
    write_dataset(url, schema, rows, row_group_size_rows=3)
    with make_reader(url, shuffle_row_groups=False,
                     reader_pool_type="serial", num_epochs=1) as r:
        with DataLoader(r, batch_size=3) as loader:
            batch = next(iter(loader))
    assert isinstance(batch["pts"], list)
    assert batch["pts"][2].shape == (3, 2)


@pytest.fixture(scope="module")
def ngram_dataset(tmp_path_factory):
    url = str(tmp_path_factory.mktemp("torch_ngram") / "ds")
    schema = Schema("Seq", [
        Field("ts", np.int64),
        Field("cam", np.float32, (4, 4), NdarrayCodec()),
        Field("label", np.int64),
    ])
    rows = [{"ts": i, "cam": np.full((4, 4), i, np.float32), "label": i % 3}
            for i in range(32)]
    write_dataset(url, schema, rows, row_group_size_rows=16)
    return url


def test_ngram_loader_yields_per_offset_tensor_dicts(ngram_dataset):
    """Reference parity: DataLoader collates ngram window dicts into
    {offset: {field: tensor}} batches (pytorch.py:130-254, collate :72-94)."""
    from petastorm_tpu.ngram import NGram

    ng = NGram({0: ["ts", "cam"], 1: ["ts", "cam", "label"]},
               delta_threshold=1, timestamp_field="ts")
    with make_reader(ngram_dataset, ngram=ng, shuffle_row_groups=False,
                     reader_pool_type="serial", num_epochs=1) as r:
        with DataLoader(r, batch_size=5) as loader:
            batches = list(loader)
    first = batches[0]
    assert set(first) == {0, 1}
    assert set(first[0]) == {"ts", "cam"}
    assert set(first[1]) == {"ts", "cam", "label"}
    assert first[0]["cam"].shape == (5, 4, 4)
    # offset-1 rows are exactly offset-0's successors, per window
    assert torch.equal(first[1]["ts"], first[0]["ts"] + 1)
    assert torch.equal(first[1]["cam"][0],
                       torch.full((4, 4), float(first[1]["ts"][0])))
    # each rowgroup of 16 consecutive ts yields 15 windows -> 30 total
    total = sum(len(b[0]["ts"]) for b in batches)
    assert total == 30


def test_ngram_loader_shuffling_keeps_windows_intact(ngram_dataset):
    from petastorm_tpu.ngram import NGram

    ng = NGram({0: ["ts"], 1: ["ts"]}, delta_threshold=1, timestamp_field="ts")
    with make_reader(ngram_dataset, ngram=ng, shuffle_row_groups=False,
                     reader_pool_type="serial", num_epochs=1) as r:
        with DataLoader(r, batch_size=6, shuffling_queue_capacity=16,
                        seed=3) as loader:
            batches = list(loader)
    starts = torch.cat([b[0]["ts"] for b in batches])
    nexts = torch.cat([b[1]["ts"] for b in batches])
    assert torch.equal(nexts, starts + 1)          # windows never split
    assert sorted(starts.tolist()) == [*range(15), *range(16, 31)]
    assert starts.tolist() != sorted(starts.tolist())  # actually shuffled


def test_ngram_stacked_loader_keeps_flat_dict(ngram_dataset):
    from petastorm_tpu.ngram import NGram

    ng = NGram({0: ["ts", "cam"], 1: ["ts", "cam"]}, delta_threshold=1,
               timestamp_field="ts", stack_timesteps=True)
    with make_reader(ngram_dataset, ngram=ng, shuffle_row_groups=False,
                     reader_pool_type="serial", num_epochs=1) as r:
        with DataLoader(r, batch_size=5) as loader:
            batch = next(iter(loader))
    assert batch["cam"].shape == (5, 2, 4, 4)      # (batch, k, ...) stacked
    assert torch.equal(batch["ts"][:, 1], batch["ts"][:, 0] + 1)


def test_decimal_friendly_collate():
    rows = [{"d": decimal.Decimal("1.5"), "x": torch.tensor(1)},
            {"d": decimal.Decimal("2.5"), "x": torch.tensor(2)}]
    out = decimal_friendly_collate(rows)
    assert torch.equal(out["d"], torch.tensor([1.5, 2.5], dtype=torch.float64))
    assert out["x"].tolist() == [1, 2]
