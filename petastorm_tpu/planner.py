"""Plan-aware static pipeline planner: seed the tuning knobs before data flows.

The autotune loop (petastorm_tpu.autotune) is runtime hill-climbing: it starts
from static defaults and discovers the host's optimum one knob-move at a time,
which costs every COLD start a climb through the bad region.  tf.data's
AUTOTUNE (PAPERS.md, arXiv:2101.12127) pairs its runtime loop with a static
analysis pass over the declared pipeline; MinatoLoader (arXiv:2509.10712)
carries learned preprocessing schedules across runs.  This module is that
static pass for this pipeline:

* **Metadata pass** - one parquet footer read (:func:`footer_stats`) yields
  rowgroup byte sizes, per-column compressed/uncompressed spans and the
  compression codec for the fields actually read.  From the decode expansion
  ratio and rowgroup geometry the planner picks initial ``workers``,
  ``decode_threads``, ``results_queue``, ``prefetch`` and (for the shared
  warm tier) a ``cache_mem`` residency target that fits the estimated
  decoded dataset.
* **Flight profiles** - at reader stop, an autotuned reader persists its
  CONVERGED knob values plus the observed delivered rate as a small JSON
  profile beside the cache location (:class:`ProfileStore`; atomic
  tmp+rename writes).  Profiles are keyed by dataset fingerprint + schema
  hash, so a rewritten dataset or changed field selection never replays
  stale knobs; a corrupt or mismatched profile is tolerated with a warning
  and the planner falls back to the metadata pass.

``make_reader(autotune=True)`` (or ``workers_count='auto'``) runs the planner
automatically and STARTS from its :class:`PlanVerdict` - the runtime loop
then only fine-tunes.  Every knob carries provenance (``profile`` /
``metadata`` / ``default`` / ``pinned``) surfaced in
``Reader.diagnostics['planner']`` and the ``planner:`` line of
``petastorm-tpu-diagnose --watch``.  ``AutotunePolicy(planner=False)``
disables the pass (docs/operations.md "Transform caching & the pipeline
planner").
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import tempfile
from typing import Dict, List, Optional, Sequence

logger = logging.getLogger(__name__)

#: profile schema version; a bump invalidates every persisted profile
PROFILE_VERSION = 1
#: subdirectory of the cache location holding the per-dataset profiles
PROFILE_DIRNAME = "profiles"
#: best-effort cap on persisted profiles per store (oldest swept first)
MAX_PROFILES = 64

#: knob provenance values, in trust order
SOURCES = ("pinned", "profile", "metadata", "default")


def default_profile_location() -> str:
    """Where profiles land when no ``cache_location`` is configured (the
    same host-wide default namespace the shared warm tier uses)."""
    from petastorm_tpu.cache_shared import DEFAULT_LOCATION

    return DEFAULT_LOCATION


def dataset_fingerprint(info) -> str:
    """Content fingerprint of a dataset: root/url + file count + rowgroup
    count + total rows + (size, mtime) of the first and last data files.
    A dataset rewritten in place (or grown/shrunk) changes the fingerprint,
    so a profile recorded against the old bytes is simply never found -
    stale knobs cannot replay.  Best-effort on filesystems that cannot
    stat (the fingerprint then keys on structure alone)."""
    digest = hashlib.md5()
    digest.update(str(getattr(info, "url", "")).encode())
    files = sorted({rg.path for rg in info.row_groups})
    total_rows = sum(rg.num_rows for rg in info.row_groups)
    digest.update(f"|files:{len(files)}|rowgroups:{len(info.row_groups)}"
                  f"|rows:{total_rows}".encode())
    for path in files[:1] + files[-1:]:
        try:
            st = info.filesystem.get_file_info(path)
            digest.update(f"|{path}:{st.size}:{st.mtime_ns}".encode())
        except Exception:  # noqa: BLE001 - fingerprint is best-effort
            digest.update(f"|{path}:?".encode())
    return digest.hexdigest()


def schema_hash(read_fields: Sequence[str], transform_signature: str) -> str:
    """Hash of what the pipeline READS + the transform applied to it: a
    changed field selection or edited transform keys a different profile
    (its converged knobs tuned a different workload)."""
    digest = hashlib.md5()
    digest.update(",".join(read_fields).encode())
    digest.update(f"|tf:{transform_signature}".encode())
    return digest.hexdigest()[:16]


@dataclasses.dataclass
class PlannedKnob:
    """One planned knob value plus where it came from and why."""

    value: int
    #: 'pinned' (user set it explicitly - the planner never overrides),
    #: 'profile' (recorded flight history), 'metadata' (parquet footer
    #: heuristics), or 'default' (the static fallback)
    source: str
    why: str

    def to_dict(self) -> dict:
        """JSON-serializable knob entry (value/source/why)."""
        return dataclasses.asdict(self)


@dataclasses.dataclass
class PlanVerdict:
    """The static pass's output: knob -> :class:`PlannedKnob`, plus the
    inputs that produced it (footer summary, profile provenance) - latched
    into ``Reader.diagnostics['planner']``."""

    knobs: Dict[str, PlannedKnob]
    fingerprint: str
    schema_hash: str
    metadata: dict
    profile: Optional[dict] = None
    profile_path: Optional[str] = None
    #: the store to persist this run's converged knobs into at reader stop
    store: Optional["ProfileStore"] = None

    def to_dict(self) -> dict:
        """JSON-serializable verdict (diagnostics / --json output)."""
        return {
            "knobs": {name: knob.to_dict()
                      for name, knob in sorted(self.knobs.items())},
            "fingerprint": self.fingerprint,
            "schema_hash": self.schema_hash,
            "metadata": self.metadata,
            "profile": ({"written_at": self.profile.get("written_at"),
                         "observed_rows_per_sec":
                             self.profile.get("observed_rows_per_sec"),
                         "knobs": self.profile.get("knobs")}
                        if self.profile else None),
            "profile_path": self.profile_path,
        }


class ProfileStore:
    """Per-dataset flight-profile persistence beside the cache location.

    One small JSON file per (dataset fingerprint, schema hash); writes are
    atomic (temp file + rename - a reader crashing mid-write can never leave
    a half profile), loads tolerate corrupt/mismatched files with a warning
    (the planner then falls back to the metadata pass), and the store sweeps
    itself to :data:`MAX_PROFILES` entries by mtime.
    """

    def __init__(self, location: Optional[str] = None):
        self._dir = os.path.join(
            os.path.abspath(location or default_profile_location()),
            PROFILE_DIRNAME)

    @property
    def directory(self) -> str:
        """The profile directory (``<cache_location>/profiles``)."""
        return self._dir

    def path_for(self, fingerprint: str, schema_hash_: str) -> str:
        """Filename for one (dataset, read-shape) profile."""
        return os.path.join(
            self._dir, f"profile-{fingerprint[:16]}-{schema_hash_[:8]}.json")

    def load(self, fingerprint: str, schema_hash_: str) -> Optional[dict]:
        """The recorded profile, or None (missing / corrupt / stale -
        never raises; a bad profile must not fail reader construction)."""
        path = self.path_for(fingerprint, schema_hash_)
        try:
            with open(path) as f:
                profile = json.load(f)
        except FileNotFoundError:
            return None
        except Exception as exc:  # noqa: BLE001 - corrupt file tolerated
            logger.warning(
                "ignoring corrupt pipeline profile %s (%s); planning from"
                " parquet metadata only", path, exc)
            return None
        if (not isinstance(profile, dict)
                or profile.get("version") != PROFILE_VERSION
                or profile.get("fingerprint") != fingerprint
                or profile.get("schema_hash") != schema_hash_
                or not isinstance(profile.get("knobs"), dict)):
            logger.warning(
                "ignoring stale/mismatched pipeline profile %s (version/"
                "fingerprint/schema mismatch); planning from parquet"
                " metadata only", path)
            return None
        return profile

    def save(self, fingerprint: str, schema_hash_: str,
             payload: dict) -> Optional[str]:
        """Atomically persist ``payload``; returns the path (None on
        failure - persistence is an optimization, never an error)."""
        payload = dict(payload, version=PROFILE_VERSION,
                       fingerprint=fingerprint, schema_hash=schema_hash_)
        path = self.path_for(fingerprint, schema_hash_)
        try:
            os.makedirs(self._dir, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=self._dir, suffix=".tmp")
            try:
                with os.fdopen(fd, "w") as f:
                    json.dump(payload, f, sort_keys=True)
                os.replace(tmp, path)  # atomic publish: all or nothing
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise
            self._sweep()
            return path
        except Exception:  # noqa: BLE001 - best-effort persistence
            logger.warning("pipeline profile write failed for %s", path,
                           exc_info=True)
            return None

    def _sweep(self) -> None:
        """Bound the store: drop oldest profiles past :data:`MAX_PROFILES`
        and any crashed-writer ``.tmp`` orphans."""
        try:
            entries = []
            for name in os.listdir(self._dir):
                p = os.path.join(self._dir, name)
                try:
                    mtime = os.stat(p).st_mtime
                except OSError:
                    continue
                if name.endswith(".tmp"):
                    import time as _time

                    if _time.time() - mtime > 300:
                        try:
                            os.remove(p)
                        except OSError:
                            pass
                    continue
                entries.append((mtime, p))
            entries.sort()
            for _mtime, p in entries[:max(0, len(entries) - MAX_PROFILES)]:
                try:
                    os.remove(p)
                except OSError:
                    pass
        except OSError:
            pass


def footer_stats(info, read_fields: Sequence[str],
                 max_rowgroups: int = 32) -> dict:
    """Summarize one parquet footer (the first data file) for the fields
    actually read: per-rowgroup compressed/uncompressed byte spans, the
    decode expansion ratio, and per-column compression codecs.  One ranged
    footer read - cheap enough for every reader construction; any failure
    returns ``{}`` and the planner falls back to defaults."""
    import pyarrow.parquet as pq

    files = sorted({rg.path for rg in info.row_groups})
    if not files:
        return {}
    try:
        with info.filesystem.open_input_file(files[0]) as f:
            md = pq.ParquetFile(f).metadata
        tops = {str(field).split(".", 1)[0] for field in read_fields}
        comp_sum = unc_sum = 0
        columns: Dict[str, dict] = {}
        n = min(md.num_row_groups, max_rowgroups)
        for i in range(n):
            rg = md.row_group(i)
            for j in range(rg.num_columns):
                col = rg.column(j)
                top = col.path_in_schema.split(".", 1)[0]
                if tops and top not in tops:
                    continue
                comp_sum += col.total_compressed_size
                unc_sum += col.total_uncompressed_size
                entry = columns.setdefault(
                    top, {"compressed": 0, "uncompressed": 0,
                          "compression": str(col.compression)})
                entry["compressed"] += col.total_compressed_size
                entry["uncompressed"] += col.total_uncompressed_size
        if n == 0:
            return {}
        total_rowgroups = len(info.row_groups)
        return {
            "file": files[0],
            "files": len(files),
            "rowgroups_sampled": n,
            "rowgroups_total": total_rowgroups,
            "rows_total": sum(rg.num_rows for rg in info.row_groups),
            "avg_rowgroup_compressed_bytes": comp_sum // n,
            "avg_rowgroup_uncompressed_bytes": unc_sum // n,
            "expansion": (unc_sum / comp_sum) if comp_sum else 1.0,
            "est_dataset_uncompressed_bytes":
                (unc_sum // n) * total_rowgroups,
            "columns": columns,
        }
    except Exception as exc:  # noqa: BLE001 - metadata pass is best-effort
        logger.warning("planner footer read failed (%s); planning from"
                       " defaults", exc)
        return {}


def _clamp(value: int, lo: int, hi: int) -> int:
    return max(lo, min(hi, int(value)))


def plan_reader(info, read_fields: Sequence[str], *, policy, cores: int,
                cache_type: str = "null",
                cache_location: Optional[str] = None,
                transform_signature: str = "-",
                split_fields: Sequence[str] = (),
                workers_count="auto",
                decode_threads="auto",
                results_queue_size: int = 10,
                results_queue_pinned: bool = False,
                image_fields: Sequence[str] = ()) -> PlanVerdict:
    """Run the static pass over a declared pipeline; returns the
    :class:`PlanVerdict` ``make_reader(autotune=True)`` starts from.

    Knob resolution order per knob: an explicitly pinned user value wins
    (provenance ``pinned``, never overridden); else the recorded flight
    profile (``profile``), clamped into the policy's bounds; else the
    parquet-footer heuristics (``metadata``); else the static default.
    ``transform_signature`` is the precomputed
    :func:`petastorm_tpu.transform.transform_signature` string (the caller
    already ran the analysis walk once - it must not repeat here).
    """
    fp = dataset_fingerprint(info)
    sh = schema_hash(read_fields, transform_signature)
    store = ProfileStore(cache_location)
    profile = store.load(fp, sh)
    meta = footer_stats(info, read_fields)
    pk = (profile or {}).get("knobs", {})
    knobs: Dict[str, PlannedKnob] = {}

    def from_profile(name: str, lo: int, hi: int) -> Optional[PlannedKnob]:
        value = pk.get(name)
        if not isinstance(value, (int, float)):
            return None
        return PlannedKnob(_clamp(int(value), lo, hi), "profile",
                           f"recorded flight profile (converged at {value})")

    # -- workers ---------------------------------------------------------------
    static_default = max(1, min(10, cores - 1))
    if workers_count != "auto":
        knobs["workers"] = PlannedKnob(int(workers_count), "pinned",
                                       "explicit workers_count")
    else:
        planned = from_profile("workers", policy.min_workers,
                               policy.max_workers)
        if planned is None and meta:
            expansion = meta["expansion"]
            avg_unc = meta["avg_rowgroup_uncompressed_bytes"]
            if expansion >= 1.8 or image_fields:
                planned = PlannedKnob(
                    _clamp(static_default, policy.min_workers,
                           policy.max_workers),
                    "metadata",
                    f"decode-heavy columns (expansion {expansion:.1f}x):"
                    " every spare core decodes")
            elif expansion < 1.3 and avg_unc < 4 * 2 ** 20:
                planned = PlannedKnob(
                    _clamp(2, policy.min_workers, policy.max_workers),
                    "metadata",
                    f"lightweight columnar rowgroups ({avg_unc >> 10}KB"
                    f" decoded, expansion {expansion:.1f}x): IO-bound, a"
                    " narrow pool avoids handoff overhead")
        if planned is None:
            planned = PlannedKnob(
                _clamp(static_default, policy.min_workers,
                       policy.max_workers),
                "default", "cores - 1, capped at 10 (the static seed)")
        knobs["workers"] = planned

    workers = knobs["workers"].value

    # -- decode_threads --------------------------------------------------------
    if decode_threads != "auto":
        knobs["decode_threads"] = PlannedKnob(int(decode_threads), "pinned",
                                              "explicit decode_threads")
    else:
        knobs["decode_threads"] = PlannedKnob(
            max(1, cores // max(1, workers)), knobs["workers"].source
            if knobs["workers"].source != "pinned" else "default",
            "usable cores / planned workers (intra-batch decode fan-out)")

    # -- results queue bound ---------------------------------------------------
    if results_queue_pinned:
        knobs["results_queue"] = PlannedKnob(int(results_queue_size),
                                             "pinned",
                                             "explicit results_queue_size")
    else:
        planned = from_profile("results_queue", policy.min_results_queue,
                               policy.max_results_queue)
        if planned is None and meta \
                and meta["avg_rowgroup_uncompressed_bytes"] > 0:
            # bound decoded-batch RAM held in the results plane to ~64MB
            # while never starving the pool (at least workers + 2 slots)
            per_batch = meta["avg_rowgroup_uncompressed_bytes"]
            planned = PlannedKnob(
                _clamp(max(workers + 2, (64 * 2 ** 20) // per_batch),
                       policy.min_results_queue, policy.max_results_queue),
                "metadata",
                f"~64MB of decoded batches at {per_batch / 2 ** 20:.1f}MB"
                "/rowgroup, floored at workers + 2")
        if planned is None:
            planned = PlannedKnob(int(results_queue_size), "default",
                                  "static default bound")
        knobs["results_queue"] = planned

    # -- loader prefetch -------------------------------------------------------
    planned = from_profile("prefetch", policy.min_prefetch,
                           policy.max_prefetch)
    if planned is None and meta \
            and meta["avg_rowgroup_uncompressed_bytes"] > 0:
        small = meta["avg_rowgroup_uncompressed_bytes"] < 2 * 2 ** 20
        planned = PlannedKnob(
            _clamp(4 if small else 2, policy.min_prefetch,
                   policy.max_prefetch),
            "metadata",
            "small rowgroups: deeper prefetch smooths assembly jitter"
            if small else "large rowgroups: shallow prefetch bounds RAM")
    if planned is None:
        planned = PlannedKnob(2, "default", "static default depth")
    knobs["prefetch"] = planned

    # -- shared warm tier residency target ------------------------------------
    if cache_type == "shared":
        planned = from_profile("cache_mem", 16, 1 << 20)
        if planned is None and meta \
                and meta.get("est_dataset_uncompressed_bytes", 0) > 0:
            est_mb = int(1.2 * meta["est_dataset_uncompressed_bytes"]) >> 20
            planned = PlannedKnob(
                max(16, est_mb), "metadata",
                f"fits the estimated decoded dataset (~{est_mb}MB) so warm"
                " epochs never evict; clamped to the arena by the tier")
        if planned is not None:
            knobs["cache_mem"] = planned

    # -- live decode split -----------------------------------------------------
    if split_fields:
        value = pk.get("decode_split")
        if value in (0, 1):
            knobs["decode_split"] = PlannedKnob(
                int(value), "profile",
                "recorded flight profile (converged split side)")

    return PlanVerdict(knobs=knobs, fingerprint=fp, schema_hash=sh,
                       metadata=meta, profile=profile,
                       profile_path=store.path_for(fp, sh), store=store)


def build_profile(reader) -> Optional[dict]:
    """Payload for :meth:`ProfileStore.save`, from a finished reader: the
    autotune controller's CONVERGED knob values, the decision count, and the
    delivered rate observed over the sampler's trailing points.  None when
    the run has nothing worth recording (nothing consumed, or no
    controller)."""
    controller = getattr(reader, "autotune", None)
    if controller is None or getattr(reader, "_consumed_items", 0) <= 0:
        return None
    knobs = {name: int(value) for name, value in controller.knobs().items()}
    observed = None
    sampler = getattr(reader, "sampler", None)
    if sampler is not None:
        try:
            # flush the trailing partial interval: a short run may not have
            # completed a single full sampling interval yet
            sampler.sample_now()
        except Exception:  # noqa: BLE001 - the profile is best-effort
            pass
        points = sampler.series()[-10:]
        total_dt = sum(pt.get("dt_s", 0.0) for pt in points)
        if total_dt > 0:
            observed = round(sum(
                pt.get("rates", {}).get("reader.rows_emitted", 0.0)
                * pt.get("dt_s", 0.0) for pt in points) / total_dt, 2)
    import time as _time

    return {"written_at": _time.time(),
            "knobs": knobs,
            "observed_rows_per_sec": observed,
            "decisions": len(controller.decisions),
            "moves_kept": int(controller.diagnostics["moves_kept"]),
            "source": "autotune"}


def write_profile(reader) -> Optional[str]:
    """Persist this reader's flight profile (called once from
    ``Reader.stop``); returns the written path or None."""
    verdict = getattr(reader, "planner", None)
    if verdict is None or verdict.store is None:
        return None
    payload = build_profile(reader)
    if payload is None:
        return None
    path = verdict.store.save(verdict.fingerprint, verdict.schema_hash,
                              payload)
    if path:
        logger.info("pipeline flight profile written to %s (knobs %s)",
                    path, payload["knobs"])
    return path
