"""Binary wire encoding for the ingest service: pickle-free, self-describing.

Two frame kinds cross every service socket (``protocol.FrameSocket`` adds
the 4-byte length prefix):

* **CTRL** (:data:`KIND_CTRL`): one control message - a dict encoded with
  the bounded tag-length-value codec below (:func:`dumps`/:func:`loads`).
  Hellos, heartbeats, acks, work assignment, failures, stats: everything
  that is not a result batch.
* **BATCH** (:data:`KIND_BATCH`): one result payload - a CTRL-encoded
  header (column names/dtypes/shapes/offsets, row count, ordinal/attempt,
  codec id) followed by the raw column buffers, in exactly the column-major
  packed form :mod:`petastorm_tpu.native.transport` uses for its shm blocks.
  Decoding builds numpy views over the received buffer - zero copies past
  the socket read - and **validates every spec against the actual buffer**
  (dtype sanity, shape/length agreement, bounds) before any array is built.

Security contract: decoding is **pure data** - no ``pickle``, no code
execution, no unbounded recursion/allocation.  Every malformed input path
raises :class:`WireFormatError` (a classified
:class:`~petastorm_tpu.errors.PetastormTpuError`), never desyncs the
stream, and never interprets attacker bytes as python objects.  Object
dtypes are refused outright (a ``dtype='O'`` buffer view would be an
unpickle in disguise).  The only remaining pickle on the service wire is
the client->worker job plane (worker factory + work-item blobs), which the
dispatcher relays as opaque bytes and only an auth-gated client's worker
ever unpickles - see the protocol module's trust-boundary notes.

Compression: BATCH bodies may be compressed end-to-end (worker encodes,
client decodes; the dispatcher relays either way).  The codec is negotiated
per (worker, client) pair at job time - ``'zlib'`` for cross-host hops,
off for co-located pairs - see :func:`negotiate_codec`.
"""

from __future__ import annotations

import struct
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from petastorm_tpu.errors import PetastormTpuError

#: wire-format generation, carried in every hello (bumped on incompatible
#: change; peers with a different value are refused loudly at hello time)
WIRE_VERSION = 2

#: frame-kind bytes (first payload byte after the length prefix)
KIND_CTRL = 0x01
KIND_BATCH = 0x02
#: pickle protocol >= 2 opcode: a frame starting with this byte is a legacy
#: v1 (pickled) peer - detected and refused without ever unpickling it
PICKLE_PROTO_BYTE = 0x80

#: codecs this build can (de)compress, in preference order (stdlib only)
SUPPORTED_CODECS = ("zlib",)
#: zlib level for BATCH bodies: speed over ratio (pixel data is large and
#: the wire is usually the bottleneck only cross-host)
_ZLIB_LEVEL = 1

# -- decode hardening bounds (all raise WireFormatError when exceeded) --------
_MAX_DEPTH = 32
_MAX_ITEMS = 1 << 20          # elements per container
_MAX_COLUMNS = 4096           # columns per batch frame
_MAX_NDIM = 16
_MAX_BODY_BYTES = 1 << 30     # matches protocol.MAX_FRAME_BYTES


class WireFormatError(PetastormTpuError):
    """A frame failed wire-format validation (truncated/corrupt header,
    unknown tag, bounds violation, dtype/shape vs buffer mismatch, refused
    payload kind).  Classified like any worker data failure - the peer that
    produced it gets a failure frame, never a desynced stream."""


_U8 = struct.Struct("!B")
_I64 = struct.Struct("!q")
_F64 = struct.Struct("!d")
_U32 = struct.Struct("!I")

_T_NONE = 0x00
_T_TRUE = 0x01
_T_FALSE = 0x02
_T_INT = 0x03
_T_FLOAT = 0x04
_T_STR = 0x05
_T_BYTES = 0x06
_T_LIST = 0x07
_T_DICT = 0x08
_T_NDARRAY = 0x09
_T_OBJARRAY = 0x0A


# -- control codec: encode ----------------------------------------------------

def _encode(out: bytearray, value: Any, depth: int) -> None:
    if depth > _MAX_DEPTH:
        raise WireFormatError("control value nests deeper than "
                              f"{_MAX_DEPTH} levels")
    if value is None:
        out += _U8.pack(_T_NONE)
    elif value is True:
        out += _U8.pack(_T_TRUE)
    elif value is False:
        out += _U8.pack(_T_FALSE)
    elif isinstance(value, (int, np.integer)):
        try:
            out += _U8.pack(_T_INT) + _I64.pack(int(value))
        except struct.error as exc:
            raise WireFormatError(
                f"int {value!r} does not fit the 64-bit wire int") from exc
    elif isinstance(value, (float, np.floating)):
        out += _U8.pack(_T_FLOAT) + _F64.pack(float(value))
    elif isinstance(value, str):
        raw = value.encode("utf-8")
        out += _U8.pack(_T_STR) + _U32.pack(len(raw)) + raw
    elif isinstance(value, (bytes, bytearray)):
        out += _U8.pack(_T_BYTES) + _U32.pack(len(value))
        out += value
    elif isinstance(value, memoryview):
        # len() of a non-byte-format/multi-dim view counts ELEMENTS, not
        # bytes - materialize so the length prefix and the body agree
        raw = bytes(value)
        out += _U8.pack(_T_BYTES) + _U32.pack(len(raw)) + raw
    elif isinstance(value, np.ndarray):
        _encode_array(out, value, depth)
    elif isinstance(value, (list, tuple)):
        if len(value) > _MAX_ITEMS:
            raise WireFormatError(f"list of {len(value)} exceeds wire bounds")
        out += _U8.pack(_T_LIST) + _U32.pack(len(value))
        for item in value:
            _encode(out, item, depth + 1)
    elif isinstance(value, dict):
        if len(value) > _MAX_ITEMS:
            raise WireFormatError(f"dict of {len(value)} exceeds wire bounds")
        out += _U8.pack(_T_DICT) + _U32.pack(len(value))
        for key, item in value.items():
            if not isinstance(key, str):
                raise WireFormatError(
                    f"wire dict keys must be str, got {type(key).__name__}")
            raw = key.encode("utf-8")
            out += _U32.pack(len(raw)) + raw
            _encode(out, item, depth + 1)
    else:
        raise WireFormatError(
            f"{type(value).__name__} is not wire-encodable (the binary"
            " control codec carries None/bool/int/float/str/bytes/list/"
            "dict/ndarray only)")


def _encode_array(out: bytearray, arr: np.ndarray, depth: int) -> None:
    if arr.ndim > _MAX_NDIM:
        raise WireFormatError(f"{arr.ndim}-d array exceeds wire bounds")
    if arr.dtype == object:
        if arr.size > _MAX_ITEMS:
            raise WireFormatError(
                f"object array of {arr.size} elements exceeds wire bounds")
        out += _U8.pack(_T_OBJARRAY) + _U8.pack(arr.ndim)
        for dim in arr.shape:
            out += _U32.pack(dim)
        for item in arr.ravel():
            _encode(out, item, depth + 1)
        return
    if arr.dtype.hasobject:
        raise WireFormatError("structured dtypes holding objects are not"
                              " wire-encodable")
    dtype_s = arr.dtype.str.encode("ascii")
    # cast("B") rejects empty arrays (zeros in shape/strides): fall back to
    # tobytes for those and for strided views (tobytes emits C order, which
    # is what decode's reshape assumes)
    raw = (memoryview(arr).cast("B")
           if arr.flags.c_contiguous and arr.nbytes else arr.tobytes())
    out += _U8.pack(_T_NDARRAY) + _U8.pack(len(dtype_s)) + dtype_s
    out += _U8.pack(arr.ndim)
    for dim in arr.shape:
        out += _U32.pack(dim)
    out += _U32.pack(arr.nbytes)
    out += raw


def dumps(value: Any) -> bytes:
    """Encode one control value (raises :class:`WireFormatError` for types
    outside the wire domain - the caller decides whether that means a bug
    or a pickle fallback)."""
    out = bytearray()
    _encode(out, value, 0)
    return bytes(out)


# -- control codec: decode ----------------------------------------------------

class _Reader:
    __slots__ = ("buf", "pos", "end")

    def __init__(self, buf, start: int = 0, end: Optional[int] = None):
        self.buf = buf
        self.pos = start
        self.end = len(buf) if end is None else end

    def take(self, n: int) -> memoryview:
        if n < 0 or self.pos + n > self.end:
            raise WireFormatError(
                f"truncated control frame (wanted {n} bytes at offset"
                f" {self.pos}, have {self.end - self.pos})")
        view = memoryview(self.buf)[self.pos:self.pos + n]
        self.pos += n
        return view

    def u8(self) -> int:
        return _U8.unpack(self.take(1))[0]

    def u32(self) -> int:
        return _U32.unpack(self.take(4))[0]


def _decode(r: _Reader, depth: int) -> Any:
    if depth > _MAX_DEPTH:
        raise WireFormatError("control frame nests deeper than "
                              f"{_MAX_DEPTH} levels")
    tag = r.u8()
    if tag == _T_NONE:
        return None
    if tag == _T_TRUE:
        return True
    if tag == _T_FALSE:
        return False
    if tag == _T_INT:
        return _I64.unpack(r.take(8))[0]
    if tag == _T_FLOAT:
        return _F64.unpack(r.take(8))[0]
    if tag == _T_STR:
        try:
            return str(r.take(r.u32()), "utf-8")
        except UnicodeDecodeError as exc:
            raise WireFormatError(f"invalid utf-8 in wire string: {exc}") \
                from exc
    if tag == _T_BYTES:
        return bytes(r.take(r.u32()))
    if tag == _T_LIST:
        count = r.u32()
        if count > _MAX_ITEMS:
            raise WireFormatError(f"wire list claims {count} items")
        return [_decode(r, depth + 1) for _ in range(count)]
    if tag == _T_DICT:
        count = r.u32()
        if count > _MAX_ITEMS:
            raise WireFormatError(f"wire dict claims {count} items")
        out = {}
        for _ in range(count):
            try:
                key = str(r.take(r.u32()), "utf-8")
            except UnicodeDecodeError as exc:
                raise WireFormatError(
                    f"invalid utf-8 in wire dict key: {exc}") from exc
            out[key] = _decode(r, depth + 1)
        return out
    if tag == _T_NDARRAY:
        dtype = _checked_dtype(str(r.take(r.u8()), "ascii", "replace"))
        shape = _decode_shape(r)
        nbytes = r.u32()
        count = _shape_count(shape)
        if count * dtype.itemsize != nbytes:
            raise WireFormatError(
                f"wire array claims {nbytes} bytes but dtype {dtype} x"
                f" shape {shape} needs {count * dtype.itemsize}")
        raw = r.take(nbytes)
        # .copy() detaches from the frame buffer AND yields a writable
        # array (consumers mutate batches in place, batch.py concat note)
        return np.frombuffer(raw, dtype=dtype,
                             count=count).reshape(shape).copy()
    if tag == _T_OBJARRAY:
        shape = _decode_shape(r)
        count = _shape_count(shape)
        if count > _MAX_ITEMS:
            # bound BEFORE np.empty: the shape alone must not command a
            # multi-GB pointer-array allocation from a 6-byte frame (the
            # same cap lists and dicts enforce)
            raise WireFormatError(f"wire object array claims {count} items")
        out = np.empty(count, dtype=object)
        for i in range(count):
            out[i] = _decode(r, depth + 1)
        return out.reshape(shape)
    raise WireFormatError(f"unknown control tag 0x{tag:02x}")


def _decode_shape(r: _Reader) -> Tuple[int, ...]:
    ndim = r.u8()
    if ndim > _MAX_NDIM:
        raise WireFormatError(f"wire array claims {ndim} dimensions")
    return tuple(r.u32() for _ in range(ndim))


def _shape_count(shape: Sequence[int]) -> int:
    count = 1
    for dim in shape:
        count *= dim
        if count > _MAX_BODY_BYTES:
            raise WireFormatError(f"wire array shape {tuple(shape)} is"
                                  " implausibly large")
    return count


def _checked_dtype(dtype_s: str) -> np.dtype:
    try:
        dtype = np.dtype(dtype_s)
    except TypeError as exc:
        raise WireFormatError(f"bad wire dtype {dtype_s!r}") from exc
    if dtype.hasobject:
        # a dtype-'O' view would deserialize pointers = an unpickle in
        # disguise; the wire refuses it no matter what the header claims
        raise WireFormatError("object dtypes are not allowed on the wire")
    if dtype.itemsize == 0 or dtype.itemsize > (1 << 20):
        raise WireFormatError(f"implausible wire dtype {dtype_s!r}")
    return dtype


def loads(data, start: int = 0, end: Optional[int] = None) -> Any:
    """Decode one control value; the encoded object must span exactly
    ``data[start:end]`` (trailing garbage = a framing bug = refused)."""
    r = _Reader(data, start, end)
    value = _decode(r, 0)
    if r.pos != r.end:
        raise WireFormatError(
            f"{r.end - r.pos} trailing byte(s) after the control value")
    return value


# -- batch frames: header + raw column buffers --------------------------------

def encode_batch_parts(batch, codec: str = "") -> Optional[Tuple[Dict, List]]:
    """Split a ColumnBatch into a BATCH-frame header dict + body buffers.

    Raw fixed-shape columns become zero-copy body parts referenced by
    ``(dtype, shape, offset, nbytes)`` specs; object/empty columns ride
    inline in the header via the control codec (strings, bytes, ragged
    arrays).  Returns None when the batch cannot travel binary (a column
    holds values outside the wire domain) - the caller's cue for the
    counted pickle fallback.  ``codec`` compresses the assembled body
    end-to-end (the dispatcher relays it opaque either way).
    """
    from petastorm_tpu.batch import ColumnBatch

    if not isinstance(batch, ColumnBatch):
        return None
    cols: Dict[str, Any] = {}
    parts: List[Any] = []
    offset = 0
    for name, col in batch.columns.items():
        if (isinstance(col, np.ndarray) and col.dtype != object
                and not col.dtype.hasobject and col.nbytes > 0):
            parts.append(col.data.cast("B") if col.flags.c_contiguous
                         else col.tobytes())
            cols[name] = ["raw", col.dtype.str, list(col.shape), offset,
                          col.nbytes]
            offset += col.nbytes
        else:
            try:
                dumps(col)  # probe: is this column inside the wire domain?
            except WireFormatError:
                return None
            cols[name] = ["inline", col]
    # "bord" (batch ordinal) not "ordinal": result frames merge this header
    # with frame-level fields, and the work item's ordinal must not clobber
    # the batch's own (None for non-decode workers)
    header = {"rows": batch.num_rows, "bord": batch.ordinal,
              "cols": cols, "blen": offset, "codec": codec or ""}
    if codec:
        if codec not in SUPPORTED_CODECS:
            raise WireFormatError(f"unknown wire codec {codec!r}")
        parts = [zlib.compress(b"".join(parts), _ZLIB_LEVEL)]
    return header, parts


def decode_batch_body(header: Dict, body) -> Any:
    """Rebuild a ColumnBatch from a BATCH frame (validated; numpy columns
    are writable views over the received buffer - zero further copies when
    uncompressed).  Raises :class:`WireFormatError` on any header/buffer
    disagreement."""
    from petastorm_tpu.batch import ColumnBatch

    codec = header.get("codec") or ""
    blen = header.get("blen")
    if not isinstance(blen, int) or blen < 0 or blen > _MAX_BODY_BYTES:
        raise WireFormatError(f"batch frame claims body of {blen!r} bytes")
    if codec:
        if codec not in SUPPORTED_CODECS:
            raise WireFormatError(
                f"batch frame compressed with unknown codec {codec!r}"
                f" (this build supports {SUPPORTED_CODECS})")
        d = zlib.decompressobj()
        try:
            body = bytearray(d.decompress(bytes(body), blen + 1))
        except zlib.error as exc:
            raise WireFormatError(f"corrupt {codec} batch body: {exc}") \
                from exc
    if len(body) != blen:
        raise WireFormatError(
            f"batch body is {len(body)} bytes, header claims {blen}")
    rows = header.get("rows")
    if not isinstance(rows, int) or rows < 0:
        raise WireFormatError(f"batch frame claims {rows!r} rows")
    specs = header.get("cols")
    if not isinstance(specs, dict) or len(specs) > _MAX_COLUMNS:
        raise WireFormatError("batch frame column table missing or oversize"
                              f" ({0 if not isinstance(specs, dict) else len(specs)}"
                              f" of max {_MAX_COLUMNS})")
    view = memoryview(body)
    columns: Dict[str, Any] = {}
    for name, spec in specs.items():
        if not isinstance(spec, (list, tuple)) or not spec:
            raise WireFormatError(f"column {name!r} has a malformed spec")
        if spec[0] == "raw":
            try:
                _, dtype_s, shape, offset, nbytes = spec
            except ValueError as exc:
                raise WireFormatError(
                    f"column {name!r} raw spec has {len(spec)} fields") \
                    from exc
            dtype = _checked_dtype(dtype_s)
            if (not isinstance(shape, (list, tuple)) or len(shape) > _MAX_NDIM
                    or not all(isinstance(d, int) and d >= 0 for d in shape)):
                raise WireFormatError(f"column {name!r} has bad shape"
                                      f" {shape!r}")
            count = _shape_count(shape)
            if (not isinstance(offset, int) or not isinstance(nbytes, int)
                    or count * dtype.itemsize != nbytes):
                raise WireFormatError(
                    f"column {name!r}: dtype {dtype} x shape {tuple(shape)}"
                    f" needs {count * dtype.itemsize} bytes, spec claims"
                    f" {nbytes!r} at {offset!r}")
            if offset < 0 or offset + nbytes > len(body):
                raise WireFormatError(
                    f"column {name!r} spans [{offset}, {offset + nbytes})"
                    f" outside the {len(body)}-byte body")
            columns[name] = np.frombuffer(
                view, dtype=dtype, count=count,
                offset=offset).reshape(shape)
        elif spec[0] == "inline":
            if len(spec) != 2:
                raise WireFormatError(
                    f"column {name!r} inline spec has {len(spec)} fields")
            columns[name] = spec[1]
        else:
            raise WireFormatError(
                f"column {name!r} has unknown spec kind {spec[0]!r}")
    try:
        return ColumnBatch(columns, rows, ordinal=header.get("bord"))
    except (ValueError, TypeError) as exc:
        raise WireFormatError(f"batch columns disagree with the claimed"
                              f" {rows} rows: {exc}") from exc


def negotiate_codec(preference: str, same_host: bool,
                    client_codecs: Sequence[str],
                    worker_codecs: Sequence[str]) -> str:
    """The per-(worker, client) BATCH-body codec: '' (off) or a member of
    :data:`SUPPORTED_CODECS` both ends advertised.

    ``preference`` is the dispatcher's policy knob: ``'auto'`` compresses
    cross-host hops only (loopback/shm pairs skip the CPU tax), ``'off'``
    never compresses, a codec name forces it for every hop that supports
    it.  Unknown peers' codec lists are intersected, so a client built
    without a codec degrades to uncompressed, never to a frame it cannot
    decode."""
    if preference == "off" or (preference == "auto" and same_host):
        return ""
    common = [c for c in SUPPORTED_CODECS
              if c in (client_codecs or ()) and c in (worker_codecs or ())]
    if preference == "auto":
        return common[0] if common else ""
    return preference if preference in common else ""
