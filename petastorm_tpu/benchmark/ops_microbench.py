"""``python -m petastorm_tpu.benchmark.ops_microbench``: on-chip op timings.

Measures, on the real accelerator, the three op-level claims RESULTS.md
records: the Pallas normalize kernel vs its XLA fallback vs host-side numpy,
the flip+normalize fusion, and the hybrid jpeg decode crossover vs host full
decode.  Prints one JSON line per measurement.
"""

from __future__ import annotations

import json
import time


def _timeit(fn, n=20):
    import jax

    jax.block_until_ready(fn())
    t0 = time.perf_counter()
    out = None
    for _ in range(n):
        out = fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n * 1000


def main() -> int:
    import jax
    import numpy as np

    from petastorm_tpu.ops import normalize as nmod
    from petastorm_tpu.ops.augment import random_flip
    from petastorm_tpu.ops.normalize import _choose_block, normalize_images

    B, H, W, C = 256, 224, 224, 3
    imgs_host = np.random.randint(0, 255, (B, H, W, C), dtype=np.uint8)
    imgs = jax.device_put(imgs_host)
    jax.block_until_ready(imgs)
    # normalize_images takes torchvision-style [0,1]-unit mean/std
    # (ops/normalize.py:91); the host baseline below computes the SAME
    # function so the timings compare like for like
    mean = np.array([0.485, 0.456, 0.406], np.float32)
    std = np.array([0.229, 0.224, 0.225], np.float32)

    # the SAME backend test normalize_images uses (normalize.py:114: 'axon'
    # is the tunneled TPU PJRT plugin), so engagement reporting cannot drift
    # from what the op actually does
    on_tpu = jax.default_backend() in ("tpu", "axon")
    pallas_engaged = bool(on_tpu and _choose_block(B, H * W * C) is not None)
    print(json.dumps({"metric": "pallas_engaged", "value": pallas_engaged,
                      "backend": jax.default_backend()}), flush=True)
    if on_tpu and not pallas_engaged:
        raise SystemExit(
            "on a TPU but the Pallas normalize path did not engage; the"
            " published kernel numbers cannot be reproduced")

    t_main = _timeit(lambda: normalize_images(imgs, mean, std))
    orig = nmod._choose_block
    nmod._choose_block = lambda n, length: None  # force the XLA fallback
    try:
        t_xla = _timeit(lambda: normalize_images(imgs, mean, std))
    finally:
        nmod._choose_block = orig

    def host_norm():
        return jax.device_put(
            (imgs_host.astype(np.float32) / 255.0 - mean) / std)

    t_host = _timeit(host_norm, n=5)
    print(json.dumps({"metric": "normalize_ms_per_256imgs",
                      "pallas" if pallas_engaged else "device": round(t_main, 3),
                      "xla_fallback": round(t_xla, 3),
                      "host_numpy_plus_f32_transfer": round(t_host, 1)}),
          flush=True)

    key = jax.random.PRNGKey(0)
    t_aug = _timeit(lambda: normalize_images(random_flip(imgs, key), mean, std))
    print(json.dumps({"metric": "flip_plus_normalize_ms_per_256imgs",
                      "value": round(t_aug, 3)}), flush=True)

    import itertools

    from petastorm_tpu.ops import random_resized_crop

    big = jax.device_put(np.random.randint(0, 255, (B, 256, 256, C),
                                           dtype=np.uint8))
    jax.block_until_ready(big)
    ctr = itertools.count()

    def _k():
        return jax.random.fold_in(key, next(ctr))

    t_rrc = _timeit(lambda: random_resized_crop(big, _k(), (224, 224)))
    t_rrc_aa = _timeit(lambda: random_resized_crop(
        big, _k(), (224, 224), antialias=True), n=5)
    t_full = _timeit(lambda: normalize_images(
        random_flip(random_resized_crop(big, _k(), (224, 224)), _k()),
        mean, std))
    print(json.dumps({"metric": "random_resized_crop_ms_per_256imgs_256to224",
                      "value": round(t_rrc, 3),
                      "antialiased": round(t_rrc_aa, 2),
                      "crop_flip_normalize_chain": round(t_full, 3)}),
          flush=True)

    try:
        import cv2
        import pyarrow as pa

        from petastorm_tpu.native.image import (available,
                                                decode_column_native,
                                                read_jpeg_coefficients_column)
        from petastorm_tpu.ops.jpeg import decode_coefficients
    except ImportError:
        return 0
    if not available():
        return 0

    from petastorm_tpu.test_util.synthetic import synthetic_jpeg_bytes

    bufs = synthetic_jpeg_bytes(64, H, W, quality=90)
    col = pa.array(bufs, type=pa.binary())
    out = np.empty((64, H, W, C), np.uint8)

    def host_path():
        decode_column_native(col, out, nthreads=1)
        return jax.device_put(out)

    planes, qtabs, layout = read_jpeg_coefficients_column(bufs)
    sampling = tuple((h, v) for (h, v, _, _) in layout.components)

    def hybrid_path():
        p, q, lay = read_jpeg_coefficients_column(bufs)
        jp, jq = jax.device_put((tuple(p), q))
        return decode_coefficients(jp, jq,
                                   image_size=(lay.height, lay.width),
                                   sampling=sampling)

    t_hostdec = _timeit(host_path, n=10)
    t_hyb = _timeit(hybrid_path, n=10)
    jp, jq = jax.device_put((tuple(planes), qtabs))
    t_chip = _timeit(lambda: decode_coefficients(
        jp, jq, image_size=(layout.height, layout.width), sampling=sampling),
        n=10)
    t0 = time.perf_counter()
    for _ in range(10):
        read_jpeg_coefficients_column(bufs)
    t_entropy = (time.perf_counter() - t0) / 10 * 1000
    print(json.dumps({"metric": "jpeg_decode_ms_per_64imgs_224",
                      "host_decode_plus_transfer": round(t_hostdec, 1),
                      "hybrid_total": round(t_hyb, 1),
                      "hybrid_host_entropy_half": round(t_entropy, 1),
                      "hybrid_chip_half": round(t_chip, 2)}), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
