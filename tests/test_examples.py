"""Example smoke tests (reference: examples/mnist/tests, examples/imagenet/tests)."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_hello_world_roundtrip(tmp_path, capsys):
    from examples.hello_world.generate_dataset import generate_hello_world_dataset
    from examples.hello_world.read_dataset import (columnar_hello_world,
                                                   jax_hello_world,
                                                   python_hello_world)

    url = str(tmp_path / "hw")
    generate_hello_world_dataset(url, rows_count=6)
    python_hello_world(url)
    columnar_hello_world(url)
    jax_hello_world(url)
    out = capsys.readouterr().out
    assert out.count("row id=") == 6
    assert "device batch: image1 (4, 128, 256, 3)" in out


def test_mnist_jax_learns(tmp_path):
    from examples.mnist.train_mnist_jax import generate_dataset, train

    url = str(tmp_path / "mnist")
    generate_dataset(url, rows=512)
    acc = train(url, epochs=2, batch_size=64, shuffling_queue_capacity=128)
    assert acc > 0.5  # synthetic digits are separable; random = 0.1


def test_mnist_torch_smoke(tmp_path):
    from examples.mnist.train_mnist_jax import generate_dataset
    from examples.mnist.train_mnist_torch import train

    url = str(tmp_path / "mnist_t")
    generate_dataset(url, rows=256)
    acc = train(url, epochs=1, batch_size=64)
    assert acc > 0.2


def test_imagenet_resnet_smoke(tmp_path):
    from examples.imagenet.train_resnet_tpu import generate_dataset, train

    url = str(tmp_path / "imagenet")
    generate_dataset(url, rows=16, side=64)
    m = train(url, steps=2, global_batch=8, side=64, num_classes=10,
              decode="host")
    assert m["samples_per_sec"] > 0
    assert 0.0 <= m["device_idle_pct"] <= 100.0
    assert m["diagnostics"]["delivered_batches"] >= m["steps"]
    # hybrid on-chip decode (the default) feeds the same training step;
    # train() itself falls back to host decode when the native lib is absent
    m = train(url, steps=2, global_batch=8, side=64, num_classes=10,
              decode="device")
    assert m["samples_per_sec"] > 0


def test_long_context_smoke(tmp_path):
    from examples.long_context.train_ring_attention import (generate_dataset,
                                                            train)

    url = str(tmp_path / "seqs")
    generate_dataset(url, rows=16, seq_len=32, vocab=64)
    losses = train(url, steps=3, global_batch=4, seq_len=32, vocab=64,
                   heads=2, head_dim=8, data_par=2)
    assert all(np.isfinite(v) for v in losses)
    # the Ulysses variant trains on the same delivery (heads=4 divides seq=4)
    losses = train(url, steps=2, global_batch=4, seq_len=32, vocab=64,
                   heads=4, head_dim=8, data_par=2, strategy="ulysses")
    assert all(np.isfinite(v) for v in losses)


def test_preemption_example_exact_resume(tmp_path):
    from examples.preemption.train_with_preemption import (generate_dataset,
                                                           train)

    url = str(tmp_path / "ds")
    generate_dataset(url, rows=1024)
    seen_a, seen_b, loss = train(url, batch_size=16, preempt_at=2,
                                 verbose=False)
    assert seen_a + seen_b == 1024      # every row exactly once across runs
    assert seen_b > 0                   # the preemption really cut mid-epoch
    assert np.isfinite(loss)


def test_spark_converter_example(tmp_path, capsys):
    from examples.spark_converter.convert_and_feed import main

    main(cache_dir=str(tmp_path / "cache"), rows=32)
    out = capsys.readouterr().out
    assert "converted: 32 rows" in out
    assert "jax loader delivered 32 rows" in out
    assert "torch DataLoader delivered 32 rows" in out
    assert "fingerprint cache" in out


def test_imagenet_tfdata_comparator_smoke(tmp_path):
    """The north-star comparator path (--input tfdata): TFRecord build from
    the stored jpegs, tf.data feed with the background device-transfer
    thread, and the SAME train step - smoke-tested at tiny shapes so the
    A/B harness the bench runs on the chip is covered by the suite too."""
    pytest.importorskip("tensorflow")
    from examples.imagenet.train_resnet_tpu import generate_dataset, train

    url = str(tmp_path / "ds")
    generate_dataset(url, rows=32, side=32)
    m = train(url, steps=2, global_batch=8, side=32, num_classes=10,
              workers=1, prefetch=2, input_pipeline="tfdata")
    assert m["input"] == "tfdata"
    assert m["steps"] == 2
    assert m["samples_per_sec"] > 0
    assert np.isfinite(m["final_loss"])

    # scan mode over the SAME feed: K steps per dispatch
    m2 = train(url, steps=4, global_batch=8, side=32, num_classes=10,
               workers=1, prefetch=2, input_pipeline="tfdata", scan_steps=2)
    assert m2["scan_steps"] == 2 and m2["steps"] == 4
    assert np.isfinite(m2["final_loss"])
