"""Throughput benchmark - one JSON line per BASELINE.json config.

The driver parses the LAST line, so the headline metric (the reference's only
published number: hello_world read rate, 709.84 samples/sec from
/root/reference/docs/benchmarks_tutorial.rst:20-21, measured via
/root/reference/petastorm/benchmark/throughput.py:113-174 defaults - thread
pool x3, 200 warmup / 1000 measured rows) prints last.  The four other
BASELINE.json configs print first, each with ``vs_baseline`` relative to the
round-2 recorded value in RESULTS.md (the reference publishes no number for
them), so regressions are visible round over round.

Configs (BASELINE.md):
  1. mnist-style Parquet via make_reader (single-process CPU row path)
  2. hello_world Unischema (PNG + variable 4-D ndarray)  <- headline, LAST
  3. imagenet CompressedImageCodec(jpeg) -> device feed (JaxDataLoader,
     on-chip hybrid decode when the chip is present)
  4. converter: in-memory data -> cached parquet -> jax loader
  5. NGram timestamped multi-frame window readout
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# glibc keeps multi-MB batch buffers pooled instead of returning them to the
# kernel per free (docs/operations.md); must be set before numpy allocates,
# so re-exec once with the env in place
if os.environ.get("_PST_BENCH_CHILD") != "1":
    env = dict(os.environ, _PST_BENCH_CHILD="1",
               MALLOC_MMAP_THRESHOLD_="268435456",
               MALLOC_TRIM_THRESHOLD_="268435456")
    os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)]
              + sys.argv[1:], env)

sys.setswitchinterval(0.001)

BASELINE_SAMPLES_PER_SEC = 709.84  # reference hello_world (BASELINE.md)
#: round-2 recorded values (RESULTS.md) - regression reference for configs the
#: reference publishes no number for.  This box's absolute rates drift +-30%
#: between sessions (RESULTS.md environment caveat); treat vs_baseline here as
#: a round-over-round regression tripwire, not a precision comparison.
R2 = {"mnist_rows_per_sec": 430_000.0,
      "imagenet_ingest_samples_per_sec": 2900.0,
      "converter_rows_per_sec": 305_000.0,
      "ngram_windows_per_sec": 164_000.0}


def _median(rates):
    # median, not max: max is optimistically biased and weakens the
    # round-over-round regression tripwire on a host with +-30% drift
    rates = sorted(rates)
    return rates[len(rates) // 2]


def _emit(metric, value, unit, baseline, note=None):
    line = {"metric": metric, "value": round(value, 2), "unit": unit,
            "vs_baseline": round(value / baseline, 3)}
    if note:
        line["note"] = note
    print(json.dumps(line), flush=True)
    return line


# -- config 1: mnist row path -------------------------------------------------

def bench_mnist(tmp):
    import numpy as np

    from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.reader import make_reader
    from petastorm_tpu.schema import Field, Schema

    url = os.path.join(tmp, "mnist")
    schema = Schema("Mnist", [
        Field("idx", np.int64, (), ScalarCodec()),
        Field("digit", np.int64, (), ScalarCodec()),
        Field("image", np.uint8, (28, 28), NdarrayCodec()),
    ])
    rng = np.random.default_rng(7)
    rows = [{"idx": i, "digit": i % 10,
             "image": rng.integers(0, 255, (28, 28), dtype=np.uint8)}
            for i in range(4096)]
    write_dataset(url, schema, rows, row_group_size_rows=1024)

    with make_reader(url, reader_pool_type="serial", num_epochs=None,
                     shuffle_row_groups=False) as r:
        it = iter(r)
        for _ in range(4096):  # warm epoch
            next(it)
        t0 = time.perf_counter()
        n = 4 * 4096
        for _ in range(n):
            next(it)
        rate = n / (time.perf_counter() - t0)
    return _emit("mnist_rows_per_sec", rate, "rows/sec",
                 R2["mnist_rows_per_sec"], note="vs round-2 recorded value")


# -- config 2: hello_world (headline) ----------------------------------------

def bench_hello_world(tmp):
    import numpy as np

    from petastorm_tpu.codecs import CompressedImageCodec, NdarrayCodec, ScalarCodec
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.reader import make_reader
    from petastorm_tpu.schema import Field, Schema

    url = os.path.join(tmp, "hello_world")
    schema = Schema("HelloWorld", [
        Field("id", np.int32, (), ScalarCodec()),
        Field("image1", np.uint8, (128, 256, 3), CompressedImageCodec("png")),
        Field("array_4d", np.uint8, (None, 128, 30, None), NdarrayCodec()),
    ])
    rng = np.random.default_rng(1234)
    rows = [{"id": i,
             "image1": rng.integers(0, 255, (128, 256, 3), dtype=np.uint8),
             "array_4d": rng.integers(0, 255, (4, 128, 30, 3), dtype=np.uint8)}
            for i in range(10)]
    write_dataset(url, schema, rows, row_group_size_mb=256)

    WARMUP, MEASURE, CYCLES = 200, 1000, 5
    with make_reader(url, reader_pool_type="thread", workers_count=3,
                     num_epochs=None) as reader:
        it = iter(reader)
        for _ in range(WARMUP):
            next(it)
        rates = []
        for _ in range(CYCLES):
            t0 = time.perf_counter()
            for _ in range(MEASURE):
                next(it)
            rates.append(MEASURE / (time.perf_counter() - t0))
    return _emit("hello_world_samples_per_sec", _median(rates),
                 "samples/sec", BASELINE_SAMPLES_PER_SEC)


# -- config 3: imagenet jpeg -> device feed -----------------------------------

def bench_imagenet(tmp):
    import numpy as np

    from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.reader import make_batch_reader
    from petastorm_tpu.schema import Field, Schema

    url = os.path.join(tmp, "imagenet224")
    schema = Schema("Img", [
        Field("label", np.int64, (), ScalarCodec()),
        Field("image", np.uint8, (224, 224, 3),
              CompressedImageCodec("jpeg", quality=90)),
    ])
    from petastorm_tpu.test_util.synthetic import synthetic_rgb_image

    rows = [{"label": i % 1000, "image": synthetic_rgb_image(i, 224, 224)}
            for i in range(256)]
    write_dataset(url, schema, rows, row_group_size_rows=32)

    import jax

    from petastorm_tpu.native import image as native_image
    placement = ({"image": "device"} if native_image.available()
                 and jax.default_backend() != "cpu" else None)

    # steady-state measurement: warm the pipeline (jit compile, file cache,
    # queue fill), then time a fixed batch count mid-stream
    with make_batch_reader(url, num_epochs=None, workers_count=1,
                           shuffle_row_groups=False,
                           decode_placement=placement) as r:
        with JaxDataLoader(r, batch_size=32, prefetch=3) as loader:
            it = iter(loader)
            for _ in range(16):
                jax.block_until_ready(next(it))
            rates = []
            for _ in range(3):
                n = 0
                t0 = time.perf_counter()
                for _ in range(32):
                    b = next(it)
                    jax.block_until_ready(b)
                    n += int(b["image"].shape[0])
                rates.append(n / (time.perf_counter() - t0))
    rate = _median(rates)
    return _emit("imagenet_ingest_samples_per_sec", rate, "samples/sec",
                 R2["imagenet_ingest_samples_per_sec"],
                 note=f"decode={'hybrid-device' if placement else 'host'};"
                      " median-of-3 vs round-2 recorded max-of-3")


# -- config 4: converter ------------------------------------------------------

def bench_converter(tmp):
    import numpy as np
    import pyarrow as pa

    import jax

    from petastorm_tpu.converter import make_converter

    rng = np.random.default_rng(3)
    n, width = 65536, 64
    table = pa.table({f"f{j}": rng.standard_normal(n).astype(np.float32)
                      for j in range(width)})
    conv = make_converter(table, cache_dir_url=os.path.join(tmp, "conv"))
    try:
        with conv.make_jax_loader(
                batch_size=4096, prefetch=3,
                reader_kwargs={"num_epochs": None, "workers_count": 1,
                               "shuffle_row_groups": False}) as loader:
            it = iter(loader)
            for _ in range(24):
                jax.block_until_ready(next(it))
            rates = []
            for _ in range(3):
                rows = 0
                t0 = time.perf_counter()
                for _ in range(32):
                    b = next(it)
                    jax.block_until_ready(b)
                    rows += int(next(iter(b.values())).shape[0])
                rates.append(rows / (time.perf_counter() - t0))
        rate = _median(rates)
    finally:
        conv.delete()
    return _emit("converter_rows_per_sec", rate, "rows/sec",
                 R2["converter_rows_per_sec"],
                 note="median-of-3 vs round-2 recorded max-of-3")


# -- config 5: ngram windows --------------------------------------------------

def bench_ngram(tmp):
    import numpy as np

    from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.ngram import NGram
    from petastorm_tpu.reader import make_reader
    from petastorm_tpu.schema import Field, Schema

    url = os.path.join(tmp, "seq")
    schema = Schema("Seq", [
        Field("ts", np.int64, (), ScalarCodec()),
        Field("cam", np.uint8, (32, 32, 3), NdarrayCodec()),
    ])
    rng = np.random.default_rng(5)
    rows = [{"ts": i,
             "cam": rng.integers(0, 255, (32, 32, 3), dtype=np.uint8)}
            for i in range(8192)]
    write_dataset(url, schema, rows, row_group_size_rows=512)

    ng = NGram({0: ["ts", "cam"], 1: ["ts", "cam"], 2: ["ts", "cam"]},
               delta_threshold=1, timestamp_field="ts")

    def run():
        wins = 0
        with make_reader(url, ngram=ng, reader_pool_type="serial",
                         num_epochs=1, shuffle_row_groups=False) as r:
            t0 = time.perf_counter()
            for b in r.iter_batches():
                wins += b.num_rows
            return wins / (time.perf_counter() - t0)

    run()
    rate = _median([run() for _ in range(3)])
    return _emit("ngram_windows_per_sec", rate, "windows/sec",
                 R2["ngram_windows_per_sec"],
                 note="median-of-3 vs round-2 recorded max-of-3")


def main() -> None:
    import shutil
    import traceback

    tmp = tempfile.mkdtemp(prefix="petastorm_tpu_bench_")
    try:
        # configs 1/3/4/5 are isolated: a failure (chip runtime down, native
        # lib missing, ...) must not suppress the driver-parsed HEADLINE line
        for fn in (bench_mnist, bench_imagenet, bench_converter, bench_ngram):
            try:
                fn(tmp)
            except Exception:  # noqa: BLE001 - reported, never fatal
                print(json.dumps({"metric": fn.__name__, "error":
                                  traceback.format_exc(limit=3)}), flush=True)
        bench_hello_world(tmp)  # headline LAST: the driver parses the last line
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
