"""On-demand native build: compiles shm_arena.cpp into a cached .so.

No pip/pybind11 in this environment, so the binding is a plain C ABI loaded
via ctypes; g++ is invoked directly the first time the library is needed and
the result is cached next to the source, keyed by a source hash.
"""

from __future__ import annotations

import hashlib
import logging
import os
import subprocess
import tempfile
from typing import Optional

logger = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "shm_arena.cpp")
_LIB_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_lib")


def _source_tag() -> str:
    with open(_SRC, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()[:16]


def lib_path() -> str:
    return os.path.join(_LIB_DIR, f"libshm_arena-{_source_tag()}.so")


def build(force: bool = False) -> Optional[str]:
    """Compile (if needed) and return the .so path, or None if no toolchain."""
    path = lib_path()
    if os.path.exists(path) and not force:
        return path
    os.makedirs(_LIB_DIR, exist_ok=True)
    # build to a temp name then rename: concurrent builders race benignly
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_LIB_DIR)
    os.close(fd)
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread",
           _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except FileNotFoundError:
        logger.warning("g++ not found; native shm transport unavailable")
        os.unlink(tmp)
        return None
    except subprocess.CalledProcessError as exc:
        logger.warning("native build failed:\n%s", exc.stderr)
        os.unlink(tmp)
        return None
    os.replace(tmp, path)
    return path
