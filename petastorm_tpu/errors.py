"""Exception types for petastorm_tpu.

Reference parity: petastorm/errors.py (NoDataAvailableError at errors.py:16-17).
"""


class PetastormTpuError(Exception):
    """Base class for all petastorm_tpu errors."""


class NoDataAvailableError(PetastormTpuError):
    """Raised when a reader shard/predicate/selector combination selects no data.

    Reference: petastorm/errors.py:16, raised at petastorm/reader.py:502-504 when
    there are fewer rowgroups than shards.
    """


class SchemaError(PetastormTpuError):
    """Schema definition, serialization, or validation failure."""


class CodecError(PetastormTpuError):
    """Codec encode/decode failure (bad dtype, non-compliant shape, ...)."""


class MetadataError(PetastormTpuError):
    """Dataset metadata is missing or unreadable (not a petastorm_tpu dataset)."""


class ReaderClosedError(PetastormTpuError):
    """Operation on a reader that has been stopped/joined."""


class EpochNotFinishedError(PetastormTpuError):
    """reset() called mid-epoch.

    Reference prohibits mid-epoch reset (petastorm/reader.py:438-445); we keep the
    same contract because in-flight work items would leak across epochs.
    """
