"""JaxDataLoader: columnar batches -> device-sharded jax.Array pytrees.

Reference parity: petastorm/pytorch.py DataLoader/BatchedDataLoader (shuffling
buffer -> collate -> torch tensors, pytorch.py:130-367) and tf_utils
``make_petastorm_dataset`` (tf_utils.py:329-399).  What replaces what:

* torch shuffling buffers        -> columnar numpy RandomShufflingBuffer
                                    (petastorm_tpu/shuffle.py)
* default_collate per batch      -> exact-size batch assembly crossing rowgroup
                                    boundaries (reference's un-wired
                                    batching_table_queue, SURVEY.md 2.13)
* torch.as_tensor(device=...)    -> ``jax.make_array_from_process_local_data``
                                    with an explicit NamedSharding: each host
                                    feeds exactly its slice of the global batch;
                                    XLA moves shards over ICI/DCN
* tf py_func/queue runners       -> a two-stage producer (assembly thread ->
                                    bounded host queue -> transfer thread ->
                                    bounded device queue, each depth
                                    ``prefetch``): the blocking host->device
                                    copy overlaps the next batch's numpy
                                    assembly, and both overlap the device step

TPU-specific behavior:

* dtype promotion happens here, once, at the device boundary
  (petastorm_tpu/dtypes.jax_feed_dtype - uint16->int32 etc., f64->f32).
* variable-shape fields must be resolved to static shapes via ``pad_shapes``
  (XLA compiles per shape; pad-to-bucket beats recompilation) or excluded.
* string/object fields cannot reach the device: select them out with ``fields=``
  or keep them host-side via ``host_fields``.
* sequence-parallel consumers: pass a PartitionSpec sharding the sequence axis
  (e.g. P('data', 'seq')); the loader materializes only this host's sequence
  slice before assembly (petastorm_tpu/parallel/mesh.local_data_slice).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, Dict, Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from petastorm_tpu.batch import ColumnBatch
from petastorm_tpu.dtypes import jax_feed_dtype
from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.native.image import COEF_COLUMN_SEP as _COEF_SEP
from petastorm_tpu.parallel.mesh import local_data_slice
from petastorm_tpu.shuffle import (NoopShufflingBuffer, RandomShufflingBuffer,
                                   iter_batched, iter_batched_multi)
from petastorm_tpu.telemetry import NULL_CONTEXT as _NULL_CONTEXT
from petastorm_tpu.telemetry import resolve as _resolve_telemetry

logger = logging.getLogger(__name__)

_QUEUE_POLL_S = 0.1
#: default straggler-release threshold (straggler_release_s='auto' with a
#: decorrelation floor): long enough that a healthy pipeline never trips it,
#: short enough that one hung/slow rowgroup does not idle the device
_DEFAULT_STRAGGLER_RELEASE_S = 2.0
#: 'auto' transfer-commit probe: a readiness sync costing more than this per
#: trivial op means the runtime charges a round trip per sync (tunneled
#: runtimes: ~115 ms observed) - async chaining then pipelines strictly better
_COMMIT_PROBE_THRESHOLD_S = 0.02


class _Done:
    pass


class _Error:
    def __init__(self, exc: BaseException):
        self.exc = exc


class _TimedSource:
    """Runs a prepared-batch generator on its own thread so the assembly
    pump can poll it WITH A TIMEOUT (straggler release needs to notice "no
    raw batch for T seconds" while the reader call is still blocked).

    ``get(timeout)`` returns the next batch, raises ``queue.Empty`` on
    timeout, ``StopIteration`` at end of stream, or re-raises the
    generator's failure.  The thread honors the loader's stop event on both
    ends of its bounded queue.
    """

    _DONE = object()

    def __init__(self, gen, stop_event: threading.Event):
        self._q: "queue.Queue" = queue.Queue(maxsize=2)
        self._stop = stop_event
        self._thread = threading.Thread(target=self._run, args=(gen,),
                                        daemon=True,
                                        name="petastorm-tpu-jax-fetch")
        self._thread.start()

    def _run(self, gen) -> None:
        try:
            for item in gen:
                if self._stop.is_set():
                    return
                self._put(item)
            self._put(self._DONE)
        except BaseException as exc:  # noqa: BLE001 - forwarded to the pump
            self._put(_Error(exc))

    def _put(self, value) -> None:
        while not self._stop.is_set():
            try:
                self._q.put(value, timeout=_QUEUE_POLL_S)
                return
            except queue.Full:
                continue

    def get(self, timeout: Optional[float]):
        while True:
            try:
                value = self._q.get(
                    timeout=timeout if timeout is not None else _QUEUE_POLL_S)
            except queue.Empty:
                if self._stop.is_set():
                    raise StopIteration from None
                if timeout is not None:
                    raise
                continue
            if value is self._DONE:
                raise StopIteration
            if isinstance(value, _Error):
                raise value.exc
            return value

    def join(self, timeout: float = 2.0) -> None:
        self._thread.join(timeout=timeout)


class JaxDataLoader:
    """Iterate device-sharded batches (dict field -> jax.Array) from a Reader.

    ``batch_size`` is the GLOBAL batch size across the whole mesh; this process
    materializes only its slice (global/process_count for a data-sharded axis).

    With ``drop_last=False`` on a mesh, the final partial batch is zero-padded to
    the static batch size (constant shapes = no XLA recompile, even shards) and
    carries an extra ``'_valid_rows'`` host int with the true row count.

    ``valid_mask_field='mask'`` (mesh only) adds a synthetic 1-D float32 device
    column: 1.0 for real rows, 0.0 for padding, sharded exactly like the data
    fields' batch axis.  Being a global array it is identical on every host -
    weight per-example losses by it instead of branching on the host-local
    ``'_valid_rows'`` (which differs across hosts on drained pads and would
    diverge pod control flow; see ``drain()``).

    ``stack_batches=K`` (scan-feed delivery): each delivered unit is a stack
    of K consecutive batches - device arrays of shape ``(K, batch, ...)``
    sharded ``PartitionSpec(None, *spec)`` - shipped in ONE transfer, for
    consumers running K train steps per dispatch via ``lax.scan``.  Both the
    per-unit transfer count and the per-call dispatch RPC amortize K-fold.
    Semantics shift to stack granularity: ``drop_last=True`` also drops a
    final short stack; with ``drop_last=False`` missing steps and partial
    rows zero-pad, ``'_valid_rows'`` becomes a per-step ``(K,)`` int array,
    the valid mask is ``(K, batch)``, and ``drain()``/``state_dict()`` count
    whole stacks.  Incompatible with ``device_shuffle_capacity`` and
    multi-bucket ``pad_shapes``.

    ``straggler_release_s`` (MinatoLoader-style, default ``'auto'``): when
    no raw batch arrives for this long while the shuffle buffer already
    holds a full batch that only its decorrelation floor
    (``min_after_retrieve``) is withholding, the floor is bypassed and the
    batch emitted - one slow-decoding rowgroup stops gating batch assembly,
    and its rows ride a later batch.  ``'auto'`` = 2 s whenever a floor
    exists; ``None`` disables.  Counted in ``loader.straggler_releases``
    telemetry and ``diagnostics['straggler_releases']``.

    ``transfer_commit`` (default ``'auto'``): whether the transfer thread
    blocks until each batch lands on device.  ``'auto'`` probes the
    runtime's readiness-sync cost once and starts in ASYNC-CHAINED mode
    (no per-batch commit) on runtimes that charge a network round trip per
    sync (r05 measured ~220 ms per 4.8 MB commit on a tunneled runtime);
    ``True``/``False`` pin it.  The adaptive mid-run disable stays armed as
    the backstop in 'auto' and True modes.

    Readers with ``decode_placement={'field': 'auto'}`` (the live
    host<->device decode split) are handled transparently: pixel-form and
    coefficient-form rowgroups assemble in separate buffers, so a split
    flip never mixes wire forms within one delivered batch.  Incompatible
    with ``stack_batches > 1``.
    """

    def __init__(self,
                 reader,
                 batch_size: int,
                 mesh: Optional[Mesh] = None,
                 shardings: Union[None, PartitionSpec, Dict[str, PartitionSpec]] = None,
                 fields: Optional[Sequence[str]] = None,
                 host_fields: Sequence[str] = (),
                 shuffling_queue_capacity: int = 0,
                 min_after_retrieve: Optional[int] = None,
                 buffer_seed: Optional[int] = None,
                 pad_shapes: Optional[Dict[str, Tuple[int, ...]]] = None,
                 pad_values: Union[float, Dict[str, float]] = 0,
                 drop_last: bool = True,
                 prefetch: Optional[int] = None,
                 keep_wide_dtypes: bool = False,
                 transform_fn: Optional[Callable[[Dict[str, np.ndarray]],
                                                 Dict[str, np.ndarray]]] = None,
                 trace_dir: Optional[str] = None,
                 device_shuffle_capacity: int = 0,
                 device_shuffle_seed: Optional[int] = None,
                 valid_mask_field: Optional[str] = None,
                 stack_batches: int = 1,
                 straggler_release_s: Union[None, float, str] = "auto",
                 transfer_commit: Union[bool, str] = "auto",
                 telemetry=None):
        self._reader = reader
        #: pipeline telemetry (petastorm_tpu.telemetry): defaults to the
        #: reader's recorder so one object observes reader -> pool -> loader;
        #: no-op unless enabled.  Loader stages: 'host-assemble' (per raw
        #: reader batch: field selection, pad-to-bucket), 'host-prep' (per
        #: delivered batch: transform_fn, row padding, mask) and
        #: 'device-transfer' (make_array / device_put / commit).
        self._telemetry = _resolve_telemetry(
            telemetry if telemetry is not None
            else getattr(reader, "telemetry", None))
        self._m_consumer_wait = self._telemetry.counter(
            "loader.consumer_wait_s")
        self._m_delivered = self._telemetry.counter("loader.batches_delivered")
        #: host-queue depth gauge: with the prefetch-depth gauge (set in
        #: __next__) the metrics sampler sees both producer stages' backlogs
        self._g_host_depth = self._telemetry.gauge("loader.host_queue_depth")
        if self._telemetry.enabled:
            register = getattr(self._telemetry, "register_stage", None)
            if register is not None:
                # visible as "no samples yet" before the first batch lands
                for stage in ("host-assemble", "host-prep",
                              "device-transfer"):
                    register(stage)
        self._mesh = mesh
        self._specs = shardings
        #: K > 1 = scan-feed delivery: each delivered unit stacks K
        #: consecutive batches as (K, batch, ...) device arrays shipped in ONE
        #: transfer, for consumers that run K train steps per dispatch via
        #: ``lax.scan`` (amortizes the fixed per-call dispatch RPC of
        #: tunneled/remote TPU runtimes AND the per-transfer dispatch, which
        #: the hand-stacked ``jnp.stack`` pattern still paid K times).
        #: Reference analog: none - the TPU-native replacement for feeding
        #: BatchedDataLoader one batch per step (petastorm/pytorch.py:257-367)
        if stack_batches < 1:
            raise PetastormTpuError("stack_batches must be >= 1")
        self._stack = int(stack_batches)
        # each entry: one target tuple, or a LIST of bucket tuples - the
        # smallest bucket fitting the batch is chosen per batch, bounding XLA
        # recompiles to the bucket count (SURVEY.md section 7 hard part (d))
        self._pad_shapes = {name: _normalize_buckets(name, spec)
                            for name, spec in (pad_shapes or {}).items()}
        self._pad_values = pad_values
        self._drop_last = drop_last
        self._keep_wide = keep_wide_dtypes
        self._transform_fn = transform_fn
        self._host_fields = list(host_fields)
        #: fields arriving as raw jpeg bytes (reader decode_placement='device');
        #: decoded on-chip in _emit via ops/jpeg.decode_coefficients
        self._device_decode = list(getattr(reader, "device_decode_fields", ()) or ())
        #: subset using the mixed-geometry wire format ('device-mixed'):
        #: decoded per geometry bucket, padded to a static target
        self._mixed_decode = frozenset(
            getattr(reader, "device_decode_mixed", ()) or ())
        #: subset under the LIVE host<->device decode split
        #: (decode_placement='auto'): a raw batch carries EITHER the pixel
        #: column or coefficient planes; assembly keeps the two forms in
        #: separate buffers (iter_batched_multi) so a split flip never mixes
        #: column sets within one delivered batch
        self._split_decode = frozenset(
            getattr(reader, "device_decode_split", ()) or ())
        #: MinatoLoader-style straggler release: when no raw batch arrives
        #: for this long while a full batch sits behind the shuffle buffer's
        #: decorrelation floor, the floor is bypassed and the batch emitted
        #: (the slow rowgroup's rows ride a later batch).  'auto' = 2 s when
        #: a floor exists, else off; None disables.
        if straggler_release_s == "auto":
            self._straggler_s: Optional[float] = (
                _DEFAULT_STRAGGLER_RELEASE_S
                if shuffling_queue_capacity and (
                    min_after_retrieve is None or min_after_retrieve > 0)
                else None)
        else:
            self._straggler_s = (float(straggler_release_s)
                                 if straggler_release_s else None)
        if (self._straggler_s is not None
                and getattr(reader, "deterministic", "off") == "seed"):
            # seed-stable delivery (docs/operations.md "Reproducibility"):
            # a straggler release fires on wall-clock timing, so one near an
            # epoch edge moves rows across a batch boundary between runs -
            # the exact nondeterminism deterministic='seed' exists to
            # eliminate.  The reader's reorder stage already prevents the
            # slow-rowgroup head-of-line blocking the release worked around.
            logger.warning(
                "straggler_release_s is a timing-driven floor bypass and is"
                " disabled under deterministic='seed' delivery (it would"
                " move rows across batch boundaries between runs); pass"
                " deterministic='off' to the reader if straggler release"
                " matters more than bit-identical batches")
            self._straggler_s = None
        self._m_straggler = self._telemetry.counter(
            "loader.straggler_releases")
        #: transfer-commit policy (see _commit): 'auto' probes the runtime's
        #: readiness-sync cost once and starts with async-chained transfers
        #: (no per-batch commit) when a sync costs a network round trip -
        #: r05 measured ~220 ms per 4.8 MB commit on the tunneled runtime;
        #: True/False pin it (True keeps the adaptive breach backstop)
        # identity, not equality: `0 in (True, False, 'auto')` is True via
        # 0 == False, but the `is False` check below would then keep commits
        # ON for transfer_commit=0 - the opposite of what was asked
        if not any(transfer_commit is v for v in (True, False, "auto")):
            raise PetastormTpuError(
                f"transfer_commit must be True, False or 'auto';"
                f" got {transfer_commit!r}")
        self._commit_mode = transfer_commit
        #: geometries seen per mixed field (diagnostics; tests assert the
        #: decode compile count stays bounded by this set's size)
        self._mixed_geometries: Dict[str, set] = {}
        #: (field, h, w) geometries already warned about as missing from the
        #: dataset-level declared-geometry contract (one warning each)
        self._geom_warned: set = set()
        #: the contract is immutable for an open reader: parse the KV JSON
        #: once here, not per decoded geometry group on the hot path
        self._declared_geometries: Dict = (
            getattr(reader, "declared_geometries", None) or {})

        # output_schema describes the columns iter_batches actually yields
        # (differs from reader.schema for ngram readers)
        schema = getattr(reader, "output_schema", None) or reader.schema
        self._schema = schema
        self._fields = list(fields) if fields is not None else [
            f.name for f in schema if f.name not in self._host_fields]
        unknown = [f for f in self._fields + self._host_fields if f not in schema]
        if unknown:
            raise PetastormTpuError(f"Unknown fields {unknown}; schema has"
                                    f" {[f.name for f in schema]}")
        host_device = [f for f in self._host_fields if f in self._device_decode]
        if host_device:
            raise PetastormTpuError(
                f"fields {host_device} use decode_placement='device' (the"
                " worker ships coefficient planes, not pixels) and cannot be"
                " delivered host-side; use decode_placement='host' or drop"
                " them from host_fields")
        if not self._fields:
            raise PetastormTpuError(
                "JaxDataLoader needs at least one device-deliverable field"
                " (all schema fields were excluded or routed to host_fields)")

        #: synthetic per-row validity column (1.0 = real row, 0.0 = padding).
        #: Unlike the host-local '_valid_rows' int, the mask is a GLOBAL device
        #: array assembled like any data field, so every host of a pod holds
        #: the same logical values - the only safe signal to weight losses by
        #: under collectives, where branching on host-local '_valid_rows'
        #: diverges control flow across hosts and hangs the pod (see drain())
        self._valid_mask = valid_mask_field
        if valid_mask_field is not None:
            if mesh is None:
                raise PetastormTpuError(
                    "valid_mask_field only applies to mesh delivery: without a"
                    " mesh no zero-padding happens, every delivered row is real")
            if valid_mask_field in schema:
                raise PetastormTpuError(
                    f"valid_mask_field {valid_mask_field!r} collides with a"
                    " schema field; pick an unused name")
            if valid_mask_field == "_valid_rows":
                raise PetastormTpuError(
                    "valid_mask_field cannot be '_valid_rows': that key is"
                    " reserved for the host-local valid-row count")
        self._validate_deliverable(schema)

        if batch_size < 1:
            raise PetastormTpuError("batch_size must be >= 1")
        self._global_batch = batch_size
        self._local_rows = self._local_layout()
        if self._mesh is not None:
            for name in self._fields:
                if name in self._mixed_decode:
                    self._validate_mixed_scatter_layout(name)

        #: HBM-resident exchange shuffle over whole device batches (the TPU
        #: analog of the reference's GPU-tensor BatchedDataLoader buffers,
        #: petastorm/pytorch_shuffling_buffer.py) - composes with the host
        #: shuffling buffer below, which mixes rows before batch assembly
        if self._stack > 1 and self._split_decode:
            raise PetastormTpuError(
                f"stack_batches={self._stack} cannot be combined with the"
                f" live decode split (decode_placement='auto' fields"
                f" {sorted(self._split_decode)}): the K stacked batches could"
                " straddle a split flip and mix wire forms. Pin the split"
                " with decode_placement='host'/'device' for scan-feed"
                " delivery.")
        if self._stack > 1:
            bucketed = [n for n, b in self._pad_shapes.items() if len(b) > 1]
            if bucketed:
                raise PetastormTpuError(
                    f"stack_batches={self._stack} needs one static shape per"
                    f" field, but {bucketed} use multi-bucket pad_shapes (the"
                    " bucket choice could differ between the K stacked"
                    " batches); give them a single pad target instead.")
            if device_shuffle_capacity:
                raise PetastormTpuError(
                    "stack_batches cannot be combined with"
                    " device_shuffle_capacity: the HBM exchange buffer holds"
                    " single batches. Use the host shuffling buffer"
                    " (shuffling_queue_capacity) instead.")

        # under deterministic='seed' delivery, unseeded buffer RNGs derive
        # from the reader's seed root (explicit seeds win): with
        # in-plan-order arrival from the reorder stage, every
        # shuffle-buffer draw is then a pure function of (seed, retrieval
        # position) and batch composition is bit-identical across runs
        from petastorm_tpu.seeding import reader_buffer_seed

        buffer_seed = reader_buffer_seed(reader, "loader.shuffle_buffer",
                                         buffer_seed)
        if device_shuffle_capacity:
            device_shuffle_seed = reader_buffer_seed(
                reader, "loader.device_shuffle", device_shuffle_seed)
        self._device_buffer = None
        if device_shuffle_capacity:
            if self._host_fields:
                raise PetastormTpuError(
                    "device_shuffle_capacity cannot be combined with"
                    " host_fields: host-side values cannot live in the HBM"
                    " buffer. Use the host shuffling buffer"
                    " (shuffling_queue_capacity) instead.")
            bucketed = [n for n, b in self._pad_shapes.items() if len(b) > 1]
            if bucketed:
                raise PetastormTpuError(
                    f"device_shuffle_capacity needs uniform batch shapes, but"
                    f" {bucketed} use multi-bucket pad_shapes; give them a"
                    " single pad target instead.")
            from petastorm_tpu.jax.device_buffer import DeviceShufflingBuffer

            self._device_buffer = DeviceShufflingBuffer(
                device_shuffle_capacity, seed=device_shuffle_seed)
        #: partial batches held back so they are emitted after the drain
        self._tail_batches = []

        if shuffling_queue_capacity and shuffling_queue_capacity > 0:
            min_after = (min_after_retrieve if min_after_retrieve is not None
                         else shuffling_queue_capacity // 2)
            self._make_buffer = lambda: RandomShufflingBuffer(
                shuffling_queue_capacity, min_after, seed=buffer_seed)
        else:
            self._make_buffer = NoopShufflingBuffer

        if prefetch is None:
            # None = planner-seeded: a reader that ran the static pipeline
            # planner (petastorm_tpu.planner) carries a planned prefetch
            # depth with provenance; everything else keeps the historical
            # default of 2.  An explicit int pins the depth.
            prefetch = 2
            verdict = getattr(reader, "planner", None)
            planned = getattr(verdict, "knobs", {}).get("prefetch") \
                if verdict is not None else None
            if planned is not None and planned.source in ("profile",
                                                          "metadata"):
                prefetch = int(planned.value)
        self._out: "queue.Queue" = queue.Queue(maxsize=max(prefetch, 1))
        # two-stage producer: the assembly thread does the numpy work (batch
        # formation, shuffle, pad) and the transfer thread does the device
        # dispatch (make_array/device_put BLOCKS for the host->device copy,
        # several ms of IO per batch) - so transfers overlap the next batch's
        # host prep instead of serializing with it
        self._host_q: "queue.Queue" = queue.Queue(maxsize=max(prefetch, 1))
        self._stop_event = threading.Event()
        self._thread = threading.Thread(target=self._assemble, daemon=True,
                                        name="petastorm-tpu-jax-assembly")
        self._transfer_thread = threading.Thread(
            target=self._transfer, daemon=True,
            name="petastorm-tpu-jax-transfer")
        self._started = False
        self._finished = False
        self._failure: Optional[BaseException] = None
        self._delivered_batches = 0
        #: producer threads that failed to quiesce within the stop() join
        #: budget ([{thread, stage}]); surfaced in diagnostics so a silent
        #: shutdown wedge is visible post-mortem, not swallowed
        self._unquiesced: list = []
        #: cumulative seconds the consumer spent blocked waiting for a batch
        #: (the live device-idle signal; see also the throughput CLI's
        #: --simulated-step-ms for an offline measurement)
        self._consumer_wait_s = 0.0
        #: batches emitted past the shuffle decorrelation floor because the
        #: source straggled (see straggler_release_s)
        self._straggler_releases = 0
        #: when set, a jax.profiler trace (device + host ingest activity,
        #: viewable in TensorBoard/Perfetto) brackets the loader's lifetime
        self._trace_dir = trace_dir
        self._tracing = False
        #: producer has queued its _Done/_Error end-of-stream marker
        self._sentinel_pending = False
        #: adaptive transfer commit (see _commit): flips False permanently
        #: when the runtime's readiness sync is pathologically expensive;
        #: transfer_commit='auto' additionally probes the sync cost up front
        #: (async-chained transfer is then the DEFAULT on round-trip
        #: runtimes, not a mid-run discovery), False starts disabled
        self._commit_transfers = self._commit_mode is not False
        self._commit_probed = self._commit_mode != "auto"
        self._commit_probe_ms: Optional[float] = None
        self._commit_count = 0       # commits observed (first is warmup)
        self._commit_breaches = 0    # CONSECUTIVE over-threshold commits
        #: per-(field, trailing-shape) cache of (sharding, local slice) - static
        #: for the loader's lifetime, rebuilt per batch otherwise
        self._placement_cache: Dict[Tuple[str, Tuple[int, ...]],
                                    Tuple[NamedSharding, Tuple[slice, ...]]] = {}
        #: (trailing shape, dtype) each field was LAST emitted with
        #: (post-transform_fn, post-promotion, post-bucket-pad) - drain
        #: alignment pads must match the last emitted batch (the same
        #: semantics as the template path, which pads from the last drained
        #: batch), not the schema
        self._emitted_layout: Dict[str, Tuple[Tuple[int, ...], np.dtype]] = {}

        # closed-loop autotuning (petastorm_tpu.autotune): an autotuned
        # reader's controller gains this loader's prefetch depth as a knob
        controller = getattr(reader, "autotune", None)
        if controller is not None and hasattr(controller, "attach_loader"):
            controller.attach_loader(self)

    # -- runtime-adjustable prefetch (docs/operations.md "Autotuning") --------

    @property
    def prefetch(self) -> int:
        """Current per-stage producer queue bound (both the host-assembly
        and the device-transfer queues; runtime-adjustable via
        :meth:`set_prefetch`)."""
        return self._out.maxsize

    def set_prefetch(self, depth: int) -> int:
        """Resize both producer-stage queue bounds in place.

        Widening wakes any producer blocked on a full queue immediately;
        narrowing never drops queued batches - puts simply block until the
        consumer drains below the new bound.  This is the autotune
        controller's prefetch knob, and is safe to call directly while the
        loader runs.  Returns the new depth.
        """
        depth = max(1, int(depth))
        for q in (self._host_q, self._out):
            # stdlib queue.Queue: maxsize is only read under the mutex, and
            # not_full shares that mutex - mutate and wake waiters atomically
            with q.not_full:
                q.maxsize = depth
                q.not_full.notify_all()
        return depth

    # -- shape/sharding bookkeeping ------------------------------------------

    def _mixed_target(self, name: str) -> Tuple[int, ...]:
        """Static (H, W[, C]) every decoded image of a 'device-mixed' field is
        padded/cropped to: the schema shape when fixed, else a SINGLE
        pad_shapes target (XLA compiles the fit once per geometry x target,
        so the target must be static)."""
        field = self._schema[name]
        if field.is_fixed_shape:
            return tuple(field.shape)
        buckets = self._pad_shapes.get(name)
        if not buckets or len(buckets) != 1:
            raise PetastormTpuError(
                f"decode_placement='device-mixed' field {name!r} has variable"
                f" shape {field.shape}: give it ONE pad_shapes target (H, W"
                "[, C]) so every geometry bucket decodes+pads to a static"
                " shape" + (f"; got {len(buckets)} buckets" if buckets else ""))
        target = tuple(buckets[0])
        if len(target) != len(field.shape):
            raise PetastormTpuError(
                f"pad_shapes[{name!r}] target {target} rank differs from the"
                f" field shape {field.shape}")
        return target

    def _validate_deliverable(self, schema) -> None:
        for name in self._fields:
            if name in self._mixed_decode:
                self._mixed_target(name)  # raises when no static target exists
                if self._mesh is not None:
                    # mesh delivery works because the decode stays HOST-LOCAL
                    # (each host compiles only the geometries it encounters -
                    # bucket sets may differ per host freely) and only the
                    # decoded pixels are declared a global array afterwards
                    # (_scatter_local_rows) - so only the batch axis may shard
                    spec = self._spec_for(name)
                    if any(ax is not None for ax in spec[1:]):
                        raise PetastormTpuError(
                            f"decode_placement='device-mixed' field {name!r}:"
                            " only the batch axis may be sharded (the decode"
                            " is host-local; trailing image axes cannot span"
                            f" hosts). Got spec {spec}.")
                continue
            if name in self._device_decode:
                continue  # raw jpeg bytes in, schema-shaped uint8 out (on-chip)
            field = schema[name]
            if field.dtype.kind in ("U", "S", "O", "M", "m"):
                raise PetastormTpuError(
                    f"Field {name!r} (dtype {field.dtype}) cannot be fed to a"
                    " device. Exclude it with fields=, or keep it host-side via"
                    " host_fields=.")
            if not field.is_fixed_shape and name not in self._pad_shapes:
                raise PetastormTpuError(
                    f"Field {name!r} has variable shape {field.shape}; XLA needs"
                    " static shapes - give it a pad_shapes entry (pad-to-bucket)"
                    " or exclude it.")

    def _spec_for(self, name: str) -> PartitionSpec:
        if isinstance(self._specs, dict):
            spec = self._specs.get(name)
        else:
            spec = self._specs
        if name == self._valid_mask and (
                not isinstance(self._specs, dict) or name not in self._specs):
            # the 1-D mask must shard its only axis exactly like the data
            # fields shard their batch axis, or local row counts diverge
            base = self._spec_for(self._fields[0]) if self._fields else None
            return PartitionSpec(
                base[0] if base is not None and len(base) else None)
        if spec is None:
            axis = self._mesh.axis_names[0] if self._mesh is not None else "data"
            spec = PartitionSpec(axis)
        return spec

    def _validate_mixed_scatter_layout(self, name: str) -> None:
        """Construction-time contract for 'device-mixed' mesh delivery: this
        host's addressable batch-axis shards must tile one contiguous block
        of exactly ``_local_rows`` rows (``_scatter_local_rows`` slices one
        host-local np.ndarray).  Depends only on (mesh, spec, global batch) -
        dim-0 slices of a batch-axis NamedSharding are independent of the
        trailing image dims - so a misconfigured mesh/spec fails fast here,
        not with an opaque shape error from
        ``make_array_from_single_device_arrays`` after the first decode."""
        spec = self._spec_for(name)
        batch_axis = spec[0] if len(spec) else None
        if batch_axis is None and self._local_rows < self._global_batch:
            # replicated batch is fine single-host (the host holds the full
            # batch); across processes each host holds only its local rows,
            # so a 'replicated' array would silently diverge per host
            raise PetastormTpuError(
                f"field {name!r}: decode_placement='device-mixed' requires the"
                " batch axis to be sharded when the batch spans processes"
                " (PartitionSpec leading entry is None, but this host"
                f" materializes only {self._local_rows} of the"
                f" {self._global_batch}-row global batch)."
                f" mesh={self._mesh!r} spec={spec!r}")
        batch_sharding = NamedSharding(self._mesh, PartitionSpec(batch_axis))
        global_shape = (self._global_batch,)
        idx_map = batch_sharding.addressable_devices_indices_map(global_shape)
        spans = sorted(
            ((sl[0].start or 0,
              sl[0].stop if sl[0].stop is not None else global_shape[0])
             for sl in idx_map.values()))
        lo = spans[0][0]
        covered = lo
        for a, b in spans:
            if a > covered:   # gap: another process' rows sit between ours
                raise PetastormTpuError(
                    f"field {name!r}: this host's addressable batch-axis"
                    f" shards are not contiguous (gap at rows [{covered},"
                    f" {a}) inside local span [{lo}, {spans[-1][1]}))."
                    " decode_placement='device-mixed' requires a mesh whose"
                    " device order keeps each process' batch rows contiguous;"
                    f" mesh={self._mesh!r} spec={spec!r}")
            covered = max(covered, b)
        if covered - lo != self._local_rows:
            raise PetastormTpuError(
                f"field {name!r}: addressable batch shards cover"
                f" {covered - lo} rows but this host owns {self._local_rows};"
                f" mesh={self._mesh!r} spec={spec!r} is not a plain"
                " batch-sharded layout supported by"
                " decode_placement='device-mixed'")

    def _local_layout(self) -> int:
        """Rows of the global batch this process materializes."""
        if self._mesh is None:
            return self._global_batch
        local_rows = None
        for name in self._fields:
            spec = self._spec_for(name)
            # probe only the batch axis: trailing sharded dims resolve per batch
            batch_axis_spec = PartitionSpec(spec[0] if len(spec) else None)
            sharding = NamedSharding(self._mesh, batch_axis_spec)
            sl = local_data_slice(sharding, (self._global_batch,))
            rows = sl[0].stop - sl[0].start
            if local_rows is None:
                local_rows = rows
            elif local_rows != rows:
                raise PetastormTpuError(
                    "All delivered fields must shard the batch axis identically"
                    f" (field {name!r} wants {rows} local rows, others"
                    f" {local_rows})")
        return int(local_rows)

    # -- producer thread ------------------------------------------------------

    def _prepare(self, batch: ColumnBatch) -> ColumnBatch:
        cols: Dict[str, np.ndarray] = {}
        for name in self._fields + self._host_fields:
            if name in self._device_decode:
                if name in self._split_decode and name in batch.columns:
                    # live split, HOST form: the worker shipped decoded
                    # pixels under the plain name - deliver like any field
                    cols[name] = batch.columns[name]
                    continue
                # the worker shipped the field as derived coefficient-plane
                # columns ('<name>#...'); pass them through batch assembly
                for key, col in batch.columns.items():
                    if key.startswith(name + _COEF_SEP):
                        cols[key] = col
                continue
            col = batch.columns[name]
            if name in self._pad_shapes:
                target = _pick_bucket(col, self._pad_shapes[name])
                col = _pad_to(col, target, self._pad_value_for(name),
                              self._schema[name].dtype)
            cols[name] = col
        return ColumnBatch(cols, batch.num_rows)

    def _pad_value_for(self, name: str):
        if isinstance(self._pad_values, dict):
            return self._pad_values.get(name, 0)
        return self._pad_values

    def _form_route(self, batch: ColumnBatch) -> tuple:
        """Assembly-partition key: which live-split fields arrived in HOST
        (pixel) form.  Constant () without split fields; around a split flip
        the two forms land in separate buffers and never concatenate."""
        if not self._split_decode:
            return ()
        return tuple(n for n in sorted(self._split_decode)
                     if n in batch.columns)

    def _on_straggler_release(self) -> None:
        self._straggler_releases += 1
        self._m_straggler.add(1)
        if self._straggler_releases == 1:
            # loud the first time: on a UNIFORMLY slow source (cold remote
            # reads slower than the threshold) every fetch gap releases, so
            # the decorrelation floor is effectively bypassed for the run -
            # a shuffle-quality tradeoff the operator must be able to see
            logger.warning(
                "straggler release: emitted a buffered batch past the"
                " shuffle decorrelation floor (no raw batch for %.1fs)."
                " Occasional releases are the point (a slow rowgroup must"
                " not gate assembly); FREQUENT ones mean the source is"
                " uniformly slower than straggler_release_s and the"
                " min_after_retrieve floor is being bypassed - raise the"
                " threshold or fix the source (watch"
                " loader.straggler_releases)", self._straggler_s)
        else:
            logger.debug("straggler release #%d (no raw batch for %.1fs)",
                         self._straggler_releases, self._straggler_s)

    def _assemble(self) -> None:
        """Stage 1: reader batches -> host-assembled local batches.

        Plain readers pump through :func:`iter_batched` exactly as before.
        Two features route through :func:`iter_batched_multi` instead: the
        live decode split (per-form assembly buffers) and straggler release
        (a fetch thread polls the reader with a timeout so a slow-decoding
        rowgroup stops gating emission of already-buffered full batches).
        """
        fetcher = None
        try:
            local_bs = self._local_rows
            tele = self._telemetry

            def prepared():
                for raw in self._reader.iter_batches():
                    if self._stop_event.is_set():
                        return
                    # 'host-assemble' (per RAW reader batch: pad-to-bucket,
                    # field selection) is a distinct stage from 'host-prep'
                    # (per DELIVERED batch in _emit) - one shared name would
                    # mix two granularities and corrupt count/mean/p50
                    with (tele.stage("host-assemble", rows=raw.num_rows)
                          if tele.enabled else _NULL_CONTEXT):
                        out = self._prepare(raw)
                    yield out

            if self._split_decode or self._straggler_s is not None:
                if self._straggler_s is not None:
                    fetcher = _TimedSource(prepared(), self._stop_event)
                    next_fn = fetcher.get
                else:
                    gen = prepared()
                    next_fn = lambda _timeout: next(gen)  # noqa: E731
                batches = iter_batched_multi(
                    next_fn, self._form_route, self._make_buffer, local_bs,
                    straggler_release_s=self._straggler_s,
                    on_straggler_release=self._on_straggler_release)
            else:
                batches = iter_batched(prepared(), self._make_buffer(),
                                       local_bs)
            for out in batches:
                if self._stop_event.is_set():
                    break
                if out.num_rows < local_bs and self._drop_last:
                    continue  # partial tail batch dropped
                self._host_push(out)
            self._host_push(_Done())
        except BaseException as exc:  # noqa: BLE001 - forwarded downstream
            self._host_push(_Error(exc))
        finally:
            if fetcher is not None:
                fetcher.join()

    def _transfer(self) -> None:
        """Stage 2: host batches -> device dispatch -> consumer queue.

        In stack mode (``stack_batches=K``) this stage groups K consecutive
        host batches and ships them as ONE ``(K, batch, ...)`` unit; the
        final short group is zero-padded to K steps (``drop_last=False``) or
        dropped (``drop_last=True``, mirroring the row-level semantics).
        """
        group = []
        try:
            while not self._stop_event.is_set():
                try:
                    item = self._host_q.get(timeout=_QUEUE_POLL_S)
                except queue.Empty:
                    continue
                if self._telemetry.enabled:
                    # stamp on the GET side too: a gauge updated only by the
                    # producer freezes at its last (high) value the moment
                    # the producer stalls - inverting the very drain-vs-stall
                    # signal the flight recorder reads it for
                    self._g_host_depth.set(self._host_q.qsize())
                if isinstance(item, _Error):
                    self._push(item)
                    self._sentinel_pending = True
                    self._abort_upstream()
                    return
                if isinstance(item, _Done):
                    break
                if self._stack > 1:
                    group.append(item)
                    if len(group) == self._stack:
                        self._emit_stack(group)
                        group = []
                else:
                    self._emit(item)
            else:
                return  # stopped
            if group and not self._drop_last:
                # partial final stack: zero-pad the missing steps so the
                # consumer's (K, ...) jit signature never changes;
                # '_valid_rows' and the valid mask mark the real rows
                self._emit_stack(group)
            if self._device_buffer is not None:
                for resident in self._device_buffer.drain():
                    if self._stop_event.is_set():
                        break
                    self._push(resident)
                for tail in self._tail_batches:
                    if self._stop_event.is_set():
                        break
                    self._push(tail)
                self._tail_batches = []
            self._push(_Done())
            self._sentinel_pending = True
        except BaseException as exc:  # noqa: BLE001 - forwarded to consumer
            self._push(_Error(exc))
            self._sentinel_pending = True
            self._abort_upstream()

    def _abort_upstream(self) -> None:
        """A producer stage failed terminally: wind down the OTHER producer
        stage, the reader, its executor and ventilator - otherwise (without a
        context manager) the assembly thread would spin on a full host queue
        and the pool would burn wakeups until process exit.  The _Error is
        already in the consumer queue, so ``__next__`` still surfaces it
        (queue drain happens before the stopped-check's StopIteration)."""
        self._stop_event.set()
        try:
            self._reader.stop()
        except Exception:  # noqa: BLE001 - teardown is best-effort
            logger.debug("reader stop during abort failed", exc_info=True)

    def _host_push(self, value) -> None:
        while not self._stop_event.is_set():
            try:
                self._host_q.put(value, timeout=_QUEUE_POLL_S)
                if self._telemetry.enabled:
                    self._g_host_depth.set(self._host_q.qsize())
                return
            except queue.Full:
                continue

    def _prep_cols(self, host_batch: ColumnBatch,
                   pad_to: Optional[int] = None):
        """Per-batch host prep shared by ``_emit`` and ``_emit_stack``:
        extract the deliverable fields, run ``transform_fn``, reject a
        runtime valid-mask collision (the schema collision is caught at
        construction; a transform can still mint the name), and zero-pad
        partial rows to ``pad_to`` (a mesh's static local batch / a stack's
        static per-step shape).  Returns ``(cols, valid_rows)``.

        A live-split field (decode_placement='auto') in HOST form is present
        under its plain name and stages like any pixel field; in device form
        its coefficient planes are handled by the device-decode path."""
        cols = {n: host_batch.columns[n] for n in self._fields
                if n not in self._device_decode
                or (n in self._split_decode and n in host_batch.columns)}
        if self._transform_fn is not None:
            cols = self._transform_fn(cols)
            if self._valid_mask is not None and self._valid_mask in cols:
                raise PetastormTpuError(
                    f"transform_fn produced a field named {self._valid_mask!r},"
                    " which collides with valid_mask_field; rename one")
        valid_rows = host_batch.num_rows
        if pad_to is not None and valid_rows < pad_to:
            # zero-pad to the static row count so the global shape (and the
            # consumer's jit signature) never changes - XLA recompiles per
            # shape, and uneven shards break global assembly
            cols = {name: _pad_rows(col, pad_to)
                    for name, col in cols.items()}
        return cols, valid_rows

    def _emit(self, host_batch: ColumnBatch) -> None:
        tele = self._telemetry
        traced = tele.enabled
        with (tele.stage("host-prep", rows=host_batch.num_rows)
              if traced else _NULL_CONTEXT):
            cols, valid_rows = self._prep_cols(
                host_batch,
                pad_to=self._local_rows if self._mesh is not None else None)
            if self._valid_mask is not None:
                mask = np.zeros(self._local_rows, np.float32)
                mask[:valid_rows] = 1.0
                cols[self._valid_mask] = mask
        transfer_stage = (tele.stage("device-transfer", rows=valid_rows)
                          if traced else _NULL_CONTEXT)
        with transfer_stage:
            device_batch = {}
            for name in self._device_decode:
                if name in self._fields and not (
                        name in self._split_decode
                        and name in host_batch.columns):
                    # (a live-split field in host form is already in `cols`
                    # as pixels and stages below like any other field)
                    decode = (self._decode_mixed_on_device
                              if name in self._mixed_decode
                              else self._decode_on_device)
                    device_batch[name] = decode(name, host_batch.columns)
            staged: Dict[str, np.ndarray] = {}
            for name, col in cols.items():
                arr = np.ascontiguousarray(col)
                feed_dtype = jax_feed_dtype(arr.dtype, keep_wide=self._keep_wide)
                if arr.dtype != feed_dtype:
                    arr = arr.astype(feed_dtype)
                self._emitted_layout[name] = (arr.shape[1:], arr.dtype)
                if self._mesh is not None:
                    sharding, sl, global_shape = self._placement_for(name, arr.shape[1:])
                    arr = arr[(slice(None),) + sl[1:]]  # sequence/model-axis slice
                    device_batch[name] = jax.make_array_from_process_local_data(
                        sharding, arr, global_shape)
                else:
                    staged[name] = arr
            if staged:
                # ONE device_put for all fields: each call pays a fixed dispatch
                # cost (an RPC on tunneled TPU runtimes), so a small label column
                # must not cost as much as the image column it rides with
                device_batch.update(jax.device_put(staged))
            self._commit(device_batch)
        for name in self._host_fields:
            device_batch[name] = host_batch.columns[name]
        if self._mesh is not None and valid_rows < self._local_rows:
            device_batch["_valid_rows"] = valid_rows
        if self._device_buffer is not None:
            if valid_rows == self._local_rows:
                out = self._device_buffer.push(device_batch)
                if out is not None:
                    self._push(out)
            else:
                # partial tail batch (different shape / '_valid_rows') cannot
                # enter the HBM buffer; stash it so it is still emitted LAST,
                # after the drain - consumers treat it as the epoch-end signal
                self._tail_batches.append(device_batch)
            return
        self._push(device_batch)

    def _emit_stack(self, group) -> None:
        """Stack-mode emit: K consecutive host batches -> ONE delivered unit
        of ``(K, batch, ...)`` device arrays, shipped in a single transfer.

        Per-step semantics match ``_emit`` exactly (transform_fn runs per
        batch BEFORE stacking, dtype promotion once on the stacked array).
        A short group (epoch end / drain with ``drop_last=False``) zero-pads
        the missing steps; partial row batches zero-pad their rows - in both
        cases ``'_valid_rows'`` becomes a per-step int array and the valid
        mask (shape ``(K, batch)``) marks the real rows, so a ``lax.scan``
        consumer runs all K steps with a constant signature and weights by
        the mask (the pod-safe pattern, see ``drain()``).
        """
        K, local = self._stack, self._local_rows
        real_steps = len(group)
        tele = self._telemetry
        traced = tele.enabled
        prepped, valids = [], []
        with (tele.stage("host-prep", steps=real_steps)
              if traced else _NULL_CONTEXT):
            for hb in group:
                # pad even without a mesh: the (K, B, ...) stack needs one
                # static per-step shape
                cols, valid = self._prep_cols(hb, pad_to=local)
                prepped.append(cols)
                valids.append(valid)

        transfer_stage = (tele.stage("device-transfer", steps=real_steps)
                          if traced else _NULL_CONTEXT)
        with transfer_stage:
            device_batch = {}
            for name in self._device_decode:
                if name in self._fields:
                    decode = (self._decode_mixed_stack
                              if name in self._mixed_decode
                              else self._decode_stack)
                    device_batch[name] = decode(name, group)

            staged: Dict[str, np.ndarray] = {}
            for name in (list(prepped[0]) if prepped else []):
                steps = [np.ascontiguousarray(p[name]) for p in prepped]
                steps += [np.zeros_like(steps[-1])] * (K - real_steps)
                arr = np.stack(steps)                      # (K, local, *trailing)
                feed_dtype = jax_feed_dtype(arr.dtype, keep_wide=self._keep_wide)
                if arr.dtype != feed_dtype:
                    arr = arr.astype(feed_dtype)
                self._emitted_layout[name] = (arr.shape[2:], arr.dtype)
                if self._mesh is not None:
                    sharding, sl, global_shape = self._placement_for(
                        name, arr.shape[2:])
                    arr = arr[(slice(None), slice(None)) + sl[2:]]
                    device_batch[name] = jax.make_array_from_process_local_data(
                        sharding, arr, global_shape)
                else:
                    staged[name] = arr
            if self._valid_mask is not None:
                mask = np.zeros((K, local), np.float32)
                for k, v in enumerate(valids):
                    mask[k, :v] = 1.0
                name = self._valid_mask
                self._emitted_layout[name] = ((), np.dtype(np.float32))
                sharding, _, global_shape = self._placement_for(name, ())
                device_batch[name] = jax.make_array_from_process_local_data(
                    sharding, mask, global_shape)
            if staged:
                # ONE device_put for the whole stack: K steps of data ride a
                # single fixed-cost dispatch instead of K (the whole point)
                device_batch.update(jax.device_put(staged))
            self._commit(device_batch)
        for name in self._host_fields:
            steps = [_pad_host_col(hb.columns[name], local) for hb in group]
            steps += [_host_filler(steps[-1])] * (K - real_steps)
            device_batch[name] = np.stack(steps)
        if real_steps < K or any(v < local for v in valids):
            device_batch["_valid_rows"] = np.asarray(
                valids + [0] * (K - real_steps), dtype=np.int64)
        self._push(device_batch)

    def _commit(self, device_batch) -> None:
        """Commit the transfers in the transfer thread: the consumer then
        never blocks on a half-copied array, and its readiness query never
        queues behind the next batch's dispatch (serialized device RPC
        channels would otherwise surface that contention as input stall).

        DEFAULT (transfer_commit='auto'): the readiness-sync cost is probed
        ONCE before the first commit - one warm trivial op timed three times
        - and when a sync alone costs a network round trip (r05 measured
        ~220 ms per 4.8 MB commit; the probe threshold is 20 ms for a
        nanosecond-scale op), async-chained transfer becomes the default
        from batch 1 instead of a mid-run discovery after two breaches.

        ADAPTIVE backstop: some tunneled/proxy runtimes degrade mid-session
        (~115 ms per sync observed on this build's tunnel in degraded
        weather - 30x a normal dispatch), which would cap delivery at ~9
        batches/s.  When a commit costs far more than the data volume can
        explain, committing is permanently disabled for this loader: async
        dispatch chains device-side, so consumers pay waits only at genuine
        use points, which pipelines strictly better on such runtimes.
        Correctness is unaffected either way.
        """
        if not self._commit_probed:
            self._probe_commit_cost()
        if not self._commit_transfers:
            return
        t0 = time.perf_counter()
        jax.block_until_ready(device_batch)
        took = time.perf_counter() - t0
        self._commit_count += 1
        if self._commit_count == 1:
            return  # first commit carries one-time executable warmup cost
        nbytes = sum(getattr(v, "nbytes", 0)
                     for v in device_batch.values()
                     if isinstance(v, jax.Array))
        # generous floor: 100 MB/s sustained transfer + 100 ms fixed is
        # slower than any healthy runtime; beyond it the sync itself is the
        # cost, not the copy.  Two CONSECUTIVE breaches are required so a
        # single GC/scheduler hiccup cannot permanently disable commits on
        # a healthy runtime (consumers would then block on un-landed arrays
        # and producer-side transfer errors would surface at use instead)
        if took > 0.1 + nbytes / 100e6:
            self._commit_breaches += 1
            if self._commit_breaches >= 2:
                self._commit_transfers = False
                logger.warning(
                    "transfer commit took %.0f ms for %.1f MB (twice in a"
                    " row) - this runtime charges a round trip per readiness"
                    " sync; disabling per-batch commit (async chaining takes"
                    " over)", took * 1e3, nbytes / 1e6)
        else:
            self._commit_breaches = 0

    def _probe_commit_cost(self) -> None:
        """transfer_commit='auto': measure a trivial readiness sync (min of
        3 after one warmup) in the transfer thread, before the first batch
        commits.  A runtime charging a round trip per sync starts in
        async-chained mode immediately; the per-batch adaptive breach logic
        stays armed either way as the backstop."""
        self._commit_probed = True
        try:
            jax.block_until_ready(jax.device_put(1.0))  # warmup/backend init
            costs = []
            for _ in range(3):
                t0 = time.perf_counter()
                jax.block_until_ready(jax.device_put(1.0))
                costs.append(time.perf_counter() - t0)
            cost = min(costs)
        except Exception:  # noqa: BLE001 - a probe failure must not break ingest
            logger.debug("transfer-commit probe failed; keeping commits on",
                         exc_info=True)
            return
        self._commit_probe_ms = cost * 1e3
        if cost > _COMMIT_PROBE_THRESHOLD_S:
            self._commit_transfers = False
            logger.info(
                "readiness sync costs %.0f ms for a trivial op - this runtime"
                " charges a round trip per sync; defaulting to async-chained"
                " transfer (no per-batch commit). transfer_commit=True"
                " overrides.", cost * 1e3)

    def _decode_stack(self, name: str, group) -> jax.Array:
        """Stack-mode variant of ``_decode_on_device``: the K batches'
        coefficient planes ship as ONE ``(K, local, ...)`` transfer and the
        on-chip dequant+IDCT+upsample+color runs once over the whole stack
        (``ops/jpeg.decode_coefficients`` handles leading batch dims)."""
        from petastorm_tpu.native.image import unpack_coef_columns
        from petastorm_tpu.ops.jpeg import decode_coefficients

        K, local = self._stack, self._local_rows
        per = [unpack_coef_columns(name, hb.columns) for hb in group]
        layout0 = per[0][2]
        for _, _, lay in per[1:]:
            if ((lay.height, lay.width, lay.components)
                    != (layout0.height, layout0.width, layout0.components)):
                raise PetastormTpuError(
                    f"field {name!r}: jpeg geometry changed between stacked"
                    " batches - decode_placement='device' requires one"
                    " geometry dataset-wide (use 'device-mixed')")

        stacked_planes = []
        for c in range(len(layout0.components)):
            steps = [_pad_rows(planes[c], local) for planes, _, _ in per]
            steps += [np.zeros_like(steps[-1])] * (K - len(per))
            stacked_planes.append(np.stack(steps))   # (K, local, bh, bw, 64)
        qt_steps = [_pad_rows(qtabs, local, fill=1) for _, qtabs, _ in per]
        qt_steps += [np.ones_like(qt_steps[-1])] * (K - len(per))
        jqt = np.stack(qt_steps)                     # (K, local, ncomp, 64)
        sampling = tuple((h, v) for (h, v, _, _) in layout0.components)
        field = self._schema[name]
        if self._mesh is None:
            jp, jq = jax.device_put((tuple(stacked_planes), jqt))
            out = decode_coefficients(
                jp, jq, image_size=(layout0.height, layout0.width),
                sampling=sampling)
        else:
            spec = self._spec_for(name)
            batch_sharding = NamedSharding(
                self._mesh,
                PartitionSpec(None, spec[0] if len(spec) else None))
            jp = tuple(jax.make_array_from_process_local_data(
                batch_sharding, p, (K, self._global_batch) + p.shape[2:])
                for p in stacked_planes)
            jq = jax.make_array_from_process_local_data(
                batch_sharding, jqt, (K, self._global_batch) + jqt.shape[2:])
            out = decode_coefficients(
                jp, jq, image_size=(layout0.height, layout0.width),
                sampling=sampling)
            if any(ax is not None for ax in spec[1:]):
                out = jax.device_put(
                    out, NamedSharding(self._mesh, PartitionSpec(None, *spec)))
        if len(field.shape) == 3 and field.shape[2] == 1 and out.ndim == 4:
            out = out[..., None]  # honor a declared (H, W, 1) grayscale shape
        return out

    def _decode_mixed_stack(self, name: str, group) -> jax.Array:
        """Stack-mode variant of ``_decode_mixed_on_device``: the K batches'
        cells decode as one flat ``K*local``-row bucket pass (host-local, as
        ever), then reshape to ``(K, local, ...)`` and scatter along the
        batch axis."""
        import jax.numpy as jnp

        from petastorm_tpu.native.image import (COEF_COLUMN_SEP,
                                                MIXED_CELL_SUFFIX)

        K, local = self._stack, self._local_rows
        key = f"{name}{COEF_COLUMN_SEP}{MIXED_CELL_SUFFIX}"
        flat = np.concatenate([hb.columns[key] for hb in group])
        n = len(flat)   # real cells form a prefix: only the LAST batch is short
        out = self._decode_mixed_flat(name, flat, K * local)
        field = self._schema[name]
        if len(field.shape) == 3 and field.shape[2] == 1 and out.ndim == 3:
            out = out[..., None]
        if n < K * local:
            out = jnp.concatenate(
                [out, jnp.zeros((K * local - n,) + out.shape[1:], out.dtype)])
        out = out.reshape((K, local) + out.shape[1:])
        if self._mesh is not None:
            out = self._scatter_stacked_rows(name, out)
        return out

    def _scatter_stacked_rows(self, name: str, out) -> jax.Array:
        """(K, local, ...) host-local decoded rows -> one global mesh array
        of shape (K, global, ...); the stack axis is unsharded, the batch
        axis scatters exactly like ``_scatter_local_rows``."""
        return self._scatter_batch_axis(name, out, lead=1)

    def _decode_mixed_on_device(self, name: str, columns: Dict[str, np.ndarray]
                                ) -> jax.Array:
        """Finish the hybrid decode of a MIXED-geometry field
        (decode_placement='device-mixed').

        The batch's object cells are re-grouped by jpeg geometry; each
        geometry bucket's planes are padded to a power-of-two size (never a
        data-dependent one - compiles stay bounded by geometries x
        log2(batch), see ``_decode_mixed_flat``), decoded, fitted
        (pad/crop) to the static target, then scattered back into batch
        order.  The wasted FLOPs on the padding rows are cheap: the on-chip
        half is ~0.4 ms per 64 images (RESULTS.md on-chip ops table).
        """
        from petastorm_tpu.native.image import (COEF_COLUMN_SEP,
                                                MIXED_CELL_SUFFIX)

        field = self._schema[name]
        col = columns[f"{name}{COEF_COLUMN_SEP}{MIXED_CELL_SUFFIX}"]
        n = len(col)
        out = self._decode_mixed_flat(name, col, max(self._local_rows, n))
        if len(field.shape) == 3 and field.shape[2] == 1 and out.ndim == 3:
            out = out[..., None]
        if self._mesh is not None:
            out = self._scatter_local_rows(name, out, n)
        return out

    def _decode_mixed_flat(self, name: str, col, batch_pad: int) -> jax.Array:
        """Bucket-decode one flat column of mixed-geometry cells.  Each
        bucket pads its group to the next power of two (min 8, capped at
        ``batch_pad``) - NOT to the full batch: padding every bucket to
        ``batch_pad`` made a G-geometry batch decode and transfer G x the
        data, which measurably handed the hybrid-decode win back to the
        host path (bench ``imagenet_ingest_mixed_samples_per_sec``).
        Power-of-two sizes keep every op static-shaped with compiles
        bounded by geometries x log2(batch) (decode/fit) plus the distinct
        per-batch size compositions (concat/gather).  Returns
        ``(len(col), *target)`` rows in column order, on the default device
        (the decode is host-local; mesh placement happens after)."""
        import jax.numpy as jnp

        from petastorm_tpu.native.image import _layout_from_meta
        from petastorm_tpu.ops.jpeg import decode_coefficients

        target = self._mixed_target(name)
        n = len(col)
        groups: Dict[bytes, list] = {}
        for i, cell in enumerate(col):
            groups.setdefault(cell[2].tobytes(), []).append(i)
        self._mixed_geometries.setdefault(name, set()).update(groups)
        parts = []
        flat_idx = np.empty(n, dtype=np.int64)
        offset = 0
        for key, idxs in groups.items():
            layout = _layout_from_meta(np.frombuffer(key, dtype=np.int32))
            self._check_declared_geometry(name, layout)
            k = len(idxs)
            pad_k = min(max(8, 1 << (k - 1).bit_length()), batch_pad)
            planes = []
            for c in range(len(layout.components)):
                stack = np.stack([col[i][0][c] for i in idxs])
                if k < pad_k:
                    stack = np.concatenate(
                        [stack, np.zeros((pad_k - k,) + stack.shape[1:],
                                         stack.dtype)])
                planes.append(stack)
            qtabs = np.stack([col[i][1] for i in idxs])
            if k < pad_k:
                qtabs = np.concatenate(
                    [qtabs, np.ones((pad_k - k,) + qtabs.shape[1:],
                                    qtabs.dtype)])
            sampling = tuple((h, v) for (h, v, _, _) in layout.components)
            jp, jq = jax.device_put((tuple(planes), qtabs))
            img = decode_coefficients(jp, jq,
                                      image_size=(layout.height, layout.width),
                                      sampling=sampling)
            if len(target) == 3:
                if img.ndim == 3:
                    img = img[..., None]
                if img.shape[-1] != target[2]:
                    if img.shape[-1] == 1:
                        img = jnp.repeat(img, target[2], axis=-1)
                    else:
                        raise PetastormTpuError(
                            f"field {name!r}: a stored jpeg decodes to"
                            f" {img.shape[-1]}-channel images but the target"
                            f" {target} wants {target[2]} channel(s); declare"
                            " a (H, W, 3) shape/target or store grayscale"
                            " jpegs")
            elif img.ndim == 4:
                raise PetastormTpuError(
                    f"field {name!r}: stored jpeg decodes to"
                    f" {img.shape[-1]}-channel images but the target {target}"
                    " is 2-D; declare a (H, W, C) shape/target")
            # fit to the static target: crop the excess, zero-pad the rest
            img = img[:, :min(img.shape[1], target[0]),
                      :min(img.shape[2], target[1])]
            pad = [(0, 0), (0, target[0] - img.shape[1]),
                   (0, target[1] - img.shape[2])]
            if img.ndim == 4:
                pad.append((0, 0))
            parts.append(jnp.pad(img, pad))        # (pad_k, *target)
            flat_idx[np.asarray(idxs)] = offset + np.arange(k)
            offset += pad_k
        stacked = (jnp.concatenate(parts, axis=0) if len(parts) > 1
                   else parts[0])
        # one static-shape gather scatters rows back into batch order and
        # drops the pad rows in the same pass
        return stacked[jnp.asarray(flat_idx)]

    def _check_declared_geometry(self, name: str, layout) -> None:
        """Warn (once per geometry) when a batch reveals an image geometry
        missing from the dataset-level contract stamped at write time - the
        compile count is then no longer bounded by the declared set."""
        shapes = self._declared_geometries.get(name)
        if not shapes:
            return  # no contract stamped (e.g. externally-written dataset)
        # channel count matters too: a grayscale jpeg at a declared color
        # size is still a NEW decode compile (the contract is shape-level;
        # subsampling variants within one shape are beyond its resolution)
        hwc = {(s[0], s[1], s[2] if len(s) > 2 else 1) for s in shapes}
        seen = (layout.height, layout.width, len(layout.components))
        key = (name,) + seen
        if seen not in hwc and key not in self._geom_warned:
            self._geom_warned.add(key)
            logger.warning(
                "field %r: jpeg geometry %s (h, w, channels) is not in the"
                " dataset's declared geometry contract %s - the on-device"
                " decode compile count is no longer bounded by the declared"
                " set; re-stamp it (petastorm-tpu-generate-metadata"
                " --scan-geometries) after changing the dataset",
                name, seen, sorted(hwc))

    def _scatter_local_rows(self, name: str, out, n: int) -> jax.Array:
        """Host-local decoded rows -> one GLOBAL mesh array.

        The mixed-geometry decode is deliberately host-local: each host
        compiles kernels only for the geometries IT encountered (the
        dataset-level contract stamped at write time -
        ``etl.metadata.declared_geometries`` - bounds the total), and bucket
        sets may differ across hosts without any cross-host agreement,
        because no collective runs inside the decode.  Mesh delivery is then
        pure data placement: zero-pad to the static local row count, split
        across this host's addressable devices, and declare the result a
        global array (``jax.make_array_from_single_device_arrays`` - no
        collective, no host round-trip of the decoded pixels).
        """
        import jax.numpy as jnp

        if n < self._local_rows:
            out = jnp.concatenate(
                [out, jnp.zeros((self._local_rows - n,) + out.shape[1:],
                                out.dtype)])
        return self._scatter_batch_axis(name, out, lead=0)

    def _scatter_batch_axis(self, name: str, out, lead: int) -> jax.Array:
        """Shared scatter: a host-local array whose batch axis sits at
        position ``lead`` (0 = plain batch, 1 = stacked ``(K, local, ...)``)
        becomes one global mesh array; any leading axes stay unsharded.
        The construction-time contract (``_validate_mixed_scatter_layout``)
        guarantees the addressable shards tile one contiguous block."""
        spec = self._spec_for(name)
        sharding = NamedSharding(
            self._mesh,
            PartitionSpec(*((None,) * lead),
                          spec[0] if len(spec) else None))
        global_shape = (tuple(out.shape[:lead]) + (self._global_batch,)
                        + tuple(out.shape[lead + 1:]))
        idx_map = sharding.addressable_devices_indices_map(global_shape)
        starts = [(sl[lead].start or 0) for sl in idx_map.values()]
        lo = min(starts)
        prefix = (slice(None),) * lead
        shards = []
        for dev, sl in idx_map.items():
            a = (sl[lead].start or 0) - lo
            b = (sl[lead].stop if sl[lead].stop is not None
                 else global_shape[lead]) - lo
            shards.append(jax.device_put(out[prefix + (slice(a, b),)], dev))
        return jax.make_array_from_single_device_arrays(
            global_shape, sharding, shards)

    def _decode_on_device(self, name: str, columns: Dict[str, np.ndarray]
                          ) -> jax.Array:
        """Finish the hybrid jpeg decode of one field (decode_placement='device').

        The entropy half already ran in the pool workers - ``columns`` holds
        the field's derived coefficient-plane columns ('<name>#...', see
        native/image.py pack_coef_columns).  Here the planes ship to the
        device(s) batch-sharded and the FLOP-heavy dequant + IDCT + upsample +
        color runs on-chip, sharded, with no cross-shard communication
        (petastorm_tpu/ops/jpeg.py).
        """
        from petastorm_tpu.native.image import unpack_coef_columns
        from petastorm_tpu.ops.jpeg import decode_coefficients

        field = self._schema[name]
        # (shape vs schema was already checked worker-side in pack_coef_columns)
        planes, qtabs, layout = unpack_coef_columns(name, columns)
        sampling = tuple((h, v) for (h, v, _, _) in layout.components)
        n = len(qtabs)
        if self._mesh is None:
            # one batched transfer for all planes + qtabs (fixed dispatch
            # cost per device_put call), then the on-chip half
            jp, jq = jax.device_put((tuple(planes), qtabs))
            out = decode_coefficients(jp, jq,
                                      image_size=(layout.height, layout.width),
                                      sampling=sampling)
        else:
            if n < self._local_rows:
                # zero coefficient blocks decode to flat gray padding rows
                # ('_valid_rows' marks how many are real, as for host fields)
                planes = [_pad_rows(p, self._local_rows) for p in planes]
                qtabs = _pad_rows(qtabs, self._local_rows, fill=1)
            spec = self._spec_for(name)
            batch_sharding = NamedSharding(
                self._mesh, PartitionSpec(spec[0] if len(spec) else None))
            jp = tuple(jax.make_array_from_process_local_data(
                batch_sharding, p, (self._global_batch,) + p.shape[1:])
                for p in planes)
            jq = jax.make_array_from_process_local_data(
                batch_sharding, qtabs, (self._global_batch,) + qtabs.shape[1:])
            out = decode_coefficients(jp, jq,
                                      image_size=(layout.height, layout.width),
                                      sampling=sampling)
            if any(ax is not None for ax in spec[1:]):
                # user sharded trailing image axes too: reshard once on device
                out = jax.device_put(out, NamedSharding(self._mesh, spec))
        if len(field.shape) == 3 and field.shape[2] == 1 and out.ndim == 3:
            out = out[..., None]  # honor a declared (H, W, 1) grayscale shape
        return out

    def _delivery_spec(self, name: str) -> PartitionSpec:
        """The PartitionSpec a delivered array for ``name`` actually uses:
        the user's spec, with an unsharded leading stack axis prepended in
        stack mode (the K stacked batches ride the same devices their rows
        would ride individually)."""
        spec = self._spec_for(name)
        if self._stack > 1:
            return PartitionSpec(None, *spec)
        return spec

    def _delivery_global(self, trailing: Tuple[int, ...]) -> Tuple[int, ...]:
        """Global shape of a delivered array with per-row ``trailing`` dims."""
        lead = (self._stack,) if self._stack > 1 else ()
        return lead + (self._global_batch,) + trailing

    def _placement_for(self, name: str, trailing: Tuple[int, ...]
                       ) -> Tuple[NamedSharding, Tuple[slice, ...], Tuple[int, ...]]:
        key = (name, trailing)
        hit = self._placement_cache.get(key)
        global_shape = self._delivery_global(trailing)
        if hit is None:
            sharding = NamedSharding(self._mesh, self._delivery_spec(name))
            sl = local_data_slice(sharding, global_shape)
            hit = (sharding, sl)
            self._placement_cache[key] = hit
        return hit[0], hit[1], global_shape

    def _push(self, value) -> None:
        while not self._stop_event.is_set():
            try:
                self._out.put(value, timeout=_QUEUE_POLL_S)
                return
            except queue.Full:
                continue

    # -- consumer -------------------------------------------------------------

    @property
    def telemetry(self):
        """The pipeline telemetry recorder this loader records into (the
        reader's by default; petastorm_tpu.telemetry)."""
        return self._telemetry

    @property
    def diagnostics(self) -> Dict:
        """Per-stage queue depths + reader diagnostics (SURVEY.md section 5:
        the TPU build's observability story).  ``prefetch_depth`` near
        capacity = host pipeline keeps up; near 0 = device is input-bound."""
        depth = self._out.qsize()
        if self._sentinel_pending:  # end-of-stream marker is not a batch
            depth = max(depth - 1, 0)
        out = {"prefetch_depth": depth,
               "prefetch_capacity": self._out.maxsize,
               "host_queue_depth": self._host_q.qsize(),
               "delivered_batches": self._delivered_batches,
               "consumer_wait_s": self._consumer_wait_s,
               # batches released past the shuffle floor because the source
               # straggled (straggler_release_s), and the transfer-commit
               # verdict (False = async-chained; probe cost when measured)
               "straggler_releases": self._straggler_releases,
               "transfer_commit": self._commit_transfers,
               "transfer_commit_probe_ms": self._commit_probe_ms,
               "finished": self._finished,
               # producer threads that missed the stop() join budget (each
               # {thread, stage}); non-empty = the shutdown was not clean
               "unquiesced_threads": list(self._unquiesced)}
        if self._stack > 1:
            out["stack_batches"] = self._stack
        if self._mixed_geometries:
            # distinct jpeg geometries decoded per 'device-mixed' field: the
            # on-chip decode compiles once per entry PER power-of-two group
            # size (bounded: geometries x log2(batch), _decode_mixed_flat)
            out["mixed_decode_geometries"] = {
                name: len(keys) for name, keys in self._mixed_geometries.items()}
            if self._declared_geometries:
                # the dataset-level bound those counts must stay under
                out["declared_geometries"] = {
                    name: len(shapes)
                    for name, shapes in self._declared_geometries.items()}
        reader_diag = getattr(self._reader, "diagnostics", None)
        if isinstance(reader_diag, dict):
            out["reader"] = reader_diag
            if reader_diag.get("skipped_rowgroups"):
                # fault ledger surfaced at the loader level too: a feed that
                # is degraded-but-running under an on_error skip policy must
                # be visible without digging into the nested reader dict
                out["skipped_rowgroups"] = reader_diag["skipped_rowgroups"]
                out["quarantined_rowgroups"] = reader_diag.get(
                    "quarantined_rowgroups", [])
        return out

    def __iter__(self):
        if not self._started:
            self._started = True
            self._thread.start()
            self._transfer_thread.start()
            if self._trace_dir:
                try:
                    jax.profiler.start_trace(self._trace_dir)
                    self._tracing = True
                except (RuntimeError, OSError) as exc:
                    # another trace already active process-wide, or unwritable
                    # dir: iterate untraced rather than fail the ingest
                    logger.warning("trace_dir=%r: could not start jax trace:"
                                   " %s", self._trace_dir, exc)
        return self

    def __next__(self) -> Dict[str, jax.Array]:
        if self._failure is not None:
            raise self._failure
        if self._finished:
            raise StopIteration  # repeatable after exhaustion (iterator protocol)
        if not self._started:
            iter(self)
        wait_start = time.perf_counter()
        while True:
            try:
                value = self._out.get(timeout=_QUEUE_POLL_S)
                waited = time.perf_counter() - wait_start
                self._consumer_wait_s += waited
                if self._telemetry.enabled:
                    self._m_consumer_wait.add(waited)
                    self._telemetry.gauge("loader.prefetch_depth").set(
                        self._out.qsize())
                break
            except queue.Empty:
                if self._stop_event.is_set():
                    self._finished = True
                    raise StopIteration
                if not self._transfer_thread.is_alive():
                    # the producer may have pushed its sentinel between our
                    # timeout and this liveness check - drain before concluding
                    try:
                        value = self._out.get_nowait()
                        break
                    except queue.Empty:
                        self._failure = PetastormTpuError(
                            "Loader producer thread died silently")
                        self._stop_trace()
                        raise self._failure
        if isinstance(value, _Done):
            self._finished = True
            self._sentinel_pending = False
            self._stop_trace()  # exhaustion flushes the trace without stop()
            raise StopIteration
        if isinstance(value, _Error):
            self._failure = value.exc
            self._sentinel_pending = False
            self._stop_trace()
            raise value.exc
        self._delivered_batches += 1
        self._m_delivered.add(1)
        return value

    # -- checkpoint/resume (reference gap: SURVEY.md section 5) ---------------

    def drain(self, all_gather_counts=None):
        """Quiesce ingest and return an iterator over every in-flight batch;
        afterwards the loader is exhausted and ``state_dict()`` is an EXACT
        cursor (zero re-read rows on resume) even with thread/process pools
        and a device shuffle buffer active.

        The preemption-checkpoint flow on a TPU pod::

            for batch in loader.drain():   # train on what's already in flight
                step(batch)
            save(loader.state_dict())      # exact - no re-reads on restart

        Quiesce happens EAGERLY in this call (not on first ``next``), so the
        returned iterator must be consumed before ``state_dict()`` for the
        exactness guarantee - an unconsumed drain leaves batches undelivered,
        which ``state_dict()``'s re-read window then covers as usual.

        Multi-host: each host freezes its pipeline at a timing-dependent
        point, so hosts drain UNEQUAL batch counts - if ``step`` runs
        pod-wide collectives, the pod would hang.  On a mesh with
        ``jax.process_count() > 1`` the hosts therefore agree on the maximum
        drained count (one small all-gather) and the shorter ones pad with
        zero batches carrying ``'_valid_rows': 0`` - every host yields the
        same number of steps.  ``all_gather_counts`` overrides the collective
        (tests; custom coordination).

        ``'_valid_rows'`` is HOST-LOCAL: the same drained step can be a real
        batch on one host and a pad on another, so a consumer that branches
        on it (``if _valid_rows == 0: continue``) diverges control flow
        across the pod and hangs the very collective drain exists to protect.
        Multi-host consumers must instead construct the loader with
        ``valid_mask_field=`` and run EVERY drained step, weighting the loss
        by the mask - a globally-consistent device array (1.0 real row / 0.0
        pad) assembled like any data field.  Proven for real (separate OS
        processes, Gloo collectives) by
        ``petastorm_tpu.parallel.selfcheck`` and
        ``tests/test_multiprocess_distributed.py``.

        With ``drop_last=True`` a final partial batch's rows are dropped
        exactly as they would be at an epoch end - and in stack mode
        (``stack_batches=K``) the accumulating short stack is dropped too,
        discarding up to K-1 FULL batches whose rows the reader cursor has
        already passed.  Training that checkpoints mid-epoch should use
        ``drop_last=False`` (mesh consumers get zero-padded ``'_valid_rows'``
        tails; stack consumers get a zero-padded final stack with per-step
        counts).
        """
        if not hasattr(self._reader, "quiesce"):
            raise PetastormTpuError(
                f"Reader {type(self._reader).__name__} does not support"
                " quiesce(); drain-to-cursor needs a petastorm_tpu Reader")
        self._reader.quiesce()

        multihost = self._mesh is not None and (
            all_gather_counts is not None or jax.process_count() > 1)
        if not multihost:
            def _local():
                while True:
                    try:
                        yield next(self)
                    except StopIteration:
                        return
            return _local()

        # multi-host: drain locally first (bounded by the in-flight window),
        # agree on the pod-wide max, pad the difference so every host steps
        # the same number of times
        local = []
        while True:
            try:
                local.append(next(self))
            except StopIteration:
                break
        if all_gather_counts is None:
            from jax.experimental import multihost_utils

            counts = multihost_utils.process_allgather(
                np.asarray([len(local)], dtype=np.int32))
            target = int(np.max(counts))
        else:
            target = int(max(all_gather_counts(len(local))))

        def _zero_array(global_shape, sharding, dtype):
            # zeros with the SAME global shape and sharding so collectives in
            # the consumer's step see identically laid-out operands; allocate
            # only shard-sized zeros (a global-shape buffer per shard would
            # spike host memory exactly at preemption time)
            shard_shape = sharding.shard_shape(global_shape)
            return jax.make_array_from_callback(
                global_shape, sharding,
                lambda idx, _s=shard_shape, _d=dtype: np.zeros(_s, _d))

        def _aligned():
            template = local[-1] if local else None
            synthesized = None
            for batch in local:
                yield batch
            for _ in range(target - len(local)):
                pad = {}
                if template is None and synthesized is None:
                    # derived lazily: when target == 0 no pad is needed and
                    # fields whose shapes cannot be derived must not raise
                    synthesized = self._pad_batch_layout()
                if template is not None:
                    for name, value in template.items():
                        if name == "_valid_rows":
                            continue
                        if isinstance(value, jax.Array):
                            pad[name] = _zero_array(value.shape, value.sharding,
                                                    value.dtype)
                        else:
                            pad[name] = value  # host fields pass through
                else:
                    # this host drained ZERO batches while a peer drained some:
                    # synthesize the padding from the schema/placement layout
                    # so this host still steps in lockstep with its peers
                    # instead of raising after the allgather (which would hang
                    # the pod mid-collective - the exact failure drain()
                    # exists to prevent)
                    for name, (shape, sharding, dtype) in synthesized.items():
                        if sharding is not None:
                            pad[name] = _zero_array(shape, sharding, dtype)
                        else:
                            pad[name] = np.zeros(shape, dtype)  # host field
                # stack mode: per-step counts, all zero (shape matches the
                # real units' '_valid_rows' array so consumer code is uniform)
                pad["_valid_rows"] = (np.zeros(self._stack, np.int64)
                                      if self._stack > 1 else 0)
                yield pad
        return _aligned()

    def _pad_batch_layout(self) -> Dict:
        """field -> (global shape, sharding | None, dtype) for synthesizing
        drain-alignment pad batches when this host delivered no batch to use
        as a template.  The placement cache (populated per emitted batch) wins
        because it reflects ``transform_fn`` output shapes; otherwise shapes
        come from the schema (fixed shapes, single-bucket pad targets, device
        decode geometry)."""
        layout: Dict[str, Tuple] = {}
        # when batches WERE emitted, their staged field set is the pytree the
        # peers' steps expect - a transform_fn may have added or dropped
        # fields relative to self._fields
        staged = (list(self._emitted_layout)
                  + [n for n in self._device_decode if n in self._fields]
                  if self._emitted_layout else list(self._fields))
        if self._valid_mask is not None and self._valid_mask not in staged:
            staged.append(self._valid_mask)
        for name in staged:
            field = self._schema[name] if name in self._schema else None
            emitted = self._emitted_layout.get(name)
            if emitted is not None:
                # last-emitted layout, not the schema's: a transform_fn may
                # have changed the dtype (uint8 image -> float32) and
                # multi-bucket pad_shapes make the trailing shape per-batch;
                # peers pad from their LAST drained batch, so mirroring the
                # last emitted batch here is the same semantics
                trailing, dtype = emitted
                sharding, _ = self._placement_cache[(name, trailing)]
            elif name == self._valid_mask:
                trailing = ()
                sharding = NamedSharding(self._mesh, self._delivery_spec(name))
                dtype = np.float32
            elif name in self._device_decode:
                # mixed fields may declare a variable shape; their static
                # delivery shape is the fit target, not the schema shape
                trailing = (self._mixed_target(name)
                            if name in self._mixed_decode
                            else tuple(field.shape))
                sharding = NamedSharding(self._mesh, self._delivery_spec(name))
                dtype = np.uint8
            else:
                if self._transform_fn is not None:
                    raise PetastormTpuError(
                        "drain() alignment on a zero-batch host cannot derive"
                        f" the padded shape of field {name!r}: a transform_fn"
                        " is set and no batch was ever emitted here to learn"
                        " its output shape - checkpoint at a step boundary"
                        " instead")
                buckets = self._pad_shapes.get(name)
                if buckets and len(buckets) > 1:
                    raise PetastormTpuError(
                        "drain() alignment on a zero-batch host cannot pick a"
                        f" pad bucket for field {name!r} (multi-bucket"
                        " pad_shapes): peers pad from their own last batch's"
                        " bucket, so a guess here could silently diverge the"
                        " pod's global shapes - checkpoint at a step boundary"
                        " instead")
                trailing = tuple(buckets[0]) if buckets else tuple(field.shape)
                sharding = NamedSharding(self._mesh, self._delivery_spec(name))
                dtype = jax_feed_dtype(field.dtype, keep_wide=self._keep_wide)
            layout[name] = (self._delivery_global(trailing), sharding, dtype)
        lead = (self._stack,) if self._stack > 1 else ()
        for name in self._host_fields:
            field = self._schema[name]
            shape = tuple(d if d is not None else 0 for d in field.shape)
            host_dtype = field.dtype if field.dtype.kind not in "USOMm" else object
            layout[name] = (lead + (self._local_rows,) + shape, None, host_dtype)
        return layout

    def state_dict(self) -> Dict:
        """Data-position cursor to pair with a training checkpoint.

        ``reader`` is the underlying work-item cursor (pass back via
        ``make_reader(..., resume_from=...)`` / ``resume_reader_kwargs``);
        ``delivered_batches`` counts device UNITS handed to the consumer -
        single batches, or whole ``(K, batch, ...)`` stacks in stack mode
        (``stack_batches=K``), so cursor granularity follows delivery
        granularity.
        Mid-epoch the reader cursor can run ahead of deliveries by the
        in-flight window - both producer-stage queues (2x ``prefetch``) plus
        ALL ``device_shuffle_capacity`` resident batches - so keep buffers
        small (or zero) when tight resume matters, or use ``drain()`` first
        for an exact cursor (see petastorm_tpu.jax.checkpoint module docs).
        """
        if not hasattr(self._reader, "state_dict"):
            raise PetastormTpuError(
                f"Reader {type(self._reader).__name__} does not support"
                " state_dict(); checkpoint/resume needs a petastorm_tpu Reader")
        return {"reader": self._reader.state_dict(),
                "delivered_batches": self._delivered_batches,
                "global_batch": self._global_batch,
                "stack_batches": self._stack}

    # -- lifecycle ------------------------------------------------------------

    def _stop_trace(self) -> None:
        if self._tracing:
            self._tracing = False
            try:
                jax.profiler.stop_trace()
            except RuntimeError as exc:  # no trace running (stopped elsewhere)
                logger.debug("stop_trace: %s", exc)

    def stop(self) -> None:
        """Stop the producer pipeline and the underlying reader."""
        self._stop_event.set()
        self._reader.stop()
        self._stop_trace()

    def join(self) -> None:
        """Wait for the producer threads and the reader to exit (after stop()).

        Each producer thread gets a bounded join; one that fails to quiesce
        (wedged in a transform_fn, a device transfer that never completes)
        is NOT silently ignored: a WARNING names the thread and its pipeline
        stage, and the failure is recorded in
        ``diagnostics['unquiesced_threads']``.  The threads are daemonic, so
        an abandoned one cannot block process exit.
        """
        if self._started:
            for t, stage in ((self._thread, "host-assemble"),
                             (self._transfer_thread, "device-transfer")):
                t.join(timeout=10)
                if t.is_alive():
                    entry = {"thread": t.name, "stage": stage}
                    if entry not in self._unquiesced:
                        self._unquiesced.append(entry)
                    logger.warning(
                        "Loader producer thread %s (stage %s) failed to"
                        " quiesce within 10s of stop(); abandoning the daemon"
                        " thread. queue depths: host=%d out=%d", t.name,
                        stage, self._host_q.qsize(), self._out.qsize())
        self._reader.join()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        self.join()


def make_jax_loader(dataset_url: str,
                    batch_size: int,
                    mesh: Optional[Mesh] = None,
                    shardings=None,
                    reader_factory=None,
                    shard_by_process: bool = True,
                    **kwargs) -> JaxDataLoader:
    """One-call path: dataset URL -> sharded reader -> JaxDataLoader.

    Shard assignment defaults to the JAX process topology
    (``jax.process_index/process_count``) - the TPU-native replacement for the
    reference's externally-supplied ``cur_shard`` + env-var rank sniffing.

    Reader kwargs (predicate, num_epochs, shuffle_seed, ...) and loader kwargs
    (shuffling_queue_capacity, pad_shapes, ...) are split automatically.
    """
    import inspect

    from petastorm_tpu.reader import make_batch_reader

    loader_params = set(inspect.signature(JaxDataLoader.__init__).parameters) - {
        "self", "reader", "batch_size", "mesh", "shardings"}
    loader_kwargs = {k: kwargs.pop(k) for k in list(kwargs) if k in loader_params}
    if "telemetry" in loader_kwargs:
        # one recorder observes the whole pipeline: the reader gets it too
        # (the loader would otherwise inherit the reader's default recorder)
        kwargs["telemetry"] = loader_kwargs["telemetry"]
    if "schema_fields" not in kwargs:
        # don't read+decode columns the loader would only throw away
        wanted = list(loader_kwargs.get("fields") or [])
        wanted += list(loader_kwargs.get("host_fields") or [])
        if wanted:
            kwargs["schema_fields"] = wanted

    if shard_by_process and "cur_shard" not in kwargs:
        cur, count = jax.process_index(), jax.process_count()
        if count > 1:
            kwargs["cur_shard"], kwargs["shard_count"] = cur, count
    factory = reader_factory or make_batch_reader
    reader = factory(dataset_url, **kwargs)
    try:
        return JaxDataLoader(reader, batch_size, mesh=mesh, shardings=shardings,
                             **loader_kwargs)
    except BaseException:
        # the loader never came to own the reader: shut it down, or its
        # executor threads/ventilator would poll forever
        reader.stop()
        reader.join()
        raise


def _pad_rows(col: np.ndarray, rows: int, fill=0) -> np.ndarray:
    """Pad a column's leading axis to ``rows`` with ``fill`` (the one shared
    tail-pad policy: zeros for data/coefficient planes, ones for quant
    tables)."""
    if len(col) >= rows:
        return col
    shape = (rows - len(col),) + col.shape[1:]
    filler = np.zeros(shape, col.dtype) if fill == 0 else np.full(
        shape, fill, col.dtype)
    return np.concatenate([col, filler])


def _host_filler(tmpl: np.ndarray) -> np.ndarray:
    """A zero-information array shaped like ``tmpl`` for missing host-side
    steps/rows (object cells fill with None, numeric with zeros)."""
    if tmpl.dtype == object:
        return np.full(tmpl.shape, None, dtype=object)
    return np.zeros_like(tmpl)


def _pad_host_col(col: np.ndarray, rows: int) -> np.ndarray:
    """Pad a host-side column to ``rows`` entries for stack assembly (object
    cells pad with None, numeric with zeros - same policy as the step filler
    ``_host_filler``)."""
    col = np.asarray(col)
    if len(col) >= rows:
        return col
    if col.dtype == object:
        filler = np.full((rows - len(col),) + col.shape[1:], None,
                         dtype=object)
    else:
        filler = np.zeros((rows - len(col),) + col.shape[1:], col.dtype)
    return np.concatenate([col, filler])


def _normalize_buckets(name: str, spec) -> list:
    """pad_shapes entry -> non-empty list of equal-rank bucket tuples, sorted
    by total size (so 'smallest fitting bucket' is a linear scan)."""
    buckets = [tuple(spec)] if spec and not isinstance(spec[0], (list, tuple)) \
        else [tuple(b) for b in spec]
    if not buckets:
        raise PetastormTpuError(f"pad_shapes[{name!r}] is empty")
    ranks = {len(b) for b in buckets}
    if len(ranks) != 1:
        raise PetastormTpuError(
            f"pad_shapes[{name!r}] buckets must share one rank, got {buckets}")
    return sorted(buckets, key=lambda b: (int(np.prod(b)), b))


def _pick_bucket(col: np.ndarray, buckets: list) -> Tuple[int, ...]:
    """Smallest bucket that fits every row of this batch (largest otherwise -
    rows are then clipped, same semantics as a single too-small target)."""
    if len(buckets) == 1:
        return buckets[0]
    if col.dtype != object:
        need = col.shape[1:]
    else:
        shapes = np.array([np.asarray(r).shape for r in col])
        need = tuple(shapes.max(axis=0)) if len(shapes) else buckets[0]
    for b in buckets:
        if len(b) == len(need) and all(t >= n for t, n in zip(b, need)):
            return b
    return buckets[-1]


def _pad_to(col: np.ndarray, target: Tuple[int, ...], pad_value, dtype) -> np.ndarray:
    """Pad/truncate each row to ``target`` shape (pad-to-bucket for XLA)."""
    n = len(col)
    target = tuple(target)
    if col.dtype != object:
        # already stacked (all rows same shape): one vectorized copy
        if col.shape[1:] == target:
            return col
        if col.ndim - 1 != len(target):
            raise PetastormTpuError(
                f"pad_shapes rank mismatch: rows have shape {col.shape[1:]},"
                f" target {target}")
        out = np.full((n,) + target, pad_value, dtype=dtype)
        clipped = tuple(slice(0, min(a, b)) for a, b in zip(col.shape[1:], target))
        out[(slice(None),) + clipped] = col[(slice(None),) + clipped]
        return out
    out = np.full((n,) + target, pad_value, dtype=dtype)
    for i in range(n):
        row = np.asarray(col[i])
        if row.ndim != len(target):
            raise PetastormTpuError(
                f"pad_shapes rank mismatch: row has shape {row.shape}, target"
                f" {target}")
        clipped = tuple(slice(0, min(a, b)) for a, b in zip(row.shape, target))
        out[(i,) + clipped] = row[clipped]
    return out
