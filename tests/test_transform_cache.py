"""Post-transform warm caching tests (ISSUE 15 tentpole a + satellites):
the closure-folded transform signature (stable across PYTHONHASHSEEDs,
changed by editing a wrapped function's body), the conservative
determinism gate (a non-deterministic / closure-opaque transform provably
never serves a cached output), transform-stage cache-key isolation in one
shared tier (editing bytecode or flipping ``deterministic`` misses
cleanly), the ``cache.transform_hits``/``cache.transform_stores``
telemetry, slot composition of a warm transform hit, and seed-stable
delivery staying bit-identical with transform caching armed."""

import logging
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

from petastorm_tpu.batch import ColumnBatch
from petastorm_tpu.cache_shared import SharedWarmCache
from petastorm_tpu.codecs import ScalarCodec
from petastorm_tpu.etl.writer import write_dataset
from petastorm_tpu.reader import make_batch_reader
from petastorm_tpu.schema import Field, Schema
from petastorm_tpu.telemetry import Telemetry
from petastorm_tpu.transform import (TransformSpec, row_transform,
                                     transform_output_cacheable,
                                     transform_signature)


def _arena_ok() -> bool:
    from petastorm_tpu.native import allocator_available

    return allocator_available()


needs_arena = pytest.mark.skipif(
    not _arena_ok() and not os.environ.get("PETASTORM_TPU_REQUIRE_ARENA"),
    reason="native shm_arena library unavailable")


def _write_ds(path, rows=64, rg=8):
    schema = Schema("T", [Field("x", np.int64, (), ScalarCodec())])
    write_dataset(str(path), schema, [{"x": i} for i in range(rows)],
                  row_group_size_rows=rg)
    return str(path)


def _scaled(k):
    def scale(cols):
        return {"x": cols["x"] * k}
    return scale


# -- closure folding (satellite 1) --------------------------------------------

def test_wrapped_function_body_changes_signature():
    """row_transform(f1) vs row_transform(f2) share the wrapper's bytecode;
    the signature must fold the CAPTURED function's code (the PR 7 closure
    caveat this PR closes)."""
    def f1(row):
        return {"x": row["x"] + 1}

    def f2(row):
        return {"x": row["x"] + 2}

    s1 = transform_signature(TransformSpec(row_transform(f1)))
    s2 = transform_signature(TransformSpec(row_transform(f2)))
    assert s1 != s2
    assert s1 == transform_signature(TransformSpec(row_transform(f1)))


def test_closure_constants_fold_into_signature():
    assert (transform_signature(TransformSpec(_scaled(2)))
            != transform_signature(TransformSpec(_scaled(3))))
    assert (transform_signature(TransformSpec(_scaled(2)))
            == transform_signature(TransformSpec(_scaled(2))))

    def norm(mean):
        def t(cols):
            return {"x": cols["x"] - mean}
        return TransformSpec(t)

    # captured ndarrays fold by VALUE: different normalization constants
    # key different cache entries
    assert (transform_signature(norm(np.ones(3)))
            != transform_signature(norm(np.zeros(3))))
    assert (transform_signature(norm(np.ones(3)))
            == transform_signature(norm(np.ones(3))))


def test_signature_stable_across_hashseeds():
    """Closure folding must not reintroduce hash-randomization sensitivity:
    two subprocesses under different PYTHONHASHSEEDs (and a third repeating
    the first) must compute the SAME signature for a transform capturing a
    frozenset + str + wrapped function."""
    code = (
        "from petastorm_tpu.transform import TransformSpec,"
        " transform_signature\n"
        "def inner(row):\n"
        "    return {'x': row['x']}\n"
        "def make():\n"
        "    keep = frozenset({'a', 'b', 'zz'})\n"
        "    tag = 'v1'\n"
        "    def t(cols):\n"
        "        assert tag and keep\n"
        "        return inner(cols)\n"
        "    return TransformSpec(t)\n"
        "print(transform_signature(make()))\n")
    sigs = []
    for seed in ("0", "1", "0"):
        env = dict(os.environ, PYTHONHASHSEED=seed, JAX_PLATFORMS="cpu")
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True, check=True)
        sigs.append(out.stdout.strip())
    assert sigs[0] == sigs[1] == sigs[2], sigs


#: module-level state for the GLOBAL-analog guard tests (a transform
#: reading/writing these is stateful without closing over anything)
_GLOBAL_STATE: list = []
_GLOBAL_FACTOR = 3


def _global_stateful(cols):
    _GLOBAL_STATE.append(1)
    return dict(cols)


def _global_scaled(cols):
    return {"x": cols["x"] * _GLOBAL_FACTOR}


def _global_writer(cols):
    global _GLOBAL_FACTOR
    _GLOBAL_FACTOR = 4
    return dict(cols)


def test_mutable_global_state_disables_caching():
    """The global analog of the closure guard (found by a live drive): a
    transform touching a module-level mutable object must never have its
    output cached, even declared deterministic=True."""
    ok, why = transform_output_cacheable(
        TransformSpec(_global_stateful, deterministic=True))
    assert not ok and "_GLOBAL_STATE" in why

    ok, why = transform_output_cacheable(
        TransformSpec(_global_writer, deterministic=True))
    assert not ok and "writes global" in why


def test_global_constants_fold_by_value(monkeypatch):
    """A module-level scalar a transform reads keys the cache by VALUE:
    changing it changes the signature (so a stale entry cannot serve), and
    the spec stays cacheable."""
    assert transform_output_cacheable(TransformSpec(_global_scaled))[0]
    s3 = transform_signature(TransformSpec(_global_scaled))
    # patch the dict the function actually reads from (its __globals__):
    # the test module can be imported under two names, so attribute
    # patching one instance would miss the other
    monkeypatch.setitem(_global_scaled.__globals__, "_GLOBAL_FACTOR", 5)
    s5 = transform_signature(TransformSpec(_global_scaled))
    assert s3 != s5


def _stochastic_helper(x):
    import random

    return x + random.random()


def _delegating_transform(cols):
    return {k: _stochastic_helper(v) for k, v in cols.items()}


class _SlottedScale:
    __slots__ = ("factor",)

    def __init__(self, factor):
        self.factor = factor

    def __call__(self, cols):
        return {k: v * self.factor for k, v in cols.items()}


class _SlottedStateful:
    __slots__ = ("seen",)

    def __init__(self):
        self.seen = []

    def __call__(self, cols):
        self.seen.append(1)
        return dict(cols)


def test_stochastic_helper_functions_refuse_caching():
    """The 'auto' name scan must cover REFERENCED and CAPTURED helper
    functions, not just the top-level body (review finding: a transform
    delegating its RNG call to a module-level helper was wrongly concluded
    cacheable)."""
    ok, why = transform_output_cacheable(TransformSpec(_delegating_transform))
    assert not ok and "random" in why

    def make():
        def jitter(x):
            return x + np.random.rand()

        def t(cols):
            return {k: jitter(v) for k, v in cols.items()}
        return TransformSpec(t)

    assert not transform_output_cacheable(make())[0]


class _ClassRoutedJitter:
    def apply(self, cols):
        return {k: v + np.random.normal() for k, v in cols.items()}


def _class_routed_transform(cols):
    return _ClassRoutedJitter().apply(cols)


def test_stochastic_class_method_refuses_caching():
    """The name scan must reach a referenced class's METHOD bodies: a
    transform routing its RNG call through Jitter().apply() refuses like
    an inline np.random call would (review finding)."""
    ok, why = transform_output_cacheable(
        TransformSpec(_class_routed_transform))
    assert not ok and ("normal" in why or "random" in why), (ok, why)
    # and editing a method changes the signature (the class's code folds)
    s1 = transform_signature(TransformSpec(_class_routed_transform))
    original = _ClassRoutedJitter.apply
    try:
        _ClassRoutedJitter.apply = lambda self, cols: dict(cols)
        s2 = transform_signature(TransformSpec(_class_routed_transform))
    finally:
        _ClassRoutedJitter.apply = original
    assert s1 != s2


def test_slotted_and_class_attr_callable_state_folds():
    """Callable-object state must fold (or refuse) regardless of where it
    lives: __slots__, instance __dict__, or class-level data attributes
    (review finding: slotted instances with different config shared one
    signature)."""
    s2 = transform_signature(TransformSpec(_SlottedScale(2),
                                           deterministic=True))
    s3 = transform_signature(TransformSpec(_SlottedScale(3),
                                           deterministic=True))
    assert s2 != s3
    assert transform_output_cacheable(
        TransformSpec(_SlottedScale(2), deterministic=True))[0]
    # mutable slotted state -> opaque, even declared deterministic
    ok, why = transform_output_cacheable(
        TransformSpec(_SlottedStateful(), deterministic=True))
    assert not ok and "seen" in why


# -- the determinism gate ------------------------------------------------------

def test_output_cacheable_matrix():
    def pure(cols):
        return dict(cols)

    assert transform_output_cacheable(TransformSpec(pure))[0]
    assert not transform_output_cacheable(
        TransformSpec(pure, deterministic=False))[0]
    assert transform_output_cacheable(
        TransformSpec(pure, deterministic=True))[0]
    assert not transform_output_cacheable(None)[0]
    # no func = pure field selection
    assert transform_output_cacheable(
        TransformSpec(removed_fields=["x"]))[0]

    def noisy(cols):
        return {k: v + np.random.rand() for k, v in cols.items()}

    ok, why = transform_output_cacheable(TransformSpec(noisy))
    assert not ok and "stochastic" in why
    # an explicit declaration overrides the name heuristic (the user owns
    # the assertion), but never the opaque-closure refusal below
    assert transform_output_cacheable(
        TransformSpec(noisy, deterministic=True))[0]


def test_opaque_closure_disables_caching_with_one_warning(caplog):
    def make():
        state = []

        def t(cols):
            state.append(1)
            return dict(cols)
        return TransformSpec(t, deterministic=True)

    ok, why = transform_output_cacheable(make())
    assert not ok and "not foldable" in why and "state" in why

    from petastorm_tpu.transform import log_output_cache_disabled

    spec = make()
    sig = transform_signature(spec)
    with caplog.at_level(logging.WARNING, logger="petastorm_tpu.transform"):
        log_output_cache_disabled(spec, why, sig)
        log_output_cache_disabled(spec, why, sig)
    warnings = [r for r in caplog.records
                if "output caching DISABLED" in r.getMessage()]
    assert len(warnings) == 1


def test_invalid_deterministic_value_refused():
    from petastorm_tpu.errors import PetastormTpuError

    with pytest.raises(PetastormTpuError, match="deterministic"):
        TransformSpec(lambda c: c, deterministic="yes")


# -- e2e: warm epochs skip decode AND transform --------------------------------

@needs_arena
def test_warm_epoch_skips_decode_and_transform(tmp_path):
    url = _write_ds(tmp_path / "ds")
    tier = str(tmp_path / "tier")
    tele = Telemetry()
    # one worker: epoch boundaries stay strict, so the counter assertions
    # are exact (with N workers an epoch-2 item can legitimately start
    # before epoch-1's identical item finished storing)
    with make_batch_reader(url, reader_pool_type="thread", workers_count=1,
                           shuffle_row_groups=False, num_epochs=2,
                           cache_type="shared", cache_location=tier,
                           transform_spec=TransformSpec(_scaled(3)),
                           telemetry=tele) as r:
        rows = sorted(int(v) for b in r.iter_batches()
                      for v in b.columns["x"])
        stats = r.warm_cache.stats()
    try:
        assert rows == sorted([i * 3 for i in range(64)] * 2)
        # 8 rowgroups: cold epoch stores 8 post-transform entries, warm
        # epoch serves all 8 - skipping decode AND transform
        assert stats["transform_stores"] == 8, stats
        assert stats["transform_hits"] == 8, stats
        c = tele.snapshot()["counters"]
        assert c["cache.transform_hits"] == 8
        assert c["cache.transform_stores"] == 8
        # the stage proof: decode and transform each ran exactly once per
        # rowgroup over TWO epochs (the warm epoch recorded zero samples)
        assert c["stage.transform.count"] == 8, c["stage.transform.count"]
        assert c["stage.decode.count"] == 8, c["stage.decode.count"]
    finally:
        SharedWarmCache(location=tier).cleanup()


def test_memory_cache_transform_counters(tmp_path):
    """Per-process caches count transform events through worker telemetry
    (no shared header to ride)."""
    url = _write_ds(tmp_path / "ds")
    tele = Telemetry()
    with make_batch_reader(url, reader_pool_type="thread", workers_count=1,
                           shuffle_row_groups=False, num_epochs=2,
                           cache_type="memory",
                           transform_spec=TransformSpec(_scaled(2)),
                           telemetry=tele) as r:
        rows = sorted(int(v) for b in r.iter_batches()
                      for v in b.columns["x"])
    assert rows == sorted([i * 2 for i in range(64)] * 2)
    c = tele.snapshot()["counters"]
    assert c["cache.transform_stores"] == 8
    assert c["cache.transform_hits"] == 8


# -- the acceptance guarantee: non-deterministic never served from cache ------

def test_undeclared_stateful_transform_reruns_every_epoch(tmp_path):
    """A transform over opaque closure state (undeclared, 'auto') must
    re-run for every rowgroup of every epoch - the cache may hold decode
    output, never this transform's."""
    url = _write_ds(tmp_path / "ds")
    calls = []

    def counting(cols):
        calls.append(1)
        return {"x": cols["x"] + 1}
    # `calls` is a list -> opaque closure state -> output caching disabled

    tele = Telemetry()
    with make_batch_reader(url, reader_pool_type="thread", workers_count=1,
                           shuffle_row_groups=False, num_epochs=2,
                           cache_type="memory",
                           transform_spec=TransformSpec(counting),
                           telemetry=tele) as r:
        rows = sorted(int(v) for b in r.iter_batches()
                      for v in b.columns["x"])
    assert rows == sorted([i + 1 for i in range(64)] * 2)
    assert len(calls) == 16  # 8 rowgroups x 2 epochs: transform never cached
    c = tele.snapshot()["counters"]
    assert c.get("cache.transform_stores", 0) == 0
    assert c.get("cache.transform_hits", 0) == 0
    # the decode tier still warms (epoch 2 decode served from cache)
    assert c.get("cache.hits", 0) == 8


def test_stochastic_transform_outputs_differ_across_epochs(tmp_path):
    """The end-to-end proof for the acceptance bullet: an RNG-sampling
    transform left on deterministic='auto' delivers DIFFERENT values each
    epoch even with a cache armed - a cached output would repeat epoch 1."""
    url = _write_ds(tmp_path / "ds", rows=16, rg=16)

    def jitter(cols):
        return {"x": cols["x"] * 1000 + np.random.randint(0, 1000)}

    with make_batch_reader(url, reader_pool_type="thread", workers_count=1,
                           shuffle_row_groups=False, num_epochs=2,
                           cache_type="memory",
                           transform_spec=TransformSpec(jitter)) as r:
        batches = [list(b.columns["x"]) for b in r.iter_batches()]
    assert len(batches) == 2
    assert batches[0] != batches[1]


# -- cache-key invalidation (satellite 3) -------------------------------------

@needs_arena
def test_decode_and_transform_entries_never_cross_serve(tmp_path):
    """One shared tier, three readers: transform-cached, plain (no
    transform), and the same transform declared non-deterministic.  Each
    must see its own values - no entry crosses stages or declarations."""
    url = _write_ds(tmp_path / "ds")
    tier = str(tmp_path / "tier")
    try:
        def read(spec):
            with make_batch_reader(url, reader_pool_type="thread",
                                   workers_count=2, shuffle_row_groups=False,
                                   cache_type="shared", cache_location=tier,
                                   transform_spec=spec) as r:
                return sorted(int(v) for b in r.iter_batches()
                              for v in b.columns["x"])

        plus = TransformSpec(_scaled(10), deterministic=True)
        assert read(plus) == [i * 10 for i in range(64)]
        # a plain reader over the SAME tier must never receive the cached
        # post-transform batches
        assert read(None) == list(range(64))
        # flipping deterministic False must recompute, not serve the entry
        # stored under deterministic=True
        calls = []

        def observed(cols):
            calls.append(1)
            return {"x": cols["x"] * 10}

        spec_off = TransformSpec(observed, deterministic=False)
        assert read(spec_off) == [i * 10 for i in range(64)]
        assert len(calls) == 8  # ran for every rowgroup despite the tier
    finally:
        SharedWarmCache(location=tier).cleanup()


@needs_arena
def test_edited_transform_bytecode_misses_cleanly(tmp_path):
    url = _write_ds(tmp_path / "ds")
    tier = str(tmp_path / "tier")
    try:
        def read(k):
            with make_batch_reader(url, reader_pool_type="thread",
                                   workers_count=2, shuffle_row_groups=False,
                                   cache_type="shared", cache_location=tier,
                                   transform_spec=TransformSpec(
                                       _scaled(k), deterministic=True)) as r:
                rows = sorted(int(v) for b in r.iter_batches()
                              for v in b.columns["x"])
                return rows, r.warm_cache.stats()

        rows1, _ = read(2)
        assert rows1 == [i * 2 for i in range(64)]
        # "edited" transform (different captured constant = different code
        # identity): must miss and deliver ITS values, never k=2's entries
        rows2, stats = read(3)
        assert rows2 == [i * 3 for i in range(64)]
        assert stats["transform_stores"] == 16  # 8 entries per variant
    finally:
        SharedWarmCache(location=tier).cleanup()


# -- slot composition ---------------------------------------------------------

@needs_arena
def test_transform_hit_materializes_into_armed_slot(tmp_path):
    """A warm POST-TRANSFORM hit still composes with the process-pool
    transport: fixed-shape columns copy straight into an armed arena batch
    slot, exactly like decode-stage hits."""
    from petastorm_tpu.native import SharedArena
    from petastorm_tpu.native.transport import SlotAllocator, _slot_scope

    tier = SharedWarmCache(location=str(tmp_path / "tier"),
                           l1_bytes=16 * 2 ** 20)
    got = None
    arena = None
    try:
        transformed = ColumnBatch(
            {"x": np.arange(32, dtype=np.float32) * 2.0}, 32)
        tier.get("rg0|stage:xform1", lambda: transformed)
        tier.note_transform_event(hit=False)
        arena = SharedArena.create(8 * 2 ** 20)
        allocator = SlotAllocator(arena)
        with _slot_scope(allocator):
            got = tier.get("rg0|stage:xform1",
                           lambda: pytest.fail("should hit"))
        tier.note_transform_event(hit=True)
        assert allocator.claim(got.columns["x"]) is not None
        allocator.rollback_claims()
        allocator.finalize(None)
        stats = tier.stats()
        assert stats["transform_hits"] == 1
        assert stats["transform_stores"] == 1
    finally:
        if got is not None:
            del got
        if arena is not None:
            arena.close()
        tier.cleanup()


# -- determinism stays bit-identical with transform caching armed -------------

@needs_arena
def test_seed_stable_delivery_with_transform_cache(tmp_path):
    """deterministic='seed' + a warm transform tier: a cold 2-worker run
    and a warm 4-worker run over the same tier must produce IDENTICAL
    stream digests and delivered bytes, with the warm run provably served
    from the transform tier."""
    url = _write_ds(tmp_path / "ds")
    tier = str(tmp_path / "tier")

    def run(workers):
        tele = Telemetry()
        with make_batch_reader(url, reader_pool_type="thread",
                               workers_count=workers, shuffle_seed=7,
                               deterministic="seed", num_epochs=2,
                               cache_type="shared", cache_location=tier,
                               transform_spec=TransformSpec(
                                   _scaled(5), deterministic=True),
                               telemetry=tele) as r:
            payload = [bytes(np.ascontiguousarray(b.columns["x"]))
                       for b in r.iter_batches()]
            digest = r.diagnostics["stream_digest"]["combined"]
        return payload, digest, tele.snapshot()["counters"]

    try:
        cold_payload, cold_digest, _cold = run(2)
        warm_payload, warm_digest, warm = run(4)
        assert cold_digest == warm_digest
        assert cold_payload == warm_payload
        assert warm.get("cache.transform_hits", 0) >= 8, warm
    finally:
        SharedWarmCache(location=tier).cleanup()
