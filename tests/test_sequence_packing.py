"""Sequence packer unit behavior (ISSUE 11 tentpole b): deterministic
first-fit-shrinking packing into (batch, seq_len) blocks with document
segment IDs / positions / loss masks, ragged delivery, and the
packed-stream digest."""

import numpy as np
import pytest

from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.sequence.packing import (SequencePacker,
                                            iter_packed_blocks,
                                            iter_packed_rows,
                                            iter_ragged_batches,
                                            packed_stream_digest)


def _docs(*lengths, base=100):
    return [np.full(n, base + i, dtype=np.int32)
            for i, n in enumerate(lengths)]


def test_masks_segments_positions():
    rows = list(iter_packed_rows(_docs(3, 4, 9), seq_len=8))
    # docs of 3 and 4 share bin 0; the 9-token doc doesn't fit and opens
    # bin 1 ... wait, 9 > 8 so it splits into 8 + 1; the 8-chunk fills a
    # fresh bin (emitted), the 1-chunk joins bin 0 (3+4+1=8, emitted full)
    assert len(rows) == 2
    by_first = sorted(rows, key=lambda r: int(r["tokens"][0]))
    mixed = by_first[0]
    assert mixed["tokens"].tolist() == [100] * 3 + [101] * 4 + [102]
    assert mixed["segment_ids"].tolist() == [1] * 3 + [2] * 4 + [3]
    assert mixed["positions"].tolist() == [0, 1, 2, 0, 1, 2, 3, 0]
    assert mixed["loss_mask"].tolist() == [1.0] * 8
    full = by_first[1]
    assert full["tokens"].tolist() == [102] * 8
    assert full["segment_ids"].tolist() == [1] * 8


def test_padding_and_fill_rate():
    p = SequencePacker(10)
    rows = list(iter_packed_rows(_docs(6, 6), 10, packer=p))
    assert len(rows) == 2
    for r in rows:
        assert r["tokens"].tolist()[6:] == [0] * 4
        assert r["segment_ids"].tolist()[6:] == [0] * 4
        assert r["loss_mask"].tolist() == [1.0] * 6 + [0.0] * 4
    stats = p.stats()
    assert stats["rows"] == 2 and stats["tokens"] == 12
    assert stats["fill_rate"] == pytest.approx(0.6)


def test_exact_token_multiset_preserved():
    rng = np.random.default_rng(3)
    docs = [rng.integers(0, 1000, int(n), dtype=np.int32)
            for n in rng.integers(1, 50, 200)]
    rows = list(iter_packed_rows(iter(docs), seq_len=64))
    packed = np.concatenate([r["tokens"][r["loss_mask"] > 0] for r in rows])
    # per-row tokens stay in segment order; the multiset must be exact
    assert sorted(packed.tolist()) == sorted(
        np.concatenate(docs).tolist())
    # no doc straddles rows except via the long-doc split (none here)
    for r in rows:
        segs = r["segment_ids"][r["loss_mask"] > 0]
        assert (np.diff(segs) >= 0).all()


def test_deterministic_pure_function_of_order():
    rng = np.random.default_rng(5)
    docs = [rng.integers(0, 99, int(n), dtype=np.int32)
            for n in rng.integers(1, 40, 150)]
    a = list(iter_packed_blocks(iter(docs), 32, 4))
    b = list(iter_packed_blocks(iter(docs), 32, 4))
    assert packed_stream_digest(a) == packed_stream_digest(b)
    # a different ORDER is a different packed stream (order sensitivity of
    # both the packer and the digest)
    c = list(iter_packed_blocks(iter(docs[::-1]), 32, 4))
    assert packed_stream_digest(a) != packed_stream_digest(c)


def test_long_doc_policies():
    rows = list(iter_packed_rows(_docs(20), 8, long_docs="split"))
    assert len(rows) == 3  # 8 + 8 + 4
    assert sum(int(r["loss_mask"].sum()) for r in rows) == 20
    # each split chunk restarts positions (its own segment)
    assert rows[2]["positions"][:4].tolist() == [0, 1, 2, 3]

    p = SequencePacker(8, long_docs="truncate")
    rows = list(iter_packed_rows(_docs(20), 8, packer=p))
    assert len(rows) == 1 and int(rows[0]["loss_mask"].sum()) == 8
    assert p.stats()["docs_truncated"] == 1
    assert p.stats()["tokens"] == 8  # truncated tokens don't count

    with pytest.raises(PetastormTpuError, match="long_docs='error'"):
        list(iter_packed_rows(_docs(20), 8, long_docs="error"))


def test_empty_and_none_docs_skipped():
    p = SequencePacker(8)
    assert p.feed(None) == [] and p.feed(np.empty(0, np.int32)) == []
    assert p.feed(np.asarray([1, 2], np.int32)) == []
    rows = p.finish()
    assert len(rows) == 1
    assert p.stats()["docs_empty"] == 2 and p.stats()["docs"] == 1


def test_eviction_closes_most_shrunk_bin():
    p = SequencePacker(10, open_bins=2)
    assert p.feed(np.full(7, 1, np.int32)) == []   # bin A: used 7
    assert p.feed(np.full(5, 2, np.int32)) == []   # bin B: used 5
    # 6 fits neither; open set full -> bin A (least remaining) is evicted
    out = p.feed(np.full(6, 3, np.int32))
    assert len(out) == 1 and out[0]["tokens"][:7].tolist() == [1] * 7
    # finish: B then the fresh bin, in creation order
    tail = p.finish()
    assert [int(r["tokens"][0]) for r in tail] == [2, 3]


def test_packer_reuse_across_calls_with_finish_false():
    """finish=False keeps one packer (and its accounting) live across
    several iter_packed_rows calls; the last call closes the bins."""
    p = SequencePacker(8)
    first = list(iter_packed_rows(_docs(6), 8, packer=p, finish=False))
    assert first == []  # the 6-token doc sits in an open bin
    rows = list(iter_packed_rows(iter(_docs(2, 8, base=200)), 8, packer=p))
    assert p.stats()["docs"] == 3 and p.stats()["tokens"] == 16
    # the 2-token doc joined the first call's open bin
    joined = [r for r in rows if r["segment_ids"].max() == 2]
    assert len(joined) == 1 and joined[0]["tokens"][:6].tolist() == [100] * 6


def test_truncate_telemetry_counter_is_monotonic():
    """long_docs='truncate' must never add a negative correction to the
    monotonic tokens counter: only the kept length is counted."""
    from petastorm_tpu.telemetry import Telemetry

    tele = Telemetry()
    p = SequencePacker(8, long_docs="truncate", telemetry=tele)
    list(iter_packed_rows(_docs(20), 8, packer=p))
    assert tele.snapshot()["counters"]["sequence.tokens_packed"] == 8


def test_feed_after_finish_refused():
    p = SequencePacker(8)
    p.finish()
    with pytest.raises(PetastormTpuError, match="after finish"):
        p.feed(np.asarray([1], np.int32))


def test_blocks_shape_and_drop_last():
    docs = _docs(*[8] * 10)  # 10 full rows at seq_len 8
    blocks = list(iter_packed_blocks(iter(docs), 8, 4))
    assert [b["tokens"].shape for b in blocks] == [(4, 8), (4, 8), (2, 8)]
    blocks = list(iter_packed_blocks(iter(docs), 8, 4, drop_last=True))
    assert [b["tokens"].shape for b in blocks] == [(4, 8), (4, 8)]
    for b in blocks:
        assert set(b) == {"tokens", "segment_ids", "positions", "loss_mask"}


def test_ragged_batches():
    docs = [np.asarray([1, 2, 3], np.int64), None,
            np.asarray([4], np.int64), np.asarray([5, 6], np.int64),
            np.asarray([7], np.int64)]
    groups = list(iter_ragged_batches(iter(docs), 3))
    assert len(groups) == 2
    g = groups[0]
    assert g["tokens"].dtype == np.int32
    assert g["offsets"].tolist() == [0, 3, 3, 4]  # None -> zero-length span
    assert g["lengths"].tolist() == [3, 0, 1]
    assert g["tokens"].tolist() == [1, 2, 3, 4]
    assert groups[1]["lengths"].tolist() == [2, 1]
    # document i is tokens[offsets[i]:offsets[i+1]]
    assert g["tokens"][g["offsets"][0]:g["offsets"][1]].tolist() == [1, 2, 3]


def test_digest_chains_and_is_content_sensitive():
    blocks = list(iter_packed_blocks(iter(_docs(5, 5, 5, 5)), 8, 2))
    whole = packed_stream_digest(blocks)
    # chaining one block at a time equals one call over the stream
    crc = 0
    for b in blocks:
        crc = packed_stream_digest([b], crc=crc)
    assert crc == whole
    mutated = [dict(b) for b in blocks]
    mutated[0] = dict(mutated[0], tokens=mutated[0]["tokens"] + 1)
    assert packed_stream_digest(mutated) != whole


def test_packer_telemetry_series():
    from petastorm_tpu.telemetry import Telemetry

    tele = Telemetry()
    p = SequencePacker(8, telemetry=tele)
    list(iter_packed_rows(_docs(6, 6, 20), 8, packer=p))
    snap = tele.snapshot()
    assert snap["counters"]["sequence.docs_packed"] == 3
    assert snap["counters"]["sequence.tokens_packed"] == 32
    assert snap["counters"]["sequence.docs_split"] == 1
    assert snap["counters"]["sequence.rows_emitted"] == p.stats()["rows"]
    assert snap["counters"]["sequence.pad_tokens"] == \
        p.stats()["rows"] * 8 - 32
    assert snap["gauges"]["sequence.fill_rate"] == pytest.approx(
        p.fill_rate)


def test_invalid_args():
    with pytest.raises(PetastormTpuError):
        SequencePacker(0)
    with pytest.raises(PetastormTpuError):
        SequencePacker(8, open_bins=0)
    with pytest.raises(PetastormTpuError):
        SequencePacker(8, long_docs="explode")
    with pytest.raises(PetastormTpuError):
        list(iter_packed_blocks(iter([]), 8, 0))
    with pytest.raises(PetastormTpuError):
        list(iter_ragged_batches(iter([]), 0))
    with pytest.raises(PetastormTpuError, match="1-D"):
        SequencePacker(8).feed(np.zeros((2, 2), np.int32))
