"""Exact-size batch assembly across input-batch boundaries.

Reference parity: petastorm/pyarrow_helpers/batching_table_queue.py -
``BatchingTableQueue`` FIFO of record batches whose ``get()`` slices exact-size
batches spanning input-table boundaries (batching_table_queue.py:21-80).  Like
the reference's, this is a composable building block: the Reader's own batch
sizing goes through the shuffling-buffer engine (petastorm_tpu/shuffle.py), and
this queue serves consumers that need strict fixed-size batches from an
arbitrary stream of :class:`ColumnBatch`/arrow data - e.g. static-shape XLA
feeds where a ragged final batch would trigger recompilation.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Optional, Union

import pyarrow as pa

from petastorm_tpu.batch import ColumnBatch
from petastorm_tpu.errors import PetastormTpuError


def _to_column_batch(data) -> ColumnBatch:
    if isinstance(data, ColumnBatch):
        return data
    if isinstance(data, pa.RecordBatch):
        data = pa.Table.from_batches([data])
    if isinstance(data, pa.Table):
        return ColumnBatch({name: data.column(name).to_numpy(zero_copy_only=False)
                            for name in data.column_names}, data.num_rows)
    raise PetastormTpuError(
        f"BatchingQueue accepts ColumnBatch/pa.Table/pa.RecordBatch, got {type(data)}")


class BatchingQueue:
    """FIFO that re-slices an arbitrary stream of batches into exact-size ones.

    ``put`` appends any-size batches; ``get`` returns a batch of exactly
    ``batch_size`` rows assembled across input boundaries (raises if not enough
    rows are buffered - check :meth:`can_get`); ``flush`` drains the ragged
    remainder.  Slices stay views until a cross-boundary assembly forces a
    concat, mirroring the zero-copy intent of the reference
    (batching_table_queue.py:50-78).
    """

    def __init__(self, batch_size: int):
        if batch_size < 1:
            raise PetastormTpuError(f"batch_size must be >= 1, got {batch_size}")
        self._batch_size = batch_size
        self._queue: Deque[ColumnBatch] = deque()
        self._head_offset = 0  # rows of queue[0] already consumed
        self._buffered = 0

    def __len__(self) -> int:
        """Rows currently buffered."""
        return self._buffered

    @property
    def batch_size(self) -> int:
        return self._batch_size

    def empty(self) -> bool:
        return self._buffered == 0

    def can_get(self) -> bool:
        return self._buffered >= self._batch_size

    def put(self, data: Union[ColumnBatch, "pa.Table", "pa.RecordBatch"]) -> None:
        batch = _to_column_batch(data)
        if len(batch) == 0:
            return
        self._queue.append(batch)
        self._buffered += len(batch)

    def _take(self, nrows: int) -> ColumnBatch:
        parts = []
        need = nrows
        while need > 0:
            head = self._queue[0]
            avail = len(head) - self._head_offset
            take = min(avail, need)
            parts.append(head.slice_rows(self._head_offset,
                                         self._head_offset + take))
            need -= take
            self._head_offset += take
            if self._head_offset == len(head):
                self._queue.popleft()
                self._head_offset = 0
        self._buffered -= nrows
        return parts[0] if len(parts) == 1 else ColumnBatch.concat(parts)

    def get(self) -> ColumnBatch:
        if not self.can_get():
            raise PetastormTpuError(
                f"BatchingQueue has {self._buffered} rows buffered; need"
                f" {self._batch_size} (check can_get(), or flush() the tail)")
        return self._take(self._batch_size)

    def flush(self) -> Optional[ColumnBatch]:
        """Everything still buffered as one batch (callers drain exact-size
        batches with ``get`` first, making this the ragged tail), or None."""
        if self._buffered == 0:
            return None
        return self._take(self._buffered)
