"""TransformSpec tests (reference model: petastorm/transform.py contract)."""

import numpy as np
import pytest

from petastorm_tpu.errors import SchemaError
from petastorm_tpu.schema import Field, Schema
from petastorm_tpu.transform import TransformSpec, row_transform, transform_schema


def _schema():
    return Schema("s", [Field("a", np.int32), Field("b", np.float32, (2,)),
                        Field("c", np.int64)])


def test_transform_schema_edit_and_remove():
    spec = TransformSpec(edit_fields=[("b", np.float64, (4,), False),
                                      ("d", np.int8, (), True)],
                         removed_fields=["c"])
    out = transform_schema(_schema(), spec)
    assert [f.name for f in out] == ["a", "b", "d"]
    assert out.b.dtype == np.float64 and out.b.shape == (4,)
    assert out.d.nullable


def test_transform_schema_selected_fields_order():
    spec = TransformSpec(selected_fields=["c", "a"])
    out = transform_schema(_schema(), spec)
    assert [f.name for f in out] == ["c", "a"]
    with pytest.raises(SchemaError):
        transform_schema(_schema(), TransformSpec(selected_fields=["zz"]))


def test_columnar_transform_applies():
    spec = TransformSpec(func=lambda cols: {**cols, "a": cols["a"] * 2},
                         removed_fields=["c"])
    out = spec({"a": np.array([1, 2]), "b": np.zeros((2, 2)), "c": np.array([0, 0])})
    assert out["a"].tolist() == [2, 4] and "c" not in out


def test_row_transform_wrapper():
    fn = row_transform(lambda row: {"a": row["a"] + 1, "v": np.full(3, row["a"])})
    out = fn({"a": np.array([1, 2, 3])})
    assert out["a"].tolist() == [2, 3, 4]
    assert out["v"].shape == (3, 3)
