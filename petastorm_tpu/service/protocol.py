"""Wire protocol for the disaggregated ingest service.

Lifts ``pool.py``'s ventilate/results contract onto length-prefixed socket
frames carrying the **v2 binary wire** (:mod:`petastorm_tpu.service.wire`):
control messages are self-describing binary dicts, result batches are
schema'd column frames (header + raw buffers), and nothing that arrives on
a service socket is ever unpickled to be *parsed* - the data plane is
pickle-free end to end.

Frame format: a 4-byte big-endian payload length, a 1-byte frame kind,
then the body:

* ``KIND_CTRL``: one control dict (:func:`wire.dumps`).  All non-result
  messages - tagged by ``"t"``:

  ======================  =====================================================
  ``client_hello``        client -> dispatcher: client_id, opaque worker
                          factory blob, hostname, shm capability, accepted
                          codecs, requeue budget, ``resume`` flag.  The
                          ``hello_ok`` reply carries the dispatcher's
                          ``boot`` id (clients count
                          ``service.dispatcher_restarts`` off a change)
                          and the ``known`` ordinal list (a journal-armed
                          warm restart tells the client which resync
                          re-sends to skip)
  ``enqueue``             client -> dispatcher: one work item
                          (:class:`WireItem` fields - structural ordinal/
                          attempt/rowgroup metadata + an opaque item blob)
  ``resync``              client -> dispatcher after a reconnect: every item
                          still in the client's in-flight ledger (dispatcher
                          dedups by ordinal against its own state)
  ``ack``                 client -> dispatcher: delivered ordinals (frees the
                          dispatcher's redelivery buffer)
  ``client_stats``        client -> dispatcher: consumer starved-seconds delta
                          (fleet-size pressure - Dispatcher.scaling_signal)
  ``bye``                 client -> dispatcher: clean goodbye (purge state)
  ``worker_hello``        worker -> dispatcher: name, capacity, hostname,
                          codecs; on a REJOIN (dispatcher restart / link
                          blip survived with ``reconnect_attempts``) also
                          ``resume`` plus the ``assignments`` it is still
                          executing and the client ``jobs`` it holds - the
                          dispatcher records claims so a reconnecting
                          client's resync re-attaches instead of
                          double-assigning
  ``heartbeat``           worker -> dispatcher: busy count + telemetry counter
                          deltas (folded into ``service.fleet.*``)
  ``failure``             worker -> dispatcher -> client: one item's classified
                          failure (formatted traceback + kind + exc_type as
                          plain fields; the client recovers the failed item
                          from its own ledger - no object rides the wire)
  ``job``                 dispatcher -> worker: a client's opaque factory blob
                          plus the negotiated shm flag and wire codec for the
                          pair (sent once per (worker, client))
  ``job_done``            dispatcher -> worker: drop that client's factory
  ``work``                dispatcher -> worker: one assigned item (WireItem)
  ``requeued``            dispatcher -> client: an in-flight item was requeued
                          off a dead worker (accounting notice)
  ``stats?``/``stats``    any -> dispatcher: state snapshot (CLI, tests)
  ``hb_ok``               dispatcher -> worker: heartbeat reply carrying the
                          dispatcher ``epoch`` (split-brain fencing: a
                          deposed primary's lower epoch is refused -
                          ``hello_ok`` carries the same field)
  ``drained?``            retiring worker -> dispatcher: "is anything still
                          assigned to me?"; answered ``drain_ok`` (send the
                          goodbye) or ``drain_wait`` (results still in
                          flight) - the drain handshake is structural, not
                          a timing window
  ``standby_hello``       standby dispatcher -> primary: subscribe to the
                          journal tail.  The ``standby_ok`` reply carries
                          the primary's ``epoch`` + ``boot``; then the
                          primary streams ``journal_sync`` frames
  ``journal_sync``        primary -> standby: journal records over the wire.
                          ``k``: ``snap`` (snapshot chunk, ``recs`` list) /
                          ``snap_end`` (snapshot complete) / ``rec`` (one
                          live tail record) / ``ping`` (idle keepalive);
                          every frame carries the primary's journal ``seq``
                          so the standby can meter its lag
                          (``service.standby_lag_items``)
  ======================  =====================================================

* ``KIND_BATCH``: one ``result`` outcome - a CTRL-encoded header (``t``,
  ordinal/attempt/rows, payload kind ``pk``, column specs, codec id)
  followed by the raw column buffers.  The dispatcher **relays the body as
  opaque bytes** (it parses only the header); the client rebuilds numpy
  columns as writable views over the received buffer - zero pickle, zero
  extra copies on the hot path.

Result payload kinds (``pk`` in the result header):

* ``"bin"`` - schema'd binary columns (the portable hot path, any host;
  body optionally compressed with the pair's negotiated codec);
* ``"shm"`` - the co-located fast path: the batch was encoded once into a
  named shared-memory arena (:mod:`petastorm_tpu.native.transport`) and
  only the descriptor crosses the socket.  Armed when both ends share a
  host AND the native transport plane is available (python >= 3.12
  PEP 688, like the process pool's shm transport);
* ``"pickle"`` - the counted fallback for results outside the wire domain
  (arbitrary worker-function outputs, unencodable transform columns).
  Decoding it is the ONE place a client may unpickle service bytes, it is
  metered (``service.frames_pickle_fallback``) so a hot fallback is
  visible, and ``ServiceExecutor(allow_pickle_results=False)`` (or
  ``PETASTORM_TPU_SERVICE_ALLOW_PICKLE=0``) refuses it outright as a
  classified failure.

.. note:: **Trust boundary (v2).**  No service endpoint unpickles anything
   to parse the wire: hellos, control frames, and result batches decode
   through the bounded binary codec, so reaching the dispatcher port no
   longer means code execution - a malicious peer can at worst present bad
   credentials or feed bogus tensors, which fail validation as classified
   errors.  ``pickle`` remains in exactly two trusted places: (1) the
   client->worker job plane - the worker factory and work-item blobs a
   token-holding client ships for the fleet to execute, relayed by the
   dispatcher as opaque bytes and unpickled only inside workers (running
   client code IS the service's job); (2) the client-side ``"pickle"``
   result fallback described above.  The handshake secret
   (:data:`AUTH_TOKEN_ENV` / ``auth_token=``) gates who may ship jobs;
   network isolation still applies for defense in depth - see
   docs/operations.md "Disaggregated ingest service".

Legacy peers: a v1 (pickled-frame) peer is detected by its first payload
byte (the pickle protocol opcode) without unpickling it, answered with a
v1-readable error frame, and disconnected - old clients fail loudly with
"protocol version mismatch" instead of desyncing.
"""

from __future__ import annotations

import hmac
import logging
import os
import pickle
import select
import socket
import struct
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from petastorm_tpu.batch import ColumnBatch
from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.service import wire
from petastorm_tpu.service.wire import (KIND_BATCH, KIND_CTRL,
                                        PICKLE_PROTO_BYTE, SUPPORTED_CODECS,
                                        WireFormatError)

logger = logging.getLogger(__name__)

#: protocol version, checked at hello time (bumped on incompatible change;
#: 2 = the pickle-free binary wire)
PROTOCOL_VERSION = 2

_LEN = struct.Struct("!I")
_U32 = struct.Struct("!I")
#: frames larger than this are refused (a decoded rowgroup batch is tens of
#: MB; anything approaching this is a corrupt length prefix, not data)
MAX_FRAME_BYTES = 1 << 30
#: a peer that cannot drain a frame for this long is declared dead (a
#: paused/SIGSTOPped trainer with a full TCP buffer must not wedge the
#: dispatcher thread sending to it - see FrameSocket send paths)
SEND_TIMEOUT_S = 30.0
#: non-blocking-send flag (0 where unsupported: send then degrades to the
#: old unbounded blocking behavior rather than breaking)
_MSG_DONTWAIT = getattr(socket, "MSG_DONTWAIT", 0)
#: environment variable all parties read their shared handshake secret
#: from (the CLI's --auth-token-file overrides it)
AUTH_TOKEN_ENV = "PETASTORM_TPU_SERVICE_TOKEN"
#: set to 0/false to make clients refuse ``"pickle"`` result payloads as
#: classified failures (hardened deployments; binary/shm results only)
ALLOW_PICKLE_ENV = "PETASTORM_TPU_SERVICE_ALLOW_PICKLE"

_KIND_CTRL_B = bytes([KIND_CTRL])
_KIND_BATCH_B = bytes([KIND_BATCH])


def resolve_auth_token(explicit: Optional[str] = None) -> Optional[str]:
    """The handshake secret: the explicit value if given, else
    :data:`AUTH_TOKEN_ENV`, else None (auth disabled)."""
    if explicit is not None:
        return explicit
    return os.environ.get(AUTH_TOKEN_ENV) or None


def resolve_allow_pickle(explicit: Optional[bool] = None) -> bool:
    """Whether this client accepts ``"pickle"`` result payloads: the
    explicit value if given, else :data:`ALLOW_PICKLE_ENV` (default on -
    arbitrary worker-function results need it; the binary plane carries
    every ColumnBatch regardless)."""
    if explicit is not None:
        return bool(explicit)
    return os.environ.get(ALLOW_PICKLE_ENV, "1").strip().lower() not in (
        "0", "false", "no", "off")


def token_matches(expected: Optional[str], presented: Any) -> bool:
    """Constant-time handshake token check (True when auth is off)."""
    if expected is None:
        return True
    if not isinstance(presented, str):
        return False
    return hmac.compare_digest(expected.encode(), presented.encode())


class FrameClosedError(PetastormTpuError):
    """The peer closed the connection (EOF mid-stream or before a frame)."""


class LegacyPickleFrameError(WireFormatError):
    """The peer sent a v1 pickled frame (detected by its first byte, never
    unpickled).  Listeners answer with a v1-readable refusal so the old
    peer fails loudly with a version message instead of desyncing."""


class WireItem:
    """Dispatcher-side view of one ventilated work item.

    The structural fields the dispatcher schedules on - ``ordinal``,
    ``attempt``, and the rowgroup-affinity key ``rg`` (``[path, index]`` or
    None) - travel as plain wire values; the work item itself is an opaque
    ``blob`` the dispatcher **never unpickles** (only the assigned worker
    does, to run the client's job - the same trust plane as the factory
    bootstrap).

    ``tc`` is the optional distributed-trace context: ``{"id": <trace id>,
    "hops": [[who, name, attempt, t_ns, off_ns], ...]}``.  Untraced items
    (the default) carry no ``tc`` key at all, so tracing is free on the
    wire when disarmed.  Every hop stamp records the stamping process
    (``who``: ``"d"`` for the dispatcher, else the worker name), the hop
    name, the item attempt it belongs to, a ``perf_counter_ns`` timestamp
    in the stamper's own clock, and that process's estimated offset to the
    dispatcher clock (``off_ns``; 0 for dispatcher stamps) - enough for
    the client to map every stamp into its own monotonic domain and merge
    the whole cross-process timeline into one Chrome trace.
    """

    __slots__ = ("ordinal", "attempt", "blob", "rg", "tc")

    def __init__(self, ordinal: int, attempt: int, blob: bytes, rg=None,
                 tc=None):
        self.ordinal = ordinal
        self.attempt = attempt
        self.blob = blob
        self.rg = rg
        self.tc = tc

    @classmethod
    def from_wire(cls, msg: Dict[str, Any]) -> "WireItem":
        ordinal, attempt = msg.get("o"), msg.get("a", 0)
        blob = msg.get("blob")
        if not isinstance(ordinal, int) or not isinstance(attempt, int) \
                or not isinstance(blob, (bytes, bytearray)):
            raise WireFormatError(f"malformed work item frame: {msg!r}")
        tc = msg.get("tc")
        if tc is not None and not isinstance(tc, dict):
            tc = None
        return cls(ordinal, attempt, bytes(blob), msg.get("rg"), tc)

    def to_wire(self) -> Dict[str, Any]:
        """Wire fields for a ``work`` frame (the inverse of
        :meth:`from_wire`)."""
        out = {"o": self.ordinal, "a": self.attempt, "blob": self.blob}
        if self.rg is not None:
            out["rg"] = self.rg
        if self.tc is not None:
            out["tc"] = self.tc
        return out

    @staticmethod
    def encode(item: Any, trace_id: Optional[int] = None) -> Dict[str, Any]:
        """Client-side: one pool ``VentilatedItem`` -> wire fields (the
        work payload is pickled into the opaque blob; rowgroup affinity
        metadata is lifted out structurally for the dispatcher).  Passing
        ``trace_id`` arms distributed tracing for this item: downstream
        hops append timing stamps to ``tc["hops"]`` and return them with
        the result."""
        work = getattr(item, "item", None)
        out = {"o": int(item.ordinal),
               "a": int(getattr(item, "attempt", 0)),
               "blob": pickle.dumps(work, protocol=pickle.HIGHEST_PROTOCOL)}
        rg = getattr(work, "row_group", None)
        if rg is not None:
            out["rg"] = [str(getattr(rg, "path", "")),
                         int(getattr(rg, "row_group", 0))]
        if trace_id is not None:
            out["tc"] = {"id": int(trace_id), "hops": []}
        return out


class _PayloadPool:
    """Recycles large frame-payload buffers for one FrameSocket.

    A data-plane socket receives a steady stream of near-identical multi-MB
    result frames; allocating and freeing each through malloc makes hot-path
    throughput hostage to process-wide allocator tuning (a raised
    ``MALLOC_MMAP_THRESHOLD_``, set for the decode plane's pooling,
    measurably slowed the relay).  The pool retains up to ``MAX`` slabs and
    lends one out when **no other reference exists** (``sys.getrefcount`` -
    the numpy views a decoded batch builds over the buffer keep its
    refcount elevated exactly as long as the data is alive, so a slab is
    reused only after its previous frame's consumers are done).  Single
    consumer per socket, like ``recv`` itself - no locking.
    """

    MAX_SLABS = 16
    MIN_BYTES = 1 << 20

    __slots__ = ("_slabs",)

    def __init__(self):
        self._slabs: List[bytearray] = []

    def take(self, length: int) -> bytearray:
        if length < self.MIN_BYTES:
            return bytearray(length)
        stale = None
        for i, ba in enumerate(self._slabs):
            # 3 = the slabs-list entry, the loop variable, and the
            # getrefcount argument: nothing else holds this slab
            if sys.getrefcount(ba) == 3:
                if len(ba) == length:
                    return ba
                if stale is None:
                    stale = i
        out = bytearray(length)
        if stale is not None:
            # variable-size streams (compressed bodies, uneven rowgroups)
            # rarely repeat a length: REPLACE a free wrong-size slab so the
            # pool never pins dead multi-MB buffers for the connection's
            # lifetime
            self._slabs[stale] = out
        elif len(self._slabs) < self.MAX_SLABS:
            self._slabs.append(out)
        return out

    def clear(self) -> None:
        self._slabs.clear()


class FrameSocket:
    """A socket speaking length-prefixed v2 binary frames.

    Sends are thread-safe (one lock per socket: the dispatcher's pump and
    reply paths send to the same worker from different threads).  ``recv``
    has a single consumer per socket (each connection gets one reader
    thread) and keeps partial frames across timeouts.

    ``send_timeout_s`` bounds how long one send may block on a peer that
    stops draining its TCP buffer; expiry declares the peer dead (the
    socket is closed - a partial frame would desync the stream anyway) and
    raises OSError, which every caller already treats as a dead peer.
    """

    def __init__(self, sock: socket.socket,
                 send_timeout_s: float = SEND_TIMEOUT_S):
        try:
            # small control frames must not sit in Nagle buffers behind a
            # large result frame; best-effort (AF_UNIX sockets refuse it)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        # multi-MB result frames: default loopback socket buffers (~200KB)
        # force dozens of wakeup round-trips per frame, which on a shared
        # core serializes against decode; best-effort enlarge
        for opt in (socket.SO_SNDBUF, socket.SO_RCVBUF):
            try:
                sock.setsockopt(socket.SOL_SOCKET, opt, 1 << 22)
            except OSError:
                pass
        # blocking mode, permanently: recv timeouts use select (see
        # _recv_some), so a send can never inherit a recv timeout and die
        # mid-frame
        sock.settimeout(None)
        self._sock = sock
        self._send_lock = threading.Lock()
        # partial-frame state, kept across recv timeouts: the 4-byte length
        # prefix, then the exact-size payload bytearray filled IN PLACE by
        # recv_into - one user-space copy per received byte, total (the
        # decoded numpy views alias this same buffer)
        self._hdr = bytearray(_LEN.size)
        self._hdr_filled = 0
        self._payload: Optional[bytearray] = None
        self._payload_filled = 0
        self._pool = _PayloadPool()
        self._closed = False
        self.send_timeout_s = send_timeout_s
        #: cumulative frame bytes (telemetry: service.frame_bytes_*)
        self.bytes_sent = 0
        self.bytes_received = 0

    # -- sending --------------------------------------------------------------

    def send(self, msg: Dict[str, Any]) -> int:
        """Encode + frame + bounded write of one control dict; returns the
        frame size in bytes.  Raises OSError when the connection is gone or
        the peer stops draining for longer than ``send_timeout_s`` (the
        socket is then closed: a partially-written frame cannot be
        resumed); :class:`WireFormatError` when ``msg`` holds values
        outside the wire domain (a caller bug, not a peer failure)."""
        return self._write_frame([_KIND_CTRL_B + wire.dumps(msg)])

    def send_batch(self, header: Dict[str, Any], parts: List[Any]) -> int:
        """Send one BATCH frame: a control-encoded ``header`` followed by
        raw body buffers, written **vectored** - the (possibly tens-of-MB)
        parts are never concatenated into a staging buffer.  Parts may be
        bytes/bytearray/memoryview (e.g. views straight over numpy column
        memory or a relayed body)."""
        encoded = wire.dumps(header)
        head = _KIND_BATCH_B + _U32.pack(len(encoded)) + encoded
        return self._write_frame([head, *parts])

    def send_legacy_error(self, message: str) -> int:
        """Answer a v1 (pickled-protocol) peer in the ONE format it can
        read: a pickled error frame.  ``pickle.dumps`` of our own literal
        is safe (only ``loads`` of attacker bytes is not); this exists so
        old clients fail loudly with the version message instead of
        crashing on undecodable bytes."""
        payload = pickle.dumps({"t": "error", "error": message}, protocol=2)
        return self._write_frame([payload])

    def _write_frame(self, chunks: List[Any]) -> int:
        total = sum(len(c) for c in chunks)
        if total > MAX_FRAME_BYTES:
            raise PetastormTpuError(
                f"frame of {total} bytes exceeds MAX_FRAME_BYTES")
        # the length prefix rides the first (always small) chunk so a
        # control frame is one send() syscall
        chunks = [_LEN.pack(total) + bytes(chunks[0]), *chunks[1:]]
        with self._send_lock:
            if self._closed:
                raise OSError("frame socket is closed")
            deadline = (None if self.send_timeout_s is None
                        else time.monotonic() + self.send_timeout_s)
            for chunk in chunks:
                deadline = self._drain(memoryview(chunk).cast("B"), deadline,
                                       total)
            self.bytes_sent += _LEN.size + total
        return _LEN.size + total

    def _drain(self, view: memoryview, deadline: Optional[float],
               frame_size: int) -> Optional[float]:
        """Write one chunk with the bounded-stall policy; returns the
        (possibly re-armed) deadline for the next chunk.  Caller holds the
        send lock."""
        while view:
            if deadline is None:
                remaining = None
            else:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self.close()
                    raise OSError(
                        f"peer did not drain a {frame_size}-byte frame"
                        f" within {self.send_timeout_s}s; declaring it"
                        " dead")
            try:
                # non-blocking attempt first, select only on a full
                # buffer: AF_UNIX sockets report not-writable long
                # before a blocking send would block, so select-first
                # would falsely time out on merely-slow local peers
                sent = self._sock.send(view, _MSG_DONTWAIT)
                view = view[sent:]
                if sent and deadline is not None:
                    # the timeout bounds a DRAIN STALL, not the whole
                    # frame: a peer accepting bytes - however slowly -
                    # is alive, so progress re-arms the deadline (a
                    # tens-of-MB result on a slow link must not be
                    # declared dead mid-transfer)
                    deadline = time.monotonic() + self.send_timeout_s
            except BlockingIOError:
                # buffer genuinely full: wait for drain with a deadline
                # so a stalled peer blocks HERE boundedly, never inside
                # a blocking sendall.  Short slices, because AF_UNIX
                # writability is stricter than EAGAIN - a slowly
                # draining peer can accept sends while select still
                # reports not-writable
                wait = 0.05 if remaining is None else min(remaining, 0.05)
                try:
                    select.select([], [self._sock], [], wait)
                except ValueError as exc:
                    # select on a concurrently-closed socket (fd -1)
                    raise OSError(
                        f"frame socket closed mid-send: {exc}") from exc
        return deadline

    # -- receiving ------------------------------------------------------------

    def recv(self, timeout: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """Next message, or None on timeout (partial frames are kept and
        completed by later calls).  Raises FrameClosedError on EOF and
        :class:`WireFormatError` on an undecodable frame (the frame was
        fully consumed first, so the stream itself stays synced).  BATCH
        frames return their header dict with the raw body attached under
        ``"_body"`` (a writable buffer - the zero-copy decode substrate).
        One deadline covers header AND body: the call returns within
        ``timeout`` total, not per fill."""
        if self._closed:
            raise FrameClosedError("frame socket is closed")
        deadline = None if timeout is None else time.monotonic() + timeout
        while self._hdr_filled < _LEN.size:
            n = self._recv_some(
                memoryview(self._hdr)[self._hdr_filled:], deadline)
            if n is None:
                return None
            self._hdr_filled += n
        if self._payload is None:
            (length,) = _LEN.unpack(self._hdr)
            if length > MAX_FRAME_BYTES:
                raise PetastormTpuError(
                    f"incoming frame claims {length} bytes (corrupt"
                    " stream?)")
            self._payload = self._pool.take(length)
            self._payload_filled = 0
        view = memoryview(self._payload)
        while self._payload_filled < len(self._payload):
            n = self._recv_some(view[self._payload_filled:], deadline)
            if n is None:
                return None
            self._payload_filled += n
        payload = self._payload
        del view
        self._payload = None
        self._hdr_filled = 0
        self.bytes_received += _LEN.size + len(payload)
        return self._parse(payload)

    @staticmethod
    def _parse(payload) -> Dict[str, Any]:
        if not len(payload):
            raise WireFormatError("empty frame")
        kind = payload[0]
        if kind == KIND_CTRL:
            msg = wire.loads(payload, 1)
            if not isinstance(msg, dict):
                raise WireFormatError(
                    f"control frame decodes to {type(msg).__name__},"
                    " expected a message dict")
            return msg
        if kind == KIND_BATCH:
            if len(payload) < 1 + _U32.size:
                raise WireFormatError("truncated batch frame header")
            (hlen,) = _U32.unpack_from(payload, 1)
            body_at = 1 + _U32.size + hlen
            if body_at > len(payload):
                raise WireFormatError(
                    f"batch frame claims a {hlen}-byte header inside a"
                    f" {len(payload)}-byte payload")
            msg = wire.loads(payload, 1 + _U32.size, body_at)
            if not isinstance(msg, dict):
                raise WireFormatError("batch header is not a message dict")
            # writable view, zero-copy: numpy columns decode straight over
            # the received buffer (the bytearray stays alive via the view)
            msg["_body"] = memoryview(payload)[body_at:]
            return msg
        if kind == PICKLE_PROTO_BYTE:
            raise LegacyPickleFrameError(
                "peer sent a v1 pickled frame; this endpoint speaks the v2"
                " binary wire (pickle frames are refused, never loaded -"
                " upgrade the peer)")
        raise WireFormatError(f"unknown frame kind 0x{kind:02x}")

    def _recv_some(self, view: memoryview, deadline: Optional[float]):
        """Receive up to ``len(view)`` bytes INTO ``view`` (one user-space
        copy, straight from the kernel); returns the byte count, or None
        once ``deadline`` (an absolute monotonic instant) passes.  Raises
        FrameClosedError on EOF.

        Non-blocking attempt first, select only when the buffer is empty.
        Timeouts come from ``select``, NOT ``settimeout``: a socket timeout
        is socket-global, so setting one for recv would also arm it for a
        concurrent send on another thread - which can then raise after a
        PARTIAL write of a large frame and permanently desync the
        length-prefixed stream."""
        while True:
            if not _MSG_DONTWAIT:
                # platform without MSG_DONTWAIT: select-first so the
                # blocking recv_into below cannot ignore the deadline
                remaining = (None if deadline is None
                             else max(0.0, deadline - time.monotonic()))
                try:
                    readable, _, _ = select.select([self._sock], [], [],
                                                   remaining)
                except ValueError as exc:
                    raise FrameClosedError(
                        f"frame socket closed locally: {exc}") from exc
                if not readable:
                    return None
            try:
                n = self._sock.recv_into(view, min(len(view), 1 << 22),
                                         _MSG_DONTWAIT)
            except BlockingIOError:
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                else:
                    remaining = None
                try:
                    readable, _, _ = select.select([self._sock], [], [],
                                                   remaining)
                except ValueError as exc:
                    # select on a locally-closed socket (fd -1, e.g. a
                    # send-timeout death on another thread): same terminal
                    # condition as EOF, and it must map to FrameClosedError
                    # so read loops reconnect instead of crashing
                    raise FrameClosedError(
                        f"frame socket closed locally: {exc}") from exc
                if not readable:
                    return None
                continue
            except OSError as exc:
                raise FrameClosedError(f"connection lost: {exc}") from exc
            except ValueError as exc:
                raise FrameClosedError(
                    f"frame socket closed locally: {exc}") from exc
            if n == 0:
                raise FrameClosedError("peer closed the connection")
            return n

    def close(self) -> None:
        """Shutdown + close; a blocked peer recv sees EOF immediately."""
        self._closed = True
        self._pool.clear()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


def connect_frames(address: Tuple[str, int],
                   timeout: float = 10.0) -> FrameSocket:
    """Open a FrameSocket to ``(host, port)`` (connect-timeout bounded)."""
    sock = socket.create_connection(address, timeout=timeout)
    sock.settimeout(None)
    return FrameSocket(sock)


def parse_address(address) -> Tuple[str, int]:
    """'host:port' / (host, port) -> (host, port).  The one place the CLI,
    client and tests agree on the address syntax."""
    if isinstance(address, (tuple, list)) and len(address) == 2:
        return str(address[0]), int(address[1])
    if isinstance(address, str) and ":" in address:
        host, _, port = address.rpartition(":")
        return host or "127.0.0.1", int(port)
    raise PetastormTpuError(
        f"service address must be 'host:port' or (host, port); got {address!r}")


def parse_address_list(address) -> List[Tuple[str, int]]:
    """Failover address syntax: ``'a:p'``/``(host, port)`` (one address) or
    ``'a:p,b:p'`` - a primary-then-standby list clients, workers and the
    autoscale prober rotate through on connection loss (docs/operations.md
    "Dispatcher HA")."""
    if isinstance(address, str) and "," in address:
        parts = [p.strip() for p in address.split(",") if p.strip()]
        if not parts:
            raise PetastormTpuError(
                f"service address list is empty: {address!r}")
        return [parse_address(p) for p in parts]
    return [parse_address(address)]


# -- result payload encoding --------------------------------------------------

def shm_transport_available() -> bool:
    """True when the native arena transport can carry local-fast-path
    payloads in this process (same gate as the process pool's shm plane)."""
    from petastorm_tpu.native import is_available

    return is_available()


def _ref_to_wire(ref) -> Dict[str, Any]:
    """ShmBatchRef -> wire fields (tuples become lists; inline values ride
    the control codec)."""
    return {"offset": ref.offset, "total": ref.total_bytes,
            "rows": ref.num_rows, "ordinal": ref.ordinal,
            "cols": {name: list(entry) for name, entry in ref.columns.items()}}


def _ref_from_wire(msg: Any):
    """Wire fields -> ShmBatchRef (bounds beyond these shapes are enforced
    by the arena view math in :func:`native.transport.decode_batch`)."""
    from petastorm_tpu.native.transport import ShmBatchRef

    if not isinstance(msg, dict) or not isinstance(msg.get("cols"), dict):
        raise WireFormatError(f"malformed shm batch descriptor: {msg!r}")
    return ShmBatchRef(
        offset=msg.get("offset"), total_bytes=int(msg.get("total", 0)),
        num_rows=int(msg.get("rows", 0)),
        columns={name: tuple(entry)
                 for name, entry in msg["cols"].items()},
        ordinal=msg.get("ordinal"))


def _wire_safe_inline(batch: ColumnBatch) -> bool:
    """True when every column that would ride inline (object dtype, empty,
    non-array) is inside the binary wire domain - checked BEFORE an arena
    encode so a doomed descriptor never strands an allocated block."""
    import numpy as np  # deferred with the rest of the batch plane

    for col in batch.columns.values():
        if (isinstance(col, np.ndarray) and col.dtype != object
                and not col.dtype.hasobject and col.nbytes > 0):
            continue
        try:
            wire.dumps(col)
        except WireFormatError:
            return False
    return True


def encode_result(value: Any, arena=None, stop_check=None,
                  codec: str = "") -> Tuple[Dict[str, Any], List[Any]]:
    """Worker-side payload encoding -> ``(header fields, body parts)``.

    With a live ``arena`` (local fast path negotiated) ColumnBatches go
    through :func:`petastorm_tpu.native.transport.encode_batch` - one
    producer-side copy into shared memory, a small ``"shm"`` descriptor on
    the wire.  Otherwise ColumnBatches travel as ``"bin"`` schema'd column
    frames (header + raw buffers, optionally ``codec``-compressed) - zero
    pickle.  Anything outside the wire domain (arbitrary worker results,
    unencodable columns) ships as the counted ``"pickle"`` fallback.
    """
    if isinstance(value, ColumnBatch):
        # the inline pre-probe runs ONLY before an arena encode (a doomed
        # descriptor would strand an allocated block); the plain binary
        # path lets encode_batch_parts run its own probe once
        if arena is not None and _wire_safe_inline(value):
            from petastorm_tpu.native.transport import ShmBatchRef, \
                encode_batch

            ref = encode_batch(arena, value, stop_check=stop_check)
            if isinstance(ref, ShmBatchRef):
                return ({"pk": "shm", "arena": arena.name,
                         "ref": _ref_to_wire(ref)}, [])
            value = ref  # encode fell back (arena full): go binary
        enc = wire.encode_batch_parts(value, codec=codec)
        if enc is not None:
            header, parts = enc
            header["pk"] = "bin"
            return header, parts
    return ({"pk": "pickle"},
            [pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)])


class PayloadDecoder:
    """Client-side payload decoding; caches attached arenas by name so the
    local fast path attaches each worker's arena once, not per batch.

    ``allow_pickle=False`` turns ``"pickle"`` fallback payloads into
    classified :class:`WireFormatError` failures instead of unpickling
    (the hardened posture - see :func:`resolve_allow_pickle`)."""

    def __init__(self, allow_pickle: bool = True):
        self._arenas: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self.allow_pickle = allow_pickle

    def decode(self, msg: Dict[str, Any]) -> Any:
        """Rebuild one result payload from its frame header (+ attached
        ``"_body"`` buffer): ``"bin"`` builds zero-copy numpy views,
        ``"shm"`` attaches the named arena and decodes the descriptor,
        ``"pickle"`` unpickles (only when allowed)."""
        pk = msg.get("pk")
        body = msg.get("_body") or b""
        if pk == "bin":
            return wire.decode_batch_body(msg, body)
        if pk == "shm":
            from petastorm_tpu.native import SharedArena
            from petastorm_tpu.native.transport import decode_batch

            name = msg.get("arena")
            if not isinstance(name, str):
                raise WireFormatError("shm payload without an arena name")
            with self._lock:
                arena = self._arenas.get(name)
                if arena is None:
                    arena = SharedArena.attach(name)
                    self._arenas[name] = arena
            return decode_batch(arena, _ref_from_wire(msg.get("ref")))
        if pk == "pickle":
            if not self.allow_pickle:
                raise WireFormatError(
                    "peer sent a pickle-fallback result and this client"
                    " refuses them (allow_pickle_results=False /"
                    f" ${ALLOW_PICKLE_ENV}=0); only binary/shm payloads"
                    " are accepted")
            return pickle.loads(bytes(body))
        raise WireFormatError(f"unknown payload kind {pk!r}")

    def close(self) -> None:
        """Detach every cached arena (held zero-copy views stay valid
        until collected, like the process pool's arena close)."""
        with self._lock:
            for arena in self._arenas.values():
                try:
                    arena.close()
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass
            self._arenas.clear()
