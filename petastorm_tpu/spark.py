"""Spark interop: decoded-row RDD over a petastorm_tpu (or legacy) dataset.

Reference parity: petastorm/spark_utils.py:23-53 - ``dataset_as_rdd`` reads the
parquet store as a Spark DataFrame and decodes each row with the dataset schema's
codecs on the executors, yielding schema namedtuples.

pyspark is not a dependency of this package (TPU ingest does not need a JVM);
everything here gates on its presence at call time.  The Spark *writer* path is
:mod:`petastorm_tpu.converter` (accepts a pyspark DataFrame when available).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

from petastorm_tpu.etl.metadata import open_dataset
from petastorm_tpu.schema import Schema


def _require_pyspark():
    try:
        import pyspark  # noqa: F401
    except ImportError as exc:
        raise NotImplementedError(
            "dataset_as_rdd requires pyspark, which is not installed. The"
            " TPU-native consumers are make_reader/make_jax_loader; Spark"
            " interop is optional.") from exc


def decode_row(row: Dict[str, Any], schema: Schema) -> Dict[str, Any]:
    """Apply each field's codec to one storage-form row dict.

    Row-level analog of the columnar decode plane (petastorm_tpu/worker.py);
    exists for executors that hand us rows, like Spark (reference
    utils.py:54-87).
    """
    out = {}
    for field in schema:
        value = row.get(field.name)
        out[field.name] = None if value is None else field.codec.decode(field, value)
    return out


def dataset_as_rdd(dataset_url: str, spark_session,
                   schema_fields: Optional[Sequence] = None):
    """Decoded-row RDD of schema namedtuples for a dataset.

    :param dataset_url: dataset URL (any scheme Spark itself can read)
    :param spark_session: a ``pyspark.sql.SparkSession``
    :param schema_fields: optional field names/regexes/Field objects to subset
    """
    _require_pyspark()
    info = open_dataset(dataset_url, require_stored_schema=True)
    schema = info.stored_schema
    df = spark_session.read.parquet(dataset_url)
    if schema_fields is not None:
        schema = schema.view(schema_fields)
        df = df.select(*list(schema.fields))
    # default arguments freeze the objects Spark must ship to executors; the
    # lambda itself must not close over `info` (holds a live filesystem)
    return df.rdd.map(
        lambda row, _schema=schema: _schema.make_namedtuple(
            **decode_row(row.asDict(), _schema)))


__all__ = ["dataset_as_rdd", "decode_row"]
