"""Single-span rowgroup prefetch: kill remote read amplification.

BENCH_r05 measured the pre_buffer path at ~1.7 ranged reads per rowgroup for
an 8-column dataset - arrow's lazy cache coalesces *adjacent* column chunks
but still splits a rowgroup across reads when chunk gaps exceed its hole
limit, and every read pays the object store's per-request latency.  This
module sizes the window ITSELF: a rowgroup's needed column chunks occupy one
contiguous byte span (parquet lays chunks out back to back), so the worker
computes the span from file metadata and fetches it in ONE ranged read
before ``read_row_group``; every chunk read then hits the window buffer.

``WindowedFile`` is a python file-object adapter over a pyarrow
``NativeFile`` (wrap it back with ``pa.PythonFile`` for parquet).  Arrow
serializes ReadAt as lock+seek+read on PythonFile objects, and a lock here
keeps explicit ``prefetch`` calls safe against parquet's IO threads anyway.

Telemetry (folded by the worker): ``io.read_calls`` (raw ranged reads
issued), ``io.rowgroups_read``, and the ``io.reads_per_rowgroup`` gauge
(reads the LAST rowgroup cost - 1.0 when the window covers it).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

#: never window a span larger than this (a single huge rowgroup should
#: stream through arrow's own chunked reads, not sit in one buffer)
MAX_WINDOW_BYTES = 256 * 1024 * 1024

#: skip the window when the contiguous span is this much larger than the
#: chunks actually needed (column-pruned reads of far-apart columns would
#: amplify bytes to save requests; let pre_buffer handle those)
MAX_SPAN_WASTE_RATIO = 1.5
MAX_SPAN_WASTE_BYTES = 8 * 1024 * 1024


def rowgroup_span(metadata, row_group: int,
                  columns: Optional[Sequence[str]] = None
                  ) -> Optional[Tuple[int, int, int]]:
    """(start, length, needed_bytes) of the byte span covering ``columns``
    of ``row_group`` (all columns when None/empty), or None when the span
    fails the amplification guards (see module docstring)."""
    rg = metadata.row_group(row_group)
    start = None
    end = None
    needed = 0
    wanted = set(columns) if columns else None
    for j in range(rg.num_columns):
        col = rg.column(j)
        if wanted is not None:
            # nested columns stamp 'a.b.c'; match the root name like arrow
            root = col.path_in_schema.split(".", 1)[0]
            if root not in wanted:
                continue
        lo = col.data_page_offset
        if col.dictionary_page_offset is not None:
            lo = min(lo, col.dictionary_page_offset)
        hi = lo + col.total_compressed_size
        needed += col.total_compressed_size
        start = lo if start is None else min(start, lo)
        end = hi if end is None else max(end, hi)
    if start is None:
        return None
    length = end - start
    if length > MAX_WINDOW_BYTES:
        return None
    if length > needed * MAX_SPAN_WASTE_RATIO + MAX_SPAN_WASTE_BYTES:
        return None
    return start, length, needed


class WindowedFile:
    """File-object protocol over a pyarrow ``NativeFile`` with an explicit
    one-read prefetch window and a raw-read counter."""

    def __init__(self, inner):
        self._inner = inner
        self._lock = threading.Lock()
        self._pos = 0
        self._size: Optional[int] = None
        self._win_start = 0
        self._win: bytes = b""
        #: ranged reads actually issued against the underlying file
        self.raw_reads = 0
        self.closed = False

    # -- window ---------------------------------------------------------------

    def prefetch(self, start: int, length: int) -> bool:
        """Fetch ``[start, start+length)`` in ONE raw read; subsequent reads
        inside the window are served from memory.  Replaces any previous
        window (rowgroups are read one at a time per worker)."""
        with self._lock:
            if (start >= self._win_start
                    and start + length <= self._win_start + len(self._win)):
                return True  # already covered
            try:
                self._inner.seek(start)
                buf = self._inner.read(length)
            except Exception:  # noqa: BLE001 - fall back to direct reads
                logger.debug("window prefetch failed", exc_info=True)
                return False
            self.raw_reads += 1
            self._win_start = start
            self._win = buf
            return True

    def discard_window(self) -> None:
        with self._lock:
            self._win = b""

    # -- python file protocol (what pa.PythonFile needs) ----------------------

    def read(self, nbytes: int = -1) -> bytes:
        with self._lock:
            if nbytes is None or nbytes < 0:
                self._inner.seek(self._pos)
                out = self._inner.read()
                self.raw_reads += 1
            else:
                lo = self._pos - self._win_start
                if 0 <= lo and lo + nbytes <= len(self._win):
                    out = self._win[lo:lo + nbytes]
                else:
                    self._inner.seek(self._pos)
                    out = self._inner.read(nbytes)
                    self.raw_reads += 1
            self._pos += len(out)
            return out

    def seek(self, offset: int, whence: int = 0) -> int:
        with self._lock:
            if whence == 0:
                self._pos = offset
            elif whence == 1:
                self._pos += offset
            elif whence == 2:
                self._pos = self._file_size() + offset
            else:
                raise ValueError(f"bad whence {whence}")
            return self._pos

    def tell(self) -> int:
        return self._pos

    def _file_size(self) -> int:
        if self._size is None:
            self._size = self._inner.size()
        return self._size

    def readable(self) -> bool:
        return True

    def writable(self) -> bool:
        return False

    def seekable(self) -> bool:
        return True

    def flush(self) -> None:
        pass

    def close(self) -> None:
        with self._lock:
            if not self.closed:
                self.closed = True
                self._win = b""
                try:
                    self._inner.close()
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass
