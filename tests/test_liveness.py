"""Liveness layer: per-item deadlines, hung-worker kill-and-replace,
straggler hedging, and the storage circuit breaker (ISSUE 3 tentpole).

The production contract under test: a worker that HANGS (stuck blocking GCS
read, pathological decode, C-level deadlock) - as opposed to one that dies,
which PR 2 already covers - must not stall the epoch.  With
``make_reader(item_deadline_s=...)`` the hung worker is SIGKILLed and
respawned (process pool) or its slot abandoned (thread pool), the item is
requeued through the attempt budget, and the epoch completes with the exact
healthy-row multiset; ``hedge_after_s`` speculatively re-issues stragglers
with first-result-wins dedup; consecutive transient-IO failures open a
circuit breaker that fails fast instead of compounding retry storms.  The
same scenario WITHOUT a deadline still stalls (proving the layer is
load-bearing), now surfacing as PipelineStallError via the first-class
``stall_abort_s`` kwarg.
"""

import logging
import queue
import time

import numpy as np
import pytest

from petastorm_tpu.errors import (CircuitOpenError, ErrorPolicy,
                                  PetastormTpuError, classify_error)
from petastorm_tpu.etl.writer import write_dataset
from petastorm_tpu.pool import (PipelineStallError, ThreadedExecutor,
                                VentilatedItem, WorkerError, make_executor)
from petastorm_tpu.reader import make_batch_reader
from petastorm_tpu.retry import (CircuitBreaker, RetryPolicy,
                                 make_circuit_breaker, retry_call)
from petastorm_tpu.schema import Field, Schema
from petastorm_tpu.telemetry import Telemetry, render_pipeline_report
from petastorm_tpu.test_util.chaos import ChaosSpec
from petastorm_tpu.test_util.stub_workers import SleepyWorker

SCHEMA = Schema("Liveness", [Field("x", np.int64)])
N_ROWS = 40
RG_ROWS = 4  # 10 rowgroups of 4 rows


def _write(tmp_path):
    url = str(tmp_path / "ds")
    write_dataset(url, SCHEMA, [{"x": i} for i in range(N_ROWS)],
                  row_group_size_rows=RG_ROWS)
    return url


def _rows_of_rowgroups(ordinals):
    out = set()
    for o in ordinals:
        out |= set(range(o * RG_ROWS, (o + 1) * RG_ROWS))
    return out


# -- chaos hang injection ------------------------------------------------------

def test_chaos_hang_spec_parse_gate_and_determinism():
    spec = ChaosSpec.parse(
        "hang_ordinals=2;5,hang_s=9,hang_on_retry=true,hang_rate=0.0,seed=3")
    assert spec.hang_ordinals == (2, 5)
    assert spec.hang_s == 9.0 and spec.hang_on_retry
    assert spec.affects_worker()
    # attempt gate mirrors kills: a requeued/hedged copy does not re-hang
    # unless hang_on_retry
    assert spec.should_hang(2, attempt=1)  # hang_on_retry=true in the spec
    gated = ChaosSpec(hang_ordinals=(2,))
    assert gated.should_hang(2, attempt=0)
    assert not gated.should_hang(2, attempt=1)
    # rate-based decisions are pure functions of (seed, kind, ordinal)
    rated = ChaosSpec(seed=1, hang_rate=0.5)
    picks = [rated.should_hang(i) for i in range(100)]
    assert picks == [rated.should_hang(i) for i in range(100)]
    assert 20 < sum(picks) < 80
    with pytest.raises(PetastormTpuError):
        ChaosSpec(hang_rate=1.5)


# -- circuit breaker units -----------------------------------------------------

class _FakeClock:
    def __init__(self):
        self.now = 100.0

    def __call__(self):
        return self.now


def test_circuit_breaker_opens_half_opens_closes():
    clock = _FakeClock()
    b = CircuitBreaker(threshold=3, cooldown_s=10.0, clock=clock)
    assert b.state == "closed"
    for _ in range(2):
        b.before_call()
        assert not b.record_failure()
    b.before_call()
    assert b.record_failure()  # third consecutive failure OPENS
    assert b.state == "open" and b.is_open and b.opens == 1
    with pytest.raises(CircuitOpenError, match="circuit breaker is open"):
        b.before_call("rowgroup read")
    assert b.failfasts == 1
    # cooldown elapses: exactly ONE caller is admitted as the probe,
    # concurrent callers keep failing fast
    clock.now += 10.0
    b.before_call("probe")
    assert b.state == "half-open"
    with pytest.raises(CircuitOpenError, match="probe in flight"):
        b.before_call("concurrent")
    # probe fails -> re-opens and restarts the cooldown
    assert b.record_failure()
    assert b.state == "open" and b.opens == 2
    clock.now += 10.0
    b.before_call("probe2")
    b.record_success()  # probe succeeds -> closed, count reset
    assert b.state == "closed" and not b.is_open
    b.before_call()
    snap = b.snapshot()
    assert snap["state"] == "closed" and snap["opens"] == 2
    # a success anywhere resets the consecutive count
    b.record_failure()
    b.record_success()
    assert b.snapshot()["consecutive_failures"] == 0


def test_circuit_breaker_policy_resolution_and_validation():
    assert make_circuit_breaker(None) is None
    assert make_circuit_breaker(
        RetryPolicy(circuit_threshold=None)) is None
    b = make_circuit_breaker(RetryPolicy(circuit_threshold=5,
                                         circuit_cooldown_s=1.0))
    assert b.threshold == 5 and b.cooldown_s == 1.0
    with pytest.raises(PetastormTpuError):
        RetryPolicy(circuit_threshold=0)
    with pytest.raises(PetastormTpuError):
        RetryPolicy(circuit_cooldown_s=-1)
    # CircuitOpenError is an OSError (classifies 'data', skip-eligible) but
    # must never itself be retried as transient
    from petastorm_tpu.retry import is_transient

    err = CircuitOpenError("open")
    assert isinstance(err, OSError)
    assert classify_error(err) == "data"
    assert not is_transient(err)


def test_retry_call_fails_fast_once_circuit_opens():
    """A failure that trips the breaker mid-retry surfaces the outage NOW
    (CircuitOpenError before the next backoff sleep), and later calls fail
    fast without invoking the function at all."""
    tele = Telemetry()
    breaker = CircuitBreaker(threshold=2, cooldown_s=60.0)
    calls = []

    def flaky():
        calls.append(1)
        raise OSError("injected transient weather")

    with pytest.raises(CircuitOpenError):
        retry_call(flaky, RetryPolicy(max_attempts=5, initial_backoff_s=0.0),
                   what="rowgroup test", sleep=lambda s: None,
                   telemetry=tele, breaker=breaker)
    # opened after 2 consecutive failures: the remaining 3 attempts of the
    # budget were NOT burned against the down store
    assert len(calls) == 2
    calls.clear()
    with pytest.raises(CircuitOpenError):
        retry_call(flaky, RetryPolicy(max_attempts=5),
                   what="rowgroup test2", sleep=lambda s: None,
                   breaker=breaker)
    assert calls == []  # not even one call while open
    assert tele.snapshot()["counters"]["liveness.circuit_opens"] == 1


def test_circuit_breaker_under_scripted_latency_fs_weather(tmp_path):
    """Scripted storage weather through the REAL filesystem layer: latent_fs
    fails the first 4 opens; the breaker opens mid-storm (short-cutting the
    retry budget), fails fast without issuing IO, re-opens on failed
    half-open probes, and closes on the first healthy probe."""
    from petastorm_tpu.test_util.latency_fs import latent_filesystem

    victim = tmp_path / "blob.bin"
    victim.write_bytes(b"\x01" * 128)
    fs, stats = latent_filesystem(latency_s=0.0, fail_first_opens=4)
    breaker = CircuitBreaker(threshold=2, cooldown_s=0.05)
    policy = RetryPolicy(max_attempts=3, initial_backoff_s=0.0)

    def read_once():
        with fs.open_input_file(str(victim)) as f:
            return f.read()

    def call():
        return retry_call(read_once, policy, what="blob",
                          sleep=lambda s: None, breaker=breaker)

    # injected failures 1+2 trip the threshold on the second attempt; the
    # third attempt of the budget is NOT burned - CircuitOpenError now
    with pytest.raises(CircuitOpenError):
        call()
    assert breaker.state == "open" and breaker.opens == 1
    assert stats.failures_injected == 2
    with pytest.raises(CircuitOpenError):  # open: fail fast, zero IO issued
        call()
    assert stats.failures_injected == 2
    for expected_opens in (2, 3):  # two failed half-open probes re-open
        time.sleep(0.06)
        with pytest.raises(CircuitOpenError):
            call()
        assert breaker.opens == expected_opens
    assert stats.failures_injected == 4  # the scripted storm is spent
    time.sleep(0.06)  # healthy probe closes the circuit
    assert call() == b"\x01" * 128
    assert breaker.state == "closed"
    assert call() == b"\x01" * 128  # and stays closed


def test_failed_probe_with_non_transient_error_releases_slot():
    """A half-open probe whose call dies with a NON-transient error (expired
    credentials, deleted file) must release the probe slot - otherwise the
    breaker reports 'probe in flight' forever and never recovers."""
    clock = _FakeClock()
    b = CircuitBreaker(threshold=1, cooldown_s=5.0, clock=clock)
    with pytest.raises(CircuitOpenError):
        retry_call(lambda: (_ for _ in ()).throw(OSError("weather")),
                   RetryPolicy(max_attempts=2, initial_backoff_s=0.0),
                   what="t", sleep=lambda s: None, breaker=b)
    clock.now += 5.0

    def durable_failure():
        raise PermissionError("token expired")

    with pytest.raises(PermissionError):  # probe call, non-transient outcome
        retry_call(durable_failure, RetryPolicy(max_attempts=2),
                   what="t", sleep=lambda s: None, breaker=b)
    # the slot was released: a later caller can still probe (and close)
    assert b.state == "half-open"
    retry_call(lambda: "ok", RetryPolicy(), what="t", breaker=b)
    assert b.state == "closed"


# -- executor-level liveness ---------------------------------------------------

def test_make_executor_validates_liveness_kwargs():
    with pytest.raises(PetastormTpuError, match="item_deadline_s"):
        make_executor("thread", item_deadline_s=0)
    with pytest.raises(PetastormTpuError, match="hedge_after_s"):
        make_executor("thread", hedge_after_s="sometimes")
    with pytest.raises(PetastormTpuError, match="hedge_after_s"):
        make_executor("process", hedge_after_s=-1)
    ex = make_executor("thread", item_deadline_s=5.0, hedge_after_s="auto")
    assert ex.diagnostics["hung_workers_killed"] == 0
    assert ex.diagnostics["hedged_items"] == 0


def test_serial_executor_accepts_but_warns_liveness(caplog):
    with caplog.at_level(logging.WARNING, logger="petastorm_tpu.pool"):
        ex = make_executor("serial", item_deadline_s=1.0)
    assert any("inoperative" in rec.message for rec in caplog.records)
    ex.start(SleepyWorker(0))
    ex.put(VentilatedItem(0, 0))
    assert ex.get(timeout=5).item == 0
    ex.stop()
    ex.join()


def test_hedge_auto_threshold_derives_from_decode_p99():
    tele = Telemetry()
    executors = [ThreadedExecutor(workers_count=1, telemetry=tele,
                                  hedge_after_s="auto"),
                 ThreadedExecutor(workers_count=1, hedge_after_s=2.5),
                 ThreadedExecutor(workers_count=1, hedge_after_s="auto")]
    ex, ex_numeric, ex_untelemetered = executors
    try:
        assert ex._hedge_threshold() is None  # no decode samples yet
        hist = tele.histogram("stage.decode.latency_s")
        for _ in range(25):
            hist.record(0.01)
        thr = ex._hedge_threshold()
        assert thr == pytest.approx(max(4.0 * hist.quantile(0.99), 0.5))
        # numeric thresholds pass straight through
        assert ex_numeric._hedge_threshold() == 2.5
        # auto without telemetry never arms (no data to calibrate against)
        assert ex_untelemetered._hedge_threshold() is None
    finally:
        for e in executors:
            e.stop()
            e.join()


# -- reader-level: hung worker recovery ----------------------------------------

def test_thread_pool_hung_worker_abandoned_epoch_exact(tmp_path):
    """A thread worker hung past item_deadline_s is abandoned (threads
    cannot be killed), its item requeued onto a sibling, and the epoch
    completes with the exact row multiset - no hang, no loss, no dupes."""
    url = _write(tmp_path)
    chaos = ChaosSpec(hang_ordinals=(3,), hang_s=60)
    tele = Telemetry()
    t0 = time.monotonic()
    with make_batch_reader(url, reader_pool_type="thread", workers_count=2,
                           shuffle_row_groups=False, chaos=chaos,
                           item_deadline_s=0.7, telemetry=tele) as r:
        rows = sorted(x for b in r.iter_batches() for x in b.columns["x"])
        diag = r.diagnostics
    # completes promptly (deadline + margin + bounded liveness join), not
    # after the 60s hang
    assert time.monotonic() - t0 < 30
    assert rows == list(range(N_ROWS))
    assert diag["hung_workers_abandoned"] == 1
    assert diag["requeued_items"] == 1
    counters = tele.snapshot()["counters"]
    assert counters["liveness.hung_workers_abandoned"] == 1
    assert counters["errors.requeued_items"] == 1


def test_thread_pool_repeat_hanging_item_quarantines_as_data(tmp_path):
    """An item that hangs EVERY worker that touches it (hang_on_retry)
    exhausts the requeue budget and quarantines as a data error under a
    skip policy - the poisoned-slow-item path of the ISSUE tentpole."""
    url = _write(tmp_path)
    chaos = ChaosSpec(hang_ordinals=(3,), hang_on_retry=True, hang_s=60)
    policy = ErrorPolicy(max_requeue_attempts=1)
    t0 = time.monotonic()
    with make_batch_reader(url, reader_pool_type="thread", workers_count=3,
                           shuffle_row_groups=False, chaos=chaos,
                           item_deadline_s=0.5, on_error=policy) as r:
        rows = sorted(x for b in r.iter_batches() for x in b.columns["x"])
        diag = r.diagnostics
    assert time.monotonic() - t0 < 60
    assert rows == sorted(set(range(N_ROWS)) - _rows_of_rowgroups([3]))
    # attempt 0 and the requeued attempt 1 both hung -> two slots abandoned
    assert diag["hung_workers_abandoned"] == 2
    assert diag["skipped_rowgroups"] == 1
    entry = diag["quarantined_rowgroups"][0]
    assert entry["ordinal"] == 3 and entry["kind"] == "data"


def test_all_thread_workers_abandoned_raises_not_wedges():
    """When every thread slot has been abandoned as hung, queued items have
    no one to run them: the pool must raise a classified WorkerError (like
    the all-dead path), never wait forever on work nobody will do."""
    from petastorm_tpu.test_util.chaos import ChaosWorker

    chaos = ChaosSpec(hang_ordinals=(0,), hang_s=60)
    ex = ThreadedExecutor(workers_count=1, item_deadline_s=0.3,
                          max_requeue_attempts=2)
    try:
        ex.start(ChaosWorker(SleepyWorker(0), chaos))
        ex.put(VentilatedItem(0, 0))
        ex.put(VentilatedItem(1, 1))
        t0 = time.monotonic()
        with pytest.raises(WorkerError, match="abandoned as hung"):
            while True:
                try:
                    ex.get(timeout=0.5)
                except queue.Empty:
                    pass
                assert time.monotonic() - t0 < 30
    finally:
        ex.stop()
        ex.join()


# -- reader-level: straggler hedging -------------------------------------------

def test_thread_pool_hedged_straggler_delivers_exactly_once(tmp_path):
    """An item straggling past hedge_after_s is speculatively re-issued to
    an idle worker; the hedge copy (attempt 1, which the chaos hang gate
    skips) wins, the row multiset is exact, and the win is counted."""
    url = _write(tmp_path)
    chaos = ChaosSpec(hang_ordinals=(4,), hang_s=60)
    tele = Telemetry()
    t0 = time.monotonic()
    with make_batch_reader(url, reader_pool_type="thread", workers_count=2,
                           shuffle_row_groups=False, chaos=chaos,
                           hedge_after_s=0.4, telemetry=tele) as r:
        rows = sorted(x for b in r.iter_batches() for x in b.columns["x"])
        diag = r.diagnostics
    assert time.monotonic() - t0 < 30
    assert rows == list(range(N_ROWS))  # exactly once, loser deduped
    assert diag["hedged_items"] == 1
    assert diag["hedge_wins"] == 1
    counters = tele.snapshot()["counters"]
    assert counters["liveness.hedged_items"] == 1
    assert counters["liveness.hedge_wins"] == 1


def test_hedge_duplicate_delivery_is_deduped():
    """Both copies of a hedged item eventually deliver: the ledger settles
    the first and drops the second (consumed counts stay exact)."""
    chaos = ChaosSpec(slow_ordinals=(2,), slow_s=1.2)
    from petastorm_tpu.test_util.chaos import ChaosWorker

    ex = ThreadedExecutor(workers_count=2, hedge_after_s=0.3)
    try:
        ex.start(ChaosWorker(SleepyWorker(0), chaos))
        for i in range(6):
            ex.put(VentilatedItem(i, i))
        out = []
        deadline = time.monotonic() + 30
        while len(out) < 6 and time.monotonic() < deadline:
            try:
                out.append(ex.get(timeout=0.5))
            except queue.Empty:
                continue
        assert sorted(v.item for v in out) == list(range(6))
        # the slow original ALSO finishes: give its duplicate time to land,
        # then verify nothing extra is ever delivered
        time.sleep(1.5)
        with pytest.raises(queue.Empty):
            ex.get(timeout=0.5)
        assert ex.diagnostics["hedged_items"] >= 1
        assert ex.diagnostics["consumed"] == 6
    finally:
        ex.stop()
        ex.join()


# -- the headline acceptance e2e ----------------------------------------------

def test_process_pool_hang_kill_and_replace_e2e(tmp_path):
    """Acceptance: >= 2 permanent hangs across process workers; with
    item_deadline_s the hung workers are SIGKILLed and REPLACED, the items
    requeue onto the respawned workers, and the epoch completes with the
    exact healthy-row multiset and liveness.hung_workers_killed >= 2."""
    url = _write(tmp_path)
    chaos = ChaosSpec(hang_ordinals=(2, 6), hang_s=300)
    tele = Telemetry()
    t0 = time.monotonic()
    with make_batch_reader(url, reader_pool_type="process", workers_count=2,
                           shuffle_row_groups=False, chaos=chaos,
                           item_deadline_s=1.5, telemetry=tele) as r:
        rows = sorted(x for b in r.iter_batches() for x in b.columns["x"])
        diag = r.diagnostics
    assert time.monotonic() - t0 < 120  # NOT the 300s hang
    assert rows == list(range(N_ROWS))  # no hang, no dupes, no lost rows
    assert diag["hung_workers_killed"] >= 2
    assert diag["requeued_items"] >= 2
    assert tele.snapshot()["counters"]["liveness.hung_workers_killed"] >= 2


def test_same_scenario_without_deadline_stalls(tmp_path):
    """Load-bearing proof: the identical hang scenario WITHOUT a deadline
    wedges the pipeline - surfaced (bounded by the test timeout) as a
    PipelineStallError from the first-class stall_abort_s kwarg, carrying
    the diagnostics snapshot that names the stuck workers."""
    url = _write(tmp_path)
    chaos = ChaosSpec(hang_ordinals=(2, 6), hang_s=300)
    t0 = time.monotonic()
    with pytest.raises(PipelineStallError) as ei:
        with make_batch_reader(url, reader_pool_type="process",
                               workers_count=2, shuffle_row_groups=False,
                               chaos=chaos, stall_warn_s=1.0,
                               stall_abort_s=3.0) as r:
            list(r.iter_batches())
    assert time.monotonic() - t0 < 90
    err = ei.value
    assert isinstance(err, WorkerError)  # existing handlers keep working
    assert err.kind == "infra"
    # diagnostics attached: the wedged state survives into the exception
    # (workers_busy may be empty if the stall raced worker spawn - the
    # snapshot itself, not its timing, is the contract)
    assert "workers_busy" in err.diagnostics, err.diagnostics
    assert err.diagnostics["consumed_items"] < err.diagnostics["expected_items"]
    assert "stall_abort_s" in str(err)


# -- stall kwargs satellite ----------------------------------------------------

def test_stall_kwargs_override_env(tmp_path, monkeypatch):
    url = _write(tmp_path)
    monkeypatch.setenv("PETASTORM_TPU_STALL_WARN_S", "77")
    monkeypatch.setenv("PETASTORM_TPU_STALL_ABORT_S", "88")
    with make_batch_reader(url, reader_pool_type="serial",
                           shuffle_row_groups=False) as r:
        assert r._stall_warn_s == 77.0 and r._stall_abort_s == 88.0
    with make_batch_reader(url, reader_pool_type="serial",
                           shuffle_row_groups=False,
                           stall_warn_s=5.0, stall_abort_s=9.0) as r:
        assert r._stall_warn_s == 5.0 and r._stall_abort_s == 9.0
    # 0 disables explicitly even when the env arms it
    with make_batch_reader(url, reader_pool_type="serial",
                           shuffle_row_groups=False, stall_abort_s=0) as r:
        assert r._stall_abort_s == 0.0


def test_stall_warn_kwarg_reaches_serial_watchdog(tmp_path, monkeypatch):
    """The serial pool's per-item watchdog is the only observer of a
    mid-item stall on that flavor: the first-class kwarg must reach it,
    not just the reader-side loop (which cannot see serial stalls)."""
    url = _write(tmp_path)
    monkeypatch.setenv("PETASTORM_TPU_STALL_WARN_S", "120")
    with make_batch_reader(url, reader_pool_type="serial",
                           shuffle_row_groups=False, stall_warn_s=7.0) as r:
        assert r._executor._stall_warn_s == 7.0
    assert make_executor("serial", stall_warn_s=3.0)._stall_warn_s == 3.0


# -- observability surfaces ----------------------------------------------------

def test_report_renders_liveness_counters_in_faults_section():
    tele = Telemetry()
    tele.counter("liveness.hung_workers_killed").add(2)
    tele.counter("liveness.hedged_items").add(3)
    tele.counter("liveness.circuit_opens").add(1)
    report = render_pipeline_report(tele.snapshot())
    faults_at = report.index("faults (")
    for name in ("liveness.hung_workers_killed = 2",
                 "liveness.hedged_items = 3",
                 "liveness.circuit_opens = 1"):
        assert report.index(name) > faults_at, report


def test_diagnose_liveness_verdict(tmp_path):
    from petastorm_tpu.tools.diagnose import (render_liveness_verdict,
                                              run_diagnosis)

    url = _write(tmp_path)
    result = run_diagnosis(url, pool_type="thread", workers_count=2)
    liveness = result["liveness"]
    for key in ("hung_workers_killed", "hedged_items", "hedge_wins",
                "circuit_opens", "circuit_open_quarantines",
                "slowest_inflight_age_s"):
        assert key in liveness
    assert "OK" in render_liveness_verdict(liveness)
    # a degraded run flips the verdict and names the intervention
    chaos = ChaosSpec(hang_ordinals=(3,), hang_s=60)
    result = run_diagnosis(url, pool_type="thread", workers_count=2,
                           chaos=chaos, item_deadline_s=0.6)
    assert result["rows"] == N_ROWS
    assert result["liveness"]["hung_workers_abandoned"] >= 1
    verdict = render_liveness_verdict(result["liveness"])
    assert "abandoned" in verdict and "OK" not in verdict


def test_cli_parsers_accept_liveness_flags(tmp_path, capsys):
    from petastorm_tpu.benchmark.cli import build_parser as bench_parser
    from petastorm_tpu.tools.diagnose import build_parser as diag_parser

    args = bench_parser().parse_args(
        ["file:///ds", "--item-deadline", "30", "--hedge-after", "auto"])
    assert args.item_deadline == 30.0 and args.hedge_after == "auto"
    args = diag_parser().parse_args(
        ["--synthetic", "--item-deadline", "10", "--hedge-after", "2.5"])
    assert args.item_deadline == 10.0 and args.hedge_after == 2.5
    # malformed values are argparse usage errors, not raw tracebacks
    for parser, argv in ((bench_parser(), ["file:///ds"]),
                         (diag_parser(), ["--synthetic"])):
        with pytest.raises(SystemExit):
            parser.parse_args(argv + ["--hedge-after", "2s"])
        assert "hedge-after" in capsys.readouterr().err


def test_reader_diagnostics_include_circuit_breaker(tmp_path):
    """A reader whose io_retries armed a breaker surfaces its state in
    diagnostics (local fs never arms one; a latent 'remote' fs does)."""
    from petastorm_tpu.test_util.latency_fs import latent_filesystem

    url = _write(tmp_path)
    with make_batch_reader(url, reader_pool_type="serial",
                           shuffle_row_groups=False) as r:
        assert r.circuit_breaker is None  # local fs: no retries, no breaker
    fs, _stats = latent_filesystem(latency_s=0.0)
    with make_batch_reader(url, reader_pool_type="serial",
                           shuffle_row_groups=False, filesystem=fs) as r:
        rows = sorted(x for b in r.iter_batches() for x in b.columns["x"])
        assert r.circuit_breaker is not None
        diag = r.diagnostics
    assert rows == list(range(N_ROWS))
    assert diag["circuit_breaker"]["state"] == "closed"
    assert diag["circuit_breaker"]["opens"] == 0


# -- loader shutdown-join satellite -------------------------------------------

class _WedgedThread:
    name = "petastorm-tpu-jax-assembly"

    def join(self, timeout=None):
        pass  # never quiesces

    def is_alive(self):
        return True


def test_loader_join_surfaces_unquiesced_threads(tmp_path, caplog):
    """JaxDataLoader.join() no longer swallows a producer thread that missed
    the stop() join budget: it logs the thread + stage and records it in
    diagnostics['unquiesced_threads']."""
    jax = pytest.importorskip("jax")  # noqa: F841 - loader needs a backend
    from petastorm_tpu.jax.loader import JaxDataLoader

    url = _write(tmp_path)
    reader = make_batch_reader(url, reader_pool_type="serial",
                               shuffle_row_groups=False)
    loader = JaxDataLoader(reader, batch_size=4)
    try:
        assert loader.diagnostics["unquiesced_threads"] == []
        # simulate a wedged assembly thread (a hung transform_fn): the real
        # thread never started, so stand in a permanently-alive stub
        loader._started = True
        wedged = _WedgedThread()
        loader._thread = wedged
        transfer = _WedgedThread()
        transfer.name = "petastorm-tpu-jax-transfer"
        loader._transfer_thread = transfer
        loader.stop()
        with caplog.at_level(logging.WARNING, logger="petastorm_tpu.jax.loader"):
            loader.join()
        assert any("failed to quiesce" in rec.message
                   for rec in caplog.records)
        entries = loader.diagnostics["unquiesced_threads"]
        assert {e["stage"] for e in entries} == {"host-assemble",
                                                 "device-transfer"}
        assert entries[0]["thread"] == "petastorm-tpu-jax-assembly"
    finally:
        reader.stop()
        reader.join()
