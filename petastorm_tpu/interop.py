"""Interop: read datasets created by the **original Petastorm** library.

The reference stamps its schema into parquet ``_common_metadata`` as a **pickle**
of a ``petastorm.unischema.Unischema`` instance under the KV key
``dataset-toolkit.unischema.v1`` (reference: etl/dataset_metadata.py:35-36,195-206),
per-file rowgroup counts as JSON under ``dataset-toolkit.num_row_groups_per_file.v1``
(dataset_metadata.py:209-242), and rowgroup indexes as a pickled indexer dict under
``dataset-toolkit.rowgroups_index.v1`` (etl/rowgroup_indexing.py:33-81).  Codec
instances are pickled inside the schema (codecs.py:20-21), and ``ScalarCodec``
embeds a pickled ``pyspark.sql.types`` instance (codecs.py:192-197).

This module decodes those payloads **without petastorm, pyspark, or cv2 installed**
via a restricted unpickler: only an explicit whitelist of symbols resolves, each to
a local shim class; any other global in the stream raises ``UnpicklingError``.
Pre-petastorm package names (``av.ml.dataset_toolkit`` etc., reference
etl/legacy.py:22-45) resolve through the same suffix-based mapping.

Storage formats are bit-compatible with our codecs (``np.save`` bytes for
ndarrays, ``np.savez_compressed`` for compressed ndarrays, standard PNG/JPEG
streams for images, native parquet scalars), so after schema conversion the
normal read path works unchanged: ``make_reader(legacy_url)`` just works.
"""

from __future__ import annotations

import io
import logging
import pickle
from collections import namedtuple
from decimal import Decimal
from typing import Dict, Optional

import numpy as np

from petastorm_tpu.codecs import (Codec, CompressedImageCodec,
                                  CompressedNdarrayCodec, NdarrayCodec,
                                  ScalarCodec)
from petastorm_tpu.errors import MetadataError
from petastorm_tpu.schema import Field, Schema

logger = logging.getLogger(__name__)

#: KV keys written by the reference (etl/dataset_metadata.py:35-36,
#: etl/rowgroup_indexing.py:30).
LEGACY_UNISCHEMA_KEY = b"dataset-toolkit.unischema.v1"
LEGACY_ROW_GROUPS_KEY = b"dataset-toolkit.num_row_groups_per_file.v1"
LEGACY_INDEX_KEY = b"dataset-toolkit.rowgroups_index.v1"


# ---------------------------------------------------------------------------
# Shim classes the restricted unpickler instantiates in place of the
# reference's own.  Attribute names match what the reference pickles.
# ---------------------------------------------------------------------------

class _ShimUnischemaField(namedtuple("UnischemaField",
                                     ["name", "numpy_dtype", "shape", "codec",
                                      "nullable"])):
    """Pickles as (class, field-values) - reference unischema.py:51-85."""


_ShimUnischemaField.__new__.__defaults__ = (None, False)


class _ShimUnischema:
    """State arrives via pickle BUILD into ``__dict__``: ``_name``, ``_fields``
    (OrderedDict name -> UnischemaField) plus one attr per field
    (reference unischema.py:179-197)."""


class _ShimNdarrayCodec:
    pass


class _ShimCompressedNdarrayCodec:
    pass


class _ShimCompressedImageCodec:
    """Attrs ``_image_codec`` ('.png'/'.jpeg'/'.jpg') and ``_quality``
    (reference codecs.py:54-63)."""


class _ShimScalarCodec:
    """Attr ``_spark_type``: a pyspark type instance (reference codecs.py:192-197)."""


class _ShimSingleFieldIndexer:
    """Attrs ``_index_name``, ``_column_name``, ``_index_data`` (defaultdict
    value -> set(rowgroup ordinal)) - reference rowgroup_indexers.py:28-31."""


class _ShimFieldNotNullIndexer:
    """Attrs ``_index_name``, ``_column_name``, ``_index_data`` (a plain set of
    rowgroup ordinals) - reference rowgroup_indexers.py:83-86."""


class _SparkTypeStub:
    """Stands in for any ``pyspark.sql.types`` class.  Only the class *name*
    (and ctor args, e.g. DecimalType(precision, scale)) matter for decoding."""

    spark_name = "UnknownType"

    def __init__(self, *args, **kwargs):
        self.args = args
        self.kwargs = kwargs


_SPARK_TYPE_STUBS: Dict[str, type] = {}


def _spark_type_stub(name: str) -> type:
    cls = _SPARK_TYPE_STUBS.get(name)
    if cls is None:
        cls = type(name, (_SparkTypeStub,), {"spark_name": name})
        _SPARK_TYPE_STUBS[name] = cls
    return cls


# ---------------------------------------------------------------------------
# Restricted unpickler
# ---------------------------------------------------------------------------

#: Reference + pre-petastorm legacy module names, matched by suffix
#: (etl/legacy.py:31-33 lists the av.* legacy packages).
_PETASTORM_SHIMS = {
    ("unischema", "Unischema"): _ShimUnischema,
    ("unischema", "UnischemaField"): _ShimUnischemaField,
    ("codecs", "NdarrayCodec"): _ShimNdarrayCodec,
    ("codecs", "CompressedNdarrayCodec"): _ShimCompressedNdarrayCodec,
    ("codecs", "CompressedImageCodec"): _ShimCompressedImageCodec,
    ("codecs", "ScalarCodec"): _ShimScalarCodec,
    ("rowgroup_indexers", "SingleFieldIndexer"): _ShimSingleFieldIndexer,
    ("rowgroup_indexers", "FieldNotNullIndexer"): _ShimFieldNotNullIndexer,
}

_SAFE_GLOBALS = {
    ("collections", "OrderedDict"),
    ("collections", "defaultdict"),
    ("builtins", "set"), ("builtins", "frozenset"), ("builtins", "list"),
    ("builtins", "dict"), ("builtins", "tuple"), ("builtins", "int"),
    ("builtins", "float"), ("builtins", "bool"), ("builtins", "str"),
    ("builtins", "bytes"), ("builtins", "bytearray"), ("builtins", "complex"),
    ("copyreg", "_reconstructor"),
    ("builtins", "object"),
    ("decimal", "Decimal"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
}


class _RestrictedUnpickler(pickle.Unpickler):
    """Resolves ONLY whitelisted globals; everything else raises."""

    def find_class(self, module: str, name: str):
        # python2-era streams (protocol <= 2) use py2 module names; apply the
        # same 2->3 mapping the stock Unpickler does before whitelisting
        from _compat_pickle import IMPORT_MAPPING, NAME_MAPPING

        if (module, name) in NAME_MAPPING:
            module, name = NAME_MAPPING[(module, name)]
        elif module in IMPORT_MAPPING:
            module = IMPORT_MAPPING[module]
        tail = module.rsplit(".", 1)[-1]
        shim = _PETASTORM_SHIMS.get((tail, name))
        if shim is not None and ("petastorm" in module or "dataset_toolkit" in module):
            return shim
        if module.startswith("pyspark.sql.types") or module == "pyspark.sql.types":
            return _spark_type_stub(name)
        if (module, name) in _SAFE_GLOBALS:
            import importlib

            return getattr(importlib.import_module(module), name)
        if module == "numpy":
            attr = getattr(np, name, None)
            if attr is np.dtype or attr is np.ndarray or (
                    isinstance(attr, type) and issubclass(attr, np.generic)):
                return attr
        raise pickle.UnpicklingError(
            f"Legacy petastorm metadata references disallowed global "
            f"{module}.{name}; refusing to unpickle")


def _restricted_loads(blob: bytes):
    return _RestrictedUnpickler(io.BytesIO(blob)).load()


# ---------------------------------------------------------------------------
# Conversion to petastorm_tpu types
# ---------------------------------------------------------------------------

def _convert_dtype(numpy_dtype) -> np.dtype:
    """UnischemaField.numpy_dtype may be a scalar type (np.int64), a dtype
    instance, Decimal, or a string type (np.str_/np.bytes_)."""
    if numpy_dtype is Decimal:
        return np.dtype("object")
    if isinstance(numpy_dtype, np.dtype):
        if numpy_dtype.kind in ("U", "S"):
            return np.dtype("object")
        return numpy_dtype
    if isinstance(numpy_dtype, type) and issubclass(numpy_dtype, (np.str_, np.bytes_)):
        return np.dtype("object")
    try:
        return np.dtype(numpy_dtype)
    except TypeError as exc:
        raise MetadataError(f"Unsupported legacy field dtype {numpy_dtype!r}") from exc


def _convert_codec(codec, dtype: np.dtype) -> Optional[Codec]:
    if codec is None or isinstance(codec, _ShimScalarCodec):
        return ScalarCodec()
    if isinstance(codec, _ShimNdarrayCodec):
        return NdarrayCodec()
    if isinstance(codec, _ShimCompressedNdarrayCodec):
        return CompressedNdarrayCodec()
    if isinstance(codec, _ShimCompressedImageCodec):
        fmt = getattr(codec, "_image_codec", ".png").lstrip(".")
        quality = int(getattr(codec, "_quality", 80))
        return CompressedImageCodec("jpeg" if fmt == "jpg" else fmt, quality)
    raise MetadataError(f"Unsupported legacy codec {type(codec).__name__}")


def convert_unischema(shim) -> Schema:
    """``_ShimUnischema`` -> :class:`petastorm_tpu.schema.Schema`."""
    name = getattr(shim, "_name", "legacy")
    legacy_fields = getattr(shim, "_fields", None)
    if not legacy_fields:
        raise MetadataError("Legacy unischema has no fields")
    fields = []
    for fname, lf in legacy_fields.items():
        dtype = _convert_dtype(lf.numpy_dtype)
        fields.append(Field(name=fname, dtype=dtype,
                            shape=tuple(lf.shape or ()),
                            codec=_convert_codec(lf.codec, dtype),
                            nullable=bool(lf.nullable)))
    return Schema(name, fields)


def load_legacy_schema(blob: bytes) -> Schema:
    """Decode a ``dataset-toolkit.unischema.v1`` payload into a Schema."""
    shim = _restricted_loads(blob)
    if not isinstance(shim, _ShimUnischema):
        raise MetadataError(
            f"Legacy unischema payload decoded to {type(shim).__name__}, "
            "expected a Unischema")
    return convert_unischema(shim)


def load_legacy_indexes(blob: bytes) -> Dict[str, "RowGroupIndexer"]:
    """Decode ``dataset-toolkit.rowgroups_index.v1`` into our indexer types,
    usable with :mod:`petastorm_tpu.selectors` unchanged."""
    from petastorm_tpu.etl.indexing import (FieldNotNullIndexer,
                                            SingleFieldIndexer, _norm_key)

    raw = _restricted_loads(blob)
    if not isinstance(raw, dict):
        raise MetadataError("Legacy rowgroup index payload is not a dict")
    out: Dict[str, object] = {}
    for name, shim in raw.items():
        if isinstance(shim, _ShimSingleFieldIndexer):
            idx = SingleFieldIndexer(shim._index_name, shim._column_name)
            for value, pieces in getattr(shim, "_index_data", {}).items():
                idx._index.setdefault(_norm_key(value), set()).update(
                    int(p) for p in pieces)
            out[name] = idx
        elif isinstance(shim, _ShimFieldNotNullIndexer):
            idx = FieldNotNullIndexer(shim._index_name, shim._column_name)
            idx._row_groups.update(int(p) for p in getattr(shim, "_index_data", ()))
            out[name] = idx
        else:
            logger.warning("Skipping unrecognized legacy indexer %r (%s)",
                           name, type(shim).__name__)
    return out
