"""Dispatcher session journal: optional warm restarts that skip re-sends.

Dispatcher crash recovery does NOT need this file: a fresh dispatcher
reconstructs its sessions from its peers (clients re-hello with their job
blob and re-send unresolved ledger items; workers rejoin and report what
they are still executing - see :mod:`petastorm_tpu.service.dispatcher`).
The journal is the *warm* variant: with ``Dispatcher(journal_path=...)``
(CLI ``--journal``) the control-plane events that define a session -
client hellos, enqueued work items, acks, purges - are appended to a
length-prefixed :mod:`petastorm_tpu.service.wire` record file, and a
restarted dispatcher replays it into ready-to-serve client sessions before
it accepts a single connection.  A reconnecting client is then told (via
``hello_ok``'s ``known`` ordinal list) which of its ledger items the
dispatcher already holds, so its resync skips re-sending them - the
restart costs one reconnect handshake instead of a window's worth of
re-enqueues.

Only control-plane state is journaled.  Result *bodies* (the multi-MB
column payloads in the redelivery buffer) never touch the journal: a
journal-restored item that was delivered-but-unacked at crash time simply
re-executes, and the client's per-ordinal ledger drops the duplicate -
exactly the cold-recovery semantics, paid only for the ack-batch-sized
tail.  Requeue ``attempt`` counters restore from the *enqueued* value, so
a restart is slightly generous to items that were mid-requeue (documented,
deliberate: the budget is a safety valve, not an exactness invariant).

Durability is flush-per-record, not fsync: a host power-loss can truncate
the tail, and :meth:`ServiceJournal.load` stops cleanly at the first
short/undecodable record (peer reconstruction covers whatever the tail
lost).  The file auto-compacts - acked items are dropped and the journal
rewritten - once the append log outgrows its live state 4x.
"""

from __future__ import annotations

import collections
import logging
import os
import struct
import threading
from typing import Any, Dict, Optional

from petastorm_tpu.service import wire
from petastorm_tpu.service.wire import WireFormatError

logger = logging.getLogger(__name__)

_LEN = struct.Struct("!I")
#: a single journal record larger than this is a corrupt length prefix
#: (records are hellos and work-item stubs, tens of KB at most)
_MAX_RECORD = 64 << 20
#: compact when the file exceeds this AND 4x the live-state size
_COMPACT_MIN_BYTES = 4 << 20


class _Session:
    """In-memory mirror of one client's journaled state (the compaction
    source and the restart payload)."""

    __slots__ = ("hello", "items")

    def __init__(self, hello: Dict[str, Any]):
        self.hello = hello
        #: ordinal -> work-item wire fields, insertion-ordered (the replay
        #: re-enqueues in the order the client ventilated)
        self.items: "collections.OrderedDict[int, Dict]" = \
            collections.OrderedDict()


class ServiceJournal:
    """Append-only session journal for one dispatcher (see module doc).

    Lifecycle: ``load()`` parses any existing file into session dicts (the
    dispatcher turns them into client states), then ``open()`` compacts and
    starts appending.  All methods are thread-safe; appends flush so an
    ordinary process death (the recovery scenario) loses nothing.
    """

    def __init__(self, path: str):
        self._path = path
        self._lock = threading.Lock()
        self._fh = None
        self._bytes = 0
        self._sessions: Dict[str, _Session] = {}

    # -- restart side ----------------------------------------------------------

    def load(self) -> Dict[str, _Session]:
        """Parse the journal (tolerating a truncated tail) into sessions;
        returns ``{client_id: _Session}``.  Call before :meth:`open`."""
        if not os.path.exists(self._path):
            return {}
        records = 0
        with open(self._path, "rb") as fh:
            while True:
                hdr = fh.read(_LEN.size)
                if len(hdr) < _LEN.size:
                    break
                (length,) = _LEN.unpack(hdr)
                if length > _MAX_RECORD:
                    logger.warning("journal %s: corrupt record length %d;"
                                   " stopping replay here", self._path, length)
                    break
                body = fh.read(length)
                if len(body) < length:
                    break  # crash-truncated tail: expected, not an error
                try:
                    rec = wire.loads(body)
                except WireFormatError:
                    logger.warning("journal %s: undecodable record after %d"
                                   " good one(s); stopping replay here",
                                   self._path, records)
                    break
                if isinstance(rec, dict):
                    self._apply(rec)
                    records += 1
        logger.info("journal %s: replayed %d record(s) into %d session(s),"
                    " %d unresolved item(s)", self._path, records,
                    len(self._sessions),
                    sum(len(s.items) for s in self._sessions.values()))
        return dict(self._sessions)

    def _apply(self, rec: Dict[str, Any]) -> None:
        kind, cid = rec.get("r"), rec.get("client")
        if not isinstance(cid, str):
            return
        if kind == "hello":
            session = self._sessions.get(cid)
            if session is None:
                self._sessions[cid] = _Session(rec)
            else:
                session.hello = rec  # reconnects refresh the job blob
        elif kind == "enq":
            session = self._sessions.get(cid)
            item = rec.get("item")
            if session is not None and isinstance(item, dict) \
                    and isinstance(item.get("o"), int):
                self._sessions[cid].items[item["o"]] = item
        elif kind == "ack":
            session = self._sessions.get(cid)
            if session is not None:
                for ordinal in rec.get("ordinals") or ():
                    session.items.pop(ordinal, None)
        elif kind == "purge":
            self._sessions.pop(cid, None)

    # -- append side -----------------------------------------------------------

    def open(self) -> "ServiceJournal":
        """Compact-rewrite the loaded state and start appending."""
        with self._lock:
            self._rewrite_locked()
        return self

    def append_hello(self, cid: str, hello: Dict[str, Any]) -> None:
        self._append(dict(hello, r="hello", client=cid))

    def append_enqueue(self, cid: str, item: Dict[str, Any]) -> None:
        self._append({"r": "enq", "client": cid, "item": item})

    def append_ack(self, cid: str, ordinals) -> None:
        self._append({"r": "ack", "client": cid, "ordinals": list(ordinals)})

    def append_purge(self, cid: str) -> None:
        self._append({"r": "purge", "client": cid})

    def _append(self, rec: Dict[str, Any]) -> None:
        try:
            encoded = wire.dumps(rec)
        except WireFormatError:
            # a hello with out-of-domain extras must not kill the control
            # plane; the session just won't warm-restart
            logger.warning("journal: unencodable record dropped (%r)",
                           rec.get("r"))
            return
        with self._lock:
            self._apply(rec)
            if self._fh is None:
                return  # load-only phase (applied to the mirror regardless)
            self._fh.write(_LEN.pack(len(encoded)) + encoded)
            self._fh.flush()
            self._bytes += _LEN.size + len(encoded)
            if self._bytes > _COMPACT_MIN_BYTES \
                    and self._bytes > 4 * self._live_bytes_locked():
                self._rewrite_locked()

    def _live_bytes_locked(self) -> int:
        total = 0
        for session in self._sessions.values():
            total += len(session.hello.get("factory") or b"") + 256
            for item in session.items.values():
                total += len(item.get("blob") or b"") + 64
        return total

    def _rewrite_locked(self) -> None:
        """Rewrite the file from the live mirror (compaction + open)."""
        if self._fh is not None:
            self._fh.close()
        tmp = self._path + ".tmp"
        with open(tmp, "wb") as fh:
            size = 0
            for cid, session in self._sessions.items():
                for rec in ([session.hello]
                            + [{"r": "enq", "client": cid, "item": item}
                               for item in session.items.values()]):
                    encoded = wire.dumps(rec)
                    fh.write(_LEN.pack(len(encoded)) + encoded)
                    size += _LEN.size + len(encoded)
        os.replace(tmp, self._path)
        self._fh = open(self._path, "ab")
        self._bytes = size

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
