"""Rowgroup indexing: value -> rowgroup lookup structures stored in dataset metadata.

Reference parity: petastorm/etl/rowgroup_indexing.py (build_rowgroup_index Spark
map-reduce, pickled into KV at rowgroup_indexing.py:33-81) and
petastorm/etl/rowgroup_indexers.py (SingleFieldIndexer value->set(piece) with
__add__ merge at rowgroup_indexers.py:21-75; FieldNotNullIndexer at 78-124).

Differences: the build is a pyarrow scan (no Spark); storage is JSON under
``petastorm-tpu.rowgroup_index.v1`` (never pickle).  Index values are normalized to
JSON-native scalars (str/int/float/bool); other types index by ``str(value)``.
"""

from __future__ import annotations

import json
import logging
from abc import ABC, abstractmethod
from typing import Dict, List, Optional, Sequence, Set

import numpy as np
import pyarrow.fs as pafs
import pyarrow.parquet as pq

from petastorm_tpu.errors import MetadataError
from petastorm_tpu.etl.metadata import (ROWGROUP_INDEX_METADATA_KEY, DatasetInfo,
                                        open_dataset, write_metadata_file)
from petastorm_tpu.schema import Schema

logger = logging.getLogger(__name__)

_INDEXER_REGISTRY: Dict[str, type] = {}


def _register(cls):
    _INDEXER_REGISTRY[cls.indexer_type] = cls
    return cls


def _norm_key(value):
    if isinstance(value, (np.generic,)):
        value = value.item()
    if isinstance(value, bool) or isinstance(value, (int, float, str)):
        return value
    return str(value)


class RowGroupIndexer(ABC):
    """Reference: RowGroupIndexerBase (petastorm/etl/__init__.py:19-29)."""

    indexer_type: str = ""

    def __init__(self, index_name: str):
        self._index_name = index_name

    @property
    def index_name(self) -> str:
        return self._index_name

    @property
    @abstractmethod
    def column_names(self) -> List[str]:
        """Columns this indexer needs read during the build."""

    @abstractmethod
    def process_row_group(self, row_group_index: int, columns: Dict[str, np.ndarray]):
        """Fold one rowgroup's column arrays into the index during the build
        scan (called once per rowgroup, in global-index order)."""
        ...

    @abstractmethod
    def indexed_values(self) -> List:
        """Every distinct value the index maps (sorted where orderable)."""
        ...

    @abstractmethod
    def get_row_group_indexes(self, value=None) -> Set[int]:
        """Global rowgroup ordinals holding ``value`` (or any indexed value
        when ``value`` is None)."""
        ...

    @abstractmethod
    def to_json(self) -> dict:
        """JSON-native payload stored under the dataset's index KV key;
        inverted by ``from_json``."""
        ...

    @classmethod
    @abstractmethod
    def from_json(cls, obj: dict) -> "RowGroupIndexer":
        ...


@_register
class SingleFieldIndexer(RowGroupIndexer):
    """value -> set(rowgroup ordinals) for one field.

    Reference: petastorm/etl/rowgroup_indexers.py:21-75.
    """

    indexer_type = "single_field"

    def __init__(self, index_name: str, index_field: str):
        super().__init__(index_name)
        self._field = index_field
        self._index: Dict[object, Set[int]] = {}

    @property
    def column_names(self) -> List[str]:
        return [self._field]

    def process_row_group(self, row_group_index: int, columns: Dict[str, np.ndarray]):
        for v in columns[self._field]:
            if v is None:
                continue
            self._index.setdefault(_norm_key(v), set()).add(row_group_index)

    def indexed_values(self) -> List:
        return sorted(self._index, key=lambda v: (str(type(v)), str(v)))

    def get_row_group_indexes(self, value=None) -> Set[int]:
        if value is None:
            raise MetadataError(f"Index {self.index_name!r} requires a lookup value")
        return set(self._index.get(_norm_key(value), set()))

    def to_json(self) -> dict:
        return {"type": self.indexer_type, "name": self.index_name, "field": self._field,
                "index": [[k, sorted(v)] for k, v in sorted(
                    self._index.items(), key=lambda kv: (str(type(kv[0])), str(kv[0])))]}

    @classmethod
    def from_json(cls, obj: dict) -> "SingleFieldIndexer":
        out = cls(obj["name"], obj["field"])
        out._index = {k: set(v) for k, v in obj["index"]}
        return out


@_register
class FieldNotNullIndexer(RowGroupIndexer):
    """Rowgroups where the field has at least one non-null value.

    Reference: petastorm/etl/rowgroup_indexers.py:78-124.
    """

    indexer_type = "field_not_null"

    def __init__(self, index_name: str, index_field: str):
        super().__init__(index_name)
        self._field = index_field
        self._row_groups: Set[int] = set()

    @property
    def column_names(self) -> List[str]:
        return [self._field]

    def process_row_group(self, row_group_index: int, columns: Dict[str, np.ndarray]):
        col = columns[self._field]
        if any(v is not None for v in col):
            self._row_groups.add(row_group_index)

    def indexed_values(self) -> List:
        return ["not_null"]

    def get_row_group_indexes(self, value=None) -> Set[int]:
        return set(self._row_groups)

    def to_json(self) -> dict:
        return {"type": self.indexer_type, "name": self.index_name, "field": self._field,
                "row_groups": sorted(self._row_groups)}

    @classmethod
    def from_json(cls, obj: dict) -> "FieldNotNullIndexer":
        out = cls(obj["name"], obj["field"])
        out._row_groups = set(obj["row_groups"])
        return out


def build_rowgroup_index(url: str, indexers: Sequence[RowGroupIndexer],
                         filesystem: Optional[pafs.FileSystem] = None,
                         storage_options: Optional[dict] = None) -> None:
    """Scan the dataset once, feed indexers, store results in ``_common_metadata``.

    Reference: build_rowgroup_index (etl/rowgroup_indexing.py:33-81) - a Spark job
    there, a sequential pyarrow scan of only the indexed columns here.
    """
    info = open_dataset(url, storage_options=storage_options, filesystem=filesystem,
                        require_stored_schema=True)
    schema: Schema = info.stored_schema
    needed = sorted({c for ix in indexers for c in ix.column_names})
    missing = [c for c in needed if c not in schema]
    if missing:
        raise MetadataError(f"Indexed fields {missing} not in dataset schema")

    by_file: Dict[str, List] = {}
    for rg in info.row_groups:
        by_file.setdefault(rg.path, []).append(rg)
    for path, refs in by_file.items():
        with info.filesystem.open_input_file(path) as f:
            pf = pq.ParquetFile(f)
            in_file = [c for c in needed if c in pf.schema_arrow.names]
            for ref in refs:
                table = pf.read_row_group(ref.row_group, columns=in_file)
                columns = {}
                for name in needed:
                    field = schema[name]
                    if name in in_file:
                        columns[name] = field.codec.decode_column(
                            field, table.column(name).combine_chunks())
                    else:
                        # partition column: constant per rowgroup, from the path
                        pvals = dict(ref.partition_values)
                        if name not in pvals:
                            raise MetadataError(
                                f"Indexed field {name!r} is neither stored in"
                                f" {path!r} nor a partition key")
                        value = pvals[name]
                        if field.dtype.kind not in ("U", "S", "O"):
                            value = field.dtype.type(value)
                        columns[name] = np.full(ref.num_rows, value, dtype=object)
                for ix in indexers:
                    ix.process_row_group(ref.global_index, columns)

    payload = {"version": 1, "indexes": [ix.to_json() for ix in indexers]}
    existing = info.kv_metadata.get(ROWGROUP_INDEX_METADATA_KEY)
    if existing:
        try:
            old = {ix["name"]: ix for ix in json.loads(existing)["indexes"]}
            new_names = {ix.index_name for ix in indexers}
            payload["indexes"] = [v for k, v in old.items() if k not in new_names] + \
                                 payload["indexes"]
        except (ValueError, KeyError):
            logger.warning("Dropping corrupt existing rowgroup index payload")
    write_metadata_file(info.filesystem, info.root_path, info.arrow_schema,
                        {ROWGROUP_INDEX_METADATA_KEY: json.dumps(payload).encode()})


def get_row_group_indexes(info: DatasetInfo) -> Dict[str, RowGroupIndexer]:
    """Load stored indexes (reference: rowgroup_indexing.py:138-160)."""
    raw = info.kv_metadata.get(ROWGROUP_INDEX_METADATA_KEY)
    if not raw:
        from petastorm_tpu import interop

        legacy = info.kv_metadata.get(interop.LEGACY_INDEX_KEY)
        if legacy:
            return interop.load_legacy_indexes(legacy)
        return {}
    payload = json.loads(raw)
    out = {}
    for obj in payload.get("indexes", []):
        cls = _INDEXER_REGISTRY.get(obj.get("type"))
        if cls is None:
            logger.warning("Unknown indexer type %r in stored index", obj.get("type"))
            continue
        ix = cls.from_json(obj)
        out[ix.index_name] = ix
    return out
