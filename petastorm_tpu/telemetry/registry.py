"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

The ingest pipeline's observability plane (SURVEY.md section 7: stage-level
metrics are the prerequisite for answering "which stage is the bottleneck?" -
the same layering tf.data uses, arxiv 2101.12127 section 4).  Dependency-free
and lock-cheap by design: instruments take one uncontended lock per update,
updates happen at rowgroup/batch granularity (hundreds per second, not per
row), and the disabled path never reaches this module at all
(``petastorm_tpu.telemetry.NULL_TELEMETRY``).

Instruments are create-once / update-many: components look their instruments
up by name once (``registry.counter(name)`` returns the same object for the
same name) and hold the reference across the hot loop.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Dict, Optional, Sequence, Tuple

#: default latency buckets (seconds) for stage histograms: 0.1 ms .. 30 s,
#: roughly 3x apart - wide enough for both an in-memory cache hit and a
#: cold remote rowgroup read to land in a resolving bucket
DEFAULT_LATENCY_BUCKETS_S: Tuple[float, ...] = (
    0.0001, 0.0003, 0.001, 0.003, 0.01, 0.03, 0.1, 0.3, 1.0, 3.0, 10.0, 30.0)


class Counter:
    """Monotonic float/int counter (rows emitted, seconds blocked, ...)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def add(self, amount: float = 1.0) -> None:
        """Increment by ``amount`` (thread-safe)."""
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        """Current total."""
        return self._value


class Gauge:
    """Last-value instrument (queue depth, workers alive, ...)."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0

    def set(self, value: float) -> None:
        """Record the latest observation (a plain attribute store: a torn
        read can only observe an older value, which is exactly a gauge's
        contract)."""
        self._value = float(value)

    @property
    def value(self) -> float:
        """Most recently set value."""
        return self._value


class Histogram:
    """Fixed-bucket histogram (per-stage latency distributions).

    ``buckets`` are the upper bounds (inclusive) of each bucket, ascending;
    one implicit overflow bucket catches everything beyond the last bound.
    Fixed buckets keep ``record`` O(log n) with zero allocation - the shape
    never adapts, so snapshots from different workers/runs are mergeable.
    """

    __slots__ = ("name", "buckets", "_counts", "_sum", "_count", "_lock")

    def __init__(self, name: str,
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_S):
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError(f"histogram {name!r} needs ascending, non-empty"
                             f" buckets; got {buckets!r}")
        self.name = name
        self.buckets = tuple(float(b) for b in buckets)
        self._counts = [0] * (len(self.buckets) + 1)  # +1: overflow
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def record(self, value: float) -> None:
        """Count ``value`` into its bucket (thread-safe, O(log buckets))."""
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1

    @property
    def count(self) -> int:
        """Total observations recorded."""
        return self._count

    @property
    def mean(self) -> float:
        """Mean of all recorded values (0 when empty)."""
        return self._sum / self._count if self._count else 0.0

    def quantile(self, q: float) -> float:
        """Approximate quantile: the upper bound of the bucket holding the
        q-th observation (the last finite bound for overflow entries)."""
        with self._lock:
            counts, total = list(self._counts), self._count
        if not total:
            return 0.0
        rank = q * total
        seen = 0
        for i, c in enumerate(counts):
            seen += c
            if seen >= rank:
                return self.buckets[min(i, len(self.buckets) - 1)]
        return self.buckets[-1]

    def snapshot(self) -> Dict:
        """Consistent copy: {buckets, counts, sum, count} (counts has one
        trailing overflow bucket beyond the last bound)."""
        with self._lock:
            return {"buckets": list(self.buckets),
                    "counts": list(self._counts),
                    "sum": self._sum,
                    "count": self._count}


class MetricsRegistry:
    """Name -> instrument map with get-or-create semantics.

    The registry lock guards only instrument CREATION; updates go through the
    per-instrument locks, so the hot path never contends on a global lock.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._t0 = time.perf_counter()

    def counter(self, name: str) -> Counter:
        """The counter named ``name`` (created on first use)."""
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name))
        return c

    def gauge(self, name: str) -> Gauge:
        """The gauge named ``name`` (created on first use)."""
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge(name))
        return g

    def histogram(self, name: str,
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        """The histogram named ``name`` (created on first use with
        ``buckets``, defaulting to DEFAULT_LATENCY_BUCKETS_S; bucket shape is
        fixed at creation - later calls return the existing instance)."""
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(
                    name, Histogram(name,
                                    buckets if buckets is not None
                                    else DEFAULT_LATENCY_BUCKETS_S))
        return h

    @property
    def uptime_s(self) -> float:
        """Seconds since this registry was created (the report's wall
        clock)."""
        return time.perf_counter() - self._t0

    def snapshot(self) -> Dict:
        """Point-in-time dict of every instrument (JSON-serializable)."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        return {
            "uptime_s": self.uptime_s,
            "counters": {n: c.value for n, c in sorted(counters.items())},
            "gauges": {n: g.value for n, g in sorted(gauges.items())},
            "histograms": {n: h.snapshot()
                           for n, h in sorted(histograms.items())},
        }
