"""Native shared-memory transport tests: allocator, transport, process pool.

Reference parity: workers_pool/tests/test_workers_pool.py exercises the zmq
data plane in both copy modes; here the native arena replaces zmq.
"""

import os

import numpy as np
import pytest

from petastorm_tpu.batch import ColumnBatch

native = pytest.importorskip("petastorm_tpu.native")
if not native.is_available():
    if os.environ.get("PETASTORM_TPU_REQUIRE_ARENA"):
        # the CI py312 job sets this: on a runtime that SHOULD have the
        # arena plane, a silent skip hides a broken .so (it did for a whole
        # PR cycle - CHANGES.md PR 6); fail loudly instead
        raise RuntimeError(
            "PETASTORM_TPU_REQUIRE_ARENA=1 but the shm arena plane is"
            " unavailable on this runtime (python >= 3.12 + buildable"
            " native lib expected; see petastorm_tpu.native.is_available)")
    pytest.skip("native toolchain unavailable", allow_module_level=True)

from petastorm_tpu.native import SharedArena  # noqa: E402
from petastorm_tpu.native.transport import (ShmBatchRef, decode_batch,  # noqa: E402
                                            encode_batch)


@pytest.fixture()
def arena():
    a = SharedArena.create(4 * 2**20)
    yield a
    a.close()


# -- allocator ----------------------------------------------------------------

def test_alloc_free_roundtrip(arena):
    free0 = arena.free_bytes()
    off = arena.alloc(1000)
    assert off is not None and off % 64 == 0
    assert arena.free_bytes() < free0
    arena.free(off)
    assert arena.free_bytes() == free0


def test_out_of_order_free_coalesces(arena):
    free0 = arena.free_bytes()
    offs = [arena.alloc(100_000) for _ in range(8)]
    assert all(o is not None for o in offs)
    # free in scrambled order; afterwards the arena must be one block again
    for i in (3, 0, 7, 1, 5, 2, 6, 4):
        arena.free(offs[i])
    assert arena.free_bytes() == free0
    assert arena.largest_free() == free0


def test_alloc_exhaustion_returns_none(arena):
    off = arena.alloc(arena.size * 2)
    assert off is None
    # fill, then fail, then free and succeed
    big = arena.alloc(arena.largest_free())
    assert big is not None
    assert arena.alloc(1024) is None
    arena.free(big)
    assert arena.alloc(1024) is not None


def test_double_free_rejected(arena):
    off = arena.alloc(64)
    arena.free(off)
    with pytest.raises(RuntimeError):
        arena.free(off)


def test_attach_shares_state(arena):
    other = SharedArena.attach(arena.name)
    off = other.alloc(4096)
    assert off is not None
    view = other.view(off, 4096)
    view[:5] = b"hello"
    del view
    assert bytes(arena.view(off, 5)) == b"hello"
    arena.free(off)
    other.close()


# -- transport ----------------------------------------------------------------

def _batch(n=10):
    rng = np.random.default_rng(0)
    return ColumnBatch({
        "x": rng.standard_normal((n, 4)).astype(np.float32),
        "i": np.arange(n, dtype=np.int64),
        "s": np.asarray([f"row{k}" for k in range(n)], dtype=object),
    }, n)


def test_encode_decode_roundtrip(arena):
    src = _batch()
    src.ordinal = 17
    ref = encode_batch(arena, src)
    assert isinstance(ref, ShmBatchRef)
    assert ref.columns["s"][0] == "inline"  # object dtype falls back
    out = decode_batch(arena, ref)
    np.testing.assert_array_equal(out.columns["x"], src.columns["x"])
    np.testing.assert_array_equal(out.columns["i"], src.columns["i"])
    assert list(out.columns["s"]) == list(src.columns["s"])
    # the ventilation ordinal must survive the shm hop or the Reader's
    # exact-prefix resume cursor silently degrades under process pools
    assert out.ordinal == 17


def test_decode_is_zero_copy_and_frees_on_gc(arena):
    free0 = arena.free_bytes()
    out = decode_batch(arena, encode_batch(arena, _batch()))
    assert arena.free_bytes() < free0          # block held by the live batch
    base = out.columns["x"].base
    while base is not None and not hasattr(base, "_arena"):
        base = getattr(base, "base", None) or getattr(base, "obj", None)
    assert base is not None                    # arrays really view the arena
    del out, base
    import gc
    gc.collect()
    assert arena.free_bytes() == free0         # lease freed the block


def test_oversized_batch_falls_back(arena):
    n = arena.size // 8  # one float64 column > size/2
    big = ColumnBatch({"x": np.zeros(n, dtype=np.float64)}, n)
    ref = encode_batch(arena, big)
    assert isinstance(ref, ColumnBatch)        # shipped by pickling, not shm


def test_full_arena_times_out_to_fallback(arena):
    hold = arena.alloc(arena.largest_free())   # wedge the arena full
    ref = encode_batch(arena, _batch(), max_wait_s=0.2)
    assert isinstance(ref, ColumnBatch)
    arena.free(hold)


def test_non_batch_results_pass_through(arena):
    assert encode_batch(arena, 42) == 42
    assert decode_batch(arena, "anything") == "anything"


# -- process executor over shm ------------------------------------------------

def test_process_executor_shm_end_to_end(tmp_path):
    """Full reader path over the process pool with the native data plane."""
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.reader import make_reader
    from petastorm_tpu.schema import Field, Schema

    url = str(tmp_path / "ds")
    schema = Schema("Shm", [Field("id", np.int64),
                            Field("vec", np.float32, (8,))])
    rng = np.random.default_rng(5)
    rows = [{"id": i, "vec": rng.standard_normal(8).astype(np.float32)}
            for i in range(64)]
    write_dataset(url, schema, rows, row_group_size_rows=8)

    with make_reader(url, reader_pool_type="process", workers_count=2,
                     shuffle_row_groups=False, num_epochs=1) as reader:
        assert reader.diagnostics.get("shm_transport") is True
        got = sorted(row.id for row in reader)
    assert got == list(range(64))


def test_process_executor_shm_disabled_still_works():
    from petastorm_tpu.pool import _ProcessExecutor
    from petastorm_tpu.test_util.stub_workers import MultiplierWorker

    ex = _ProcessExecutor(workers_count=1, use_shm=False)
    try:
        ex.start(MultiplierWorker(3))
        ex.put(7)
        assert ex.get(timeout=30) == 21
        assert ex.diagnostics["shm_transport"] is False
    finally:
        ex.stop()
        ex.join()


def test_diagnostics_safe_after_join():
    """Regression: free_bytes() on a closed arena dereferenced NULL (SIGSEGV)."""
    from petastorm_tpu.pool import _ProcessExecutor
    from petastorm_tpu.test_util.stub_workers import MultiplierWorker

    ex = _ProcessExecutor(workers_count=1, use_shm=True)
    ex.start(MultiplierWorker(2))
    ex.put(3)
    assert ex.get(timeout=30) == 6
    ex.stop()
    ex.join()
    diag = ex.diagnostics
    assert diag["shm_transport"] is True
    assert diag["shm_free_bytes"] == 0  # closed arena reports 0, not a crash


def test_arena_close_deferred_then_retried():
    """close() with live views defers; a later close() retries the unmap."""
    arena = SharedArena.create(2**20)
    out = decode_batch(arena, encode_batch(arena, _batch()))
    arena.close()
    assert arena.closed
    with pytest.raises(RuntimeError):
        arena.alloc(64)
    del out
    import gc
    gc.collect()
    arena.close()  # second attempt actually unmaps now
    assert arena._unmapped


# -- native batched image decode ----------------------------------------------

class TestNativeImageDecode:
    """native/image.py: batched libpng/libjpeg decode of arrow binary columns."""

    @pytest.fixture(autouse=True)
    def _need_lib(self):
        from petastorm_tpu.native import image as native_image

        if not native_image.available():
            pytest.skip("native image decoder unavailable")

    def _encode_png(self, img):
        import io as _io

        from PIL import Image

        buf = _io.BytesIO()
        Image.fromarray(img).save(buf, format="PNG")
        return buf.getvalue()

    def test_png_batch_matches_source(self):
        import pyarrow as pa

        from petastorm_tpu.native.image import decode_column_native

        rng = np.random.default_rng(7)
        imgs = [rng.integers(0, 255, (32, 48, 3), dtype=np.uint8) for _ in range(5)]
        col = pa.array([self._encode_png(i) for i in imgs], type=pa.binary())
        out = np.empty((5, 32, 48, 3), np.uint8)
        assert decode_column_native(col, out)
        for i in range(5):
            np.testing.assert_array_equal(out[i], imgs[i])

    def test_grayscale_and_internal_threads(self):
        import pyarrow as pa

        from petastorm_tpu.native.image import decode_column_native

        rng = np.random.default_rng(3)
        imgs = [rng.integers(0, 255, (16, 24), dtype=np.uint8) for _ in range(8)]
        col = pa.array([self._encode_png(i) for i in imgs], type=pa.binary())
        out = np.empty((8, 16, 24), np.uint8)
        assert decode_column_native(col, out, nthreads=4)
        for i in range(8):
            np.testing.assert_array_equal(out[i], imgs[i])

    def test_color_png_to_gray_matches_cv2(self):
        """Color streams decoded into a grayscale field must match the cv2
        per-cell fallback bit-for-bit (BT.601 integer math), so tensors do not
        depend on whether the native library built."""
        import pyarrow as pa

        cv2 = pytest.importorskip("cv2")
        from petastorm_tpu.native.image import decode_column_native

        rng = np.random.default_rng(11)
        imgs = [rng.integers(0, 255, (12, 10, 3), dtype=np.uint8) for _ in range(4)]
        encoded = [self._encode_png(i) for i in imgs]
        col = pa.array(encoded, type=pa.binary())
        out = np.empty((4, 12, 10), np.uint8)
        assert decode_column_native(col, out)
        for i in range(4):
            expect = cv2.imdecode(np.frombuffer(encoded[i], np.uint8),
                                  cv2.IMREAD_GRAYSCALE)
            np.testing.assert_array_equal(out[i], expect)

    def test_sliced_column_respects_offset(self):
        import pyarrow as pa

        from petastorm_tpu.native.image import decode_column_native

        rng = np.random.default_rng(5)
        imgs = [rng.integers(0, 255, (8, 8, 3), dtype=np.uint8) for _ in range(6)]
        col = pa.array([self._encode_png(i) for i in imgs], type=pa.binary())
        out = np.empty((3, 8, 8, 3), np.uint8)
        assert decode_column_native(col.slice(2, 3), out)
        for i in range(3):
            np.testing.assert_array_equal(out[i], imgs[2 + i])

    def test_corrupt_stream_raises(self):
        import pyarrow as pa

        from petastorm_tpu.errors import CodecError
        from petastorm_tpu.native.image import decode_column_native

        col = pa.array([b"\x89PNG but not really"], type=pa.binary())
        with pytest.raises(CodecError, match="cell 0"):
            decode_column_native(col, np.empty((1, 8, 8, 3), np.uint8))

    def test_shape_mismatch_raises(self):
        import pyarrow as pa

        from petastorm_tpu.errors import CodecError
        from petastorm_tpu.native.image import decode_column_native

        rng = np.random.default_rng(1)
        img = rng.integers(0, 255, (16, 16, 3), dtype=np.uint8)
        col = pa.array([self._encode_png(img)], type=pa.binary())
        with pytest.raises(CodecError):
            decode_column_native(col, np.empty((1, 8, 8, 3), np.uint8))

    def test_null_cells_fall_back(self):
        import pyarrow as pa

        from petastorm_tpu.native.image import decode_column_native

        col = pa.array([None], type=pa.binary())
        assert not decode_column_native(col, np.empty((1, 8, 8, 3), np.uint8))

    def test_codec_uses_native_path(self, monkeypatch):
        """CompressedImageCodec.decode_column routes through the native decoder."""
        import pyarrow as pa

        from petastorm_tpu.codecs import CompressedImageCodec
        from petastorm_tpu.native import image as native_image
        from petastorm_tpu.schema import Field

        calls = []
        orig = native_image.decode_column_native

        def spy(column, out, nthreads=1):
            calls.append(len(column))
            return orig(column, out, nthreads=nthreads)

        monkeypatch.setattr(native_image, "decode_column_native", spy)
        codec = CompressedImageCodec("png")
        field = Field("img", np.uint8, (16, 16, 3), codec)
        rng = np.random.default_rng(2)
        imgs = [rng.integers(0, 255, (16, 16, 3), dtype=np.uint8) for _ in range(4)]
        col = pa.array([codec.encode(field, i) for i in imgs], type=pa.binary())
        out = codec.decode_column(field, col)
        assert calls == [4]
        for i in range(4):
            np.testing.assert_array_equal(out[i], imgs[i])

    def _encode_jpeg(self, img, quality=90):
        import io as _io

        from PIL import Image

        buf = _io.BytesIO()
        Image.fromarray(img).save(buf, format="JPEG", quality=quality)
        return buf.getvalue()

    def test_jpeg_batch_matches_cv2(self):
        cv2 = pytest.importorskip("cv2")
        import pyarrow as pa

        from petastorm_tpu.native.image import decode_column_native

        rng = np.random.default_rng(11)
        imgs = [rng.integers(0, 255, (32, 48, 3), dtype=np.uint8) for _ in range(4)]
        enc = [self._encode_jpeg(i) for i in imgs]
        col = pa.array(enc, type=pa.binary())
        out = np.empty((4, 32, 48, 3), np.uint8)
        assert decode_column_native(col, out)
        for i in range(4):
            ref = cv2.cvtColor(
                cv2.imdecode(np.frombuffer(enc[i], np.uint8), cv2.IMREAD_COLOR),
                cv2.COLOR_BGR2RGB)
            np.testing.assert_array_equal(out[i], ref)

    def test_jpeg_grayscale(self):
        import pyarrow as pa

        from petastorm_tpu.native.image import decode_column_native

        grad = np.tile(np.linspace(0, 255, 24, dtype=np.uint8), (16, 1))
        col = pa.array([self._encode_jpeg(grad)], type=pa.binary())
        out = np.empty((1, 16, 24), np.uint8)
        assert decode_column_native(col, out)
        assert np.abs(out[0].astype(int) - grad.astype(int)).mean() < 3

    def test_jpeg_dimension_mismatch_raises(self):
        import pyarrow as pa

        from petastorm_tpu.errors import CodecError
        from petastorm_tpu.native.image import decode_column_native

        rng = np.random.default_rng(13)
        img = rng.integers(0, 255, (16, 16, 3), dtype=np.uint8)
        col = pa.array([self._encode_jpeg(img)], type=pa.binary())
        with pytest.raises(CodecError):
            decode_column_native(col, np.empty((1, 8, 8, 3), np.uint8))

    def test_truncated_jpeg_raises_not_crashes(self):
        """setjmp error trap: a truncated stream must error cleanly."""
        import pyarrow as pa

        from petastorm_tpu.errors import CodecError
        from petastorm_tpu.native.image import decode_column_native

        rng = np.random.default_rng(17)
        img = rng.integers(0, 255, (16, 16, 3), dtype=np.uint8)
        enc = self._encode_jpeg(img)
        col = pa.array([enc[:len(enc) // 4]], type=pa.binary())
        with pytest.raises(CodecError):
            decode_column_native(col, np.empty((1, 16, 16, 3), np.uint8))
