"""Pipeline telemetry: metrics registry + stage spans + Chrome-trace export.

One ``Telemetry`` object observes a whole ingest pipeline (reader ->
executor pool -> decode workers -> jax loader).  Components accept a
``telemetry=`` argument and thread it through construction; the default
resolves to the process-wide instance when ``PETASTORM_TPU_TELEMETRY=1`` is
set, else to ``NULL_TELEMETRY`` - a no-op recorder whose hot-path cost is a
single attribute check (``tele.enabled``), so the decode loop pays at most a
branch when telemetry is off.

Usage::

    from petastorm_tpu import telemetry
    tele = telemetry.Telemetry()
    with make_reader(url, telemetry=tele) as reader:
        rows = list(reader)
    print(tele.pipeline_report())        # "dominant stage: decode ..."
    tele.export_chrome_trace("/tmp/ingest_trace.json")   # open in Perfetto

Instrumentation contract used across the repo::

    tele = self._telemetry
    if tele.enabled:                     # the only cost when disabled
        with tele.stage("decode", ordinal=n):
            result = fn(item)
    else:
        result = fn(item)

Stage timers feed three sinks at once: a ``stage.<name>.busy_s`` counter and
``stage.<name>.count`` (the pipeline report's utilization math), a
``stage.<name>.latency_s`` histogram (tail latency), and a trace span (the
Chrome timeline).  Process pools: the parent instruments ventilation and
queue waits; worker-side stage metrics recorded inside spawned worker
processes stay in those processes (the env var is inherited, so they record
independently) - use the thread pool when one merged report matters.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional, Sequence

from petastorm_tpu.telemetry.registry import (DEFAULT_LATENCY_BUCKETS_S,
                                              Counter, Gauge, Histogram,
                                              MetricsRegistry)
from petastorm_tpu.telemetry.report import (STAGE_ORDER, dominant_stage,
                                            render_pipeline_report)
from petastorm_tpu.telemetry.trace import TraceBuffer

__all__ = [
    "Telemetry", "NullTelemetry", "NULL_TELEMETRY", "MetricsRegistry",
    "Counter", "Gauge", "Histogram", "TraceBuffer", "resolve", "enable",
    "enabled_from_env", "render_pipeline_report", "dominant_stage",
    "STAGE_ORDER", "DEFAULT_LATENCY_BUCKETS_S", "ENV_VAR", "NULL_CONTEXT",
    # live observability layer (imported lazily - see module __getattr__):
    # continuous sampling + flight recorder (telemetry.sampler) and the
    # Prometheus/JSONL export sinks (telemetry.export)
    "MetricsSampler", "flight_record", "dump_flight_record",
    "load_flight_records", "MetricsExportServer", "render_prometheus",
    "write_jsonl",
]

_LAZY = {
    "MetricsSampler": "petastorm_tpu.telemetry.sampler",
    "flight_record": "petastorm_tpu.telemetry.sampler",
    "dump_flight_record": "petastorm_tpu.telemetry.sampler",
    "load_flight_records": "petastorm_tpu.telemetry.sampler",
    "MetricsExportServer": "petastorm_tpu.telemetry.export",
    "render_prometheus": "petastorm_tpu.telemetry.export",
    "write_jsonl": "petastorm_tpu.telemetry.export",
}


def __getattr__(name: str):
    # keep `import petastorm_tpu.telemetry` free of http.server etc. on the
    # hot import path; the observability layer loads on first touch
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(target), name)

#: setting this to 1/true/yes/on enables the process-default recorder
ENV_VAR = "PETASTORM_TPU_TELEMETRY"


class _StageTimer:
    """Context manager recording one stage execution into counters, the
    latency histogram and the trace buffer (see module docstring)."""

    __slots__ = ("_tele", "_name", "_args", "_t0")

    def __init__(self, tele: "Telemetry", name: str, args: Optional[Dict]):
        self._tele = tele
        self._name = name
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t0 = self._t0
        dur_ns = time.perf_counter_ns() - t0
        self._tele._record_stage(self._name, t0, dur_ns, self._args)
        return False


class _SpanTimer:
    """Context manager recording one trace span (no stage counters)."""

    __slots__ = ("_tele", "_name", "_cat", "_args", "_t0")

    def __init__(self, tele: "Telemetry", name: str, cat: str,
                 args: Optional[Dict]):
        self._tele = tele
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t0 = self._t0
        self._tele.trace.add(self._name, self._cat, t0,
                             time.perf_counter_ns() - t0, self._args)
        return False


class Telemetry:
    """The live recorder: a MetricsRegistry plus a TraceBuffer.

    Thread-safe throughout; one instance is shared by every component of a
    pipeline (and may be shared across pipelines for a process-wide view).
    """

    enabled = True

    def __init__(self, max_trace_events: int = 200_000):
        self.registry = MetricsRegistry()
        self.trace = TraceBuffer(max_events=max_trace_events)
        # per-stage [busy_ns, count] accumulators; mirrored into counters at
        # snapshot time would lose liveness, so they ARE counters directly
        self._stage_lock = threading.Lock()
        self._stage_hists: Dict[str, Histogram] = {}

    # -- instruments ----------------------------------------------------------

    def counter(self, name: str) -> Counter:
        """The counter named ``name`` (created on first use)."""
        return self.registry.counter(name)

    def gauge(self, name: str) -> Gauge:
        """The gauge named ``name`` (created on first use)."""
        return self.registry.gauge(name)

    def histogram(self, name: str,
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        """The histogram named ``name`` (created on first use; ``buckets``
        default to the stage-latency buckets)."""
        return self.registry.histogram(name, buckets)

    def register_stage(self, name: str) -> None:
        """Pre-create stage ``name``'s instruments (zero-valued counters +
        empty histogram) ahead of its first execution, so reports, the
        metrics sampler and ``diagnose --watch`` frames show the stage as
        "no samples yet" instead of omitting it - a short or just-started
        run must not misname the dominant stage by eliding a late-starting
        one.  Components that know their stages call this at construction
        (ventilator, reader, jax loader)."""
        self.registry.counter(f"stage.{name}.busy_s")
        self.registry.counter(f"stage.{name}.count")
        with self._stage_lock:
            self._stage_hists.setdefault(
                name, self.registry.histogram(f"stage.{name}.latency_s"))

    # -- spans / stage timers -------------------------------------------------

    def span(self, name: str, cat: str = "span", **args) -> _SpanTimer:
        """Trace-only span (shows on the Chrome timeline, no counters)."""
        return _SpanTimer(self, name, cat, args or None)

    def stage(self, name: str, **args) -> _StageTimer:
        """Span + busy-seconds counter + latency histogram for a pipeline
        stage (``ventilate``/``decode``/``transform``/``host-prep``/
        ``device-transfer``, or any component-private stage name)."""
        return _StageTimer(self, name, args or None)

    def record_stage(self, name: str, start_ns: int, dur_ns: int,
                     args: Optional[Dict] = None) -> None:
        """Record one stage execution with an explicit duration - for callers
        that must adjust the measured time (e.g. the ventilator subtracts
        queue-full wait so a blocked ``put`` is not mistaken for busy work);
        prefer ``stage()`` everywhere else."""
        self._record_stage(name, start_ns, dur_ns, args)

    def _record_stage(self, name: str, t0_ns: int, dur_ns: int,
                      args: Optional[Dict]) -> None:
        dur_s = dur_ns / 1e9
        self.registry.counter(f"stage.{name}.busy_s").add(dur_s)
        self.registry.counter(f"stage.{name}.count").add(1)
        hist = self._stage_hists.get(name)
        if hist is None:
            with self._stage_lock:
                hist = self._stage_hists.setdefault(
                    name, self.registry.histogram(f"stage.{name}.latency_s"))
        hist.record(dur_s)
        self.trace.add(name, "stage", t0_ns, dur_ns, args)

    # -- output ---------------------------------------------------------------

    def snapshot(self) -> Dict:
        """JSON-serializable point-in-time view of every instrument, plus
        trace-buffer accounting (``trace_events``/``trace_dropped``)."""
        snap = self.registry.snapshot()
        snap["trace_events"] = len(self.trace)
        snap["trace_dropped"] = self.trace.dropped
        return snap

    def pipeline_report(self) -> str:
        """Human-readable bottleneck summary (stage utilization, queue-full
        vs queue-empty time, dominant stage)."""
        return render_pipeline_report(self.snapshot())

    def chrome_trace(self) -> Dict:
        """Recorded spans in Chrome ``trace_event`` JSON (Perfetto-loadable)."""
        return self.trace.chrome_trace()

    def export_chrome_trace(self, path: str) -> str:
        """Write ``chrome_trace()`` JSON to ``path``; returns the path."""
        return self.trace.export_chrome_trace(path)


class _NullContext:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class _NullInstrument:
    __slots__ = ()
    value = 0.0
    count = 0
    mean = 0.0

    def add(self, amount: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def record(self, value: float) -> None:
        pass


_NULL_CTX = _NullContext()
#: shared do-nothing context manager: instrumented code paths that already
#: branched on ``tele.enabled`` can keep a single ``with`` statement
#: (``with tele.stage(...) if enabled else NULL_CONTEXT:``)
NULL_CONTEXT = _NULL_CTX
_NULL_INSTRUMENT = _NullInstrument()


class NullTelemetry:
    """The zero-cost disabled recorder (the default).

    Every method returns a shared no-op; instrumented hot loops guard with
    ``if tele.enabled:`` so the disabled path costs one attribute check and
    never allocates.
    """

    enabled = False

    def counter(self, name: str) -> _NullInstrument:
        """The shared no-op instrument."""
        return _NULL_INSTRUMENT

    def gauge(self, name: str) -> _NullInstrument:
        """The shared no-op instrument."""
        return _NULL_INSTRUMENT

    def histogram(self, name: str, buckets=None) -> _NullInstrument:
        """The shared no-op instrument."""
        return _NULL_INSTRUMENT

    def register_stage(self, name: str) -> None:
        """No-op."""

    def span(self, name: str, cat: str = "span", **args) -> _NullContext:
        """The shared do-nothing context manager."""
        return _NULL_CTX

    def stage(self, name: str, **args) -> _NullContext:
        """The shared do-nothing context manager."""
        return _NULL_CTX

    def record_stage(self, name: str, start_ns: int, dur_ns: int,
                     args: Optional[Dict] = None) -> None:
        """No-op."""

    def snapshot(self) -> Dict:
        """Always empty."""
        return {}

    def pipeline_report(self) -> str:
        """A pointer at how to enable telemetry."""
        return ("telemetry disabled - pass telemetry= to make_reader /"
                f" JaxDataLoader or set {ENV_VAR}=1")

    def chrome_trace(self) -> Dict:
        """An empty (but valid) Chrome trace object."""
        return {"traceEvents": []}

    def export_chrome_trace(self, path: str) -> str:
        """Write the empty trace to ``path`` (keeps CLI flows uniform)."""
        import json

        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path


NULL_TELEMETRY = NullTelemetry()

_default_lock = threading.Lock()
_default: Optional[Telemetry] = None


def enabled_from_env() -> bool:
    """True when ``PETASTORM_TPU_TELEMETRY`` opts this process in."""
    return os.environ.get(ENV_VAR, "").strip().lower() in ("1", "true", "yes",
                                                           "on")


def enable() -> Telemetry:
    """The process-default live recorder (created on first use).  Spawned
    worker processes inherit the env var and create their own."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = Telemetry()
    return _default


def resolve(telemetry=None):
    """Normalize a component's ``telemetry=`` argument to a recorder.

    ``None`` -> the process default when ``PETASTORM_TPU_TELEMETRY=1``, else
    the no-op recorder; ``True``/``False`` -> process default / no-op
    explicitly; a ``Telemetry`` (or compatible) instance passes through.
    The env var is re-read on every call, so setting it after import works.
    """
    if telemetry is None:
        return enable() if enabled_from_env() else NULL_TELEMETRY
    if telemetry is True:
        return enable()
    if telemetry is False:
        return NULL_TELEMETRY
    return telemetry
