"""On-device (XLA/Pallas) data ops.

Reference parity: the decode half of CompressedImageCodec + the normalize work
every training loop does on host in the reference stack (petastorm/codecs.py:92-101
decodes on CPU; torch/tf pipelines then normalize on device or host).  Here
uint8->float normalize runs ON-CHIP fused (BASELINE.json north star: "uint8->float
normalization happens on-chip"), keeping the host->device transfer at 1 byte/pixel
(4x less PCIe/DCN traffic than shipping float32).
"""

from petastorm_tpu.ops.augment import (cutmix, mixup, random_crop,
                                       random_crop_flip, random_flip,
                                       random_resized_crop, resize_images)
from petastorm_tpu.ops.normalize import normalize_images
from petastorm_tpu.ops.ring_attention import (ring_attention,
                                              ring_attention_sharded)
from petastorm_tpu.ops.ulysses import (ulysses_attention,
                                       ulysses_attention_sharded)

__all__ = ["normalize_images", "ring_attention", "ring_attention_sharded",
           "ulysses_attention", "ulysses_attention_sharded",
           "random_crop", "random_flip", "random_crop_flip",
           "random_resized_crop", "resize_images", "mixup", "cutmix"]
