"""Closed-loop fleet autoscaling + multi-tenant QoS (ISSUE 14):
the AutoscaleSupervisor's verdict loop / settle / hysteresis / graceful
retirement / self-healing floor / exec-hook contract, the dispatcher's
weighted deficit-round-robin with strict priority tiers, admission
control, per-client in-flight caps, the configurable starved threshold,
scaling_signal edge cases, and the per-client counter-cap warning."""

import json
import threading
import time

import numpy as np
import pytest

from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.etl.writer import write_dataset
from petastorm_tpu.pool import VentilatedItem
from petastorm_tpu.reader import make_batch_reader
from petastorm_tpu.schema import Field, Schema
from petastorm_tpu.service.autoscale import (AutoscalePolicy,
                                             AutoscaleSupervisor,
                                             ExecHookSpawner,
                                             InProcessSpawner)
from petastorm_tpu.service.client import ServiceExecutor
from petastorm_tpu.service.dispatcher import (Dispatcher,
                                              compute_recommendation)
from petastorm_tpu.service.worker import ServiceWorker
from petastorm_tpu.telemetry import Telemetry


class PlainEchoFactory:
    def __call__(self):
        return lambda item: item.item


#: serve order observed AT the worker (module-global so the factory keeps
#: pointing at it through the pickle hop to in-process worker threads)
SERVED_ORDER = []


class OrderRecordingEchoFactory:
    """Echo that appends each item to SERVED_ORDER as the worker decodes
    it: the single source of truth for assignment order (client-side
    delivery timestamps race across drain threads)."""

    def __call__(self):
        def fn(item):
            SERVED_ORDER.append(item.item)
            return item.item

        return fn


class SlowEchoFactory:
    """Per-item decode delay: makes a 1-worker fleet a real bottleneck."""

    def __init__(self, delay_s=0.01):
        self.delay_s = delay_s

    def __call__(self):
        delay = self.delay_s

        def fn(item):
            time.sleep(delay)
            return item.item

        return fn


def _wait_for(cond, timeout=15.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def _start_worker(addr, capacity=1, name=None):
    worker = ServiceWorker(addr, capacity=capacity, name=name,
                           heartbeat_interval_s=0.3)
    thread = threading.Thread(target=worker.run, daemon=True)
    thread.start()
    return worker


@pytest.fixture
def dispatcher():
    disp = Dispatcher(telemetry=Telemetry(), heartbeat_timeout_s=5.0).start()
    try:
        yield disp, f"127.0.0.1:{disp.port}"
    finally:
        disp.stop()
        disp.join()


# -- multi-tenant QoS: weighted shares ----------------------------------------

def test_weighted_shares_proportional_and_starvation_free(dispatcher):
    """Acceptance (ISSUE 14): two concurrent greedy clients with weights
    3:1 on a capacity-1 fleet - while both are active, delivered-row
    shares land within 15% of the configured 75/25 split, and the
    low-weight client keeps making progress throughout (no starvation)."""
    disp, addr = dispatcher
    _start_worker(addr, capacity=1)
    _wait_for(lambda: len(disp.stats()["workers"]) == 1)
    n = 80
    results = {}
    done_at = {}

    def run_client(tag, weight):
        ex = ServiceExecutor(addr, telemetry=Telemetry(), window=8,
                             weight=weight)
        ex.start(SlowEchoFactory(0.01))
        deliveries = []

        def feed():
            for i in range(n):
                ex.put(VentilatedItem(i, f"{tag}-{i}"))

        feeder = threading.Thread(target=feed)
        feeder.start()
        for _ in range(n):
            deliveries.append((time.monotonic(), ex.get(timeout=60.0)))
        done_at[tag] = time.monotonic()
        results[tag] = deliveries
        feeder.join()
        ex.stop()
        ex.join()

    threads = [threading.Thread(target=run_client, args=("A", 3.0)),
               threading.Thread(target=run_client, args=("B", 1.0))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # exactness first: QoS must never lose or duplicate a row
    for tag in ("A", "B"):
        assert sorted(int(v.split("-")[1])
                      for _, v in results[tag]) == list(range(n)), tag
    # shares measured while BOTH were active (at the first finisher's
    # completion moment)
    first_done = min(done_at.values())
    got_a = sum(1 for t, _ in results["A"] if t <= first_done)
    got_b = sum(1 for t, _ in results["B"] if t <= first_done)
    share_a = got_a / (got_a + got_b)
    assert abs(share_a - 0.75) <= 0.15, \
        f"A={got_a} B={got_b} share={share_a:.2f} (want 0.75 +- 0.15)"
    # starvation freedom: the low-weight client made real progress while
    # the heavy one was still running
    assert got_b >= n * 0.1, f"B starved: {got_b}/{n} while A ran"


def test_strict_priority_tiers(dispatcher):
    """Priority is STRICT: with both clients' full backlogs pending before
    any worker exists, every high-tier item is SERVED (decoded at the
    capacity-1 worker) before any low-tier one.  Order is measured at the
    worker - client-side delivery timestamps race across drain threads."""
    disp, addr = dispatcher
    n = 25
    del SERVED_ORDER[:]
    hi = ServiceExecutor(addr, telemetry=Telemetry(), window=2 * n,
                         priority=1)
    lo = ServiceExecutor(addr, telemetry=Telemetry(), window=2 * n,
                         priority=0)
    hi.start(OrderRecordingEchoFactory())
    lo.start(OrderRecordingEchoFactory())
    try:
        for i in range(n):
            hi.put(VentilatedItem(i, f"hi-{i}"))
            lo.put(VentilatedItem(i, f"lo-{i}"))
        _wait_for(lambda: sum(c["pending"] for c in
                              disp.stats()["clients"].values()) == 2 * n,
                  what="full backlog pending")
        _start_worker(addr, capacity=1)
        hi_done = []
        lo_done = []

        def drain(ex, out):
            for _ in range(n):
                out.append(ex.get(timeout=30.0))

        threads = [threading.Thread(target=drain, args=(hi, hi_done)),
                   threading.Thread(target=drain, args=(lo, lo_done))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sorted(hi_done) == sorted(f"hi-{i}" for i in range(n))
        assert sorted(lo_done) == sorted(f"lo-{i}" for i in range(n))
        served = list(SERVED_ORDER)
        assert len(served) == 2 * n
        last_hi = max(i for i, v in enumerate(served)
                      if v.startswith("hi-"))
        first_lo = min(i for i, v in enumerate(served)
                       if v.startswith("lo-"))
        assert last_hi < first_lo, \
            (f"a low-priority item was served while high-tier work was"
             f" pending: {served}")
        qos = disp.stats()["qos"]
        prios = {q["priority"] for q in qos.values()}
        assert prios == {0, 1}, qos
    finally:
        for ex in (hi, lo):
            ex.stop()
            ex.join()


def test_admission_control_max_clients():
    """A NEW session past max_clients is refused with a clear error (and
    counted) while admitted sessions keep working."""
    disp = Dispatcher(telemetry=Telemetry(), max_clients=1).start()
    addr = f"127.0.0.1:{disp.port}"
    try:
        _start_worker(addr, capacity=1)
        ex = ServiceExecutor(addr, telemetry=Telemetry(), window=4)
        ex.start(PlainEchoFactory())
        ex.put(VentilatedItem(0, "first"))
        assert ex.get(timeout=15.0) == "first"
        refused = ServiceExecutor(addr, telemetry=Telemetry(), window=4)
        with pytest.raises(OSError, match="admission refused"):
            refused.start(PlainEchoFactory())
        counters = disp.stats()["counters"]
        assert counters.get("service.qos.admission_refused", 0) == 1
        # the admitted session is unaffected
        ex.put(VentilatedItem(1, "second"))
        assert ex.get(timeout=15.0) == "second"
        ex.stop()
        ex.join()
    finally:
        disp.stop()
        disp.join()


def test_per_client_inflight_cap():
    """max_client_inflight caps what one client occupies at the workers:
    its in-flight count never exceeds the cap even with spare fleet
    capacity, and the deferral is counted."""
    disp = Dispatcher(telemetry=Telemetry(), max_client_inflight=2).start()
    addr = f"127.0.0.1:{disp.port}"
    try:
        _start_worker(addr, capacity=4)
        _wait_for(lambda: len(disp.stats()["workers"]) == 1)
        ex = ServiceExecutor(addr, telemetry=Telemetry(), window=16)
        ex.start(SlowEchoFactory(0.03))
        n = 24
        max_seen = 0

        def feed():
            for i in range(n):
                ex.put(VentilatedItem(i, f"i-{i}"))

        feeder = threading.Thread(target=feed)
        feeder.start()
        got = []
        while len(got) < n:
            try:
                got.append(ex.get(timeout=10.0))
            except Exception:  # noqa: BLE001 - assert below names the gap
                break
            stats = disp.stats()
            for c in stats["clients"].values():
                max_seen = max(max_seen, c["inflight"])
        feeder.join()
        assert sorted(int(v.split("-")[1]) for v in got) == list(range(n))
        assert max_seen <= 2, f"inflight cap breached: {max_seen}"
        counters = disp.stats()["counters"]
        assert counters.get("service.qos.capped_deferrals", 0) >= 1
        ex.stop()
        ex.join()
    finally:
        disp.stop()
        disp.join()


def test_reader_qos_kwargs_need_service_address(tmp_path):
    url = str(tmp_path / "ds")
    schema = Schema("QoSInts", [Field("x", np.int64)])
    write_dataset(url, schema, [{"x": i} for i in range(20)],
                  row_group_size_rows=10)
    with pytest.raises(PetastormTpuError, match="service_weight"):
        make_batch_reader(url, service_weight=2.0)
    with pytest.raises(PetastormTpuError, match="service_weight"):
        make_batch_reader(url, service_priority=1)


def test_client_weight_validation():
    with pytest.raises(PetastormTpuError, match="weight must be > 0"):
        ServiceExecutor("127.0.0.1:1", weight=0.0)


# -- satellite: per-client counter cap ----------------------------------------

def test_counter_cap_warns_once_and_stats_stay_exact(dispatcher, caplog):
    """The 100-client registry-counter cap warns ONCE when it trips, adds
    no new counter names past it, and leaves the exact per-client
    accounting (stats()/qos) untouched."""
    disp, _addr = dispatcher
    disp._client_counter_ids.update(f"cid{i:03d}" for i in range(100))
    with caplog.at_level("WARNING"):
        disp._count_client_rows("overflow-client", 10)
        disp._count_client_rows("overflow-client", 10)
        disp._count_client_rows("another-over", 5)
    warnings = [r for r in caplog.records
                if "per-client counter cap" in r.message]
    assert len(warnings) == 1, [r.message for r in caplog.records]
    names = disp.telemetry.snapshot()["counters"]
    assert not any("overflow-client"[:12] in k for k in names)
    assert not any("another-over"[:12] in k for k in names)
    # a pre-cap client still counts
    disp._count_client_rows("cid000", 7)
    names = disp.telemetry.snapshot()["counters"]
    assert names.get("service.client.cid000.rows") == 7


# -- satellite: scaling_signal edge cases -------------------------------------

def test_scaling_signal_empty_window(dispatcher):
    """No reports, no clients, no workers: pressure 0, verdict ok."""
    disp, _addr = dispatcher
    sig = disp.scaling_signal()
    assert sig["pressure"] == 0.0
    assert sig["recommendation"] == "ok"
    assert sig["pending_items"] == 0


def test_scaling_signal_excludes_reports_older_than_window(dispatcher):
    disp, _addr = dispatcher
    now = time.monotonic()
    with disp._lock:
        disp._starved_reports.append((now - 30.0, 50.0))  # stale
        disp._starved_reports.append((now - 0.1, 0.5))    # live
    sig = disp.scaling_signal(window_s=10.0)
    assert sig["pressure"] == pytest.approx(0.05, abs=0.01), sig


def test_scaling_signal_zero_queue_never_grows(dispatcher):
    """Pressure without queued work must NOT recommend grow: the clients'
    bottleneck is not fleet capacity if nothing is waiting for a worker."""
    disp, addr = dispatcher
    _start_worker(addr, capacity=2)
    _wait_for(lambda: len(disp.stats()["workers"]) == 1)
    ex = ServiceExecutor(addr, telemetry=Telemetry(), window=4)
    ex.start(PlainEchoFactory())
    try:
        # a loudly-starved client with an EMPTY queue
        ex._starved_s = 50.0
        ex._stats_sent_at = 0.0
        ex._maybe_send_stats()
        _wait_for(lambda: disp.scaling_signal()["pressure"] > 1.0,
                  what="starved report folded")
        sig = disp.scaling_signal()
        assert sig["pending_items"] == 0
        assert sig["recommendation"] != "grow", sig
    finally:
        ex.stop()
        ex.join()


def test_scaling_signal_purged_client_reports_never_grow(dispatcher):
    """Reports from a client purged past its grace must not leave the
    signal recommending growth for a fleet with no one to serve."""
    disp, addr = dispatcher
    ex = ServiceExecutor(addr, telemetry=Telemetry(), window=4)
    ex.start(PlainEchoFactory())
    ex.put(VentilatedItem(0, "queued"))  # no workers: stays pending
    ex._starved_s = 50.0
    ex._stats_sent_at = 0.0
    ex._maybe_send_stats()
    _wait_for(lambda: disp.scaling_signal()["pressure"] > 1.0,
              what="starved report folded")
    assert disp.scaling_signal()["recommendation"] == "grow"
    ex.stop()  # clean bye -> immediate purge
    ex.join()
    _wait_for(lambda: not disp.stats()["clients"], what="client purged")
    sig = disp.scaling_signal()
    assert sig["pressure"] > 1.0  # reports still in the window...
    assert sig["recommendation"] != "grow", sig  # ...but no one to serve


def test_scaling_signal_threshold_configurable(dispatcher):
    """Satellite: the pressure threshold threads end to end - per call,
    per dispatcher (ctor/--starved-threshold), instead of hard-reading the
    AutotunePolicy class attribute."""
    disp, addr = dispatcher
    ex = ServiceExecutor(addr, telemetry=Telemetry(), window=4)
    ex.start(PlainEchoFactory())
    try:
        ex.put(VentilatedItem(0, "queued"))  # pending work, no workers
        now = time.monotonic()
        with disp._lock:
            disp._starved_reports.append((now, 1.0))  # pressure 0.1
        _wait_for(lambda: any(c["pending"] for c in
                              disp.stats()["clients"].values()),
                  what="queued item visible at the dispatcher")
        assert disp.scaling_signal()["recommendation"] == "grow"  # capacity 0
        sig = disp.scaling_signal(threshold=0.05)
        assert sig["starved_threshold"] == 0.05
        assert sig["recommendation"] == "grow"
    finally:
        ex.stop()
        ex.join()
    # dispatcher-level default
    disp2 = Dispatcher(telemetry=Telemetry(), starved_threshold=0.33).start()
    try:
        assert disp2.scaling_signal()["starved_threshold"] == 0.33
    finally:
        disp2.stop()
        disp2.join()


def test_compute_recommendation_rule():
    # grow needs clients AND pending
    assert compute_recommendation(1.0, 0.2, pending=3, capacity=0,
                                  busy_fraction=0, clients=1) == "grow"
    assert compute_recommendation(1.0, 0.2, pending=0, capacity=0,
                                  busy_fraction=0, clients=1) == "ok"
    assert compute_recommendation(1.0, 0.2, pending=3, capacity=2,
                                  busy_fraction=1.0, clients=0) == "ok"
    # shrink: idle capacity, even with zero clients
    assert compute_recommendation(0.0, 0.2, pending=0, capacity=4,
                                  busy_fraction=0.0, clients=0) == "shrink"
    assert compute_recommendation(0.0, 0.2, pending=0, capacity=4,
                                  busy_fraction=0.5, clients=1) == "ok"


# -- the supervisor (deterministic unit tests on canned signals) --------------

def _sig(recommendation, pressure=0.5, pending=4, capacity=2,
         busy=0.0, clients=1):
    return {"pressure": pressure, "starved_threshold": 0.2,
            "busy_fraction": busy, "pending_items": pending,
            "worker_capacity": capacity, "workers": capacity,
            "connected_clients": clients,
            "recommendation": recommendation}


class FakeDispatcher:
    """Canned scaling signals, popped one per poll (last one repeats)."""

    port = 0

    def __init__(self, signals):
        self.signals = list(signals)

    def scaling_signal(self, window_s=10.0, threshold=None):
        if len(self.signals) > 1:
            return self.signals.pop(0)
        return self.signals[0]


class FakeSpawner:
    external = False

    def __init__(self, retire_ok=True):
        self.spawned = []
        self.retired = []
        self.killed = []
        self.retire_ok = retire_ok
        self.dead = set()

    def spawn(self, name):
        self.spawned.append(name)
        return name

    def alive(self, handle):
        return handle not in self.dead

    def retire(self, handle, timeout_s):
        self.retired.append(handle)
        return self.retire_ok

    def kill(self, handle):
        self.killed.append(handle)


def _supervisor(signals, spawner=None, **policy_kwargs):
    policy_kwargs.setdefault("min_workers", 0)
    policy_kwargs.setdefault("max_workers", 4)
    policy_kwargs.setdefault("grow_windows", 2)
    policy_kwargs.setdefault("shrink_windows", 2)
    policy_kwargs.setdefault("settle_s", 0.2)
    policy_kwargs.setdefault("poll_interval_s", 0.05)
    return AutoscaleSupervisor(
        dispatcher=FakeDispatcher(signals),
        spawner=spawner or FakeSpawner(),
        policy=AutoscalePolicy(**policy_kwargs))


def test_supervisor_grows_only_on_sustained_pressure():
    sup = _supervisor([_sig("grow"), _sig("ok"), _sig("grow"), _sig("grow")])
    assert sup.step() is None      # grow x1: streak 1 < grow_windows
    assert sup.step() is None      # ok: streak resets
    assert sup.step() is None      # grow x1 again
    assert sup.step() == "scale-up"
    assert sup.spawner.spawned == ["as1"]


def test_supervisor_settles_after_scale_event():
    sup = _supervisor([_sig("grow")], settle_s=0.5)
    sup.step()
    assert sup.step() == "scale-up"
    # inside the settle window verdicts do not accumulate
    assert sup.step() is None
    assert sup.step() is None
    assert len(sup.spawner.spawned) == 1
    time.sleep(0.6)
    sup.step()
    assert sup.step() == "scale-up"
    assert len(sup.spawner.spawned) == 2


def test_supervisor_respects_max_workers():
    sup = _supervisor([_sig("grow")], grow_windows=1, settle_s=0.0,
                      max_workers=2)
    assert sup.step() == "scale-up"
    assert sup.step() == "scale-up"
    assert sup.step() is None  # at the ceiling
    assert len(sup.spawner.spawned) == 2


def test_supervisor_shrinks_gracefully_and_counts_force_kills():
    spawner = FakeSpawner()
    sup = _supervisor([_sig("grow"), _sig("shrink")], grow_windows=1,
                      shrink_windows=2, settle_s=0.0, spawner=spawner)
    assert sup.step() == "scale-up"
    sup.step()
    assert sup.step() == "scale-down"
    assert spawner.retired == ["as1"]
    assert not spawner.killed
    assert sup.summary()["counters"]["workers_retired"] == 1
    assert sup.summary()["counters"]["workers_force_killed"] == 0
    # a drain that misses its budget is force-killed (and counted)
    spawner2 = FakeSpawner(retire_ok=False)
    sup2 = _supervisor([_sig("grow"), _sig("shrink")], grow_windows=1,
                       shrink_windows=2, settle_s=0.0, spawner=spawner2,
                       drain_timeout_s=0.1)
    sup2.step()
    sup2.step()
    assert sup2.step() == "scale-down"
    assert spawner2.killed == ["as1"]
    assert sup2.summary()["counters"]["workers_force_killed"] == 1


def test_supervisor_floor_is_self_healing():
    spawner = FakeSpawner()
    sup = _supervisor([_sig("ok")], min_workers=2, spawner=spawner)
    assert sup.step() == "floor"
    assert len(spawner.spawned) == 2
    # one dies on its own: reaped + respawned by the floor, no verdict
    spawner.dead.add(spawner.spawned[0])
    assert sup.step() == "floor"
    assert len(spawner.spawned) == 3
    assert sup.summary()["counters"]["workers_lost"] == 1
    assert sup.fleet_size(None) == 2


def test_supervisor_stop_retires_spawned_fleet():
    spawner = FakeSpawner()
    sup = _supervisor([_sig("grow")], grow_windows=1, settle_s=0.0,
                      spawner=spawner)
    sup.step()
    assert len(spawner.spawned) == 1
    sup.stop()
    assert spawner.retired == ["as1"]
    assert sup.fleet_size(None) == 0


def test_exec_hook_contract(tmp_path):
    """The --exec-hook contract: one JSON object on stdin per scale event
    with action/address/workers/target/pressure/policy fields; bounds
    apply to the OBSERVED worker count for external fleets."""
    out = tmp_path / "events.jsonl"
    hook = ExecHookSpawner(f"cat >> {out}")
    sup = AutoscaleSupervisor(
        dispatcher=FakeDispatcher(
            [_sig("grow", capacity=1), _sig("grow", capacity=1)]),
        spawner=hook,
        policy=AutoscalePolicy(min_workers=0, max_workers=4, grow_windows=1,
                               settle_s=0.0, poll_interval_s=0.05))
    assert sup.step() == "scale-up"
    events = [json.loads(line) for line in
              out.read_text().strip().splitlines()]
    assert len(events) == 1
    ev = events[0]
    assert ev["action"] == "scale_up"
    assert ev["workers"] == 1 and ev["target"] == 2
    assert ev["policy"] == {"min_workers": 0, "max_workers": 4}
    assert "pressure" in ev and "reason" in ev
    # a failing hook is counted, not raised
    sup_fail = AutoscaleSupervisor(
        dispatcher=FakeDispatcher([_sig("grow")]),
        spawner=ExecHookSpawner("exit 3"),
        policy=AutoscalePolicy(min_workers=0, grow_windows=1, settle_s=0.0))
    sup_fail.step()
    assert sup_fail.summary()["counters"]["exec_hook_failures"] == 1


def test_exec_hook_floor_never_actuates_on_a_failed_probe(tmp_path):
    """An external fleet is sized off the OBSERVED worker count; a failed
    probe makes that a guess.  The floor branch must NOT hand the
    orchestrator target=min_workers off a guessed fleet of 0 - that would
    shrink a healthy fleet the supervisor cannot see (and re-fire every
    poll)."""
    out = tmp_path / "events.jsonl"
    sup = AutoscaleSupervisor(
        "127.0.0.1:1",  # dead address: every probe fails
        spawner=ExecHookSpawner(f"cat >> {out}"),
        policy=AutoscalePolicy(min_workers=2, max_workers=8,
                               poll_interval_s=0.05, settle_s=0.2))
    assert sup.step() is None
    assert sup.step() is None
    assert not out.exists(), out.read_text()
    # a live signal showing a short fleet DOES hold the floor...
    sup2 = AutoscaleSupervisor(
        dispatcher=FakeDispatcher([_sig("ok", capacity=1)]),
        spawner=ExecHookSpawner(f"cat >> {out}"),
        policy=AutoscalePolicy(min_workers=2, max_workers=8,
                               poll_interval_s=0.05, settle_s=60.0))
    assert sup2.step() == "floor"
    events = [json.loads(l) for l in out.read_text().strip().splitlines()]
    assert len(events) == 1 and events[0]["target"] == 2
    # ...and settles instead of re-firing while registration lags
    assert sup2.step() is None
    assert len(out.read_text().strip().splitlines()) == 1


def test_admission_counts_only_connected_sessions():
    """A crashed trainer riding out its reconnect grace must not hold a
    seat against its replacement: the max_clients cap counts CONNECTED
    sessions only."""
    disp = Dispatcher(telemetry=Telemetry(), max_clients=1).start()
    addr = f"127.0.0.1:{disp.port}"
    try:
        _start_worker(addr, capacity=1)
        ex = ServiceExecutor(addr, telemetry=Telemetry(), window=4)
        ex.start(PlainEchoFactory())
        ex.put(VentilatedItem(0, "a"))
        assert ex.get(timeout=15.0) == "a"
        # simulate an unclean death mid-grace: the session state lingers
        # but the seat frees the moment the connection is gone
        with disp._lock:
            cid = next(iter(disp._clients))
            disp._clients[cid].connected = False
            disp._clients[cid].disconnected_at = time.monotonic()
        ex2 = ServiceExecutor(addr, telemetry=Telemetry(), window=4)
        ex2.start(PlainEchoFactory())  # must be ADMITTED
        ex2.put(VentilatedItem(0, "b"))
        assert ex2.get(timeout=15.0) == "b"
        ex2.stop()
        ex2.join()
        ex.stop()
        ex.join()
    finally:
        disp.stop()
        disp.join()


def test_supervisor_remote_probe_and_threshold_override(dispatcher):
    """The address-mode supervisor probes stats frames and re-judges the
    verdict under its own --starved-threshold using the shared rule."""
    disp, addr = dispatcher
    ex = ServiceExecutor(addr, telemetry=Telemetry(), window=4)
    ex.start(PlainEchoFactory())
    try:
        ex.put(VentilatedItem(0, "queued"))
        now = time.monotonic()
        with disp._lock:
            disp._starved_reports.append((now, 1.0))  # pressure 0.1
        _start_worker(addr, capacity=1)  # so capacity > 0: verdict hinges
        _wait_for(lambda: len(disp.stats()["workers"]) == 1)
        #                  purely on the threshold
        sup = AutoscaleSupervisor(
            addr, spawner=FakeSpawner(),
            policy=AutoscalePolicy(min_workers=0, starved_threshold=0.05))
        sig = sup.signal()
        assert sig is not None
        assert sig["starved_threshold"] == 0.05
        # 0.1 > 0.05 and work is pending (the worker may or may not have
        # drained the one item yet; accept both verdicts consistently)
        expected = compute_recommendation(
            sig["pressure"], 0.05, sig["pending_items"],
            sig["worker_capacity"], sig["busy_fraction"],
            sig["connected_clients"])
        assert sig["recommendation"] == expected
        # probe failure path: dead address
        sup2 = AutoscaleSupervisor(
            "127.0.0.1:1", spawner=FakeSpawner(),
            policy=AutoscalePolicy(min_workers=0))
        assert sup2.signal() is None
        assert sup2.summary()["counters"]["probe_failures"] == 1
    finally:
        ex.stop()
        ex.join()


def test_supervisor_ctor_validation():
    with pytest.raises(PetastormTpuError, match="exactly one"):
        AutoscaleSupervisor()
    with pytest.raises(PetastormTpuError, match="exactly one"):
        AutoscaleSupervisor("127.0.0.1:1", dispatcher=FakeDispatcher([]))
    with pytest.raises(PetastormTpuError, match="explicit spawner"):
        AutoscaleSupervisor(dispatcher=FakeDispatcher([]))
    with pytest.raises(PetastormTpuError, match="max_workers"):
        AutoscalePolicy(min_workers=4, max_workers=2)
    with pytest.raises(PetastormTpuError, match="non-empty"):
        ExecHookSpawner("  ")


# -- graceful worker retirement (the scale-down primitive) --------------------

def test_worker_graceful_retire_finishes_inflight(dispatcher):
    """retire() drains: every item the worker held is DELIVERED (not
    requeued), the dispatcher stops assigning to it the moment it
    announces, and the worker exits clean."""
    disp, addr = dispatcher
    worker = ServiceWorker(addr, capacity=2, heartbeat_interval_s=0.3)
    thread = threading.Thread(target=worker.run, daemon=True)
    thread.start()
    _wait_for(lambda: len(disp.stats()["workers"]) == 1)
    ex = ServiceExecutor(addr, telemetry=Telemetry(), window=8)
    ex.start(SlowEchoFactory(0.05))
    n = 10
    got = []

    def feed():
        for i in range(n):
            ex.put(VentilatedItem(i, f"i-{i}"))

    feeder = threading.Thread(target=feed)
    feeder.start()
    got.append(ex.get(timeout=15.0))  # the worker is mid-stream now
    retire_done = {}

    def retire():
        retire_done["graceful"] = worker.retire(timeout=30.0)

    retirer = threading.Thread(target=retire)
    retirer.start()
    # the retiring worker must still complete what it holds; remaining
    # items stay PENDING at the dispatcher (no free non-draining workers)
    # until a replacement joins
    _wait_for(lambda: disp.stats()["workers"].get(
        worker.worker_name, {}).get("draining", False) or
        worker.worker_name not in disp.stats()["workers"],
        what="draining visible in stats")
    _start_worker(addr, capacity=2, name="replacement")
    while len(got) < n:
        got.append(ex.get(timeout=30.0))
    retirer.join(timeout=30.0)
    feeder.join()
    assert retire_done.get("graceful") is True
    assert worker.retired_gracefully
    assert sorted(int(v.split("-")[1]) for v in got) == list(range(n))
    counters = disp.stats()["counters"]
    assert counters.get("service.requeued_items", 0) == 0, counters
    assert counters.get("service.qos.workers_draining", 0) == 1
    ex.stop()
    ex.join()
    thread.join(timeout=5.0)
    assert not thread.is_alive()


# -- supervision through a dispatcher failover (ISSUE 17 satellite) -----------

def test_supervisor_probes_through_failover_address_list():
    """An address-mode supervisor given the failover list keeps judging
    the LIVE fleet across a primary kill: pre-failover it probes the
    primary and SKIPS the unpromoted standby; post-failover it rotates to
    the promoted standby instead of reporting a dead fleet."""
    from petastorm_tpu.test_util.matrix import ha_fleet

    with ha_fleet(n_workers=1, capacity=1) as fleet:
        sup = AutoscaleSupervisor(
            fleet.address, spawner=FakeSpawner(),
            policy=AutoscalePolicy(min_workers=0))
        # a healthy primary answers; the probe stays parked on it
        assert sup.signal() is not None
        assert sup._probe_index == 0
        # an unpromoted standby is NOT a probe target: with only the
        # standby to ask, the probe fails rather than supervising a
        # mirror that assigns nothing
        lone = AutoscaleSupervisor(
            fleet.standby_direct, spawner=FakeSpawner(),
            policy=AutoscalePolicy(min_workers=0))
        assert lone.signal() is None
        assert lone.summary()["counters"]["probe_failures"] == 1
        # kill the primary mid-supervision: the next probe rotates to the
        # promoted standby and supervision continues uninterrupted
        fleet.failover()
        sig = sup.signal()
        assert sig is not None, "supervisor lost the fleet at failover"
        assert sup._probe_index == 1
        # the worker rejoins the promoted standby; supervision sees it
        _wait_for(lambda: (sup.signal() or {}).get("worker_capacity",
                                                   0) >= 1,
                  what="rejoined capacity visible through the probe")
        assert sup.summary()["counters"].get("probe_failures", 0) == 0
