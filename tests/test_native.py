"""Native shared-memory transport tests: allocator, transport, process pool.

Reference parity: workers_pool/tests/test_workers_pool.py exercises the zmq
data plane in both copy modes; here the native arena replaces zmq.
"""

import numpy as np
import pytest

from petastorm_tpu.batch import ColumnBatch

native = pytest.importorskip("petastorm_tpu.native")
if not native.is_available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)

from petastorm_tpu.native import SharedArena  # noqa: E402
from petastorm_tpu.native.transport import (ShmBatchRef, decode_batch,  # noqa: E402
                                            encode_batch)


@pytest.fixture()
def arena():
    a = SharedArena.create(4 * 2**20)
    yield a
    a.close()


# -- allocator ----------------------------------------------------------------

def test_alloc_free_roundtrip(arena):
    free0 = arena.free_bytes()
    off = arena.alloc(1000)
    assert off is not None and off % 64 == 0
    assert arena.free_bytes() < free0
    arena.free(off)
    assert arena.free_bytes() == free0


def test_out_of_order_free_coalesces(arena):
    free0 = arena.free_bytes()
    offs = [arena.alloc(100_000) for _ in range(8)]
    assert all(o is not None for o in offs)
    # free in scrambled order; afterwards the arena must be one block again
    for i in (3, 0, 7, 1, 5, 2, 6, 4):
        arena.free(offs[i])
    assert arena.free_bytes() == free0
    assert arena.largest_free() == free0


def test_alloc_exhaustion_returns_none(arena):
    off = arena.alloc(arena.size * 2)
    assert off is None
    # fill, then fail, then free and succeed
    big = arena.alloc(arena.largest_free())
    assert big is not None
    assert arena.alloc(1024) is None
    arena.free(big)
    assert arena.alloc(1024) is not None


def test_double_free_rejected(arena):
    off = arena.alloc(64)
    arena.free(off)
    with pytest.raises(RuntimeError):
        arena.free(off)


def test_attach_shares_state(arena):
    other = SharedArena.attach(arena.name)
    off = other.alloc(4096)
    assert off is not None
    view = other.view(off, 4096)
    view[:5] = b"hello"
    del view
    assert bytes(arena.view(off, 5)) == b"hello"
    arena.free(off)
    other.close()


# -- transport ----------------------------------------------------------------

def _batch(n=10):
    rng = np.random.default_rng(0)
    return ColumnBatch({
        "x": rng.standard_normal((n, 4)).astype(np.float32),
        "i": np.arange(n, dtype=np.int64),
        "s": np.asarray([f"row{k}" for k in range(n)], dtype=object),
    }, n)


def test_encode_decode_roundtrip(arena):
    src = _batch()
    ref = encode_batch(arena, src)
    assert isinstance(ref, ShmBatchRef)
    assert ref.columns["s"][0] == "inline"  # object dtype falls back
    out = decode_batch(arena, ref)
    np.testing.assert_array_equal(out.columns["x"], src.columns["x"])
    np.testing.assert_array_equal(out.columns["i"], src.columns["i"])
    assert list(out.columns["s"]) == list(src.columns["s"])


def test_decode_is_zero_copy_and_frees_on_gc(arena):
    free0 = arena.free_bytes()
    out = decode_batch(arena, encode_batch(arena, _batch()))
    assert arena.free_bytes() < free0          # block held by the live batch
    base = out.columns["x"].base
    while base is not None and not hasattr(base, "_arena"):
        base = getattr(base, "base", None) or getattr(base, "obj", None)
    assert base is not None                    # arrays really view the arena
    del out, base
    import gc
    gc.collect()
    assert arena.free_bytes() == free0         # lease freed the block


def test_oversized_batch_falls_back(arena):
    n = arena.size // 8  # one float64 column > size/2
    big = ColumnBatch({"x": np.zeros(n, dtype=np.float64)}, n)
    ref = encode_batch(arena, big)
    assert isinstance(ref, ColumnBatch)        # shipped by pickling, not shm


def test_full_arena_times_out_to_fallback(arena):
    hold = arena.alloc(arena.largest_free())   # wedge the arena full
    ref = encode_batch(arena, _batch(), max_wait_s=0.2)
    assert isinstance(ref, ColumnBatch)
    arena.free(hold)


def test_non_batch_results_pass_through(arena):
    assert encode_batch(arena, 42) == 42
    assert decode_batch(arena, "anything") == "anything"


# -- process executor over shm ------------------------------------------------

def test_process_executor_shm_end_to_end(tmp_path):
    """Full reader path over the process pool with the native data plane."""
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.reader import make_reader
    from petastorm_tpu.schema import Field, Schema

    url = str(tmp_path / "ds")
    schema = Schema("Shm", [Field("id", np.int64),
                            Field("vec", np.float32, (8,))])
    rng = np.random.default_rng(5)
    rows = [{"id": i, "vec": rng.standard_normal(8).astype(np.float32)}
            for i in range(64)]
    write_dataset(url, schema, rows, row_group_size_rows=8)

    with make_reader(url, reader_pool_type="process", workers_count=2,
                     shuffle_row_groups=False, num_epochs=1) as reader:
        assert reader.diagnostics.get("shm_transport") is True
        got = sorted(row.id for row in reader)
    assert got == list(range(64))


def test_process_executor_shm_disabled_still_works():
    from petastorm_tpu.pool import _ProcessExecutor
    from petastorm_tpu.test_util.stub_workers import MultiplierWorker

    ex = _ProcessExecutor(workers_count=1, use_shm=False)
    try:
        ex.start(MultiplierWorker(3))
        ex.put(7)
        assert ex.get(timeout=30) == 21
        assert ex.diagnostics["shm_transport"] is False
    finally:
        ex.stop()
        ex.join()


def test_diagnostics_safe_after_join():
    """Regression: free_bytes() on a closed arena dereferenced NULL (SIGSEGV)."""
    from petastorm_tpu.pool import _ProcessExecutor
    from petastorm_tpu.test_util.stub_workers import MultiplierWorker

    ex = _ProcessExecutor(workers_count=1, use_shm=True)
    ex.start(MultiplierWorker(2))
    ex.put(3)
    assert ex.get(timeout=30) == 6
    ex.stop()
    ex.join()
    diag = ex.diagnostics
    assert diag["shm_transport"] is True
    assert diag["shm_free_bytes"] == 0  # closed arena reports 0, not a crash


def test_arena_close_deferred_then_retried():
    """close() with live views defers; a later close() retries the unmap."""
    arena = SharedArena.create(2**20)
    out = decode_batch(arena, encode_batch(arena, _batch()))
    arena.close()
    assert arena.closed
    with pytest.raises(RuntimeError):
        arena.alloc(64)
    del out
    import gc
    gc.collect()
    arena.close()  # second attempt actually unmaps now
    assert arena._unmapped
