"""ImageNet-style ResNet-50 training feed on TPU: the flagship benchmark path.

Reference parity: examples/imagenet/ (petastorm ImageNet dataset + pytorch
feed).  TPU re-design: JPEG-compressed images are stored via
CompressedImageCodec, decoded by host workers, shipped as uint8 (1 byte/pixel
over PCIe/DCN), normalized ON-CHIP (ops.normalize_images, fused by XLA into
the first conv), and the global batch is sharded over the mesh's 'data' axis
by the loader.  Run with --steps/--rows sized for your pod; the defaults are
smoke-test sized.

This is the BASELINE.md north-star shape: samples/sec/chip feeding ResNet-50.
"""

import argparse
import tempfile
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
from petastorm_tpu.etl.writer import write_dataset
from petastorm_tpu.jax import JaxDataLoader
from petastorm_tpu.models import ResNet50
from petastorm_tpu.ops import (normalize_images, random_flip,
                               random_resized_crop)
from petastorm_tpu.reader import make_reader
from petastorm_tpu.schema import Field, Schema


def imagenet_schema(side: int) -> Schema:
    return Schema("ImagenetLike", [
        Field("label", np.int64, (), ScalarCodec()),
        Field("image", np.uint8, (side, side, 3),
              CompressedImageCodec("jpeg", quality=90)),
    ])


def generate_dataset(url: str, rows: int, side: int, seed: int = 0) -> None:
    rng = np.random.default_rng(seed)
    schema = imagenet_schema(side)

    def row(i):
        label = int(rng.integers(0, 1000))
        base = rng.integers(0, 255, (side, side, 3)).astype(np.uint8)
        return {"label": label, "image": base}

    write_dataset(url, schema, (row(i) for i in range(rows)),
                  row_group_size_rows=max(rows // 8, 1), mode="overwrite")


def train(dataset_url: str, steps: int, global_batch: int, side: int,
          num_classes: int = 1000, decode: str = "device",
          workers: int = 4, prefetch: int = 2, cache: str = "null") -> dict:
    """Run ``steps`` real ResNet-50 train steps fed by the loader; returns a
    metrics dict incl. samples/sec/chip and the input-attributable device-idle
    percentage (consumer wait vs wall time over the measured window)."""
    devices = jax.devices()
    mesh = Mesh(np.asarray(devices), ("data",))
    model = ResNet50(num_classes=num_classes)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, side, side, 3), jnp.bfloat16))
    # replicate params across the mesh; batch is sharded over 'data'
    params = jax.device_put(params, NamedSharding(mesh, P()))
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = tx.init(params)

    @jax.jit
    def train_step(params, opt_state, image_u8, label, key):
        def loss_fn(p):
            k1, k2 = jax.random.split(key)
            # the full ImageNet train transform, ON-CHIP: per-image
            # RandomResizedCrop (scale/ratio sampling, one static-shape
            # kernel), flip, then uint8 -> bf16 normalize - host workers
            # stay decode-only
            imgs = random_resized_crop(image_u8, k1, (side, side))
            imgs = random_flip(imgs, k2)
            x = normalize_images(imgs)          # on-chip uint8 -> bf16 + scale
            logits = model.apply(p, x)
            onehot = jax.nn.one_hot(label, num_classes)
            return -(jax.nn.log_softmax(logits) * onehot).sum(-1).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # decode='device': hybrid jpeg decode - host does only entropy decode,
    # dequant + IDCT + upsample + color run on-chip (ops/jpeg.py)
    if decode == "device":
        from petastorm_tpu.native import image as native_image

        if not native_image.available():
            print("native image library unavailable; falling back to host decode")
            decode = "host"
    placement = {"image": "device"} if decode == "device" else None
    # cache='memory' keeps decoded (or entropy-decoded, for decode='device')
    # batches in a host LRU: epochs after the first skip parquet+jpeg work
    # entirely - the answer for datasets that fit host RAM
    reader = make_reader(dataset_url, num_epochs=None, workers_count=workers,
                         decode_placement=placement, cache_type=cache)
    step = 0
    with JaxDataLoader(reader, batch_size=global_batch, mesh=mesh,
                       prefetch=prefetch,
                       shardings={"image": P("data"), "label": P("data")}) as loader:
        it = iter(loader)
        # warmup: compile, fill queues
        aug_key = jax.random.PRNGKey(17)
        batch = next(it)
        params, opt_state, loss = train_step(params, opt_state,
                                             batch["image"], batch["label"],
                                             aug_key)
        jax.block_until_ready(loss)
        # consumer_wait_s accumulates while __next__ blocks on the prefetch
        # queue: the delta over the measured window IS the device-idle time
        # attributable to input starvation during REAL train steps
        wait0 = loader.diagnostics["consumer_wait_s"]
        t0 = time.perf_counter()
        for batch in it:
            params, opt_state, loss = train_step(params, opt_state,
                                                 batch["image"], batch["label"],
                                                 jax.random.fold_in(aug_key, step))
            step += 1
            if step >= steps:
                break
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        diag = loader.diagnostics
        input_wait_s = diag["consumer_wait_s"] - wait0
    samples = steps * global_batch
    return {
        "samples_per_sec": samples / dt,
        "samples_per_sec_per_chip": samples / dt / len(devices),
        "device_idle_pct": 100.0 * input_wait_s / dt,
        "steps": steps,
        "global_batch": global_batch,
        "wall_s": dt,
        "decode": decode,
        "n_devices": len(devices),
        "final_loss": float(loss),
        "diagnostics": diag,
    }


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--dataset-url", default=None)
    parser.add_argument("--rows", type=int, default=256)
    parser.add_argument("--side", type=int, default=224)
    parser.add_argument("--steps", type=int, default=8)
    parser.add_argument("--global-batch", type=int, default=32)
    parser.add_argument("--decode", choices=("host", "device"), default="device",
                        help="device = hybrid on-chip jpeg decode")
    parser.add_argument("--workers", type=int, default=4)
    parser.add_argument("--prefetch", type=int, default=2)
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--cache", choices=("null", "memory", "local-disk"),
                        default="null",
                        help="memory = host LRU; warm epochs skip all decode")
    parser.add_argument("--skip-generate", action="store_true",
                        help="dataset-url already holds the dataset")
    parser.add_argument("--json", action="store_true",
                        help="print the metrics dict as one JSON line")
    args = parser.parse_args()
    url = args.dataset_url or tempfile.mkdtemp(prefix="imagenet_tpu_") + "/imagenet"
    if not args.skip_generate:
        generate_dataset(url, args.rows, args.side)
    m = train(url, args.steps, args.global_batch, args.side,
              num_classes=args.num_classes, decode=args.decode,
              workers=args.workers, prefetch=args.prefetch, cache=args.cache)
    if args.json:
        import json

        print(json.dumps(m))
    else:
        print(f"{m['steps'] * m['global_batch']} samples in {m['wall_s']:.2f}s"
              f" = {m['samples_per_sec']:.1f} samples/sec"
              f" ({m['samples_per_sec_per_chip']:.1f} samples/sec/chip on"
              f" {m['n_devices']} chip(s)), device idle"
              f" {m['device_idle_pct']:.1f}% (input-bound), final loss"
              f" {m['final_loss']:.4f}")
