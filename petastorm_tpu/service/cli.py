"""``petastorm-tpu-service``: run a dispatcher or a fleet worker.

Usage::

    petastorm-tpu-service dispatcher --port 7737 [--metrics-port 9100]
    petastorm-tpu-service worker --address HOST:7737 [--capacity 4]
    petastorm-tpu-service autoscale --address HOST:7737 --max-workers 8
    petastorm-tpu-service stats --address HOST:7737 [--watch]

``autoscale`` runs the closed-loop fleet supervisor
(:mod:`petastorm_tpu.service.autoscale`): it polls the dispatcher's
scaling signal and spawns/retires local worker subprocesses (or invokes
``--exec-hook`` for k8s-style orchestrators), printing one JSON line per
scale event and a final counters summary.  SIGTERM/Ctrl-C drains the
spawned fleet gracefully before exiting.  A ``worker`` process retires
gracefully on SIGTERM too (drain in-flight, flush, goodbye).

Topology and sizing guidance: docs/operations.md "Disaggregated ingest
service".  Trainers connect with ``make_reader(...,
service_address='HOST:7737')``.

The dispatcher binds loopback by default.  The v2 wire is pickle-free
binary frames (parsing service bytes can no longer execute code), but the
service's *job* is running client-shipped worker factories on the fleet -
so the handshake secret (``$PETASTORM_TPU_SERVICE_TOKEN`` or
``--auth-token-file``) decides who may ship code to workers.  Bind
non-loopback interfaces only on trusted networks, with the token set on
every party.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import time
from typing import List, Optional


_TRUST_WARNING = (
    "SECURITY: the v2 wire is pickle-free binary frames (merely reaching"
    " the port no longer yields code execution), but workers execute the"
    " worker factory each REGISTERED client ships - that is the service's"
    " job.  Set a shared secret via $PETASTORM_TPU_SERVICE_TOKEN or"
    " --auth-token-file (all parties must agree) to decide who may"
    " register, and expose non-loopback interfaces only on trusted"
    " networks.  See docs/operations.md 'Disaggregated ingest service'.")


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-service",
        description="Disaggregated ingest service: dispatcher + workers",
        epilog=_TRUST_WARNING)
    sub = parser.add_subparsers(dest="command", required=True)

    d = sub.add_parser("dispatcher", help="run the dispatcher control plane",
                       epilog=_TRUST_WARNING)
    d.add_argument("--host", default="127.0.0.1",
                   help="bind address (default loopback; binding a"
                   " non-loopback interface exposes remote code execution"
                   " to that network - see the SECURITY note below)")
    d.add_argument("--port", type=int, default=7737,
                   help="listen port (0 = ephemeral, printed at start)")
    d.add_argument("--heartbeat-timeout", type=float, default=10.0,
                   metavar="S", help="declare a silent worker dead after"
                   " this many seconds (default 10)")
    d.add_argument("--client-grace", type=float, default=30.0, metavar="S",
                   help="keep a disconnected client's state this long for a"
                   " reconnect (default 30)")
    d.add_argument("--max-requeue-attempts", type=int, default=None,
                   help="default per-item requeue budget for clients that"
                   " do not bring their own")
    d.add_argument("--assignment-deadline", type=float, default=None,
                   metavar="S", help="liveness backstop: drop a worker"
                   " whose assigned item produced no outcome for S seconds"
                   " (it keeps heartbeating while wedged in user code);"
                   " size WELL above the slowest legitimate decode."
                   " Default off")
    d.add_argument("--metrics-port", type=int, default=None, metavar="N",
                   help="serve service.* series in Prometheus text format"
                   " on localhost:N (0 = ephemeral)")
    d.add_argument("--stats-interval", type=float, default=0.0, metavar="S",
                   help="print a JSON stats line (fleet, clients, scaling"
                   " signal) every S seconds (0 = off)")
    d.add_argument("--auth-token-file", default=None, metavar="PATH",
                   help="file holding the shared handshake secret every"
                   " hello must present (overrides"
                   " $PETASTORM_TPU_SERVICE_TOKEN)")
    d.add_argument("--journal", default=None, metavar="PATH",
                   help="session journal file for WARM restarts: client"
                   " sessions + unresolved work items replay from it on"
                   " start, and reconnecting clients skip re-sending what"
                   " it restored.  Crash recovery works WITHOUT it (peers"
                   " reconstruct the state); the journal just makes a"
                   " restart cheaper (docs/operations.md 'Fault domains')")
    d.add_argument("--journal-fsync", action="store_true",
                   help="fsync every journal record (power-loss-proof tail"
                   " at a device round-trip per append, metered as"
                   " service.journal_fsyncs; default off - flush-only, a"
                   " host power loss can truncate the tail and peers/"
                   "standby re-fetch the difference)")
    d.add_argument("--standby-of", default=None, metavar="HOST:PORT",
                   help="run as the HOT STANDBY of the primary dispatcher"
                   " at HOST:PORT: tail its session journal over the wire"
                   " (journal_sync frames), refuse client/worker hellos"
                   " while it lives, and promote with warm state when it"
                   " dies.  Point peers at a failover list"
                   " 'primary:port,standby:port' so they rotate here on"
                   " promotion (docs/operations.md 'Dispatcher HA')")
    d.add_argument("--replay-buffer-mb", type=int, default=256, metavar="MB",
                   help="cap on unacked result BODIES retained for"
                   " reconnect replay, across all clients (default 256);"
                   " overflow degrades the oldest to header-only and the"
                   " owning client re-fetches on reconnect"
                   " (service.replay_bodies_dropped)")
    d.add_argument("--compression", default=None,
                   choices=["auto", "off", "zlib"],
                   help="result-batch body compression, negotiated per"
                   " (worker, client) pair: 'auto' (default) compresses"
                   " cross-host hops only, 'off' never, 'zlib' wherever"
                   " both ends support it (defaults to"
                   " $PETASTORM_TPU_SERVICE_COMPRESSION)")
    d.add_argument("--starved-threshold", type=float, default=None,
                   metavar="X", help="scaling-signal pressure (starved-"
                   "seconds per second) above which the signal recommends"
                   " grow (default: the in-process autotune policy's"
                   " starved_threshold)")
    d.add_argument("--max-clients", type=int, default=None, metavar="N",
                   help="admission control: refuse NEW client sessions past"
                   " N live ones (reconnects always pass; default"
                   " unbounded)")
    d.add_argument("--max-client-inflight", type=int, default=None,
                   metavar="N", help="per-client cap on items in flight at"
                   " workers: a client at the cap waits for its own results"
                   " before being assigned more, so one greedy trainer"
                   " degrades itself, not the fleet (default: bounded only"
                   " by each client's window)")

    w = sub.add_parser("worker", help="run one fleet worker",
                       epilog=_TRUST_WARNING)
    w.add_argument("--address", required=True, metavar="HOST:PORT",
                   help="dispatcher address; a comma-separated failover"
                   " list 'primary:port,standby:port' makes registration"
                   " rotate onto the promoted standby when the primary"
                   " dies (pair with --reconnect-attempts)")
    w.add_argument("--capacity", type=int, default=2,
                   help="concurrent work items this worker accepts"
                   " (default 2)")
    w.add_argument("--name", default=None, help="worker name (default"
                   " assigned by the dispatcher)")
    w.add_argument("--shm-size-mb", type=int, default=0, metavar="MB",
                   help="arm the co-located-client shared-memory fast path"
                   " with an arena this large (0 = plain frame payloads;"
                   " needs the native transport plane)")
    w.add_argument("--reconnect-attempts", type=int, default=0,
                   help="survive dispatcher restarts: retry registration"
                   " this many times (default 0 = exit with the dispatcher)")
    w.add_argument("--auth-token-file", default=None, metavar="PATH",
                   help="file holding the dispatcher's shared handshake"
                   " secret (overrides $PETASTORM_TPU_SERVICE_TOKEN)")

    a = sub.add_parser(
        "autoscale", help="run the closed-loop fleet supervisor",
        epilog="The supervisor spawns `worker` subprocesses against"
               " --address (or invokes --exec-hook) off the dispatcher's"
               " grow/ok/shrink scaling signal.  Scale-down is graceful:"
               " the worker drains its in-flight items before exiting, so"
               " deterministic streams ride scale events untouched.  See"
               " docs/operations.md 'Fleet autoscaling & QoS'.")
    a.add_argument("--address", required=True, metavar="HOST:PORT",
                   help="dispatcher address to supervise; a comma-"
                   "separated failover list 'primary:port,standby:port'"
                   " keeps the supervisor probing through a dispatcher"
                   " failover instead of reporting a dead fleet")
    a.add_argument("--min-workers", type=int, default=1,
                   help="fleet floor, held self-healingly (default 1)")
    a.add_argument("--max-workers", type=int, default=8,
                   help="fleet ceiling (default 8)")
    a.add_argument("--poll-interval", type=float, default=1.0, metavar="S",
                   help="scaling-signal poll cadence (default 1s)")
    a.add_argument("--grow-windows", type=int, default=3, metavar="N",
                   help="consecutive grow verdicts before a scale-up"
                   " (default 3)")
    a.add_argument("--shrink-windows", type=int, default=6, metavar="N",
                   help="consecutive shrink verdicts before a scale-down"
                   " (default 6)")
    a.add_argument("--settle", type=float, default=5.0, metavar="S",
                   help="post-scale-event settle window before verdicts"
                   " accumulate again (default 5s)")
    a.add_argument("--capacity", type=int, default=2,
                   help="capacity of spawned workers (default 2)")
    a.add_argument("--starved-threshold", type=float, default=None,
                   metavar="X", help="override the grow pressure threshold"
                   " for this supervisor (default: whatever the dispatcher"
                   " reports)")
    a.add_argument("--drain-timeout", type=float, default=30.0, metavar="S",
                   help="graceful-drain budget per retirement before a"
                   " force-kill (default 30s)")
    a.add_argument("--shm-size-mb", type=int, default=0, metavar="MB",
                   help="arm spawned workers' co-located shm fast path"
                   " (default 0 = off)")
    a.add_argument("--exec-hook", default=None, metavar="CMD",
                   help="replace local spawning: run CMD through the shell"
                   " with one JSON scale event on stdin ({action:"
                   " scale_up|scale_down, address, workers, target,"
                   " pressure, recommendation, reason, policy}) - the"
                   " orchestrator owns the fleet; bounds then apply to the"
                   " OBSERVED worker count")
    a.add_argument("--auth-token-file", default=None, metavar="PATH",
                   help="file holding the dispatcher's shared handshake"
                   " secret (overrides $PETASTORM_TPU_SERVICE_TOKEN)")

    s = sub.add_parser(
        "stats", help="print one dispatcher stats snapshot (or a live"
        " top-style fleet view with --watch)")
    s.add_argument("--address", required=True, metavar="HOST:PORT")
    s.add_argument("--watch", action="store_true",
                   help="refresh a top-style fleet view (per-worker load,"
                   " fleet-merged stage/hop latencies, counter rates, the"
                   " structured event tail) every --interval seconds"
                   " instead of printing one JSON snapshot")
    s.add_argument("--interval", type=float, default=2.0, metavar="S",
                   help="--watch refresh cadence (default 2s)")
    s.add_argument("--auth-token-file", default=None, metavar="PATH",
                   help="file holding the dispatcher's shared handshake"
                   " secret (overrides $PETASTORM_TPU_SERVICE_TOKEN)")
    return parser


def _auth_token(args) -> Optional[str]:
    """The handshake secret for this invocation: --auth-token-file wins,
    else $PETASTORM_TPU_SERVICE_TOKEN (resolved by each component)."""
    if args.auth_token_file is None:
        return None
    with open(args.auth_token_file, encoding="utf-8") as f:
        token = f.read().strip()
    if not token:
        raise SystemExit(f"auth token file {args.auth_token_file} is empty")
    return token


def _run_dispatcher(args) -> int:
    from petastorm_tpu.errors import DEFAULT_REQUEUE_ATTEMPTS
    from petastorm_tpu.service.dispatcher import Dispatcher
    from petastorm_tpu.telemetry import Telemetry

    dispatcher = Dispatcher(
        host=args.host, port=args.port, telemetry=Telemetry(),
        heartbeat_timeout_s=args.heartbeat_timeout,
        client_grace_s=args.client_grace,
        max_requeue_attempts=(args.max_requeue_attempts
                              if args.max_requeue_attempts is not None
                              else DEFAULT_REQUEUE_ATTEMPTS),
        assignment_deadline_s=args.assignment_deadline,
        metrics_port=args.metrics_port,
        auth_token=_auth_token(args),
        wire_codec=args.compression,
        journal_path=args.journal,
        journal_fsync=args.journal_fsync,
        standby_of=args.standby_of,
        replay_buffer_bytes=args.replay_buffer_mb * 2 ** 20,
        starved_threshold=args.starved_threshold,
        max_clients=args.max_clients,
        max_client_inflight=args.max_client_inflight)
    dispatcher.start()
    print(f"dispatcher listening on {args.host}:{dispatcher.port}",
          flush=True)
    if args.standby_of:
        print(f"standby of {args.standby_of}", flush=True)
    if dispatcher.metrics_server is not None:
        print(f"metrics: http://127.0.0.1:{dispatcher.metrics_server.port}"
              "/metrics", flush=True)
    try:
        while True:
            time.sleep(args.stats_interval or 3600.0)
            if args.stats_interval:
                print(json.dumps(dispatcher.stats()), flush=True)
    except KeyboardInterrupt:
        print("dispatcher stopping", flush=True)
    finally:
        dispatcher.stop()
        dispatcher.join()
    return 0


def _run_worker(args) -> int:
    from petastorm_tpu.service.worker import run_worker

    try:
        return run_worker(args.address, capacity=args.capacity,
                          name=args.name,
                          shm_size_bytes=args.shm_size_mb * 2 ** 20,
                          reconnect_attempts=args.reconnect_attempts,
                          auth_token=_auth_token(args),
                          # SIGTERM = graceful drain (the autoscale
                          # supervisor's scale-down path); 2nd = hard stop
                          install_signal_handlers=True)
    except KeyboardInterrupt:
        return 0


def _run_autoscale(args) -> int:
    from petastorm_tpu.service.autoscale import (AutoscalePolicy,
                                                 AutoscaleSupervisor,
                                                 ExecHookSpawner,
                                                 SubprocessSpawner)

    policy = AutoscalePolicy(
        min_workers=args.min_workers, max_workers=args.max_workers,
        poll_interval_s=args.poll_interval, grow_windows=args.grow_windows,
        shrink_windows=args.shrink_windows, settle_s=args.settle,
        worker_capacity=args.capacity,
        starved_threshold=args.starved_threshold,
        drain_timeout_s=args.drain_timeout)
    if args.exec_hook:
        spawner = ExecHookSpawner(args.exec_hook)
    else:
        spawner = SubprocessSpawner(
            args.address, capacity=args.capacity,
            shm_size_mb=args.shm_size_mb,
            auth_token_file=args.auth_token_file)
    supervisor = AutoscaleSupervisor(
        args.address, policy=policy, spawner=spawner,
        auth_token=_auth_token(args),
        on_event=lambda e: print(json.dumps(e), flush=True))
    print(json.dumps({"event": "supervising", "address": args.address,
                      "min_workers": policy.min_workers,
                      "max_workers": policy.max_workers,
                      "exec_hook": bool(args.exec_hook)}), flush=True)

    import signal as _signal

    def _on_term(_signum, _frame):
        raise KeyboardInterrupt  # unify SIGTERM with Ctrl-C: drain + exit

    try:
        _signal.signal(_signal.SIGTERM, _on_term)
    except ValueError:
        pass
    try:
        supervisor.run()
    except KeyboardInterrupt:
        print(json.dumps({"event": "stopping"}), flush=True)
    finally:
        supervisor.stop()  # graceful fleet drain (bounded per worker)
        print(json.dumps({"event": "stopped",
                          "summary": supervisor.summary()}), flush=True)
    return 0


def _probe(address: str, token, kind: str, timeout: float = 10.0):
    """One-shot dispatcher probe (``stats?`` / ``fleet?`` / ``events?``):
    short-lived connection, one reply frame, payload or None."""
    from petastorm_tpu.service.protocol import connect_frames, parse_address

    conn = connect_frames(parse_address(address))
    try:
        conn.send({"t": kind, "token": token})
        reply = conn.recv(timeout=timeout)
    finally:
        conn.close()
    if not isinstance(reply, dict):
        return None
    return reply.get(kind.rstrip("?"))


def render_fleet_frame(stats: Optional[dict], fleet: Optional[dict],
                       prev_fleet: Optional[dict] = None,
                       dt_s: float = 0.0, elapsed_s: float = 0.0) -> str:
    """One ``stats --watch`` frame: the fleet aggregation plane rendered
    top-style.  Pure function of two probe payloads (plus the previous
    fleet snapshot for counter rates) so tests render from canned dicts."""
    lines = []
    fleet = fleet or {}
    stats = stats or {}
    workers = fleet.get("workers", {}) or {}
    lines.append(
        f"== petastorm-tpu fleet  t={elapsed_s:6.1f}s"
        f"  epoch={fleet.get('epoch', '?')}"
        f"  uptime={fleet.get('uptime_s', 0.0):.0f}s"
        f"  workers={len(workers)} ==")
    ha = stats.get("ha") or {}
    if ha:
        parts = [f"role={ha.get('role', '?')}",
                 f"journal_seq={ha.get('journal_seq', 0)}"]
        for peer, st in sorted((ha.get("standbys") or {}).items()):
            parts.append(f"standby {peer}:"
                         f" lag={st.get('standby_lag_items', '?')} item(s)")
        if ha.get("role") == "standby":
            parts.append(f"lag={ha.get('standby_lag_items', '?')} item(s)")
        lines.append("ha: " + "  ".join(parts))
    if workers:
        lines.append(f"{'worker':<14} {'busy/cap':>9} {'infl':>5}"
                     f" {'hb_age':>7} {'exec_p50ms':>11} {'exec_p99ms':>11}")
        for name in sorted(workers):
            w = workers[name]
            hists = w.get("hists", {}) or {}
            ex = (hists.get("service.hop.worker_exec")
                  or hists.get("stage.service.encode.latency_s") or {})
            p50 = (f"{ex['p50_s'] * 1e3:>11.1f}"
                   if ex.get("p50_s") is not None and ex.get("count")
                   else f"{'-':>11}")
            p99 = (f"{ex['p99_s'] * 1e3:>11.1f}"
                   if ex.get("p99_s") is not None and ex.get("count")
                   else f"{'-':>11}")
            drain = "  (draining)" if w.get("draining") else ""
            lines.append(
                f"{name:<14} {w.get('busy', 0):>4}/{w.get('capacity', 0):<4}"
                f" {w.get('inflight', 0):>5}"
                f" {w.get('heartbeat_age_s', 0.0):>6.1f}s {p50} {p99}"
                f"{drain}")
    else:
        lines.append("workers: (none registered)")
    merged = fleet.get("merged_hists", {}) or {}
    hops = {n: h for n, h in merged.items() if n.startswith("service.hop.")}
    if hops:
        hop_parts = []
        for n in sorted(hops):
            h = hops[n]
            if not h.get("count"):
                continue
            hop_parts.append(f"{n[len('service.hop.'):]}"
                             f"={h.get('p50_s', 0.0) * 1e3:.1f}"
                             f"/{h.get('p99_s', 0.0) * 1e3:.1f}ms")
        if hop_parts:
            lines.append("fleet hop p50/p99: " + "  ".join(hop_parts))
    counters = fleet.get("fleet_counters", {}) or {}
    if prev_fleet and dt_s > 0:
        prev_counters = prev_fleet.get("fleet_counters", {}) or {}
        rates = sorted(
            ((n, (v - prev_counters.get(n, 0.0)) / dt_s)
             for n, v in counters.items()),
            key=lambda kv: -kv[1])
        top = [f"{n}={r:.1f}/s" for n, r in rates[:6] if r > 0]
        if top:
            lines.append("fleet rates: " + "  ".join(top))
    scaling = fleet.get("scaling") or stats.get("scaling") or {}
    if scaling:
        lines.append(
            f"scaling: {scaling.get('recommendation', '?')}"
            f"  pressure={scaling.get('pressure', 0.0):.2f}"
            f"  workers={scaling.get('workers', len(workers))}")
    events = fleet.get("events") or ()
    if events:
        lines.append("events (newest last):")
        for ev in list(events)[-8:]:
            extra = "  ".join(
                f"{k}={v}" for k, v in sorted(ev.items())
                if k not in ("ts", "src", "kind"))
            lines.append(f"  [{ev.get('src', '?'):>10}]"
                         f" {ev.get('kind', '?')}"
                         + (f"  {extra}" if extra else ""))
    return "\n".join(lines)


def _run_stats(args) -> int:
    from petastorm_tpu.service.protocol import resolve_auth_token

    token = resolve_auth_token(_auth_token(args))
    if not args.watch:
        payload = _probe(args.address, token, "stats?")
        if payload is None:
            print("unexpected reply from dispatcher", file=sys.stderr)
            return 1
        print(json.dumps(payload, indent=2, sort_keys=True))
        return 0
    clear = "\x1b[H\x1b[2J" if sys.stdout.isatty() else ""
    prev_fleet, prev_t = None, None
    t0 = time.monotonic()
    try:
        while True:
            try:
                stats = _probe(args.address, token, "stats?")
                fleet = _probe(args.address, token, "fleet?")
            except OSError as exc:
                print(f"{clear}dispatcher unreachable: {exc}", flush=True)
                time.sleep(args.interval)
                continue
            now = time.monotonic()
            frame = render_fleet_frame(
                stats, fleet, prev_fleet,
                dt_s=(now - prev_t) if prev_t is not None else 0.0,
                elapsed_s=now - t0)
            print(f"{clear}{frame}" + ("" if clear else "\n"), flush=True)
            prev_fleet, prev_t = fleet, now
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def main(argv: Optional[List[str]] = None) -> int:
    # dispatcher/worker processes are I/O pumps with a few cooperating
    # threads; the default 5ms GIL switch interval adds whole milliseconds
    # of convoy latency per relayed frame on busy hosts
    sys.setswitchinterval(0.001)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    args = build_parser().parse_args(argv)
    if args.command == "dispatcher":
        return _run_dispatcher(args)
    if args.command == "worker":
        return _run_worker(args)
    if args.command == "autoscale":
        return _run_autoscale(args)
    return _run_stats(args)


if __name__ == "__main__":
    sys.exit(main())
