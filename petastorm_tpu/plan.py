"""Read plan: deterministic, seedable, shardable ordering of rowgroup work items.

Reference parity: the rowgroup filtering/ordering logic inside Reader.__init__ -
shard filter ``index % shard_count == cur_shard`` (petastorm/reader.py:492-509),
``shuffle_row_groups`` ventilation-order shuffle re-done per epoch
(petastorm/workers_pool/ventilator.py:143-144), and ``shuffle_row_drop_partitions``
splitting each rowgroup into N items keeping 1/N rows each
(petastorm/reader.py:565-592).

Design differences (TPU-first):

* The epoch order is a **pure function of (seed, epoch, shard)** - the reference
  shuffles with unseeded ``random.shuffle`` in the ventilator thread, so orders are
  irreproducible and there is no mid-epoch resume.  Determinism here gives (a) exact
  multi-host agreement without communication (every host computes every shard's
  plan), and (b) checkpoint/resume via a plain (epoch, position) cursor - the gap
  called out in SURVEY.md section 5.
* Two shard modes: ``static`` is reference-compatible (rowgroup i on shard
  ``i % shard_count`` forever; shuffle only permutes order within the shard) and
  ``epoch`` re-deals rowgroups to shards each epoch from the seeded global
  permutation (global shuffle across shards; still zero-communication).
* Sharding defaults are wired to ``jax.process_index()/process_count()`` by the
  reader layer, not here - this module stays jax-free.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from petastorm_tpu.errors import NoDataAvailableError, PetastormTpuError
from petastorm_tpu.etl.metadata import RowGroupRef
from petastorm_tpu.seeding import seed_stream


@dataclasses.dataclass(frozen=True)
class WorkItem:
    """One unit of executor work: a rowgroup, optionally restricted to a row-drop
    partition (keep rows in [start_fraction, end_fraction) of the group).

    Reference: shuffle_row_drop_partitions ventilation items
    (petastorm/reader.py:577-592; row arithmetic py_dict_reader_worker.py:254-274).
    """

    row_group: RowGroupRef
    drop_partition: Optional[Tuple[int, int]] = None  # (partition_index, num_partitions)

    @property
    def num_rows(self) -> int:
        if self.drop_partition is None:
            return self.row_group.num_rows
        idx, count = self.drop_partition
        start, stop = _drop_slice(self.row_group.num_rows, idx, count)
        return stop - start

    def row_slice(self) -> Tuple[int, int]:
        if self.drop_partition is None:
            return 0, self.row_group.num_rows
        idx, count = self.drop_partition
        return _drop_slice(self.row_group.num_rows, idx, count)


def _drop_slice(num_rows: int, idx: int, count: int) -> Tuple[int, int]:
    base = num_rows // count
    extra = num_rows % count
    start = idx * base + min(idx, extra)
    stop = start + base + (1 if idx < extra else 0)
    return start, stop


class ReadPlan:
    """Epoch-indexed, shard-filtered, seeded ordering over rowgroups."""

    def __init__(self,
                 row_groups: Sequence[RowGroupRef],
                 shard_index: Optional[int] = None,
                 shard_count: Optional[int] = None,
                 shuffle_row_groups: bool = True,
                 shuffle_seed: Optional[int] = None,
                 shuffle_row_drop_partitions: int = 1,
                 shard_mode: str = "static"):
        if (shard_index is None) != (shard_count is None):
            raise PetastormTpuError("shard_index and shard_count must be set together")
        if shard_count is not None:
            if not 0 <= shard_index < shard_count:
                raise PetastormTpuError(
                    f"shard_index {shard_index} out of range for shard_count {shard_count}")
            if shard_count > len(row_groups):
                # reference raises NoDataAvailableError here (reader.py:502-504)
                raise NoDataAvailableError(
                    f"Dataset has {len(row_groups)} rowgroups but {shard_count} shards"
                    " were requested; some shards would be empty. Write the dataset"
                    " with more/smaller rowgroups or reduce shard_count.")
        if shard_mode not in ("static", "epoch"):
            raise PetastormTpuError(f"Unknown shard_mode {shard_mode!r}")
        if shuffle_row_drop_partitions < 1:
            raise PetastormTpuError("shuffle_row_drop_partitions must be >= 1")
        self._row_groups = list(row_groups)
        self._shard_index = shard_index
        self._shard_count = shard_count
        self._shuffle = shuffle_row_groups
        self._seed = 0 if shuffle_seed is None else shuffle_seed
        self._drop_partitions = shuffle_row_drop_partitions
        self._shard_mode = shard_mode

    @property
    def row_groups(self) -> List[RowGroupRef]:
        return self._row_groups

    def rows_per_epoch(self) -> int:
        return sum(item.num_rows for item in self.epoch_items(0))

    def epoch_items(self, epoch: int) -> List[WorkItem]:
        """The exact ordered work-item list for one epoch of this shard."""
        n = len(self._row_groups)
        if n == 0:
            return []
        if self._shuffle:
            # the centralized derivation (petastorm_tpu.seeding): the epoch
            # permutation is a pure function of (seed, epoch) that is stable
            # across interpreters, hosts and PYTHONHASHSEED - the root of the
            # seed-stable delivery invariant (docs/operations.md
            # "Reproducibility")
            order = seed_stream(self._seed, epoch, "plan.permutation").permutation(n)
        else:
            order = np.arange(n)

        if self._shard_count is None:
            mine = order
        elif self._shard_mode == "static":
            # shard membership fixed by global index (reference reader.py:508);
            # permutation only affects order within the shard
            mine = order[order % self._shard_count == self._shard_index]
        else:  # epoch mode: deal the permuted sequence round-robin to shards
            mine = order[self._shard_index::self._shard_count]

        items: List[WorkItem] = []
        for gi in mine:
            rg = self._row_groups[int(gi)]
            if self._drop_partitions == 1:
                items.append(WorkItem(rg))
            else:
                items.extend(WorkItem(rg, (k, self._drop_partitions))
                             for k in range(self._drop_partitions))
        if self._shuffle and self._drop_partitions > 1:
            # re-shuffle so partitions of one rowgroup don't stay adjacent
            sub = seed_stream(self._seed, epoch,
                              "plan.drop-shuffle").permutation(len(items))
            items = [items[int(i)] for i in sub]
        return items

    def total_items(self, num_epochs: int) -> int:
        """Items across ``num_epochs`` epochs (uniform epoch length)."""
        return len(self.epoch_items(0)) * num_epochs


class ElasticResumePlan:
    """Plan for resuming a partially-consumed epoch under a NEW shard layout.

    The reference cannot do this at all ("no elastic re-sharding, no mid-epoch
    resume", SURVEY.md section 5); here it falls out of determinism: every old
    shard's epoch order is a pure function of (seed, epoch, shard), so the
    not-yet-consumed remainder of the in-progress epoch is reconstructible
    from the old shards' cursors alone - no data exchange, every new host
    computes the same answer.

    Epochs are REBASED: ``epoch_items(0)`` is this new shard's deal of the
    leftover items, ``epoch_items(e >= 1)`` delegates to a normal plan for the
    old layout's epoch ``resume_epoch + e`` under the new shard layout.

    Exactness matches ``Reader.state_dict``: exact when every old shard was
    checkpointed at an epoch boundary or in lockstep; mid-epoch, each cursor
    counts *completed* items, so up to the in-flight window per old shard may
    be re-read (never lost).
    """

    def __init__(self, base: ReadPlan, resume_epoch: int,
                 leftover: Sequence[WorkItem]):
        self._base = base
        self._resume_epoch = resume_epoch
        self._leftover = list(leftover)
        self.row_groups = base.row_groups

    @property
    def resume_epoch(self) -> int:
        return self._resume_epoch

    @property
    def leftover_len(self) -> int:
        return len(self._leftover)

    @property
    def base_items_per_epoch(self) -> int:
        return len(self._base.epoch_items(0))

    def epoch_items(self, epoch: int) -> List[WorkItem]:
        if epoch == 0:
            return list(self._leftover)
        return self._base.epoch_items(self._resume_epoch + epoch)

    def rows_per_epoch(self) -> int:
        return sum(item.num_rows for item in self._leftover)

    def total_items(self, num_epochs: int) -> int:
        if num_epochs <= 0:
            return 0
        return len(self._leftover) + self._base.total_items(num_epochs - 1)


def resolve_cursor(state: dict, shard: Optional[int] = None) -> Tuple[int, int]:
    """(absolute position, items_per_epoch) of a checkpoint in BASE-plan
    coordinates, translating cursors taken from an elastically-resumed reader
    (whose epochs were rebased around the leftover epoch).

    A mid-leftover cursor has no base-coordinate equivalent (leftover items
    interleave several old shards) and is refused loudly.
    """
    who = f"old shard {shard}: " if shard is not None else ""
    if "items_per_epoch" not in state:
        raise PetastormTpuError(
            f"{who}cursor lacks 'items_per_epoch' - pass the full"
            " Reader.state_dict() (older/stripped cursors cannot be"
            " safety-checked and are refused)")
    pos = int(state["position"])
    ipe = int(state["items_per_epoch"])
    rebased = state.get("elastic_rebased")
    if rebased is None:
        return pos, ipe
    leftover = int(rebased["leftover_len"])
    if pos < leftover:
        raise PetastormTpuError(
            f"{who}cursor is mid-way through an elastic leftover epoch"
            f" (position {pos} < leftover {leftover}); it cannot be mapped"
            " back to per-shard coordinates. Checkpoint again after the"
            " leftover epoch finishes.")
    base_ipe = int(rebased["base_items_per_epoch"])
    base_pos = (int(rebased["resume_epoch"]) + 1) * base_ipe + (pos - leftover)
    return base_pos, base_ipe


def elastic_resume_plan(row_groups: Sequence[RowGroupRef],
                        states: Sequence[dict],
                        new_shard_index: int,
                        new_shard_count: int,
                        shuffle_row_groups: bool = True,
                        shuffle_seed: Optional[int] = None,
                        shuffle_row_drop_partitions: int = 1,
                        shard_mode: str = "static") -> ElasticResumePlan:
    """Build the resume plan for ONE new shard from ALL old shards' cursors.

    ``states``: every old shard's ``Reader.state_dict()``, ordered by old
    shard index (length = old shard count).  Shuffle/seed/drop/shard-mode
    arguments must match the original run - the orderings are recomputed, not
    stored.  The in-progress epoch is the earliest epoch any old shard had
    not finished; ahead-of-lockstep shards contribute nothing to the leftover
    (their few next-epoch items are re-read, never lost).
    """
    old_count = len(states)
    if old_count < 1:
        raise PetastormTpuError("elastic resume needs at least one old state")
    if not 0 <= new_shard_index < new_shard_count:
        raise PetastormTpuError(
            f"new_shard_index {new_shard_index} out of range for"
            f" {new_shard_count}")

    def shard_plan(idx: int, count: Optional[int]) -> ReadPlan:
        return ReadPlan(row_groups,
                        shard_index=idx if count else None,
                        shard_count=count,
                        shuffle_row_groups=shuffle_row_groups,
                        shuffle_seed=shuffle_seed,
                        shuffle_row_drop_partitions=shuffle_row_drop_partitions,
                        shard_mode=shard_mode)

    cursors = []  # (epoch, offset, plan) per old shard
    for s, state in enumerate(states):
        plan_s = (shard_plan(s, old_count) if old_count > 1
                  else shard_plan(0, None))
        ipe = len(plan_s.epoch_items(0))
        pos, stored_ipe = resolve_cursor(state, shard=s)
        if stored_ipe != ipe:
            raise PetastormTpuError(
                f"old shard {s}: checkpoint says {stored_ipe} items/epoch but"
                f" the recomputed plan has {ipe} - dataset contents or plan"
                " settings (seed/shuffle/drop/shard_mode) changed since the"
                " checkpoint")
        epoch, off = (pos // ipe, pos % ipe) if ipe else (0, 0)
        cursors.append((epoch, off, plan_s))

    resume_epoch = min(epoch for epoch, _, _ in cursors)
    leftover: List[WorkItem] = []
    for epoch, off, plan_s in cursors:
        if epoch == resume_epoch:
            leftover.extend(plan_s.epoch_items(resume_epoch)[off:])
    dealt = leftover[new_shard_index::new_shard_count]
    base = shard_plan(new_shard_index,
                      new_shard_count if new_shard_count > 1 else None)
    # rebased epoch 1 == old epoch resume_epoch + 1
    return ElasticResumePlan(base, resume_epoch, dealt)
