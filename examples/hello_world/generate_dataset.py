"""Generate the hello-world dataset: id + PNG image + variable 4-D array.

Reference parity: examples/hello_world/petastorm_dataset/
generate_petastorm_dataset.py (HelloWorldSchema, 10 rows) - but Spark-free:
``write_dataset`` encodes and stamps metadata directly through pyarrow.
"""

import argparse

import numpy as np

from petastorm_tpu.codecs import CompressedImageCodec, NdarrayCodec, ScalarCodec
from petastorm_tpu.etl.writer import write_dataset
from petastorm_tpu.schema import Field, Schema

HelloWorldSchema = Schema("HelloWorld", [
    Field("id", np.int32, (), ScalarCodec()),
    Field("image1", np.uint8, (128, 256, 3), CompressedImageCodec("png")),
    Field("array_4d", np.uint8, (None, 128, 30, None), NdarrayCodec()),
])


def row_generator(i: int, rng: np.random.Generator) -> dict:
    return {
        "id": i,
        "image1": rng.integers(0, 255, (128, 256, 3), dtype=np.uint8),
        "array_4d": rng.integers(0, 255, (4, 128, 30, 3), dtype=np.uint8),
    }


def generate_hello_world_dataset(output_url: str, rows_count: int = 10,
                                 seed: int = 1) -> None:
    rng = np.random.default_rng(seed)
    write_dataset(output_url, HelloWorldSchema,
                  (row_generator(i, rng) for i in range(rows_count)),
                  row_group_size_mb=256, mode="overwrite")


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("output_url", nargs="?", default="/tmp/hello_world_dataset")
    parser.add_argument("--rows", type=int, default=10)
    args = parser.parse_args()
    generate_hello_world_dataset(args.output_url, args.rows)
    print(f"wrote {args.rows} rows to {args.output_url}")
