"""Ingest-service dispatcher: worker registry + per-client work assignment.

The dispatcher owns each client's deterministic plan stream (the client's
Ventilator feeds it :class:`~petastorm_tpu.pool.VentilatedItem`\\ s over the
wire, in exactly the order the seeded :class:`~petastorm_tpu.plan.ReadPlan`
produced them) and assigns items to registered workers, with the same
fault-tolerance semantics the in-process pools implement:

* a worker that disconnects or misses heartbeats has its in-flight items
  **requeued** onto surviving workers through the per-item attempt budget
  (``VentilatedItem.attempt`` rides the wire, so chaos injection and
  quarantine classification behave identically to the local pools);
* an item whose budget is spent surfaces to its client as a classified
  infrastructure failure (the client raises the same ``WorkerError`` the
  pools would);
* in-worker *data* failures (corrupt rowgroup, codec error) are forwarded
  to the client unchanged - ``on_error`` skip policies quarantine them
  client-side exactly as with a local pool.

Data-plane role: the dispatcher is a **buffer relay**.  Result frames are
parsed only to their control header (ordinal, rows, payload kind); the
column payload - the ~MBs of pixel data - is forwarded to the owning
client as opaque bytes, never decoded, never unpickled
(:mod:`petastorm_tpu.service.protocol`).  Work items likewise cross the
dispatcher as :class:`~petastorm_tpu.service.protocol.WireItem`\\ s:
structural scheduling metadata (ordinal, attempt, rowgroup-affinity key)
plus an opaque blob only the assigned worker opens.  The wire-encoding mix
is metered per relayed result (``service.frames_binary`` /
``frames_pickle_fallback`` / ``frames_shm``) so a hot pickle fallback is
visible, not silent.

Delivery is exactly-once per client: results are buffered until the client
**acks** them, so a dropped client connection replays unacked results on
reconnect and the client-side per-ordinal ledger dedups any overlap.

Rowgroup affinity: items are routed by a stable hash of their rowgroup so
repeated reads of one rowgroup (two clients on one dataset) prefer the same
worker - and co-located workers sharing a ``cache_type='shared'`` warm tier
decode each rowgroup once fleet-wide regardless.

Fleet sizing: clients piggyback their consumer starved-seconds (the
``queue.results_empty_wait_s`` signal petastorm_tpu.autotune drives worker
counts with) and :meth:`Dispatcher.scaling_signal` turns the aggregate into
a grow/ok/shrink recommendation plus a ``service.scale_pressure`` gauge -
the operator's (or an orchestrator's) cue to resize the fleet
(docs/operations.md "Disaggregated ingest service").
"""

from __future__ import annotations

import collections
import logging
import os
import socket
import threading
import time
import zlib
from typing import Any, Deque, Dict, List, Optional, Set, Tuple

from petastorm_tpu.errors import DEFAULT_REQUEUE_ATTEMPTS, PetastormTpuError
from petastorm_tpu.pool import VentilatedItem
from petastorm_tpu.service.protocol import (PROTOCOL_VERSION,
                                            FrameClosedError, FrameSocket,
                                            LegacyPickleFrameError, WireItem,
                                            resolve_auth_token, token_matches)
from petastorm_tpu.service.wire import SUPPORTED_CODECS, negotiate_codec
from petastorm_tpu.telemetry import resolve as _resolve_telemetry

logger = logging.getLogger(__name__)

#: telemetry counter prefixes a worker heartbeat may fold into the
#: dispatcher's registry as ``service.fleet.<name>`` (fleet-wide decode /
#: cache accounting - the observable proof of decode-once sharing; the
#: ``service.`` entry folds the workers' own wire-encoding mix and stage
#: counters so encode-side behavior is visible at the control plane)
FLEET_COUNTER_PREFIXES = ("decode.", "worker.", "cache.", "io.", "service.",
                          "stage.service.")


class _WorkerState:
    __slots__ = ("name", "conn", "capacity", "hostname", "inflight",
                 "last_heartbeat", "busy", "jobs_sent", "gone", "codecs")

    def __init__(self, name: str, conn: FrameSocket, capacity: int,
                 hostname: str, codecs=()):
        self.name = name
        self.conn = conn
        self.capacity = max(1, int(capacity))
        self.hostname = hostname
        #: wire codecs this worker can compress BATCH bodies with
        self.codecs = tuple(codecs or ())
        #: (client_id, ordinal) assignments awaiting a result
        self.inflight: Set[Tuple[str, int]] = set()
        self.last_heartbeat = time.monotonic()
        self.busy = 0
        self.jobs_sent: Set[str] = set()
        self.gone = False


class _Assignment:
    __slots__ = ("item", "worker", "assigned_at")

    def __init__(self, item: VentilatedItem, worker: str):
        self.item = item
        self.worker = worker
        self.assigned_at = time.monotonic()


class _ClientState:
    __slots__ = ("client_id", "conn", "factory", "hostname", "shm_ok",
                 "max_requeue", "pending", "inflight", "unacked", "rows",
                 "results", "requeued", "connected", "disconnected_at",
                 "codecs")

    def __init__(self, client_id: str, conn: FrameSocket, factory: bytes,
                 hostname: str, shm_ok: bool, max_requeue: int, codecs=()):
        self.client_id = client_id
        self.conn = conn
        self.factory = factory
        self.hostname = hostname
        self.shm_ok = shm_ok
        self.max_requeue = max_requeue
        #: wire codecs this client can decompress BATCH bodies of
        self.codecs = tuple(codecs or ())
        #: items awaiting assignment (requeues go to the FRONT so a
        #: recovered item does not wait behind a whole epoch)
        self.pending: Deque[WireItem] = collections.deque()
        #: ordinal -> _Assignment at a worker
        self.inflight: Dict[int, _Assignment] = {}
        #: ordinal -> outcome frame delivered but not yet acked (replayed
        #: verbatim on reconnect; bounded by the client's in-flight window)
        self.unacked: Dict[int, Dict] = {}
        self.rows = 0
        self.results = 0
        self.requeued = 0
        self.connected = True
        self.disconnected_at: Optional[float] = None

    def known_ordinals(self) -> Set[int]:
        known = set(self.inflight) | set(self.unacked)
        known.update(i.ordinal for i in self.pending)
        return known


class Dispatcher:
    """The ingest-service control plane (one process serves many clients).

    ``heartbeat_timeout_s``: a worker silent this long is declared dead and
    its in-flight items requeue (socket EOF - the common death - is
    detected immediately; the timeout covers a worker whose heartbeat
    thread died with the process).  A worker wedged INSIDE user decode/IO
    code keeps heartbeating - that failure mode needs
    ``assignment_deadline_s``: when set, an assignment with no outcome for
    that long declares its worker hung and drops it (connection closed ->
    the worker process exits; its items requeue through the budget) - the
    service-plane analog of the process pool's SIGKILL-and-respawn.  Off
    by default, like ``item_deadline_s`` locally; size it WELL above the
    slowest legitimate rowgroup decode.
    ``client_grace_s``: a disconnected client's state (pending + in-flight
    + unacked results) is kept this long for a reconnect before purging.
    ``max_requeue_attempts``: default per-item budget; each client's hello
    may carry its own (the reader's ``on_error`` policy budget travels with
    the job, keeping service and in-process semantics identical).
    ``auth_token``: shared handshake secret; defaults to
    ``$PETASTORM_TPU_SERVICE_TOKEN``.  When set, every hello (worker,
    client, stats) must present it or the connection is refused.  The v2
    wire is pickle-free binary frames (the token gates who may ship jobs
    to the fleet, not frame parsing) - see the protocol module's
    trust-boundary notes.
    ``wire_codec``: BATCH-body compression policy, negotiated per
    (worker, client) pair at job time - ``'auto'`` (default; compress
    cross-host hops only), ``'off'``, or a codec name to force it
    everywhere both ends support it.  Defaults to
    ``$PETASTORM_TPU_SERVICE_COMPRESSION`` when unset.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 telemetry=None,
                 heartbeat_timeout_s: float = 10.0,
                 client_grace_s: float = 30.0,
                 max_requeue_attempts: int = DEFAULT_REQUEUE_ATTEMPTS,
                 assignment_deadline_s: Optional[float] = None,
                 metrics_port: Optional[int] = None,
                 auth_token: Optional[str] = None,
                 wire_codec: Optional[str] = None):
        if assignment_deadline_s is not None and assignment_deadline_s <= 0:
            raise PetastormTpuError(
                "assignment_deadline_s must be > 0 or None")
        if wire_codec is None:
            wire_codec = os.environ.get(
                "PETASTORM_TPU_SERVICE_COMPRESSION", "auto")
        if wire_codec not in ("auto", "off") + SUPPORTED_CODECS:
            raise PetastormTpuError(
                f"wire_codec must be 'auto', 'off' or one of"
                f" {SUPPORTED_CODECS}; got {wire_codec!r}")
        self._wire_codec = wire_codec
        self._host = host
        self._requested_port = port
        self._heartbeat_timeout_s = float(heartbeat_timeout_s)
        self._client_grace_s = float(client_grace_s)
        self._assignment_deadline_s = assignment_deadline_s
        self._max_requeue = int(max_requeue_attempts)
        self._auth_token = resolve_auth_token(auth_token)
        self.telemetry = _resolve_telemetry(telemetry)
        self._lock = threading.RLock()
        self._workers: Dict[str, _WorkerState] = {}
        self._clients: Dict[str, _ClientState] = {}
        self._client_order: List[str] = []  # round-robin fairness cursor
        self._rr = 0
        self._stop_event = threading.Event()
        self._threads: List[threading.Thread] = []
        self._listener: Optional[socket.socket] = None
        self.port: Optional[int] = None
        self._started_at = time.monotonic()
        #: (monotonic, starved_s delta) reports from clients - the fleet
        #: pressure window (scaling_signal)
        self._starved_reports: Deque[Tuple[float, float]] = collections.deque(
            maxlen=512)
        self._worker_seq = 0
        self._client_counter_ids: Set[str] = set()
        self._metrics_port = metrics_port
        self.metrics_server = None
        # -- service.* telemetry (rides the registry -> Prometheus/--watch) --
        tele = self.telemetry
        self._g_workers = tele.gauge("service.registered_workers")
        self._g_clients = tele.gauge("service.connected_clients")
        self._g_pending = tele.gauge("service.pending_items")
        self._g_inflight = tele.gauge("service.inflight_items")
        self._g_pressure = tele.gauge("service.scale_pressure")
        self._m_assigned = tele.counter("service.assigned_items")
        self._m_completed = tele.counter("service.completed_items")
        self._m_requeued = tele.counter("service.requeued_items")
        self._m_failures = tele.counter("service.forwarded_failures")
        self._m_dup = tele.counter("service.duplicate_results")
        self._m_bytes_in = tele.counter("service.frame_bytes_received")
        self._m_bytes_out = tele.counter("service.frame_bytes_sent")
        self._m_rows = tele.counter("service.client_rows")
        # wire-encoding mix of relayed results: the pickle fallback being
        # hot must be VISIBLE (ci.sh asserts frames_pickle_fallback == 0
        # on the result path of its smoke topology)
        self._m_frames_bin = tele.counter("service.frames_binary")
        self._m_frames_pkl = tele.counter("service.frames_pickle_fallback")
        self._m_frames_shm = tele.counter("service.frames_shm")

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "Dispatcher":
        """Bind the listener (``self.port`` is then live) and start the
        accept + monitor threads; returns self for chaining."""
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind((self._host, self._requested_port))
        listener.listen(64)
        listener.settimeout(0.2)
        self._listener = listener
        self.port = listener.getsockname()[1]
        for target, name in ((self._accept_loop, "accept"),
                             (self._monitor_loop, "monitor")):
            t = threading.Thread(target=target, daemon=True,
                                 name=f"petastorm-tpu-dispatcher-{name}")
            t.start()
            self._threads.append(t)
        if self._metrics_port is not None and self.telemetry.enabled:
            from petastorm_tpu.telemetry.export import MetricsExportServer

            self.metrics_server = MetricsExportServer(
                self.telemetry, port=self._metrics_port)
            self.metrics_server.start()
        logger.info("Dispatcher listening on %s:%d", self._host, self.port)
        if self._auth_token is None and self._host not in (
                "127.0.0.1", "localhost", "::1"):
            logger.warning(
                "Dispatcher is listening on %s with NO auth token: anyone"
                " who can reach this port can register as a client and ship"
                " a worker factory the fleet will execute (the v2 binary"
                " wire removed unpickle-on-parse, not the execute-client-"
                "jobs feature).  Restrict to a trusted network and set"
                " $PETASTORM_TPU_SERVICE_TOKEN (docs/operations.md"
                " 'Disaggregated ingest service').", self._host)
        return self

    def stop(self) -> None:
        """Close the listener and every live connection; workers and
        clients see EOF immediately."""
        self._stop_event.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._lock:
            conns = ([w.conn for w in self._workers.values()]
                     + [c.conn for c in self._clients.values() if c.connected])
        for conn in conns:
            conn.close()
        if self.metrics_server is not None:
            self.metrics_server.stop()

    def join(self, timeout: float = 5.0) -> None:
        """Bounded wait for the service threads after :meth:`stop`."""
        deadline = time.monotonic() + timeout
        for t in self._threads:
            t.join(max(0.0, deadline - time.monotonic()))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        self.join()

    # -- connection handling --------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop_event.is_set():
            try:
                sock, _addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed at stop
            t = threading.Thread(target=self._serve_conn,
                                 args=(FrameSocket(sock),), daemon=True,
                                 name="petastorm-tpu-dispatcher-conn")
            t.start()
            # prune finished connection threads as we go: a long-lived
            # dispatcher probed by `stats` every few seconds would otherwise
            # accumulate dead Thread objects for its whole lifetime
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _serve_conn(self, conn: FrameSocket) -> None:
        try:
            hello = conn.recv(timeout=10.0)
        except LegacyPickleFrameError:
            # a v1 (pickled-wire) peer, detected WITHOUT unpickling it:
            # answer in the one format it can read so it fails loudly with
            # the version message instead of desyncing or hanging
            logger.warning("Refusing legacy v1 (pickled-frame) peer: this"
                           " dispatcher speaks the v2 binary wire")
            try:
                conn.send_legacy_error(
                    "protocol version mismatch: this dispatcher speaks the"
                    f" v2 binary wire (PROTOCOL_VERSION {PROTOCOL_VERSION});"
                    " upgrade the client/worker")
            except OSError:
                pass
            conn.close()
            return
        except Exception:  # noqa: BLE001 - drop bad conns (EOF, garbage)
            conn.close()
            return
        if hello is None or self._stop_event.is_set():
            # a connection that raced the accept loop against stop() must be
            # refused here: sending hello_ok and then never reading would
            # leave the peer waiting on a silent live socket
            conn.close()
            return
        kind = hello.get("t")
        if not token_matches(self._auth_token, hello.get("token")):
            # auth gate before ANY hello processing: an untokened peer gets
            # a refusal and a closed socket, never a registered state
            logger.warning("Refusing %r connection: bad/missing auth token",
                           kind)
            if self.telemetry.enabled:
                self.telemetry.counter("service.auth_rejected").add(1)
            try:
                conn.send({"t": "error", "error": "bad auth token"})
            except OSError:
                pass
            conn.close()
            return
        try:
            if kind == "worker_hello":
                self._worker_loop(conn, hello)
            elif kind == "client_hello":
                self._client_loop(conn, hello)
            elif kind == "stats?":
                conn.send({"t": "stats", "stats": self.stats()})
                conn.close()
            else:
                logger.warning("Dropping connection with bad hello %r", kind)
                conn.close()
        except FrameClosedError:
            pass
        except Exception:  # noqa: BLE001 - one bad conn must not kill serving
            if not self._stop_event.is_set():
                logger.warning("Dispatcher connection handler failed",
                               exc_info=True)

    # -- worker side ----------------------------------------------------------

    def _worker_loop(self, conn: FrameSocket, hello: Dict) -> None:
        if hello.get("protocol") != PROTOCOL_VERSION:
            conn.send({"t": "error", "error": "protocol version mismatch"})
            conn.close()
            return
        with self._lock:
            self._worker_seq += 1
            name = hello.get("worker") or f"worker-{self._worker_seq}"
            if name in self._workers:
                name = f"{name}-{self._worker_seq}"
            state = _WorkerState(name, conn, hello.get("capacity", 1),
                                 hello.get("hostname", ""),
                                 codecs=hello.get("codecs") or ())
            self._workers[name] = state
            self._g_workers.set(len(self._workers))
        conn.send({"t": "hello_ok", "worker": name})
        logger.info("Worker %s registered (capacity %d, host %s)", name,
                    state.capacity, state.hostname or "?")
        self._pump()
        bytes_folded = 0
        try:
            while not self._stop_event.is_set():
                msg = conn.recv(timeout=1.0)
                if conn.bytes_received > bytes_folded:
                    self._m_bytes_in.add(conn.bytes_received - bytes_folded)
                    bytes_folded = conn.bytes_received
                if msg is None:
                    continue
                kind = msg.get("t")
                if kind == "heartbeat":
                    self._on_heartbeat(state, msg)
                elif kind == "result":
                    self._on_result(state, msg)
                elif kind == "failure":
                    self._on_worker_failure(state, msg)
                elif kind == "bye":
                    break
        except FrameClosedError:
            pass
        finally:
            self._worker_gone(name)

    def _on_heartbeat(self, state: _WorkerState, msg: Dict) -> None:
        state.last_heartbeat = time.monotonic()
        state.busy = int(msg.get("busy", 0))
        deltas = msg.get("counters") or {}
        if self.telemetry.enabled:
            for cname, delta in deltas.items():
                if delta and cname.startswith(FLEET_COUNTER_PREFIXES):
                    self.telemetry.counter(f"service.fleet.{cname}").add(delta)

    def _on_result(self, state: _WorkerState, msg: Dict) -> None:
        cid, ordinal = msg["client"], msg["ordinal"]
        state.last_heartbeat = time.monotonic()
        duplicate = False
        # ONE critical section from duplicate check to outcome recording:
        # splitting them would let _purge_client (grace expiry, bye) pop
        # the client in between, silently losing the result into an
        # orphaned _ClientState
        with self._lock:
            state.inflight.discard((cid, ordinal))
            client = self._clients.get(cid)
            if client is None or client.inflight.pop(ordinal, None) is None:
                # late duplicate (the ordinal was requeued and its sibling
                # delivered first, or the client was purged): drop - the
                # client-side ledger would drop it anyway
                duplicate = True
                conn = None
            else:
                # buffer relay: forward the worker's result header verbatim
                # (minus its routing field) with the column payload as
                # opaque bytes - the dispatcher never decodes it
                out = {k: v for k, v in msg.items() if k != "client"}
                out["worker"] = state.name
                client.unacked[ordinal] = out
                client.results += 1
                client.rows += int(msg.get("rows", 0))
                conn = client.conn if client.connected else None
        pk = msg.get("pk")
        if pk == "bin":
            self._m_frames_bin.add(1)
        elif pk == "shm":
            self._m_frames_shm.add(1)
        elif pk == "pickle":
            self._m_frames_pkl.add(1)
        if duplicate:
            # outside the lock: _pump's sends must never run while this
            # thread holds the dispatcher lock (a worker with a full TCP
            # buffer would stall every other connection's thread)
            self._m_dup.add(1)
            self._stamp_gauges()
            self._pump()
            return
        self._m_completed.add(1)
        self._m_rows.add(int(msg.get("rows", 0)))
        if self.telemetry.enabled:
            # per-client rows ride the registry under a bounded name set: a
            # dispatcher serving an unbounded client churn must not grow the
            # registry forever (stats() always has per-client exact counts)
            if cid in self._client_counter_ids \
                    or len(self._client_counter_ids) < 100:
                self._client_counter_ids.add(cid)
                self.telemetry.counter(
                    f"service.client.{cid[:12]}.rows").add(
                        int(msg.get("rows", 0)))
        if conn is not None:
            self._send_to_client(cid, conn, out)
        # no _stamp_gauges here: the monitor loop stamps every 0.5s, and a
        # per-result lock+scan on the relay hot path costs real throughput
        # on a core shared with decode
        self._pump()

    def _on_worker_failure(self, state: _WorkerState, msg: Dict) -> None:
        cid, ordinal = msg["client"], msg["ordinal"]
        state.last_heartbeat = time.monotonic()
        with self._lock:
            state.inflight.discard((cid, ordinal))
            client = self._clients.get(cid)
            if client is None:
                return
            assign = client.inflight.pop(ordinal, None)
            if assign is None:
                self._m_dup.add(1)
                return
        # failures are plain fields on the wire (formatted traceback, kind,
        # exc_type) - no object envelope; the client recovers the failed
        # item from its own in-flight ledger
        if msg.get("kind", "data") == "infra":
            # in-worker infra failure (e.g. MemoryError): the item is
            # healthy, the worker wasn't - same treatment as a death
            self._requeue_or_fail(
                cid, ordinal, assign,
                f"in-worker infra failure ({msg.get('exc_type')})")
        else:
            self._forward_failure(cid, ordinal,
                                  formatted=msg.get("formatted"),
                                  kind=msg.get("kind", "data"),
                                  exc_type=msg.get("exc_type"))
        self._pump()

    def _worker_gone(self, name: str) -> None:
        with self._lock:
            state = self._workers.pop(name, None)
            if state is None or state.gone:
                return
            state.gone = True
            lost = list(state.inflight)
            self._g_workers.set(len(self._workers))
        state.conn.close()
        if lost:
            logger.warning("Worker %s lost with %d in-flight item(s);"
                           " requeueing", name, len(lost))
        for cid, ordinal in lost:
            with self._lock:
                client = self._clients.get(cid)
                assign = client.inflight.pop(ordinal, None) if client else None
            if assign is not None:
                self._requeue_or_fail(cid, ordinal, assign,
                                      f"worker {name} death")
        self._pump()

    def _requeue_or_fail(self, cid: str, ordinal: int, assign: _Assignment,
                         why: str) -> None:
        """Pool `_requeue_lost` semantics across the wire: re-ventilate
        through the attempt budget, else surface a classified infra failure."""
        with self._lock:
            client = self._clients.get(cid)
            if client is None:
                return
            attempt = getattr(assign.item, "attempt", 0)
            if attempt < client.max_requeue:
                retry = WireItem(ordinal, attempt + 1, assign.item.blob,
                                 assign.item.rg)
                client.pending.appendleft(retry)
                client.requeued += 1
                conn = client.conn if client.connected else None
                notice = {"t": "requeued", "ordinal": ordinal,
                          "attempt": attempt + 1, "why": why}
            else:
                conn = None
                notice = None
        if notice is not None:
            self._m_requeued.add(1)
            logger.warning("Requeueing work item %s for client %s after %s"
                           " (attempt %d/%d)", ordinal, cid, why, attempt + 1,
                           client.max_requeue)
            if conn is not None:
                self._send_to_client(cid, conn, notice)
            return
        self._forward_failure(
            cid, ordinal, message=(
                f"Work item {ordinal} lost to {why}; requeue budget exhausted"
                f" ({attempt} requeue(s) of max {client.max_requeue})"
                " - possible crash/OOM"),
            kind="infra")

    def _forward_failure(self, cid: str, ordinal: int,
                         formatted: Optional[str] = None,
                         message: Optional[str] = None, kind: str = "data",
                         exc_type: Optional[str] = None) -> None:
        with self._lock:
            client = self._clients.get(cid)
            if client is None:
                return
            out = {"t": "failure", "ordinal": ordinal, "kind": kind}
            if formatted is not None:
                out["formatted"] = formatted
            if message is not None:
                out["message"] = message
            if exc_type is not None:
                out["exc_type"] = exc_type
            client.unacked[ordinal] = out
            conn = client.conn if client.connected else None
        self._m_failures.add(1)
        if conn is not None:
            self._send_to_client(cid, conn, out)

    # -- client side ----------------------------------------------------------

    def _client_loop(self, conn: FrameSocket, hello: Dict) -> None:
        if hello.get("protocol") != PROTOCOL_VERSION:
            conn.send({"t": "error", "error": "protocol version mismatch"})
            conn.close()
            return
        cid = hello["client"]
        with self._lock:
            client = self._clients.get(cid)
            if client is None:
                client = _ClientState(
                    cid, conn, hello.get("factory"),
                    hello.get("hostname", ""), bool(hello.get("shm_ok")),
                    int(hello.get("max_requeue", self._max_requeue)),
                    codecs=hello.get("codecs") or ())
                self._clients[cid] = client
                self._client_order.append(cid)
                logger.info("Client %s registered", cid)
            else:
                # reconnect: swap the connection in, replay unacked outcomes
                old = client.conn
                client.conn = conn
                client.connected = True
                client.disconnected_at = None
                if old is not conn:
                    old.close()
                logger.info("Client %s reconnected (%d unacked outcome(s)"
                            " to replay)", cid, len(client.unacked))
            replay = list(client.unacked.values())
            self._g_clients.set(
                sum(1 for c in self._clients.values() if c.connected))
        conn.send({"t": "hello_ok", "client": cid})
        for out in replay:
            self._send_to_client(cid, conn, out)
        self._pump()
        bytes_folded = 0
        try:
            while not self._stop_event.is_set():
                msg = conn.recv(timeout=1.0)
                if conn.bytes_received > bytes_folded:
                    self._m_bytes_in.add(conn.bytes_received - bytes_folded)
                    bytes_folded = conn.bytes_received
                if msg is None:
                    continue
                kind = msg.get("t")
                if kind == "enqueue":
                    with self._lock:
                        client.pending.append(WireItem.from_wire(msg["item"]))
                    self._pump()
                elif kind == "ack":
                    with self._lock:
                        for ordinal in msg["ordinals"]:
                            client.unacked.pop(ordinal, None)
                elif kind == "resync":
                    self._on_resync(client, msg)
                elif kind == "client_stats":
                    starved = float(msg.get("starved_s", 0.0))
                    if starved > 0:
                        self._starved_reports.append(
                            (time.monotonic(), starved))
                elif kind == "stats?":
                    conn.send({"t": "stats", "stats": self.stats()})
                elif kind == "bye":
                    self._purge_client(cid, reason="clean goodbye")
                    return
        except FrameClosedError:
            pass
        finally:
            with self._lock:
                current = self._clients.get(cid)
                if current is not None and current.conn is conn:
                    current.connected = False
                    current.disconnected_at = time.monotonic()
                    self._g_clients.set(sum(1 for c in self._clients.values()
                                            if c.connected))
            if self._stop_event.is_set():
                # stop-path exit (not a client-side drop): close the socket
                # so the peer sees EOF instead of an idle live connection
                conn.close()

    def _on_resync(self, client: _ClientState, msg: Dict) -> None:
        """Reconnect recovery: re-enqueue any ledger item the dispatcher has
        no record of (an ``enqueue`` frame lost in the dying connection)."""
        with self._lock:
            known = client.known_ordinals()
            restored = 0
            for entry in msg.get("items", ()):
                item = WireItem.from_wire(entry)
                if item.ordinal not in known:
                    client.pending.append(item)
                    restored += 1
        if restored:
            logger.info("Client %s resync restored %d lost work item(s)",
                        client.client_id, restored)
        self._pump()

    def _send_to_client(self, cid: str, conn: FrameSocket, out: Dict) -> None:
        try:
            if "_body" in out:
                # result relay: re-frame the header, forward the payload
                # bytes untouched (vectored write - no staging copy)
                header = {k: v for k, v in out.items() if k != "_body"}
                self._m_bytes_out.add(
                    conn.send_batch(header, [out["_body"]]))
            else:
                self._m_bytes_out.add(conn.send(out))
        except OSError:
            # connection died mid-send: the outcome stays in unacked and
            # replays on reconnect; the client read loop marks disconnect
            logger.debug("send to client %s failed (kept for replay)", cid)

    def _purge_client(self, cid: str, reason: str) -> None:
        notify = []
        with self._lock:
            client = self._clients.pop(cid, None)
            if client is None:
                return
            if cid in self._client_order:
                self._client_order.remove(cid)
            dropped = len(client.pending) + len(client.inflight)
            for worker in self._workers.values():
                worker.inflight = {(c, o) for c, o in worker.inflight
                                   if c != cid}
                if cid in worker.jobs_sent:
                    notify.append(worker.conn)
            self._g_clients.set(sum(1 for c in self._clients.values()
                                    if c.connected))
        for conn in notify:  # sends stay outside the dispatcher lock
            try:
                conn.send({"t": "job_done", "client": cid})
            except OSError:
                pass
        client.conn.close()
        logger.info("Client %s purged (%s; %d undelivered item(s) dropped)",
                    cid, reason, dropped)
        self._stamp_gauges()

    # -- assignment -----------------------------------------------------------

    def _pick_worker(self, item: VentilatedItem, free: List[_WorkerState],
                     stable: Optional[List[str]] = None) -> _WorkerState:
        """Rowgroup-affine choice among workers with spare capacity: the
        same rowgroup prefers the same worker (warm-tier locality), falling
        back to least-loaded.

        The affine worker is ``crc32(path:rowgroup)`` modulo the stable
        name-sorted list of ALL live workers - a deterministic digest
        (built-in ``hash()`` is PYTHONHASHSEED-randomized per process) over
        a membership-stable list (indexing the momentary free list would
        move the mapping whenever fleet load shifts), so affinity survives
        dispatcher restarts and load churn.  Only when the affine worker is
        saturated does the item go to the least-loaded free one.

        ``stable`` lets _pump hoist the sorted name list out of its
        assignment loop (membership cannot change while it holds the lock).
        """
        if isinstance(item, WireItem):
            # the wire plane lifts the affinity key out structurally so the
            # dispatcher never opens the item blob
            rg_key = (f"{item.rg[0]}:{item.rg[1]}"
                      if isinstance(item.rg, (list, tuple))
                      and len(item.rg) == 2 else None)
        else:
            # direct VentilatedItem (tests, in-process callers)
            work = getattr(item, "item", None)
            rg = getattr(work, "row_group", None)
            rg_key = (f"{getattr(rg, 'path', '')}:"
                      f"{getattr(rg, 'row_group', 0)}"
                      if rg is not None else None)
        if rg_key is not None:
            if stable is None:
                stable = sorted(w.name for w in self._workers.values()
                                if not w.gone)
            key = zlib.crc32(rg_key.encode())
            affine = self._workers.get(stable[key % len(stable)])
            if affine is not None and affine in free:
                return affine
        return min(free, key=lambda w: len(w.inflight))

    def _pump(self) -> None:
        """Assign pending items to free workers (round-robin across clients
        for fairness).  Sends happen outside the lock; assignment state is
        recorded first, so a failed send surfaces as a worker death whose
        requeue path recovers the item."""
        sends: List[Tuple[_WorkerState, Dict]] = []
        with self._lock:
            stable = sorted(w.name for w in self._workers.values()
                            if not w.gone)
            while True:
                free = [w for w in self._workers.values()
                        if not w.gone and len(w.inflight) < w.capacity]
                if not free:
                    break
                # round-robin over clients with pending work
                order = self._client_order
                candidates = [cid for cid in order
                              if self._clients[cid].pending]
                if not candidates:
                    break
                self._rr = (self._rr + 1) % len(candidates)
                cid = candidates[self._rr % len(candidates)]
                client = self._clients[cid]
                item = client.pending.popleft()
                worker = self._pick_worker(item, free, stable)
                client.inflight[item.ordinal] = _Assignment(item, worker.name)
                worker.inflight.add((cid, item.ordinal))
                if cid not in worker.jobs_sent:
                    worker.jobs_sent.add(cid)
                    same_host = bool(client.hostname
                                     and client.hostname == worker.hostname)
                    sends.append((worker, {
                        "t": "job", "client": cid, "factory": client.factory,
                        "shm_ok": client.shm_ok and same_host,
                        # BATCH-body compression for this pair: off for
                        # co-located hops, negotiated for cross-host ones
                        "codec": negotiate_codec(
                            self._wire_codec, same_host, client.codecs,
                            worker.codecs)}))
                sends.append((worker, {"t": "work", "client": cid,
                                       "item": item.to_wire()}))
                self._m_assigned.add(1)
        for worker, msg in sends:
            try:
                self._m_bytes_out.add(worker.conn.send(msg))
            except OSError:
                # dying worker: its read loop will run _worker_gone, which
                # requeues everything it held (including this item)
                logger.debug("send to worker %s failed", worker.name)
        if sends:
            self._stamp_gauges()

    def _stamp_gauges(self) -> None:
        with self._lock:
            pending = sum(len(c.pending) for c in self._clients.values())
            inflight = sum(len(c.inflight) for c in self._clients.values())
        self._g_pending.set(pending)
        self._g_inflight.set(inflight)

    # -- monitoring / scaling -------------------------------------------------

    def _monitor_loop(self) -> None:
        while not self._stop_event.wait(0.5):
            now = time.monotonic()
            dead = []
            hung = {}
            with self._lock:
                for name, w in self._workers.items():
                    if now - w.last_heartbeat > self._heartbeat_timeout_s:
                        dead.append(name)
                if self._assignment_deadline_s is not None:
                    # liveness backstop for workers wedged INSIDE user code:
                    # they keep heartbeating (the heartbeat thread is
                    # independent), so a stuck ASSIGNMENT is the signal
                    for c in self._clients.values():
                        for ordinal, assign in c.inflight.items():
                            age = now - assign.assigned_at
                            if (age > self._assignment_deadline_s
                                    and assign.worker in self._workers):
                                hung.setdefault(assign.worker,
                                                (ordinal, age))
                expired = [cid for cid, c in self._clients.items()
                           if not c.connected and c.disconnected_at is not None
                           and now - c.disconnected_at > self._client_grace_s]
            for name in dead:
                logger.warning("Worker %s missed heartbeats for %.0fs;"
                               " declaring it dead", name,
                               self._heartbeat_timeout_s)
                self._worker_gone(name)
            for name, (ordinal, age) in hung.items():
                if name in dead:
                    continue
                logger.warning(
                    "Worker %s has held item %s for %.1fs >"
                    " assignment_deadline_s=%.1f; declaring it hung and"
                    " dropping it (its items requeue; the remote process"
                    " exits on the closed connection)", name, ordinal, age,
                    self._assignment_deadline_s)
                if self.telemetry.enabled:
                    self.telemetry.counter(
                        "service.hung_workers_dropped").add(1)
                self._worker_gone(name)
            for cid in expired:
                self._purge_client(cid, reason="reconnect grace expired")
            self._g_pressure.set(self.scaling_signal()["pressure"])
            self._stamp_gauges()

    def scaling_signal(self, window_s: float = 10.0) -> Dict[str, Any]:
        """Fleet-size pressure from the clients' queue-wait signals.

        ``pressure`` is the aggregate consumer starved-seconds per second
        over the last ``window_s`` (clients report their
        ``queue.results_empty_wait_s`` deltas - the exact signal
        petastorm_tpu.autotune grows local worker pools on).  Crossing the
        autotune policy's ``starved_threshold`` with work queued means the
        fleet is the bottleneck -> ``'grow'``; an idle fleet with nothing
        pending -> ``'shrink'``; else ``'ok'``.
        """
        from petastorm_tpu.autotune import AutotunePolicy

        threshold = AutotunePolicy.starved_threshold
        now = time.monotonic()
        with self._lock:
            starved = sum(delta for t, delta in self._starved_reports
                          if now - t <= window_s)
            pending = sum(len(c.pending) for c in self._clients.values())
            inflight = sum(len(c.inflight) for c in self._clients.values())
            capacity = sum(w.capacity for w in self._workers.values())
            clients = sum(1 for c in self._clients.values() if c.connected)
        pressure = starved / window_s
        busy_frac = (inflight / capacity) if capacity else 0.0
        if clients and (pressure > threshold or not capacity) \
                and (pending > 0 or not capacity):
            recommendation = "grow"
        elif capacity and clients and busy_frac < 0.1 and pending == 0 \
                and pressure < threshold / 4:
            recommendation = "shrink"
        else:
            recommendation = "ok"
        return {"pressure": round(pressure, 4),
                "starved_threshold": threshold,
                "busy_fraction": round(busy_frac, 4),
                "pending_items": pending, "worker_capacity": capacity,
                "recommendation": recommendation}

    def stats(self) -> Dict[str, Any]:
        """Point-in-time service snapshot (CLI ``stats`` / tests /
        operators): fleet membership, per-client progress, counters, and
        the scaling signal."""
        with self._lock:
            workers = {name: {"capacity": w.capacity, "busy": w.busy,
                              "inflight": len(w.inflight),
                              "hostname": w.hostname,
                              "heartbeat_age_s": round(
                                  time.monotonic() - w.last_heartbeat, 2)}
                       for name, w in self._workers.items()}
            clients = {cid: {"connected": c.connected,
                             "pending": len(c.pending),
                             "inflight": len(c.inflight),
                             "unacked": len(c.unacked),
                             "rows": c.rows, "results": c.results,
                             "requeued": c.requeued}
                       for cid, c in self._clients.items()}
        counters = {}
        if self.telemetry.enabled:
            counters = {k: v for k, v in
                        self.telemetry.snapshot()["counters"].items()
                        if k.startswith("service.")}
        return {"uptime_s": round(time.monotonic() - self._started_at, 1),
                "port": self.port, "workers": workers, "clients": clients,
                "counters": counters, "scaling": self.scaling_signal()}
