"""Ring attention (context parallelism) tests on the virtual 8-device mesh.

Proves the long-context feed path: loader delivers sequence-sharded batches
(P("data", "seq")), ring attention consumes them with K/V ppermute rotation,
results match a replicated full-attention reference exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from petastorm_tpu.ops.ring_attention import ring_attention


def _mesh(data=2, seq=4):
    devs = np.asarray(jax.devices()[:data * seq]).reshape(data, seq)
    return Mesh(devs, ("data", "seq"))


def _reference_attention(q, k, v, causal, scale=None):
    d = q.shape[-1]
    scale = scale or 1.0 / (d ** 0.5)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        s = q.shape[2]
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask, scores, -jnp.inf)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(scores, axis=-1), v)


@pytest.mark.parametrize("causal", [False, True])
def test_matches_full_attention(causal):
    mesh = _mesh()
    rng = np.random.default_rng(0)
    b, h, s, d = 2, 2, 32, 16
    q, k, v = (jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
               for _ in range(3))
    out = ring_attention(q, k, v, mesh=mesh, causal=causal)
    ref = _reference_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_differentiable():
    mesh = _mesh()
    rng = np.random.default_rng(1)
    b, h, s, d = 2, 2, 16, 8
    q, k, v = (jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
               for _ in range(3))

    def loss_ring(q, k, v):
        return ring_attention(q, k, v, mesh=mesh, causal=True).sum()

    def loss_ref(q, k, v):
        return _reference_attention(q, k, v, True).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4)


def test_loader_feeds_ring_attention_end_to_end(tmp_path):
    """Long-context CP training step fed by the real loader: tokens arrive
    sequence-sharded over the 'seq' mesh axis exactly as ring attention
    expects (SURVEY.md section 2.14 SP/CP delivery contract)."""
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.reader import make_reader
    from petastorm_tpu.schema import Field, Schema

    mesh = _mesh()
    seq_len, vocab, d, heads = 32, 50, 16, 2
    global_batch = 4

    schema = Schema("LongCtx", [Field("tokens", np.int32, (seq_len,)),
                                Field("label", np.int32)])
    rng = np.random.default_rng(7)
    rows = [{"tokens": rng.integers(0, vocab, seq_len).astype(np.int32),
             "label": int(rng.integers(0, 2))} for _ in range(16)]
    url = str(tmp_path / "longctx")
    write_dataset(url, schema, rows, row_group_size_rows=8)

    k0 = jax.random.PRNGKey(0)
    params = {
        "embed": jax.random.normal(k0, (vocab, heads * d), jnp.float32) * 0.02,
        "out": jax.random.normal(k0, (heads * d, 2), jnp.float32) * 0.02,
    }

    def loss_fn(p, tokens, label):
        b, s = tokens.shape
        x = p["embed"][tokens]                       # (B, S, H*D)
        x = x.reshape(b, s, heads, d).transpose(0, 2, 1, 3)  # (B, H, S, D)
        o = ring_attention(x, x, x, mesh=mesh, causal=True)
        o = o.transpose(0, 2, 1, 3).reshape(b, s, heads * d)
        logits = o.mean(axis=1) @ p["out"]
        onehot = jax.nn.one_hot(label, 2)
        return -(jax.nn.log_softmax(logits) * onehot).sum(-1).mean()

    grad_step = jax.jit(jax.value_and_grad(loss_fn))

    with mesh:
        reader = make_reader(url, shuffle_row_groups=False, num_epochs=1)
        with JaxDataLoader(reader, batch_size=global_batch, mesh=mesh,
                           shardings={"tokens": P("data", "seq"),
                                      "label": P("data")}) as loader:
            batch = next(iter(loader))
            assert batch["tokens"].sharding.spec == P("data", "seq")
            loss, grads = grad_step(params, batch["tokens"], batch["label"])
    assert np.isfinite(float(loss))
    assert all(np.isfinite(np.asarray(g)).all() for g in jax.tree.leaves(grads))
