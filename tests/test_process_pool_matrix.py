"""Feature matrix under the PROCESS pool + shm transport (VERDICT r2 item 8).

The reference runs its full behavior matrix across every pool flavor
(tests/test_end_to_end.py:44-59).  Spawn costs ~1-3 s/worker on the 1-core CI
host, so the cells here are the representative behaviors whose code paths
differ under process isolation: predicate split-read, ngram window formation,
local-disk cache reuse across epochs, and quiesce-exact resume cursors - all
crossing the C++ shm arena instead of in-process queues.
"""

import collections

import numpy as np
import pytest

from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
from petastorm_tpu.etl.writer import write_dataset
from petastorm_tpu.ngram import NGram
from petastorm_tpu.predicates import in_lambda
from petastorm_tpu.reader import make_reader
from petastorm_tpu.schema import Field, Schema
from petastorm_tpu.test_util.synthetic import create_test_dataset

WORKERS = 2


def _div3(cols):
    return cols["id"] % 3 == 0


#: module-level (spawn workers pickle the predicate; locals cannot cross)
DIV3 = in_lambda(["id"], _div3, vectorized=True)


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("pp_e2e") / "ds")
    rows = create_test_dataset(path, num_rows=48, row_group_size_rows=8)
    return path, rows


@pytest.fixture(scope="module")
def seq_dataset(tmp_path_factory):
    url = str(tmp_path_factory.mktemp("pp_seq") / "seq")
    schema = Schema("Seq", [
        Field("ts", np.int64, (), ScalarCodec()),
        Field("cam", np.uint8, (8, 8), NdarrayCodec()),
    ])
    rng = np.random.default_rng(2)
    write_dataset(url, schema,
                  [{"ts": i, "cam": rng.integers(0, 255, (8, 8), dtype=np.uint8)}
                   for i in range(64)],
                  row_group_size_rows=16)
    return url


def test_predicate_split_read_under_process_pool(dataset):
    """Predicate split-read (predicate cols decode first, mask pre-decode)
    with the mask crossing the shm transport."""
    url, rows = dataset
    with make_reader(url, reader_pool_type="process", workers_count=WORKERS,
                     predicate=DIV3, shuffle_row_groups=False) as r:
        got = sorted(row.id for row in r)
    assert got == [i for i in range(48) if i % 3 == 0]


def test_ngram_windows_under_process_pool(seq_dataset):
    """NGram window formation inside spawned workers; windows (nested column
    naming) must survive the shm hop intact."""
    ng = NGram({0: ["ts", "cam"], 1: ["ts", "cam"]}, delta_threshold=1,
               timestamp_field="ts")
    with make_reader(seq_dataset, ngram=ng, reader_pool_type="process",
                     workers_count=WORKERS, num_epochs=1,
                     shuffle_row_groups=False) as r:
        windows = list(r)
    assert len(windows) == 64 - 16 // 16 * 4  # 4 rowgroups x (16-1) windows
    for w in windows:
        assert w[1].ts == w[0].ts + 1
        assert w[0].cam.shape == (8, 8)


def test_local_disk_cache_under_process_pool(dataset, tmp_path):
    """cache_type='local-disk' is the documented cache for process pools
    (memory cache is refused there): epoch 2 must serve identical rows and
    the cache directory must hold entries written by the spawned workers."""
    url, rows = dataset
    cache_dir = str(tmp_path / "cache")
    with make_reader(url, reader_pool_type="process", workers_count=WORKERS,
                     cache_type="local-disk", cache_location=cache_dir,
                     num_epochs=2, shuffle_row_groups=False,
                     schema_fields=["id", "matrix"]) as r:
        ids = [row.id for row in r]
    counts = collections.Counter(ids)
    assert sorted(counts) == list(range(48)) and set(counts.values()) == {2}
    import os

    cached = [f for _, _, fs in os.walk(cache_dir) for f in fs]
    assert cached, "local-disk cache wrote nothing"


def test_quiesce_exact_resume_under_process_pool(tmp_path):
    """quiesce() -> exhaust -> state_dict() must be an EXACT cursor even with
    spawned workers completing items out of ventilation order (ordinals ride
    the shm transport); the resumed reader replays the rest exactly once."""
    # many small rowgroups so the bounded in-flight window cannot swallow the
    # whole epoch before quiesce
    url = str(tmp_path / "resume_ds")
    create_test_dataset(url, num_rows=48, row_group_size_rows=2)
    seen = []
    with make_reader(url, reader_pool_type="process", workers_count=WORKERS,
                     results_queue_size=2, num_epochs=1, shuffle_seed=11,
                     schema_fields=["id"]) as r:
        it = iter(r)
        for _ in range(10):
            seen.append(next(it).id)
        r.quiesce()
        for row in it:
            seen.append(row.id)
        state = r.state_dict()
    assert state["ordinal_exact"]
    resumed = []
    with make_reader(url, reader_pool_type="process", workers_count=WORKERS,
                     num_epochs=1, shuffle_seed=11, schema_fields=["id"],
                     resume_from=state) as r:
        resumed = [row.id for row in r]
    counts = collections.Counter(seen + resumed)
    assert sorted(counts) == list(range(48)) and max(counts.values()) == 1
    assert resumed, "quiesce consumed the whole epoch; nothing left to resume"
