"""Schema-driven synthetic Reader stand-in (no parquet, no IO).

Reference parity: petastorm/test_util/reader_mock.py:19-82 - a fake reader that
generates schema-conformant rows so framework adapters (tf/pytorch/jax loaders)
can be tested and micro-benchmarked without touching storage.

TPU-first difference: the mock speaks the same columnar protocol as the real
Reader (``iter_batches()`` yielding ColumnBatch), so the loaders' hot path is
exercised unchanged.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import numpy as np

from petastorm_tpu.batch import ColumnBatch
from petastorm_tpu.schema import Schema


def schema_data_generator(schema: Schema, rng: np.random.Generator,
                          batch_size: int) -> Dict[str, np.ndarray]:
    """Random column dict conformant to ``schema`` (fixed shapes only)."""
    cols: Dict[str, np.ndarray] = {}
    for f in schema:
        shape = tuple(d if d is not None else 3 for d in f.shape)
        full = (batch_size,) + shape
        if f.dtype.kind == "O":
            cols[f.name] = np.asarray(
                [f"{f.name}_{i}" for i in range(batch_size)], dtype=object)
        elif f.dtype.kind in "ui":
            cols[f.name] = rng.integers(0, 127, full).astype(f.dtype)
        elif f.dtype.kind == "b":
            cols[f.name] = rng.integers(0, 2, full).astype(bool)
        else:
            cols[f.name] = rng.standard_normal(full).astype(f.dtype)
    return cols


class ReaderMock:
    """Duck-typed Reader: same iteration/lifecycle surface, synthetic data.

    ``generator(schema, rng, batch_size) -> {name: array}`` may be supplied to
    control values; by default `schema_data_generator` is used.  A finite
    ``num_batches`` makes the mock iterable to exhaustion like a 1-epoch reader;
    ``None`` streams forever (benchmark mode).
    """

    def __init__(self, schema: Schema,
                 generator: Optional[Callable] = None,
                 batch_size: int = 16,
                 num_batches: Optional[int] = 64,
                 seed: int = 0):
        self.schema = schema
        self.output_schema = schema
        self.batched_output = True
        self.last_row_consumed = False
        self.ngram = None
        self._generator = generator or schema_data_generator
        self._batch_size = batch_size
        self._num_batches = num_batches
        self._rng = np.random.default_rng(seed)
        self._produced = 0
        self._stopped = False
        self._namedtuple_type = schema.make_namedtuple_type()
        self._pending_rows: Optional[ColumnBatch] = None
        self._pending_pos = 0

    # -- columnar protocol (what the jax/pytorch/tf loaders consume) ----------

    def _make_batch(self) -> ColumnBatch:
        cols = self._generator(self.schema, self._rng, self._batch_size)
        return ColumnBatch(cols, self._batch_size)

    def iter_batches(self):
        while not self._stopped:
            if (self._num_batches is not None
                    and self._produced >= self._num_batches):
                self.last_row_consumed = True
                return
            self._produced += 1
            yield self._make_batch()

    # -- row protocol ---------------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self):
        if self._pending_rows is None or self._pending_pos >= self._pending_rows.num_rows:
            if (self._num_batches is not None
                    and self._produced >= self._num_batches):
                self.last_row_consumed = True
                raise StopIteration
            self._produced += 1
            self._pending_rows = self._make_batch()
            self._pending_pos = 0
        row = self._pending_rows.row(self._pending_pos)
        self._pending_pos += 1
        return self._namedtuple_type(**{n: row[n] for n in self.schema.fields})

    # -- lifecycle ------------------------------------------------------------

    def reset(self) -> None:
        self._produced = 0
        self._pending_rows = None
        self._pending_pos = 0
        self.last_row_consumed = False

    def stop(self) -> None:
        self._stopped = True

    def join(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        self.join()

    @property
    def diagnostics(self) -> dict:
        return {"produced_batches": self._produced}
