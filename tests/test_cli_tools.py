"""L7 tests: benchmark harness, copy-dataset / generate-metadata CLIs, reader mock.

Reference parity: tests/test_benchmark.py (smoke), tests around
petastorm_generate_metadata, tests/test_copy_dataset.py behavior.
"""

import json
import os

import numpy as np
import pytest

from petastorm_tpu.benchmark.cli import main as throughput_main
from petastorm_tpu.benchmark.dummy_reader import loader_microbench
from petastorm_tpu.benchmark.throughput import (jax_loader_throughput,
                                                reader_throughput)
from petastorm_tpu.codecs import NdarrayCodec
from petastorm_tpu.etl.generate_metadata import main as genmeta_main
from petastorm_tpu.etl.metadata import open_dataset
from petastorm_tpu.reader import make_batch_reader, make_reader
from petastorm_tpu.schema import Field, Schema
from petastorm_tpu.test_util.reader_mock import ReaderMock
from petastorm_tpu.test_util.synthetic import create_test_dataset
from petastorm_tpu.tools.copy_dataset import copy_dataset
from petastorm_tpu.tools.copy_dataset import main as copy_main

SMALL_SCHEMA = Schema("Small", [
    Field("id", np.int64),
    Field("value", np.float32, (3,), NdarrayCodec()),
    Field("opt", np.float64, nullable=True),
])


def _small_rows(n):
    rng = np.random.default_rng(7)
    return [{"id": i, "value": rng.standard_normal(3).astype(np.float32),
             "opt": None if i % 3 == 0 else float(i)} for i in range(n)]


@pytest.fixture(scope="module")
def small_ds(tmp_path_factory):
    from petastorm_tpu.etl.writer import write_dataset
    url = str(tmp_path_factory.mktemp("cli") / "small")
    rows = _small_rows(30)
    write_dataset(url, SMALL_SCHEMA, rows, row_group_size_rows=5)
    return url, rows


def test_reader_throughput_row(small_ds):
    url, _ = small_ds
    res = reader_throughput(url, warmup_cycles=5, measure_cycles=20,
                            workers_count=2)
    assert res.samples == 20
    assert res.samples_per_sec > 0
    assert res.rss_mb > 0


def test_reader_throughput_batch(small_ds):
    url, _ = small_ds
    res = reader_throughput(url, warmup_cycles=1, measure_cycles=4,
                            read_method="batch", workers_count=2)
    assert res.samples >= 4  # rows, counted per columnar batch
    assert res.samples_per_sec > 0


def test_jax_loader_throughput(small_ds):
    url, _ = small_ds
    res = jax_loader_throughput(url, batch_size=8, warmup_batches=1,
                                measure_batches=3, workers_count=2,
                                field_regex=["id", "value"])
    assert res.samples == 3 * 8
    assert res.samples_per_sec > 0


def test_throughput_cli_json(small_ds, capsys):
    url, _ = small_ds
    rc = throughput_main([url, "-n", "2", "-m", "10", "--json"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["samples"] == 10


def test_loader_microbench_smoke():
    results = loader_microbench(batch_sizes=(8,), warmup_batches=1,
                                measure_batches=3,
                                kinds=("torch", "torch_batched", "jax"))
    assert len(results) == 3
    assert all(r["samples_per_sec"] > 0 for r in results)


# -- copy-dataset -------------------------------------------------------------

def test_copy_dataset_full(small_ds, tmp_path):
    url, rows = small_ds
    target = str(tmp_path / "copy")
    n = copy_dataset(url, target)
    assert n == len(rows)
    with make_reader(target, shuffle_row_groups=False) as r:
        got = sorted(row.id for row in r)
    assert got == [row["id"] for row in rows]


def test_copy_dataset_field_subset(small_ds, tmp_path):
    url, _ = small_ds
    target = str(tmp_path / "subset")
    copy_dataset(url, target, field_regex=["id"])
    info = open_dataset(target, require_stored_schema=True)
    from petastorm_tpu.etl.metadata import infer_or_load_schema
    assert [f.name for f in infer_or_load_schema(info)] == ["id"]


def test_copy_dataset_not_null(small_ds, tmp_path):
    url, rows = small_ds
    target = str(tmp_path / "notnull")
    n = copy_dataset(url, target, not_null_fields=["opt"])
    expected = [r for r in rows if r["opt"] is not None]
    assert n == len(expected)
    with make_reader(target, shuffle_row_groups=False) as r:
        assert all(row.opt is not None for row in r)


def test_copy_dataset_overwrite_guard(small_ds, tmp_path):
    from petastorm_tpu.errors import SchemaError
    url, _ = small_ds
    target = str(tmp_path / "guard")
    copy_dataset(url, target)
    with pytest.raises(SchemaError, match="already contains"):
        copy_dataset(url, target)
    # --overwrite replaces
    n = copy_dataset(url, target, overwrite_output=True)
    assert n == 30


def test_copy_dataset_cli(small_ds, tmp_path, capsys):
    url, _ = small_ds
    target = str(tmp_path / "cli_copy")
    rc = copy_main([url, target, "--field-regex", "id", "value"])
    assert rc == 0
    assert "copied 30 rows" in capsys.readouterr().out


# -- generate-metadata --------------------------------------------------------

def test_generate_metadata_restores_deleted(small_ds, tmp_path):
    url, rows = small_ds
    target = str(tmp_path / "regen")
    copy_dataset(url, target)
    meta = os.path.join(target, "_common_metadata")
    os.remove(meta)
    # schema travels inside the data files, so regeneration needs no args
    rc = genmeta_main([target])
    assert rc == 0
    assert os.path.exists(meta)
    with make_reader(target, shuffle_row_groups=False) as r:
        assert sorted(row.id for row in r) == [row["id"] for row in rows]


def test_generate_metadata_infer_plain_parquet(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq
    plain = tmp_path / "plain"
    plain.mkdir()
    pq.write_table(pa.table({"x": [1, 2, 3], "y": [0.5, 1.5, 2.5]}),
                   plain / "data.parquet")
    rc = genmeta_main([str(plain), "--infer"])
    assert rc == 0
    with make_batch_reader(str(plain), shuffle_row_groups=False) as r:
        batch = next(iter(r))
        assert list(batch.x) == [1, 2, 3]


def test_generate_metadata_schema_from(small_ds, tmp_path):
    import pyarrow.parquet as pq
    url, _ = small_ds
    # a bare-file copy (no metadata at all): borrow schema from the original
    import pyarrow.fs as pafs
    import shutil
    target = tmp_path / "borrowed"
    target.mkdir()
    for f in os.listdir(url):
        if f.endswith(".parquet"):
            shutil.copy(os.path.join(url, f), target / f)
    rc = genmeta_main([str(target), "--schema-from", url])
    assert rc == 0
    with make_reader(str(target), shuffle_row_groups=False) as r:
        assert len(list(r)) == 30


# -- reader mock --------------------------------------------------------------

def test_reader_mock_rows_and_batches():
    mock = ReaderMock(SMALL_SCHEMA.view(["id", "value"]), batch_size=4,
                      num_batches=3)
    rows = list(mock)
    assert len(rows) == 12
    assert rows[0].value.shape == (3,)
    assert mock.last_row_consumed
    mock.reset()
    batches = list(mock.iter_batches())
    assert len(batches) == 3
    assert batches[0].num_rows == 4


# -- petastorm-tpu-metadata show (reference etl/metadata_util.py:15-70) -------

def test_show_metadata_human(small_ds, capsys):
    from petastorm_tpu.tools.show_metadata import main as show_main

    url, rows = small_ds
    assert show_main(["show", url]) == 0
    out = capsys.readouterr().out
    assert "Schema:" in out and "id" in out and "NdarrayCodec" in out
    assert "Rowgroups: 6 across" in out          # 30 rows / rg_size 5
    assert f"{len(rows)} rows total" in out
    assert "nullable" in out                     # the 'opt' field
    assert "KV metadata keys:" in out


def test_show_metadata_json_and_indexes(small_ds, tmp_path, capsys):
    from petastorm_tpu.etl.indexing import (SingleFieldIndexer,
                                            build_rowgroup_index)
    from petastorm_tpu.tools.show_metadata import main as show_main

    url, rows = small_ds
    build_rowgroup_index(url, [SingleFieldIndexer("by_id", "id")])
    assert show_main(["show", "--rowgroups", "--json", url]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert {f["name"] for f in doc["schema"]} == {"id", "value", "opt"}
    assert doc["schema_source"] == "stored"
    assert doc["rowgroups"]["total_rows"] == len(rows)
    assert doc["rowgroups"]["rows_per_group_median"] == 5
    assert sum(f["rows"] for f in doc["files"]) == len(rows)
    by_id = [ix for ix in doc["indexes"] if ix["name"] == "by_id"]
    assert by_id and by_id[0]["num_indexed_values"] == len(rows)
    assert any("schema" in k for k in doc["kv_metadata_keys"])


def test_show_metadata_schema_only(small_ds, capsys):
    from petastorm_tpu.tools.show_metadata import main as show_main

    url, _ = small_ds
    assert show_main(["show", "--schema-only", "--json", url]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert set(doc) == {"url", "schema_source", "schema"}


def test_generate_metadata_scan_geometries(tmp_path):
    """--scan-geometries repairs the geometry contract after external writes:
    header-only parse of the image columns, merged into the stamped set."""
    cv2 = pytest.importorskip("cv2")  # noqa: F841
    from petastorm_tpu.codecs import CompressedImageCodec
    from petastorm_tpu.etl.generate_metadata import main as gen_main
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.reader import make_batch_reader
    from petastorm_tpu.schema import Field, Schema

    schema = Schema("ScanGeo", [
        Field("idx", np.int64),
        Field("image", np.uint8, (None, None, 3),
              CompressedImageCodec("jpeg", quality=90)),
    ])
    rng = np.random.default_rng(3)
    geoms = [(16, 24), (24, 16)]
    url = str(tmp_path / "ds")
    write_dataset(url, schema,
                  [{"idx": i,
                    "image": rng.integers(0, 255, geoms[i % 2] + (3,),
                                          dtype=np.uint8)}
                   for i in range(8)],
                  row_group_size_rows=4)
    # simulate an external engine: wipe the stamped contract
    import pyarrow.parquet as pq

    from petastorm_tpu.etl.metadata import GEOMETRIES_METADATA_KEY
    meta = pq.read_metadata(f"{url}/_common_metadata")
    kv = {k: v for k, v in (meta.metadata or {}).items()
          if k != GEOMETRIES_METADATA_KEY}
    pq.write_metadata(meta.schema.to_arrow_schema().with_metadata(kv),
                      f"{url}/_common_metadata")
    with make_batch_reader(url, num_epochs=1) as r:
        assert r.declared_geometries == {}

    assert gen_main([url, "--scan-geometries"]) == 0
    with make_batch_reader(url, num_epochs=1) as r:
        declared = r.declared_geometries
    assert sorted(declared["image"]) == sorted(g + (3,) for g in geoms)

    # a rescan is authoritative: stale shapes from rewritten files DISAPPEAR
    # (append-mode stamps merge, but --scan-geometries replaces)
    from petastorm_tpu.etl.writer import stamp_dataset_metadata
    stamp_dataset_metadata(url, geometries={"image": {(99, 99, 3)}})
    with make_batch_reader(url, num_epochs=1) as r:
        assert (99, 99, 3) in r.declared_geometries["image"]  # merged in
    assert gen_main([url, "--scan-geometries"]) == 0
    with make_batch_reader(url, num_epochs=1) as r:
        assert sorted(r.declared_geometries["image"]) == sorted(
            g + (3,) for g in geoms)  # stale shape replaced away


def test_scan_geometries_empty_rescan_clears_contract(tmp_path):
    """--scan-geometries REPLACE semantics must hold even when the rescan
    finds NOTHING: an empty authoritative scan stamps an empty contract
    (the KV merge in write_metadata_file would otherwise silently preserve
    the stale geometry key)."""
    pytest.importorskip("cv2")
    import pyarrow as pa
    import pyarrow.parquet as pq

    from petastorm_tpu.codecs import CompressedImageCodec
    from petastorm_tpu.etl.generate_metadata import main as gen_main
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.reader import make_batch_reader
    from petastorm_tpu.schema import Field, Schema

    schema = Schema("ScanGeoEmpty", [
        Field("idx", np.int64),
        Field("image", np.uint8, (None, None, 3),
              CompressedImageCodec("jpeg", quality=90)),
    ])
    rng = np.random.default_rng(5)
    url = str(tmp_path / "ds")
    write_dataset(url, schema,
                  [{"idx": i,
                    "image": rng.integers(0, 255, (16, 16, 3), dtype=np.uint8)}
                   for i in range(4)])
    with make_batch_reader(url, num_epochs=1) as r:
        assert r.declared_geometries == {"image": [(16, 16, 3)]}

    # an external engine rewrites the image cells to unparseable bytes: the
    # header scan now finds no geometry at all
    import glob
    import os

    for path in glob.glob(os.path.join(url, "*.parquet")):
        table = pq.read_table(path)
        junk = pa.array([b"not-an-image"] * table.num_rows, pa.binary())
        idx = table.schema.get_field_index("image")
        table = table.set_column(idx, table.schema.field(idx), junk)
        pq.write_table(table, path)

    assert gen_main([url, "--scan-geometries"]) == 0
    with make_batch_reader(url, num_epochs=1) as r:
        assert r.declared_geometries == {}  # stale (16,16,3) contract cleared


def test_image_dims_header_parse():
    """Header-only geometry parse: png IHDR, jpeg SOF, jpeg with legal 0xFF
    fill bytes before the marker, and junk."""
    from petastorm_tpu.etl.generate_metadata import _image_dims

    png = (b"\x89PNG\r\n\x1a\n" + b"\x00\x00\x00\rIHDR"
           + (24).to_bytes(4, "big") + (16).to_bytes(4, "big")
           + bytes([8, 2, 0, 0, 0]))
    assert _image_dims(png) == (16, 24, 3)

    def sof(h, w, c):
        return (b"\xff\xc0" + (8 + 3 * c).to_bytes(2, "big") + b"\x08"
                + h.to_bytes(2, "big") + w.to_bytes(2, "big")
                + bytes([c]) + b"\x00" * (3 * c))

    app0 = b"\xff\xe0" + (16).to_bytes(2, "big") + b"JFIF\x00" + b"\x00" * 9
    assert _image_dims(b"\xff\xd8" + app0 + sof(32, 48, 3) + b"\x00" * 8) \
        == (32, 48, 3)
    # legal fill bytes between segments must not be read as a marker+length
    assert _image_dims(b"\xff\xd8" + b"\xff\xff\xff" + sof(7, 9, 1)
                       + b"\x00" * 16) == (7, 9, 1)
    assert _image_dims(b"not an image at all, definitely not") is None


def test_valid_mask_field_rejects_reserved_name(small_ds):
    import jax
    from jax.sharding import Mesh

    from petastorm_tpu.errors import PetastormTpuError
    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.reader import make_reader

    url, _ = small_ds
    mesh = Mesh(np.array(jax.devices()), ("data",))
    reader = make_reader(url, schema_fields=["id"])
    with pytest.raises(PetastormTpuError, match="reserved"):
        JaxDataLoader(reader, batch_size=8, mesh=mesh,
                      valid_mask_field="_valid_rows")
    reader.stop(); reader.join()
