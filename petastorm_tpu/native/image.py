"""ctypes binding for the native batched PNG/JPEG decoder (image_decode.cpp).

``decode_column_native`` decodes a whole ``pyarrow`` binary column of encoded
image streams into one preallocated contiguous uint8 array in a single
GIL-released C call, reading the streams zero-copy straight out of the arrow
data buffer (no ``to_pylist``, no per-cell Python objects).

Replaces the reference's per-cell ``cv2.imdecode`` loop
(petastorm/codecs.py:92-101) on the hot path; codecs.CompressedImageCodec falls
back to cv2/PIL when the native library or the input shape doesn't qualify.
"""

from __future__ import annotations

import ctypes
import logging
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

def _configure(lib: ctypes.CDLL) -> None:
    lib.pst_decode_image_batch.restype = ctypes.c_int
    lib.pst_decode_image_batch.argtypes = [
        ctypes.c_void_p,  # const uint8_t* const* srcs (uint64 array)
        ctypes.c_void_p,  # const uint64_t* lens
        ctypes.c_int,     # n
        ctypes.c_void_p,  # uint8_t* out
        ctypes.c_uint64,  # stride
        ctypes.c_int, ctypes.c_int, ctypes.c_int,  # h, w, c
        ctypes.c_int,     # nthreads
    ]
    lib.pst_decode_image.restype = ctypes.c_int
    lib.pst_decode_image.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_void_p,
        ctypes.c_int, ctypes.c_int, ctypes.c_int,
    ]


def _load() -> Optional[ctypes.CDLL]:
    from petastorm_tpu.native import build

    return build.load_library("image_decode", _configure)


def available() -> bool:
    return _load() is not None


def _column_pointers(column) -> Optional[tuple]:
    """(ptrs uint64 array, lens uint64 array) for a binary arrow array, zero-copy."""
    import pyarrow as pa

    if column.null_count:
        return None
    typ = column.type
    if typ == pa.binary():
        off_dtype = np.int32
    elif typ == pa.large_binary():
        off_dtype = np.int64
    else:
        return None
    buffers = column.buffers()  # [validity, offsets, data]
    if len(buffers) != 3 or buffers[1] is None or buffers[2] is None:
        return None
    n = len(column)
    offsets = np.frombuffer(
        buffers[1], dtype=off_dtype, count=n + 1,
        offset=column.offset * np.dtype(off_dtype).itemsize).astype(np.uint64)
    ptrs = np.uint64(buffers[2].address) + offsets[:-1]
    lens = offsets[1:] - offsets[:-1]
    return ptrs, lens


def decode_column_native(column, out: np.ndarray, nthreads: int = 1) -> bool:
    """Decode a binary arrow column of PNG/JPEG streams into ``out``.

    ``out`` must be contiguous uint8 of shape (n, h, w, c) or (n, h, w).
    Returns False (without touching ``out``'s validity) when the native path
    doesn't apply; raises on an actual decode failure.
    """
    lib = _load()
    if lib is None:
        return False
    if out.dtype != np.uint8 or not out.flags.c_contiguous:
        return False
    if out.ndim == 3:
        n, h, w = out.shape
        c = 1
    elif out.ndim == 4:
        n, h, w, c = out.shape
    else:
        return False
    if c not in (1, 3, 4):
        return False
    pointers = _column_pointers(column)
    if pointers is None:
        return False
    ptrs, lens = pointers
    if len(ptrs) != n:
        return False
    if n == 0:
        return True
    rc = lib.pst_decode_image_batch(
        ptrs.ctypes.data, lens.ctypes.data, n,
        out.ctypes.data, np.uint64(out.strides[0]), h, w, c, nthreads)
    if rc != 0:
        from petastorm_tpu.errors import CodecError

        raise CodecError(
            f"native image decode failed at cell {rc - 1} (expected shape "
            f"({h}, {w}, {c}) uint8; corrupt stream or shape mismatch)")
    return True
