"""In-memory data -> cached parquet -> loaders: the high-level converter API.

Reference parity: petastorm/spark/spark_dataset_converter.py (681 LoC) -
``make_spark_converter(df)`` materializes a DataFrame under a parent cache dir
(spark_dataset_converter.py:61-81,166-175), dedupes repeated conversions by
analyzed query plan + params (448-484), registers atexit cleanup (117-121),
converts float precision (496-529), then ``SparkDatasetConverter.make_tf_dataset/
make_torch_dataloader`` wrap the cached parquet in framework loaders (203-278).
Rank-consistency of ``cur_shard/shard_count`` is checked against launcher env
vars, warning only (124-163); S3 eventual consistency is handled by waiting for
files (565-595); a median-file-size advisory flags tiny files (598-617).

TPU-first differences: the JVM-free path is first-class - input is a pandas
DataFrame or pyarrow Table, deduped by content fingerprint (sha256 over schema
+ column buffers + write params) - and the first-class consumer is
``make_jax_loader`` (mesh-sharded device batches) with the torch loader kept
for parity.  A Spark DataFrame (when pyspark is importable) materializes ON
THE EXECUTORS via ``df.write.parquet`` with query-plan dedup and MLlib
vector->array conversion, exactly like the reference (:546-562,:496-529,
:448-484) - the driver never collects the data.
"""

from __future__ import annotations

import atexit
import hashlib
import logging
import os
import posixpath
import time
import uuid
import warnings
from typing import Dict, List, Optional, Sequence, Union

import numpy as np
import pyarrow as pa
import pyarrow.fs as pafs
import pyarrow.parquet as pq

from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.etl.writer import (DEFAULT_ROW_GROUP_SIZE_MB,
                                      stamp_dataset_metadata)
from petastorm_tpu.fs import get_filesystem_and_path, normalize_dir_url
from petastorm_tpu.reader import make_reader
from petastorm_tpu.schema import Schema

logger = logging.getLogger(__name__)

#: env var naming the parent cache dir (reference: spark conf key
#: 'petastorm.spark.converter.parentCacheDirUrl', spark_dataset_converter.py:61-81)
CACHE_DIR_ENV_VAR = "PETASTORM_TPU_CONVERTER_CACHE_DIR"

_MIN_ADVISED_FILE_SIZE_BYTES = 50 * 1024 * 1024  # reference advisory threshold

#: converters created this process, for atexit cleanup
_registered_converters: List["DatasetConverter"] = []
#: live converter per cache_url: a content-dedup hit returns the SAME handle,
#: so delete() cannot destroy a dataset another handle still uses
_converters_by_url: Dict[str, "DatasetConverter"] = {}


def _cleanup_at_exit() -> None:
    for conv in list(_registered_converters):
        try:
            conv.delete()
        except Exception:  # noqa: BLE001 - best-effort cleanup at interpreter exit
            logger.warning("Failed to clean converter cache %s", conv.cache_url,
                           exc_info=True)


atexit.register(_cleanup_at_exit)


def _is_spark_dataframe(data) -> bool:
    """Duck-typed: a pyspark.sql.DataFrame has a JVM-backed writer and schema.
    (No isinstance - pyspark may be absent, and tests exercise the path with
    stand-ins, the same approach as tests/test_interop.py.)"""
    return (hasattr(data, "write") and hasattr(data, "schema")
            and hasattr(data, "toPandas"))


def _to_arrow_table(data, dtype: Optional[str]) -> pa.Table:
    """Normalize supported inputs to a pyarrow Table, applying float precision."""
    if isinstance(data, pa.Table):
        table = data
    elif hasattr(data, "columns") and hasattr(data, "dtypes"):  # pandas
        table = pa.Table.from_pandas(data, preserve_index=False)
    else:
        raise PetastormTpuError(
            f"Unsupported input type {type(data).__name__}: expected a pandas"
            " DataFrame, pyarrow Table, or Spark DataFrame")
    if dtype is None:
        return table
    if dtype not in ("float32", "float64"):
        raise PetastormTpuError(f"dtype must be 'float32', 'float64' or None,"
                                f" got {dtype!r}")
    # float precision normalization (reference spark_dataset_converter.py:496-529)
    target = pa.float32() if dtype == "float32" else pa.float64()
    source = pa.float64() if dtype == "float32" else pa.float32()
    fields = []
    changed = False
    for f in table.schema:
        if f.type == source:
            fields.append(pa.field(f.name, target, f.nullable))
            changed = True
        elif (pa.types.is_list(f.type) and f.type.value_type == source):
            fields.append(pa.field(f.name, pa.list_(target), f.nullable))
            changed = True
        else:
            fields.append(f)
    if not changed:
        return table
    return table.cast(pa.schema(fields))


def _spark_prepare_df(df, dtype: Optional[str]):
    """Spark-side column normalization, on executors at write time.

    Reference behavior (spark_dataset_converter.py:496-529): MLlib
    ``VectorUDT`` columns convert to arrays (with a warning - the conversion
    loses sparsity), and float precision is normalized per ``dtype``.
    Everything happens through Spark column expressions, so nothing is
    collected to the driver.
    """
    if dtype not in (None, "float32", "float64"):
        raise PetastormTpuError(f"dtype must be 'float32', 'float64' or None,"
                                f" got {dtype!r}")
    target_scalar = {"float32": "float", "float64": "double"}.get(dtype)
    source_scalar = {"float32": "DoubleType", "float64": "FloatType"}.get(dtype)
    for field in df.schema.fields:
        type_name = type(field.dataType).__name__
        if type_name == "VectorUDT":
            from pyspark.ml.functions import vector_to_array
            from pyspark.sql.functions import col

            warnings.warn(
                f"Column {field.name!r} is an MLlib vector; converting to an"
                f" array of {dtype or 'float64'} (sparse vectors densify)")
            df = df.withColumn(field.name, vector_to_array(
                col(field.name), dtype=dtype or "float64"))
        elif dtype is not None and type_name == source_scalar:
            from pyspark.sql.functions import col

            df = df.withColumn(field.name,
                               col(field.name).cast(target_scalar))
        elif dtype is not None and type_name == "ArrayType" and \
                type(field.dataType.elementType).__name__ == source_scalar:
            from pyspark.sql.functions import col

            df = df.withColumn(field.name, col(field.name).cast(
                f"array<{target_scalar}>"))
    return df


def _spark_fingerprint(df, params: Dict) -> str:
    """Dedup key for a Spark DataFrame: its analyzed logical plan + params
    (the reference's cache key, spark_dataset_converter.py:448-484).  Content
    hashing would require collecting the data - the thing this path avoids."""
    try:
        plan = df._jdf.queryExecution().analyzed().toString()  # noqa: SLF001
    except Exception:  # noqa: BLE001 - non-JVM stand-ins in tests
        plan = None
    if not plan:
        try:
            plan = f"{df.schema.json()}|semantic:{df.semanticHash()}"
        except Exception:  # noqa: BLE001
            # no stable identity available: a fresh dir per conversion
            # (correct, just no dedup)
            plan = f"{df.schema.json()}|uuid:{uuid.uuid4().hex}"
    digest = hashlib.sha256()
    digest.update(plan.encode())
    digest.update(repr(sorted(params.items())).encode())
    return digest.hexdigest()[:20]


def _publish_dir(fs: pafs.FileSystem, tmp_root: str, root: str) -> None:
    """Atomically publish ``tmp_root`` at ``root``.  A lost rename race -
    another process published the same fingerprinted content first - is
    benign: keep theirs, drop ours.  The race is recognized by the OUTCOME
    (a dataset with parquet data now exists at ``root``), not the exception
    type, because filesystems surface the collision differently (OSError,
    ArrowInvalid, backend-specific errors).  A bare debris directory at
    ``root`` does NOT count as a winner: deleting our complete tmp output in
    its favor would silently yield an empty dataset."""
    def _parquet_count(path: str) -> int:
        try:
            return sum(1 for i in fs.get_file_info(pafs.FileSelector(path))
                       if i.type == pafs.FileType.File
                       and i.path.endswith(".parquet"))
        except (OSError, FileNotFoundError):
            return 0

    ours = _parquet_count(tmp_root)
    try:
        fs.move(tmp_root, root)
    except Exception as move_exc:  # noqa: BLE001 - re-raised unless race confirmed
        # the winner must look at least as complete as what we tried to
        # publish: on filesystems where move is per-file copy+delete, OUR
        # OWN failed half-move must not read as a winning peer (deleting
        # tmp_root would then destroy the only complete copy)
        try:
            won = (fs.get_file_info(root).type == pafs.FileType.Directory
                   and _parquet_count(root) >= max(ours, 1))
        except Exception:  # noqa: BLE001 - verification itself failed
            raise move_exc  # unknown outcome: surface the original failure
        if not won:
            raise
        logger.info("Lost publish race for %s; keeping the winner", root)
        fs.delete_dir(tmp_root)


def _move_debris_aside(fs: pafs.FileSystem, root: str, ds_url: str) -> None:
    """A directory with no published parquet sits at the cache target
    (crashed pre-atomic-rename writer, or foreign files): move it ASIDE
    rather than deleting in place, so a concurrent atomic publish landing in
    the remaining window is taken out of the way (and re-materialized from
    the same fingerprinted content) instead of destroyed."""
    logger.warning("Clearing incomplete materialization at %s", ds_url)
    aside = posixpath.join(posixpath.dirname(root),
                           f".stale-{posixpath.basename(root)}"
                           f"-{uuid.uuid4().hex[:8]}")
    try:
        fs.move(root, aside)
        fs.delete_dir(aside)
    except FileNotFoundError:
        pass  # another process cleared it first


def _materialize_spark_df(df, ds_url: str, cache_dir_url: str,
                          fs: pafs.FileSystem, root: str,
                          compression_codec: str,
                          row_group_size_mb: float) -> None:
    """Executor-side materialization: ``df.write.parquet`` into a temp dir,
    then an atomic rename publishes it (the arrow path's scheme) - a crashed
    job leaves only an unadopted ``.tmp-*`` dir, never a partial dataset at
    the cache URL, and concurrent converters of the same plan race benignly.
    The driver never holds the data, so DataFrames larger than driver RAM
    convert fine (reference spark_dataset_converter.py:546-562, incl. the
    ``parquet.block.size`` option at :553-555)."""
    tag = posixpath.basename(root)
    tmp_url = posixpath.join(cache_dir_url, f".tmp-{tag}-{uuid.uuid4().hex[:8]}")
    _, tmp_root = get_filesystem_and_path(tmp_url)
    (df.write.mode("overwrite")
       .option("compression", compression_codec)
       .option("parquet.block.size", int(row_group_size_mb * 2**20))
       .parquet(tmp_url))
    wrote = [i.path for i in fs.get_file_info(pafs.FileSelector(tmp_root))
             if i.type == pafs.FileType.File and i.path.endswith(".parquet")]
    if not wrote:
        fs.delete_dir(tmp_root)
        raise PetastormTpuError(
            f"Spark wrote no parquet files for {ds_url!r} (empty DataFrame?)")
    _publish_dir(fs, tmp_root, root)


def _share_live_handle(ds_url: str, delete_at_exit: bool):
    """Same content converted earlier in this process: share the handle, so
    one delete() cannot destroy the dataset under another reference.
    Persistence wins on disagreement: if any caller asked to keep the cache
    (delete_at_exit=False), un-register the exit cleanup."""
    live = _converters_by_url.get(ds_url)
    if live is None or live._deleted:  # noqa: SLF001
        return None
    if not delete_at_exit and live._owns_cache:  # noqa: SLF001
        live._owns_cache = False
        if live in _registered_converters:
            _registered_converters.remove(live)
    elif delete_at_exit and not live._owns_cache:  # noqa: SLF001
        warnings.warn(
            f"Cache {ds_url} was already created with delete_at_exit=False;"
            " it will be kept despite this call's delete_at_exit=True.")
    return live


def _register_converter(conv: "DatasetConverter", delete_at_exit: bool) -> None:
    _converters_by_url[conv.cache_url] = conv
    if delete_at_exit:
        _registered_converters.append(conv)


def _make_spark_converter(df, cache_dir_url: str, *, dtype, compression_codec,
                          row_group_size_mb, delete_at_exit,
                          storage_options) -> "DatasetConverter":
    """Spark-DataFrame input: materialize ON THE EXECUTORS via
    ``df.write.parquet`` (reference spark_dataset_converter.py:546-562) - the
    driver never collects the data, so frames larger than driver RAM convert.
    MLlib vector columns convert to arrays first (:496-529); dedup is by
    analyzed query plan + params (:448-484)."""
    df = _spark_prepare_df(df, dtype)
    compression_codec = compression_codec or "snappy"
    params = {"codec": compression_codec, "rg_mb": row_group_size_mb,
              "v": 2, "engine": "spark"}
    tag = _spark_fingerprint(df, params)
    ds_url = posixpath.join(cache_dir_url, f"converted-{tag}")
    fs, root = get_filesystem_and_path(ds_url, storage_options)

    live = _share_live_handle(ds_url, delete_at_exit)
    if live is not None:
        return live

    def _published_files():
        """Parquet files of a COMPLETE materialization only: published dirs
        arrive whole via the atomic rename and carry _SUCCESS (Spark's
        committer) or _common_metadata (our stamp); a bare dir of part files
        is a crashed/foreign write and must not be silently adopted."""
        info = fs.get_file_info(root)
        if info.type != pafs.FileType.Directory:
            return []
        entries = [i for i in fs.get_file_info(pafs.FileSelector(root))
                   if i.type == pafs.FileType.File]
        names = {posixpath.basename(i.path) for i in entries}
        if not ({"_SUCCESS", "_common_metadata"} & names):
            return []
        return [i.path for i in entries if i.path.endswith(".parquet")]

    files = _published_files()
    if not files and fs.get_file_info(root).type == pafs.FileType.Directory:
        # a concurrent converter of the same plan may have published (atomic
        # rename) between the check above and now - re-check before touching
        # the directory, then move it ASIDE rather than deleting: if a publish
        # still lands in the remaining window, the move takes the complete
        # dataset out of the way (and we re-materialize the identical plan)
        # instead of destroying it
        files = _published_files()
        if not files:
            _move_debris_aside(fs, root, ds_url)
    if not files:
        _materialize_spark_df(df, ds_url, cache_dir_url, fs, root,
                              compression_codec, row_group_size_mb)
        files = _published_files()
        if not files:
            raise PetastormTpuError(
                f"Materialized Spark dataset at {ds_url!r} has no complete"
                " parquet output (committer wrote no _SUCCESS marker?)")
    else:
        logger.info("Reusing cached converted dataset %s", ds_url)

    # eventual-consistency wait BEFORE any footer read (module header;
    # reference spark_dataset_converter.py:565-595)
    _wait_files_available(fs, files)
    # schema + row count come from the written footers - never from the driver
    num_rows = 0
    arrow_schema = None
    for path in files:
        with fs.open_input_file(path) as f:
            meta = pq.ParquetFile(f)
            num_rows += meta.metadata.num_rows
            if arrow_schema is None:
                arrow_schema = meta.schema_arrow
    schema = Schema.from_arrow_schema(arrow_schema, name=f"Converted_{tag[:8]}")
    stamp_dataset_metadata(ds_url, schema, storage_options=storage_options)
    _advise_on_file_sizes(fs, files)
    conv = DatasetConverter(ds_url, files, num_rows, schema,
                            _owns_cache=delete_at_exit,
                            storage_options=storage_options)
    _register_converter(conv, delete_at_exit)
    return conv


def _fingerprint(table: pa.Table, params: Dict) -> str:
    """Content hash: schema + write params + every column buffer.

    Zero-copy slices share the parent's untrimmed buffers and differ only in
    array offset/length, so those are hashed too - otherwise every slice of a
    table would collide with the full table.
    """
    h = hashlib.sha256()
    h.update(str(sorted(params.items())).encode())
    h.update(table.schema.serialize().to_pybytes())
    h.update(str(table.num_rows).encode())
    for batch in table.to_batches():
        for col in batch.columns:
            h.update(f"{col.offset}:{len(col)};".encode())
            for buf in col.buffers():
                if buf is not None:
                    h.update(buf)
    return h.hexdigest()[:24]


def _check_shard_rank_env(cur_shard: Optional[int],
                          shard_count: Optional[int]) -> None:
    """Warn (never fail) when cur_shard/shard_count disagree with the launcher's
    env vars or the JAX distributed runtime (reference rank discovery,
    spark_dataset_converter.py:124-163)."""
    env_rank = env_size = None
    for rank_var, size_var in (("HOROVOD_RANK", "HOROVOD_SIZE"),
                               ("OMPI_COMM_WORLD_RANK", "OMPI_COMM_WORLD_SIZE"),
                               ("PMI_RANK", "PMI_SIZE")):
        if rank_var in os.environ:
            env_rank = int(os.environ[rank_var])
            env_size = int(os.environ.get(size_var, 0)) or None
            break
    if env_rank is None:
        try:
            import jax

            if jax.process_count() > 1:
                env_rank, env_size = jax.process_index(), jax.process_count()
        except Exception:  # noqa: BLE001 - jax may be uninitialized here
            return
    if env_rank is None:
        return
    if cur_shard is None and shard_count is None:
        warnings.warn(
            f"A distributed launcher is active (rank {env_rank}"
            f"{f' of {env_size}' if env_size else ''}) but no cur_shard/"
            "shard_count was given: every process will read ALL the data.")
    elif cur_shard != env_rank or (env_size is not None
                                   and shard_count != env_size):
        warnings.warn(
            f"cur_shard={cur_shard}/shard_count={shard_count} disagrees with"
            f" the launcher (rank {env_rank}"
            f"{f' of {env_size}' if env_size else ''}); double-check your"
            " sharding arguments.")


def _wait_files_available(fs: pafs.FileSystem, paths: Sequence[str],
                          timeout_s: float = 30.0) -> None:
    """Poll until every path exists - object stores are eventually consistent
    (reference S3 wait, spark_dataset_converter.py:565-595)."""
    deadline = time.monotonic() + timeout_s
    missing = list(paths)
    while missing:
        infos = fs.get_file_info(missing)
        missing = [i.path for i in infos if i.type == pafs.FileType.NotFound]
        if not missing:
            return
        if time.monotonic() > deadline:
            raise PetastormTpuError(
                f"Timed out after {timeout_s}s waiting for {len(missing)}"
                f" dataset files (e.g. {missing[0]!r}) to become visible")
        time.sleep(0.25)


def _advise_on_file_sizes(fs: pafs.FileSystem, paths: Sequence[str]) -> None:
    sizes = [i.size for i in fs.get_file_info(list(paths))
             if i.type == pafs.FileType.File]
    if sizes and float(np.median(sizes)) < _MIN_ADVISED_FILE_SIZE_BYTES:
        logger.warning(
            "The median converted file size is %.1f MB (< %d MB). Small files"
            " hurt IO throughput; consider converting more data at once or"
            " raising row_group_size_mb.",
            float(np.median(sizes)) / 2**20,
            _MIN_ADVISED_FILE_SIZE_BYTES // 2**20)


class _TfDatasetContextManager:
    """Owns the reader backing a tf.data.Dataset; stops it on exit."""

    def __init__(self, reader, make_dataset):
        self._reader = reader
        self.dataset = make_dataset(reader)

    def __enter__(self):
        return self.dataset

    def __exit__(self, *exc):
        self._reader.stop()
        self._reader.join()


class DatasetConverter:
    """Handle on a materialized (cached) dataset + loader factories.

    Reference: SparkDatasetConverter (spark_dataset_converter.py:166-278).
    """

    def __init__(self, cache_url: str, file_urls: List[str], dataset_size: int,
                 schema: Schema, _owns_cache: bool = True,
                 storage_options: Optional[dict] = None):
        self.cache_url = cache_url
        self.file_urls = list(file_urls)
        self.dataset_size = dataset_size
        self.schema = schema
        self.storage_options = storage_options
        self._owns_cache = _owns_cache
        self._deleted = False

    def _reader(self, kwargs: Dict):
        kwargs.setdefault("storage_options", self.storage_options)
        return make_reader(self.cache_url, **kwargs)

    def __len__(self) -> int:
        return self.dataset_size

    # -- loader factories -----------------------------------------------------

    def make_reader(self, **kwargs):
        """A petastorm_tpu Reader over the cached dataset."""
        _check_shard_rank_env(kwargs.get("cur_shard"), kwargs.get("shard_count"))
        return self._reader(dict(kwargs))

    def make_jax_loader(self, batch_size: int, mesh=None, shardings=None,
                        reader_kwargs: Optional[Dict] = None, **loader_kwargs):
        """Context manager yielding mesh-sharded device batches
        (reference analog: make_tf_dataset, spark_dataset_converter.py:203-244)."""
        from petastorm_tpu.jax import JaxDataLoader

        reader_kwargs = dict(reader_kwargs or {})
        _check_shard_rank_env(reader_kwargs.get("cur_shard"),
                              reader_kwargs.get("shard_count"))
        reader = self._reader(reader_kwargs)
        try:
            return JaxDataLoader(reader, batch_size=batch_size, mesh=mesh,
                                 shardings=shardings, **loader_kwargs)
        except Exception:
            # otherwise the reader's executor threads/ventilator poll forever
            reader.stop()
            reader.join()
            raise

    def make_torch_dataloader(self, batch_size: int = 32,
                              shuffling_queue_capacity: int = 0,
                              reader_kwargs: Optional[Dict] = None,
                              **loader_kwargs):
        """Torch DataLoader over the cached dataset (reference
        make_torch_dataloader, spark_dataset_converter.py:246-278)."""
        from petastorm_tpu.pytorch import BatchedDataLoader

        reader_kwargs = dict(reader_kwargs or {})
        _check_shard_rank_env(reader_kwargs.get("cur_shard"),
                              reader_kwargs.get("shard_count"))
        reader = self._reader(reader_kwargs)
        try:
            return BatchedDataLoader(
                reader, batch_size=batch_size,
                shuffling_queue_capacity=shuffling_queue_capacity, **loader_kwargs)
        except Exception:
            reader.stop()
            reader.join()
            raise

    def make_tf_dataset(self, reader_kwargs: Optional[Dict] = None):
        """Context manager yielding a ``tf.data.Dataset`` over the cached
        dataset; the backing reader is stopped on exit (reference
        TFDatasetContextManager, spark_dataset_converter.py:311-338)."""
        from petastorm_tpu.tf import make_petastorm_dataset  # gated on tf import

        reader_kwargs = dict(reader_kwargs or {})
        _check_shard_rank_env(reader_kwargs.get("cur_shard"),
                              reader_kwargs.get("shard_count"))
        reader = self._reader(reader_kwargs)
        try:
            return _TfDatasetContextManager(reader, make_petastorm_dataset)
        except Exception:
            reader.stop()
            reader.join()
            raise

    # -- lifecycle ------------------------------------------------------------

    def delete(self) -> None:
        """Remove the cached dataset files (reference converter.delete)."""
        if self._deleted or not self._owns_cache:
            self._deleted = True
            return
        fs, root = get_filesystem_and_path(self.cache_url, self.storage_options)
        try:
            fs.delete_dir(root)
        except FileNotFoundError:
            pass
        self._deleted = True
        if self in _registered_converters:
            _registered_converters.remove(self)
        if _converters_by_url.get(self.cache_url) is self:
            del _converters_by_url[self.cache_url]


def make_converter(data,
                   cache_dir_url: Optional[str] = None,
                   *,
                   dtype: Optional[str] = "float32",
                   compression_codec: Optional[str] = None,
                   row_group_size_mb: float = DEFAULT_ROW_GROUP_SIZE_MB,
                   delete_at_exit: bool = True,
                   storage_options: Optional[dict] = None) -> DatasetConverter:
    """Materialize in-memory data to cached parquet, return loader factories.

    Repeated calls with identical content+params reuse the cached dataset
    (content-fingerprint dedup; the reference dedupes by Spark query plan,
    spark_dataset_converter.py:448-484).
    """
    cache_dir_url = cache_dir_url or os.environ.get(CACHE_DIR_ENV_VAR)
    if not cache_dir_url:
        raise PetastormTpuError(
            "No cache directory: pass cache_dir_url= or set"
            f" ${CACHE_DIR_ENV_VAR} (reference analog:"
            " petastorm.spark.converter.parentCacheDirUrl)")
    cache_dir_url = normalize_dir_url(cache_dir_url)

    if _is_spark_dataframe(data):
        return _make_spark_converter(data, cache_dir_url, dtype=dtype,
                                     compression_codec=compression_codec,
                                     row_group_size_mb=row_group_size_mb,
                                     delete_at_exit=delete_at_exit,
                                     storage_options=storage_options)

    table = _to_arrow_table(data, dtype)
    # "snappy" is what the write below actually uses when codec is None; the
    # params dict must record the same value or an explicit codec='snappy'
    # call would materialize a second byte-identical cache entry
    compression_codec = compression_codec or "snappy"
    params = {"codec": compression_codec, "rg_mb": row_group_size_mb, "v": 2}
    tag = _fingerprint(table, params)
    ds_url = posixpath.join(cache_dir_url, f"converted-{tag}")

    fs, root = get_filesystem_and_path(ds_url, storage_options)
    schema = Schema.from_arrow_schema(table.schema, name=f"Converted_{tag[:8]}")

    live = _share_live_handle(ds_url, delete_at_exit)
    if live is not None:
        return live

    existing = fs.get_file_info(root)
    if existing.type == pafs.FileType.Directory:
        # another process already materialized this content
        files = [i.path for i in fs.get_file_info(pafs.FileSelector(root))
                 if i.type == pafs.FileType.File
                 and i.path.endswith(".parquet")]
        if files:
            logger.info("Reusing cached converted dataset %s", ds_url)
            conv = DatasetConverter(ds_url, files, table.num_rows, schema,
                                    _owns_cache=delete_at_exit,
                                    storage_options=storage_options)
            _register_converter(conv, delete_at_exit)
            return conv
        _move_debris_aside(fs, root, ds_url)

    # write to a temp dir then rename: concurrent converters of the same
    # content race benignly (one rename wins, both see a complete dataset)
    _, cache_root = get_filesystem_and_path(cache_dir_url, storage_options)
    tmp_root = posixpath.join(cache_root, f".tmp-{tag}-{uuid.uuid4().hex[:8]}")
    fs.create_dir(tmp_root, recursive=True)
    rows_per_group = max(
        1, int(row_group_size_mb * 2**20
               / max(table.nbytes / max(table.num_rows, 1), 1)))
    data_path = posixpath.join(tmp_root, "part-00000.parquet")
    from petastorm_tpu.schema import SCHEMA_METADATA_KEY

    stamped = table.replace_schema_metadata(
        {SCHEMA_METADATA_KEY: schema.to_json().encode()})
    pq.write_table(stamped, data_path, filesystem=fs,
                   row_group_size=rows_per_group,
                   compression=compression_codec)
    _publish_dir(fs, tmp_root, root)
    stamp_dataset_metadata(ds_url, schema, storage_options=storage_options)
    files = [i.path for i in fs.get_file_info(pafs.FileSelector(root))
             if i.type == pafs.FileType.File and i.path.endswith(".parquet")]
    _wait_files_available(fs, files)
    _advise_on_file_sizes(fs, files)
    conv = DatasetConverter(ds_url, files, table.num_rows, schema,
                            _owns_cache=delete_at_exit,
                            storage_options=storage_options)
    _register_converter(conv, delete_at_exit)
    return conv
