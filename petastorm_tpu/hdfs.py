"""HDFS namenode high availability: nameservice resolution + failover client.

Reference parity: petastorm/hdfs/namenode.py (313 LoC) - ``HdfsNamenodeResolver``
parses hdfs-site.xml/core-site.xml for nameservices (hdfs/namenode.py:31-120),
``HAHdfsClient`` retries filesystem calls against up to 2 namenodes with
reconnect-on-failure (hdfs/namenode.py:146-241), and ``HdfsConnector`` owns the
round-robin connect logic (hdfs/namenode.py:244-313).

Design differences: the reference subclasses the long-deprecated
``pyarrow.hdfs.HadoopFileSystem`` python class and decorates every public method.
Modern pyarrow filesystems are C++ objects that cannot be subclassed that way, so
the HA client here is a :class:`pyarrow.fs.FileSystemHandler` wrapped in
``pyarrow.fs.PyFileSystem`` - a *real* ``pyarrow.fs.FileSystem`` accepted by every
parquet/dataset API in this package, whose every call funnels through one failover
gate.  Configuration parsing prefers ``HADOOP_CONF_DIR`` (the modern convention)
before the ``HADOOP_HOME``-style install roots the reference checks.
"""

from __future__ import annotations

import logging
import os
import xml.etree.ElementTree as ET
from typing import Dict, List, Optional, Tuple
from urllib.parse import urlparse

import pyarrow.fs as pafs

logger = logging.getLogger(__name__)

#: HDFS HA supports at most 2 namenodes per nameservice (same bound as the
#: reference, hdfs/namenode.py:248).
MAX_NAMENODES = 2
#: Re-connect/retry budget per filesystem call (reference hdfs/namenode.py:152).
MAX_FAILOVER_ATTEMPTS = 2


class HdfsConnectError(IOError):
    """No namenode in the list accepted a connection."""


class MaxFailoversExceeded(RuntimeError):
    """A filesystem call kept failing across reconnect attempts."""

    def __init__(self, failed_exceptions, max_failover_attempts, func_name):
        self.failed_exceptions = failed_exceptions
        self.max_failover_attempts = max_failover_attempts
        self.func_name = func_name
        super().__init__(
            f"Failover attempts exceeded maximum ({max_failover_attempts}) for"
            f" action {func_name!r}. Exceptions:\n{failed_exceptions}")


def _load_site_xml(xml_path: str, into: Dict[str, str]) -> None:
    try:
        for prop in ET.parse(xml_path).getroot().iter("property"):
            name, value = prop.find("name"), prop.find("value")
            if name is not None and value is not None and name.text:
                into[name.text] = value.text or ""
    except ET.ParseError as exc:
        logger.error("Unparseable hadoop site XML %s: %s", xml_path, exc)
    except OSError:
        pass  # absent file: fine, sites are optional


_CONFIG_CACHE: Dict[str, Dict[str, str]] = {}


def load_hadoop_configuration(conf_dir: Optional[str] = None) -> Dict[str, str]:
    """Flat dict of hadoop properties from ``{conf_dir}/{hdfs,core}-site.xml``.

    When ``conf_dir`` is None, checks ``HADOOP_CONF_DIR`` first, then the
    ``etc/hadoop`` of ``HADOOP_HOME``/``HADOOP_PREFIX``/``HADOOP_INSTALL``
    (reference env order at hdfs/namenode.py:44-57).  Parsed configs are cached
    per directory - URL resolution runs this on every ``hdfs://`` dataset open
    and in every worker process.
    """
    if conf_dir is None:
        if "HADOOP_CONF_DIR" in os.environ:
            conf_dir = os.environ["HADOOP_CONF_DIR"]
        else:
            for env in ("HADOOP_HOME", "HADOOP_PREFIX", "HADOOP_INSTALL"):
                if env in os.environ:
                    conf_dir = os.path.join(os.environ[env], "etc", "hadoop")
                    break
    if conf_dir is None:
        # a valid setup: pyarrow's libhdfs reads the cluster config itself, so
        # URL resolution falls through to it (debug, not warning - this runs on
        # every hdfs:// open)
        logger.debug(
            "No HADOOP_CONF_DIR/HADOOP_HOME set; python-level namenode HA"
            " resolution disabled")
        return {}
    cached = _CONFIG_CACHE.get(conf_dir)
    if cached is None:
        cached = {}
        _load_site_xml(os.path.join(conf_dir, "hdfs-site.xml"), cached)
        _load_site_xml(os.path.join(conf_dir, "core-site.xml"), cached)
        _CONFIG_CACHE[conf_dir] = cached
    return dict(cached)


class HdfsNamenodeResolver:
    """Resolves HDFS namenodes for a logical nameservice from hadoop config.

    Reference: hdfs/namenode.py:31-129.
    """

    def __init__(self, hadoop_configuration: Optional[Dict[str, str]] = None):
        if hadoop_configuration is None:
            hadoop_configuration = load_hadoop_configuration()
        self._config = hadoop_configuration

    def resolve_hdfs_name_service(self, nameservice: str) -> Optional[List[str]]:
        """``['host1:8020', 'host2:8020']`` for a configured nameservice, else
        None (the authority may simply be a plain hostname - reference
        hdfs/namenode.py:108-110)."""
        namenodes = self._config.get("dfs.ha.namenodes." + nameservice)
        if not namenodes:
            return None
        out = []
        for nn in namenodes.split(","):
            key = f"dfs.namenode.rpc-address.{nameservice}.{nn.strip()}"
            addr = self._config.get(key)
            if not addr:
                raise RuntimeError(
                    f"Failed to get property {key!r} from the hadoop"
                    " configuration; check your hdfs-site.xml")
            out.append(addr)
        return out

    def resolve_default_hdfs_service(self) -> Tuple[str, List[str]]:
        """(nameservice, namenode list) from ``fs.defaultFS``."""
        default_fs = self._config.get("fs.defaultFS")
        if not default_fs:
            raise RuntimeError(
                "Failed to get property 'fs.defaultFS' from the hadoop"
                " configuration; check your core-site.xml")
        nameservice = urlparse(default_fs).netloc
        namenodes = self.resolve_hdfs_name_service(nameservice)
        if namenodes is None:
            raise IOError(
                f"Unable to get namenodes for default service {default_fs!r}"
                " from the hadoop configuration")
        return nameservice, namenodes


class HdfsConnector:
    """Owns the actual connect call; swap/mock point for tests (reference
    hdfs/namenode.py:244-262)."""

    @classmethod
    def connect_namenode(cls, host: str, port: int, user: Optional[str] = None):
        return pafs.HadoopFileSystem(host=host, port=port, user=user)

    @classmethod
    def try_next_namenode(cls, index_of_nn: int, namenodes: List[str],
                          user: Optional[str] = None) -> Tuple[int, object]:
        """Round-robin connect starting AFTER ``index_of_nn`` so a retry lands
        on a different namenode (reference hdfs/namenode.py:288-313)."""
        n = len(namenodes)
        if n:
            for i in range(1, MAX_NAMENODES + 1):
                idx = (index_of_nn + i) % n
                authority = namenodes[idx]
                parsed = urlparse("hdfs://" + authority)
                try:
                    return idx, cls.connect_namenode(
                        parsed.hostname or "default", parsed.port or 8020, user)
                except OSError as exc:
                    # expected when this namenode is the standby
                    logger.debug("Namenode %s refused connection: %s",
                                 authority, exc)
        raise HdfsConnectError(
            f"Unable to connect to HDFS cluster (namenodes: {namenodes})")


class _HaFilesystemHandler(pafs.FileSystemHandler):
    """``pyarrow.fs.FileSystemHandler`` delegating every filesystem operation to
    the currently connected namenode, reconnecting to the next one and retrying
    on IO errors, up to MAX_FAILOVER_ATTEMPTS reconnects per call."""

    def __init__(self, connector_cls, namenodes: List[str], user: Optional[str]):
        self._connector_cls = connector_cls
        self._namenodes = list(namenodes)
        self._user = user
        self._index_of_nn = -1
        self._fs = None
        self._do_connect()

    def _do_connect(self) -> None:
        self._index_of_nn, self._fs = self._connector_cls.try_next_namenode(
            self._index_of_nn, self._namenodes, self._user)

    def __reduce__(self):
        # worker processes reconnect on unpickle rather than shipping a live
        # connection (reference: HAHdfsClient.__reduce__, hdfs/namenode.py:232-235)
        return self.__class__, (self._connector_cls, self._namenodes, self._user)

    #: OSError subclasses that describe the FILE, not the connection - the
    #: answer will not change on another namenode; re-raise untouched so
    #: callers' `except FileNotFoundError` etc. still match
    _NON_TRANSIENT = (FileNotFoundError, FileExistsError, PermissionError,
                      IsADirectoryError, NotADirectoryError)

    def _call(self, method: str, *args, **kwargs):
        failures = []
        while len(failures) <= MAX_FAILOVER_ATTEMPTS:
            try:
                return getattr(self._fs, method)(*args, **kwargs)
            except self._NON_TRANSIENT:
                raise
            except OSError as exc:
                failures.append(exc)
                if len(failures) <= MAX_FAILOVER_ATTEMPTS:
                    self._do_connect()
        raise MaxFailoversExceeded(failures, MAX_FAILOVER_ATTEMPTS, method)

    # -- FileSystemHandler interface ------------------------------------------

    def get_type_name(self):
        return "ha-hdfs"

    def normalize_path(self, path):
        return self._call("normalize_path", path)

    def get_file_info(self, paths):
        return self._call("get_file_info", paths)

    def get_file_info_selector(self, selector):
        return self._call("get_file_info", selector)

    def create_dir(self, path, recursive):
        self._call("create_dir", path, recursive=recursive)

    def delete_dir(self, path):
        self._call("delete_dir", path)

    def delete_dir_contents(self, path, missing_dir_ok=False):
        self._call("delete_dir_contents", path, missing_dir_ok=missing_dir_ok)

    def delete_root_dir_contents(self):
        self._call("delete_dir_contents", "/", accept_root_dir=True)

    def delete_file(self, path):
        self._call("delete_file", path)

    def move(self, src, dest):
        self._call("move", src, dest)

    def copy_file(self, src, dest):
        self._call("copy_file", src, dest)

    def open_input_stream(self, path):
        return self._call("open_input_stream", path)

    def open_input_file(self, path):
        return self._call("open_input_file", path)

    def open_output_stream(self, path, metadata):
        return self._call("open_output_stream", path, metadata=metadata)

    def open_append_stream(self, path, metadata):
        return self._call("open_append_stream", path, metadata=metadata)

    def __eq__(self, other):
        return (isinstance(other, _HaFilesystemHandler)
                and self._namenodes == other._namenodes
                and self._user == other._user)

    def __ne__(self, other):
        return not self.__eq__(other)


def connect_to_either_namenode(namenodes: List[str], user: Optional[str] = None,
                               connector_cls=None):
    """HA ``pyarrow.fs.FileSystem`` over the given namenode list.

    Reference: HdfsConnector.connect_to_either_namenode (hdfs/namenode.py:264-281).
    """
    if connector_cls is None:
        connector_cls = HdfsConnector  # late-bound so tests can swap it
    if not namenodes or len(namenodes) > MAX_NAMENODES:
        raise ValueError(
            f"Must supply 1..{MAX_NAMENODES} namenode URLs, got {namenodes!r}")
    return pafs.PyFileSystem(_HaFilesystemHandler(connector_cls, namenodes, user))


def resolve_url_namenodes(url: str,
                          hadoop_configuration: Optional[Dict[str, str]] = None,
                          ) -> Optional[List[str]]:
    """Namenode list for an ``hdfs://`` URL's authority, or None when the URL
    names no configured HA nameservice (plain host, or no hadoop config) - the
    single resolution rule shared by :func:`resolve_and_connect` and
    ``fs.get_filesystem_and_path`` so their behavior cannot drift.
    """
    parsed = urlparse(url)
    resolver = HdfsNamenodeResolver(hadoop_configuration)
    if parsed.netloc:
        return resolver.resolve_hdfs_name_service(parsed.netloc)
    try:
        return resolver.resolve_default_hdfs_service()[1]
    except (RuntimeError, IOError):
        return None  # no usable fs.defaultFS HA config


def resolve_and_connect(url: str, user: Optional[str] = None,
                        hadoop_configuration: Optional[Dict[str, str]] = None,
                        connector_cls=None):
    """``hdfs://nameservice/path`` or ``hdfs:///path`` -> (HA filesystem, path).

    Authorities that are configured HA nameservices connect through the
    failover client; a plain ``host[:port]`` authority connects directly
    (still through the reconnect gate, with a one-element namenode list).
    """
    parsed = urlparse(url)
    namenodes = resolve_url_namenodes(url, hadoop_configuration)
    if namenodes is None:
        if not parsed.netloc:
            raise RuntimeError(
                f"Cannot resolve {url!r}: no authority in the URL and no"
                " fs.defaultFS HA configuration available")
        namenodes = [parsed.netloc]
    fs = connect_to_either_namenode(namenodes, user=user,
                                    connector_cls=connector_cls)
    return fs, parsed.path
