"""Ulysses-style sequence parallelism: all-to-all head/sequence resharding.

The second of the two standard context-parallel attention strategies
(SURVEY.md's long-context mandate: "ring attention or all-to-all
sequence/context parallelism"; PAPERS.md: DeepSpeed-Ulysses).  Where ring
attention keeps the sequence sharded and rotates K/V blocks P-1 times,
Ulysses pays exactly TWO collectives: an ``all_to_all`` that re-shards from
sequence-sharded (every device holds S/P of all H heads) to head-sharded
(every device holds ALL of the sequence for H/P heads), then plain full
attention locally, then the inverse ``all_to_all``.

Trade-off (why both exist):

* Ulysses moves ``3 * S/P * H * D`` in one shot and computes dense local
  attention - fewer, bigger collectives, but requires ``H % P == 0`` and each
  device materializes full-S activations for its heads (memory ~ S).
* Ring never materializes full S anywhere (memory ~ S/P) and has no head
  divisibility constraint, but runs P-1 neighbor exchanges.

Both consume the SAME loader delivery: sequence-sharded batches
(``shardings={"tokens": P("data", "seq")}``) - which is the point of hosting
them here: they validate the CP feed contract end-to-end.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from petastorm_tpu.ops._compat import shard_map as _shard_map


def _full_attention(q, k, v, scale, causal):
    """Dense softmax attention, (B, H, S, D) all-local."""
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        s = q.shape[2]
        mask = jnp.tril(jnp.ones((s, s), dtype=bool))
        scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def ulysses_attention_sharded(q, k, v, axis_name: str, causal: bool = False,
                              scale: Optional[float] = None):
    """Call INSIDE ``shard_map``: q/k/v are local sequence slices
    (B, H, S_local, D) with the head count divisible by the axis size.

    Collective #1: q/k/v stacked into ONE ``all_to_all``
    (3, B, H, S/P, D) -> (3, B, H/P, S, D)  [heads scatter, sequence
    gathers]; local dense attention (float32 accumulation, matching
    ring_attention's numerics); collective #2 inverts for the output.
    """
    p = jax.lax.psum(1, axis_name)
    b, h, s_local, d = q.shape
    if h % p:
        raise ValueError(
            f"Ulysses needs heads ({h}) divisible by the '{axis_name}' axis"
            f" size ({p}); use ring_attention for indivisible head counts")
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    out_dtype = q.dtype

    qkv = jnp.stack([q, k, v])  # one collective for all three
    qkv = jax.lax.all_to_all(qkv, axis_name, split_axis=2, concat_axis=3,
                             tiled=True)  # (3, B, H/P, S, D)
    q, k, v = (x.astype(jnp.float32) for x in qkv)
    o = _full_attention(q, k, v, scale, causal)  # (B, H/P, S, D) f32
    o = jax.lax.all_to_all(o, axis_name, split_axis=2, concat_axis=1,
                           tiled=True)           # (B, H, S/P, D)
    return o.astype(out_dtype)


@functools.partial(jax.jit, static_argnames=("mesh", "seq_axis", "batch_axes",
                                             "causal", "scale"))
def ulysses_attention(q, k, v, mesh: Mesh, seq_axis: str = "seq",
                      batch_axes: tuple = ("data",), causal: bool = False,
                      scale: Optional[float] = None):
    """Mesh-level entry point, same contract as ``ops.ring_attention``:
    q/k/v are global (B, H, S, D) arrays with the sequence dim sharded over
    ``seq_axis`` (the loader's ``P("data", "seq")`` delivery), batch over
    ``batch_axes``; heads must be divisible by the ``seq_axis`` size."""
    spec = P(batch_axes, None, seq_axis, None)
    inner = functools.partial(ulysses_attention_sharded, axis_name=seq_axis,
                              causal=causal, scale=scale)
    fn = _shard_map(inner, mesh=mesh, in_specs=(spec, spec, spec),
                    out_specs=spec)
    sharding = NamedSharding(mesh, spec)
    q, k, v = (jax.lax.with_sharding_constraint(x, sharding) for x in (q, k, v))
    return fn(q, k, v)
