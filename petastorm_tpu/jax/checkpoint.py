"""Loader checkpoint/resume, integrated with orbax training checkpoints.

Reference gap being filled (SURVEY.md section 5): petastorm has NO
checkpoint/resume - epochs restart from scratch (reader.py:423-447) and
iterator state is lost.  Here the reader already exposes a deterministic
work-item cursor (``Reader.state_dict``, seeded plans); this module pairs that
cursor with the model/optimizer state inside ONE orbax checkpoint so training
jobs resume both compute and data position together.

Semantics inherited from the reader cursor (petastorm_tpu/reader.py docstring):
the cursor counts *completed* work items, which can run ahead of what the
loader delivered by the in-flight window (executor queues + loader prefetch +
shuffling buffer + the HBM device shuffle buffer, whose ``capacity`` batches
count toward the window in full) - including across a delivered-epoch
boundary when ``num_epochs > 1`` (the reader prefetches into the next epoch).
The cursor is strictly exact only when the reader is fully exhausted (a
completed ``num_epochs=1`` run); everywhere else resume skips at most the
in-flight window.  To bound that window tightly, use
``shuffling_queue_capacity=0``, ``device_shuffle_capacity=0``, ``prefetch=1``
and a small results queue.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)

_LOADER_KEY = "petastorm_tpu_loader"
_STATE_KEY = "state"


def make_checkpoint_manager(directory: str, max_to_keep: Optional[int] = 3,
                            **options_kwargs):
    """An ``orbax.checkpoint.CheckpointManager`` configured for composite
    (train-state + loader-state) checkpoints."""
    import os

    import orbax.checkpoint as ocp

    # orbax requires absolute paths but only errors later, mid-save (possibly
    # async, after real training time); normalize up front instead
    if "://" not in str(directory):
        directory = os.path.abspath(directory)
    options = ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                           **options_kwargs)
    return ocp.CheckpointManager(directory, options=options)


def save_checkpoint(manager, step: int, train_state: Any,
                    loader_or_state) -> bool:
    """Save ``train_state`` (pytree) + the loader's data cursor at ``step``.

    ``loader_or_state``: a JaxDataLoader / Reader (its ``state_dict()`` is
    taken) or an already-extracted state dict.
    """
    import orbax.checkpoint as ocp

    state = (loader_or_state if isinstance(loader_or_state, dict)
             else loader_or_state.state_dict())
    return manager.save(step, args=ocp.args.Composite(**{
        _STATE_KEY: ocp.args.StandardSave(train_state),
        _LOADER_KEY: ocp.args.JsonSave(state),
    }))


def restore_checkpoint(manager, train_state_template: Any,
                       step: Optional[int] = None):
    """Restore ``(train_state, loader_state)`` from ``step`` (default latest).

    Feed ``loader_state`` back via ``resume_reader_kwargs`` (or pass
    ``resume_from=loader_state['reader']`` to make_reader/make_jax_loader).
    """
    import orbax.checkpoint as ocp

    step = step if step is not None else manager.latest_step()
    if step is None:
        raise ValueError("No checkpoint found to restore")
    restored = manager.restore(step, args=ocp.args.Composite(**{
        _STATE_KEY: ocp.args.StandardRestore(train_state_template),
        _LOADER_KEY: ocp.args.JsonRestore(),
    }))
    return restored[_STATE_KEY], restored[_LOADER_KEY]


def resume_reader_kwargs(loader_state: Dict) -> Dict:
    """kwargs for make_reader/make_batch_reader/make_jax_loader that resume
    iteration at the checkpointed cursor.  The caller must pass the SAME
    dataset/shard/shuffle-seed/num-epochs configuration as the original run
    (the cursor indexes into that deterministic plan).

    The FULL reader state is passed through: ``items_per_epoch`` feeds the
    settings-changed safety check, and ``elastic_rebased`` (present on
    cursors from elastically-resumed readers) carries the coordinate
    translation - stripping either would disable a refusal path.
    """
    reader_state = loader_state.get("reader", loader_state)
    return {"resume_from": dict(reader_state)}
