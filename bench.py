"""Throughput benchmark - prints ONE JSON line for the driver.

Config mirrors the reference's only published numbers (BASELINE.md): the
hello_world dataset read rate via ``petastorm-throughput.py`` defaults - thread
pool, 3 workers, 200 warmup / 1000 measured samples over the HelloWorldSchema
(id int32, 128x256x3 PNG image, variable 4-D uint8 array; 10 rows,
/root/reference/examples/hello_world/petastorm_dataset/generate_petastorm_dataset.py:29-41,
/root/reference/petastorm/benchmark/throughput.py:39).  Baseline: 709.84
samples/sec (docs/benchmarks_tutorial.rst:20-21, hardware unspecified).

Ours is measured on the same row-oriented make_reader path (the slowest,
apples-to-apples path - the columnar/jax path is far faster).
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_SAMPLES_PER_SEC = 709.84
WARMUP, MEASURE = 200, 1000
CYCLES = 5  # median-of-cycles: one 1000-sample window is ~0.3s and noisy


def build_hello_world(url: str) -> None:
    import numpy as np

    from petastorm_tpu.codecs import CompressedImageCodec, NdarrayCodec, ScalarCodec
    from petastorm_tpu.etl.writer import write_dataset
    from petastorm_tpu.schema import Field, Schema

    schema = Schema("HelloWorld", [
        Field("id", np.int32, (), ScalarCodec()),
        Field("image1", np.uint8, (128, 256, 3), CompressedImageCodec("png")),
        Field("array_4d", np.uint8, (None, 128, 30, None), NdarrayCodec()),
    ])
    rng = np.random.default_rng(1234)
    rows = [{"id": i,
             "image1": rng.integers(0, 255, (128, 256, 3), dtype=np.uint8),
             "array_4d": rng.integers(0, 255, (4, 128, 30, 3), dtype=np.uint8)}
            for i in range(10)]
    write_dataset(url, schema, rows, row_group_size_mb=256)


def main() -> None:
    from petastorm_tpu.reader import make_reader

    tmp = tempfile.mkdtemp(prefix="petastorm_tpu_bench_")
    url = os.path.join(tmp, "hello_world")
    build_hello_world(url)

    with make_reader(url, reader_pool_type="thread", workers_count=3,
                     num_epochs=None) as reader:
        it = iter(reader)
        for _ in range(WARMUP):
            next(it)
        rates = []
        for _ in range(CYCLES):
            t0 = time.perf_counter()
            for _ in range(MEASURE):
                next(it)
            rates.append(MEASURE / (time.perf_counter() - t0))

    rates.sort()
    value = rates[len(rates) // 2]
    print(json.dumps({
        "metric": "hello_world_samples_per_sec",
        "value": round(value, 2),
        "unit": "samples/sec",
        "vs_baseline": round(value / BASELINE_SAMPLES_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
