"""Dataset ETL: metadata, writers, rowgroup indexing.

Reference parity: petastorm/etl/ (~1,000 LoC) - dataset_metadata.py (schema +
rowgroup-count stamping, load_row_groups), rowgroup_indexing.py, rowgroup_indexers.py.
Spark-free: all ETL here is pyarrow-native; Spark interop lives in petastorm_tpu/spark.
"""

from petastorm_tpu.etl.indexing import (FieldNotNullIndexer, RowGroupIndexer,
                                        SingleFieldIndexer, build_rowgroup_index,
                                        get_row_group_indexes)
from petastorm_tpu.etl.metadata import (DatasetInfo, RowGroupRef, infer_or_load_schema,
                                        load_row_groups, open_dataset)
from petastorm_tpu.etl.writer import materialize_dataset, write_dataset

__all__ = [
    "DatasetInfo", "RowGroupRef", "open_dataset", "load_row_groups",
    "infer_or_load_schema", "materialize_dataset", "write_dataset",
    "RowGroupIndexer", "SingleFieldIndexer", "FieldNotNullIndexer",
    "build_rowgroup_index", "get_row_group_indexes",
]
