"""Multi-host coordinated writes (parallel.distributed_write_dataset).

Reference analog: materialize_dataset's Spark-coordinated write + post-write
metadata stamp (petastorm/etl/dataset_metadata.py:53-133).  Multi-host is
simulated in-process with a threading.Barrier coordinator, the same way shard
reading is simulated with several Readers (SURVEY.md section 4).
"""

import threading

import numpy as np
import pytest

from petastorm_tpu.codecs import NdarrayCodec
from petastorm_tpu.parallel import distributed_write_dataset
from petastorm_tpu.reader import make_reader
from petastorm_tpu.schema import Field, Schema

HOSTS = 4


def _schema():
    return Schema("DistWrite", [
        Field("id", np.int64),
        Field("vec", np.float32, (3,), NdarrayCodec()),
    ])


def _rows(n=64):
    return [{"id": i, "vec": np.full(3, i, dtype=np.float32)} for i in range(n)]


def test_distributed_write_and_readback(tmp_path):
    url = str(tmp_path / "ds")
    schema, rows = _schema(), _rows()
    barrier = threading.Barrier(HOSTS, timeout=30)
    results, errors = {}, []

    def host(idx):
        try:
            results[idx] = distributed_write_dataset(
                url, schema, rows[idx::HOSTS],
                process_index=idx, process_count=HOSTS,
                sync_fn=lambda tag: barrier.wait(),
                row_group_size_rows=8)
        except BaseException as exc:  # noqa: BLE001
            errors.append((idx, exc))

    threads = [threading.Thread(target=host, args=(i,)) for i in range(HOSTS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    # each host wrote its own part files, all distinct
    all_files = [f for fs in results.values() for f in fs]
    assert len(all_files) == len(set(all_files)) == HOSTS
    for idx, files in results.items():
        assert all(f"part-{idx:05d}" in f for f in files)
    # the stamped dataset reads back complete and correct
    with make_reader(url, shuffle_row_groups=False, num_epochs=1) as r:
        got = sorted((int(row.id), float(row.vec[0])) for row in r)
    assert got == [(i, float(i)) for i in range(64)]


def test_distributed_write_guards():
    schema = _schema()
    with pytest.raises(ValueError, match="out of range"):
        distributed_write_dataset("file:///tmp/x", schema, [],
                                  process_index=4, process_count=4,
                                  sync_fn=lambda t: None)
    with pytest.raises(ValueError, match="owned by"):
        distributed_write_dataset("file:///tmp/x", schema, [],
                                  process_index=0, process_count=1,
                                  sync_fn=lambda t: None,
                                  file_prefix="custom")


def test_single_host_defaults_no_jax_distributed(tmp_path):
    """process_count=1: barrier is a no-op; behaves like write_dataset+stamp."""
    url = str(tmp_path / "ds")
    files = distributed_write_dataset(url, _schema(), _rows(8),
                                      process_index=0, process_count=1,
                                      sync_fn=lambda t: None)
    assert len(files) == 1
    with make_reader(url, num_epochs=1) as r:
        assert len(list(r)) == 8


def _run_hosts(target, n=HOSTS):
    barrier = threading.Barrier(n, timeout=30)
    errors = {}

    def host(idx):
        try:
            target(idx, lambda tag: barrier.wait())
        except BaseException as exc:  # noqa: BLE001
            errors[idx] = exc

    threads = [threading.Thread(target=host, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in threads), "deadlocked host thread"
    return errors


def test_rerun_mode_error_fails_everywhere_without_duplicates(tmp_path):
    from petastorm_tpu.errors import PetastormTpuError

    url = str(tmp_path / "ds")
    schema, rows = _schema(), _rows(16)

    def write(idx, sync):
        distributed_write_dataset(url, schema, rows[idx::HOSTS],
                                  process_index=idx, process_count=HOSTS,
                                  sync_fn=sync)

    assert _run_hosts(write) == {}
    # crashed-job rerun protection: default mode='error' rejects on ALL hosts
    errors = _run_hosts(write)
    assert sorted(errors) == list(range(HOSTS))
    assert all(isinstance(e, PetastormTpuError) for e in errors.values())
    with make_reader(url, num_epochs=1) as r:
        assert len(list(r)) == 16  # original data intact, no duplicates
    # the refused rerun must not leave failure-marker debris in the healthy
    # dataset (host 0 removes its preflight marker after peers observe it)
    import os
    assert not any(f.startswith("_distributed_write_failed")
                   for f in os.listdir(url))

    # explicit overwrite replaces cleanly
    def rewrite(idx, sync):
        distributed_write_dataset(url, schema, rows[idx::HOSTS],
                                  process_index=idx, process_count=HOSTS,
                                  sync_fn=sync, mode="overwrite")

    assert _run_hosts(rewrite) == {}
    with make_reader(url, num_epochs=1) as r:
        assert len(list(r)) == 16


def test_one_host_write_failure_fails_all_hosts(tmp_path):
    """A failed host drops a marker; host 0 refuses to stamp; every host
    raises instead of deadlocking or stamping a short dataset."""
    from petastorm_tpu.errors import PetastormTpuError

    url = str(tmp_path / "ds")
    schema, rows = _schema(), _rows(16)

    def write(idx, sync):
        local = rows[idx::HOSTS]
        if idx == 2:  # poison one host's rows: encode fails mid-write
            local = local + [{"id": "not-an-int", "vec": None}]
        distributed_write_dataset(url, schema, local,
                                  process_index=idx, process_count=HOSTS,
                                  sync_fn=sync)

    errors = _run_hosts(write)
    assert sorted(errors) == list(range(HOSTS))  # everyone raised
    assert any("not stamped" in str(e) or "metadata was not stamped" in str(e)
               for i, e in errors.items() if i != 2)
    # the dataset was never stamped (host 0 refused) and the failed host's
    # marker is on disk for post-mortem
    import os

    assert not os.path.exists(os.path.join(url, "_common_metadata"))
    assert os.path.exists(
        os.path.join(url, "_distributed_write_failed.2"))


def test_distributed_write_stamps_merged_geometry_contract(tmp_path):
    """Each host sees only its own rows' image shapes; the stamped dataset
    must carry the UNION (the dataset-level geometry contract the
    'device-mixed' decode bounds its compiles by)."""
    cv2 = pytest.importorskip("cv2")  # noqa: F841 - jpeg encode in the codec
    from petastorm_tpu.codecs import CompressedImageCodec
    from petastorm_tpu.reader import make_batch_reader

    schema = Schema("DistWriteGeo", [
        Field("id", np.int64),
        Field("image", np.uint8, (None, None, 3),
              CompressedImageCodec("jpeg", quality=90)),
    ])
    rng = np.random.default_rng(7)
    # host i writes ONLY geometry i - no single host sees the full set
    geoms = [(16, 24), (24, 16), (32, 16), (16, 32)]
    rows = [{"id": i,
             "image": rng.integers(0, 255, geoms[i % HOSTS] + (3,),
                                   dtype=np.uint8)}
            for i in range(32)]
    url = str(tmp_path / "ds")
    barrier = threading.Barrier(HOSTS, timeout=30)
    errors = []

    def host(idx):
        try:
            distributed_write_dataset(
                url, schema, rows[idx::HOSTS],
                process_index=idx, process_count=HOSTS,
                sync_fn=lambda tag: barrier.wait(),
                row_group_size_rows=4)
        except BaseException as exc:  # noqa: BLE001
            errors.append((idx, exc))

    threads = [threading.Thread(target=host, args=(i,)) for i in range(HOSTS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors

    with make_batch_reader(url, num_epochs=1) as r:
        declared = r.declared_geometries
    assert sorted(declared["image"]) == sorted(g + (3,) for g in geoms)
    # sidecars were cleaned up after the merge
    import os
    assert not [f for f in os.listdir(url) if "geometries" in f]
