"""Operator CLIs (L7): dataset copy, metadata regeneration.

Reference parity: petastorm/tools/ and the console scripts in setup.py:90-96.
"""
