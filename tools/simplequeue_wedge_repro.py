#!/usr/bin/env python
"""Standalone reproduction of the CPython SimpleQueue timed-get wedge.

This is the minimal form of the bug that froze a full test-suite run
(RESULTS.md round-5 post-mortem) and motivated moving ThreadedExecutor's
input queue from ``queue.SimpleQueue`` to ``queue.Queue``
(petastorm_tpu/pool.py).  Pure stdlib, no petastorm_tpu imports.

Mechanism (confirmed by disassembling the installed CPython 3.12.12
``_queue`` extension — see RESULTS.md for the control-flow walkthrough):

``SimpleQueue.get(timeout=t)`` waits by acquiring an internal lock that
``put`` releases.  When a waiter's blocking acquire SUCCEEDS (a put
landed late in its window) but a sibling consumer — already executing
inside ``get()`` on the GIL — pops the item before the winner reacquires
the GIL, the winner loops, finds the queue empty, and recomputes its
remaining timeout as ``deadline - now`` WITHOUT clamping at zero.  Once
the deadline expired during the GIL-reacquisition gap, that remainder is
negative, and ``PyThread_acquire_lock_timed`` treats a negative timeout
as INFINITE.  The "timed" get then blocks until the next ``put`` — or
forever, if no put ever comes (exactly the epoch-end/teardown state of a
worker pool, which is why the bug presents as a terminal hang).

Hit-rate levers (why this script fires in minutes while naive hammers
run clean): tiny get timeouts make "a put lands inside the waiter's
window, near its deadline" near-certain per put; several churning
consumers supply the in-``get()`` thief; producer silences remove the
rescuing put so the wedge becomes observable.

Exit 3 = wedge observed (a consumer stuck in get(timeout=1ms) for >3 s).
Typical time-to-wedge on a 1-core host: 1-10 minutes.
"""
import queue
import random
import sys
import threading
import time

N_CONSUMERS = 8
GET_TIMEOUT_S = 0.001
STUCK_THRESHOLD_S = 3.0

q = queue.SimpleQueue()
stop = threading.Event()
stuck_since = [None] * N_CONSUMERS


def consumer(i):
    while not stop.is_set():
        stuck_since[i] = time.monotonic()
        try:
            q.get(timeout=GET_TIMEOUT_S)
        except queue.Empty:
            pass
        stuck_since[i] = None


def producer():
    rnd = random.Random(7)
    while not stop.is_set():
        q.put(1)
        time.sleep(rnd.uniform(0.0005, 0.002))
        if rnd.random() < 0.02:
            time.sleep(4.0)  # silence: a wedged getter has no rescuer


def main():
    budget = float(sys.argv[1]) if len(sys.argv) > 1 else 1800
    threads = [threading.Thread(target=consumer, args=(i,), daemon=True)
               for i in range(N_CONSUMERS)]
    threads.append(threading.Thread(target=producer, daemon=True))
    for t in threads:
        t.start()
    t0 = time.time()
    while time.time() - t0 < budget:
        time.sleep(1)
        now = time.monotonic()
        held = [(i, round(now - s, 2)) for i, s in enumerate(stuck_since)
                if s and now - s > STUCK_THRESHOLD_S]
        if held:
            print(f"WEDGED: SimpleQueue.get(timeout={GET_TIMEOUT_S}) stuck"
                  f" for {held} (elapsed {time.time() - t0:.0f}s)",
                  flush=True)
            sys.exit(3)
    print(f"no wedge in {budget:.0f}s (probabilistic - rerun or raise the"
          " budget)", flush=True)
    stop.set()


if __name__ == "__main__":
    main()
