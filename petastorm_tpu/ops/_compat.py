"""jax version compatibility shims shared by the ops modules."""

from __future__ import annotations

import jax

try:  # jax >= 0.6 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # 0.4.x keeps it under experimental
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(*args, **kwargs):
        # the 0.4.x replication checker mis-types lax.cond branches (its own
        # error names check_rep=False as the workaround; the top-level API's
        # varying-manual-axes tracking fixed this class of false positive)
        kwargs.setdefault("check_rep", False)
        return _exp_shard_map(*args, **kwargs)
