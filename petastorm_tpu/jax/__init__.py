"""JAX delivery layer: readers -> device-sharded ``jax.Array`` batches.

This is the BASELINE.json north star ("a petastorm.jax.DataLoader alongside
petastorm.pytorch and tf_utils"): ColumnBatches from the reader land on TPU as
global ``jax.Array``s with a caller-chosen ``NamedSharding``, with host-side
shuffle/batch/pad and a device-transfer prefetch queue in between.
"""

from petastorm_tpu.jax.checkpoint import (make_checkpoint_manager,
                                          restore_checkpoint,
                                          resume_reader_kwargs,
                                          save_checkpoint)
from petastorm_tpu.jax.loader import JaxDataLoader, make_jax_loader

__all__ = ["JaxDataLoader", "make_jax_loader", "make_checkpoint_manager",
           "save_checkpoint", "restore_checkpoint", "resume_reader_kwargs"]
