"""User transforms applied on reader workers.

Reference parity: petastorm/transform.py - TransformSpec(func, edit_fields,
removed_fields, selected_fields) (transform.py:27-57) and ``transform_schema``
deriving the post-transform schema (transform.py:60-89).

Difference: the transform here is **columnar** - ``func`` receives a dict of numpy
column arrays (one entry per field, batch-major) and returns the same, matching the
batch path the reference applies via pandas (arrow_reader_worker.py:190-222).  A
``row_transform`` convenience wraps a per-row function for row-path readers.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from petastorm_tpu.errors import SchemaError
from petastorm_tpu.schema import Field, Schema

#: edit_fields entries: (name, numpy_dtype, shape, nullable)
EditFieldT = Tuple[str, "np.dtype", Tuple[Optional[int], ...], bool]


class TransformSpec:
    """Worker-side columnar transform: ``func(columns) -> columns`` plus the
    schema edits it implies (``edit_fields`` added/retyped, ``removed_fields``
    dropped, ``selected_fields`` kept) - the reader's output schema reflects
    the edits before any data flows (reference transform_spec semantics)."""
    def __init__(self,
                 func: Optional[Callable[[Dict[str, np.ndarray]], Dict[str, np.ndarray]]] = None,
                 edit_fields: Optional[Sequence[EditFieldT]] = None,
                 removed_fields: Optional[Sequence[str]] = None,
                 selected_fields: Optional[Sequence[str]] = None):
        self.func = func
        self.edit_fields = list(edit_fields or [])
        self.removed_fields = list(removed_fields or [])
        self.selected_fields = list(selected_fields) if selected_fields is not None else None

    def __call__(self, columns: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        out = self.func(columns) if self.func is not None else dict(columns)
        for name in self.removed_fields:
            out.pop(name, None)
        if self.selected_fields is not None:
            out = {k: out[k] for k in self.selected_fields}
        return out


def _hash_code_object(code, update) -> None:
    """Feed a code object's CONTENT (bytecode, names, stable const tokens,
    nested code objects recursively) into ``update``.  repr() of a code
    object embeds its memory address and repr() of a set is
    hash-randomization-ordered - both would make the digest differ between
    interpreters, silently defeating cross-process cache sharing."""
    import types

    update(code.co_code)
    update(repr(code.co_names).encode())
    update(repr(code.co_varnames).encode())
    for const in code.co_consts:
        if isinstance(const, types.CodeType):
            _hash_code_object(const, update)
        elif isinstance(const, frozenset):
            update(("frozenset:"
                    + ",".join(sorted(map(repr, const)))).encode())
        else:
            update(repr(const).encode())


def transform_signature(spec: Optional["TransformSpec"]) -> str:
    """Short content signature of a transform, for shared-cache keys.

    Two readers sharing the host-wide warm tier must never trade entries
    across DIFFERENT transforms (docs/operations.md "Warm cache"), so the
    cache key carries this digest.  The function half hashes the compiled
    bytecode + constants (recursively through nested code objects, so the
    digest is stable ACROSS interpreters - editing the function body changes
    the key, restarting the process does not) and degrades to the qualified
    name; the schema-edit half hashes the declared field edits.  Best-effort
    by design: a closure over changed external state is not detectable -
    documented operator caveat.
    """
    if spec is None:
        return "-"
    import hashlib

    digest = hashlib.md5()
    func = getattr(spec, "func", None)
    if func is not None:
        # plain function, or a callable object's __call__ (its configuring
        # instance state falls under the documented closure caveat)
        code = getattr(func, "__code__", None) or getattr(
            getattr(func, "__call__", None), "__code__", None)
        if code is not None:
            _hash_code_object(code, digest.update)
        digest.update((f"{getattr(func, '__module__', '')}."
                       f"{getattr(func, '__qualname__', '')}."
                       f"{type(func).__qualname__}").encode())
    digest.update(repr(getattr(spec, "edit_fields", None)).encode())
    digest.update(repr(getattr(spec, "removed_fields", None)).encode())
    digest.update(repr(getattr(spec, "selected_fields", None)).encode())
    return digest.hexdigest()[:12]


def transform_schema(schema: Schema, spec: TransformSpec) -> Schema:
    """Derive the post-transform schema (reference: transform.py:60-89)."""
    fields = list(schema)
    by_name = {f.name: i for i, f in enumerate(fields)}
    for name, dtype, shape, nullable in spec.edit_fields:
        new = Field(name, np.dtype(dtype), tuple(shape), nullable=nullable)
        if name in by_name:
            fields[by_name[name]] = new
        else:
            by_name[name] = len(fields)
            fields.append(new)
    fields = [f for f in fields if f.name not in set(spec.removed_fields)]
    if spec.selected_fields is not None:
        missing = set(spec.selected_fields) - {f.name for f in fields}
        if missing:
            raise SchemaError(f"selected_fields {sorted(missing)} not in post-transform schema")
        order = {n: i for i, n in enumerate(spec.selected_fields)}
        fields = sorted((f for f in fields if f.name in order), key=lambda f: order[f.name])
    return Schema(schema.name, fields)


def row_transform(fn: Callable[[Dict[str, object]], Dict[str, object]]):
    """Adapt a per-row dict->dict function to the columnar transform contract."""
    def columnar(columns: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        names = list(columns)
        n = len(columns[names[0]]) if names else 0
        rows = [fn({k: columns[k][i] for k in names}) for i in range(n)]
        if not rows:
            return columns
        out: Dict[str, np.ndarray] = {}
        for k in rows[0]:
            vals = [r[k] for r in rows]
            first = np.asarray(vals[0])
            if first.ndim > 0 and all(np.asarray(v).shape == first.shape for v in vals):
                out[k] = np.stack([np.asarray(v) for v in vals])
            else:
                col = np.empty(len(vals), dtype=object)
                for i, v in enumerate(vals):
                    col[i] = v
                out[k] = col if first.ndim > 0 else np.asarray(vals)
        return out
    return columnar
