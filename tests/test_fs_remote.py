"""End-to-end reader over NON-local filesystems (VERDICT round-1 weak #6).

Two schemes drive the fsspec fallback branch (fs.py:85-93) the way a real
object store would, without network:

* ``memory://`` - fsspec's in-process store: full write -> stamp -> read ->
  jax feed loop, plus multi-URL expansion.  Process pools cannot see another
  process's memory store, so these use thread/serial pools (the documented
  contract for non-re-derivable filesystems, fs.py:124-127).
* ``dir::file`` (fsspec DirFileSystem over a local dir, resolved from
  ``storage_options``) - re-derivable in a CHILD process, proving
  FilesystemFactory pickles into spawn workers and re-resolves there
  (reference: the serializable filesystem_factory, fs_utils.py:42-196).
"""

import numpy as np
import pytest

from petastorm_tpu.codecs import NdarrayCodec
from petastorm_tpu.etl.writer import write_dataset
from petastorm_tpu.fs import get_filesystem_and_path
from petastorm_tpu.reader import make_batch_reader, make_reader
from petastorm_tpu.schema import Field, Schema

fsspec = pytest.importorskip("fsspec")

ROWS = 32


def _schema():
    return Schema("Remote", [
        Field("id", np.int64),
        Field("vec", np.float32, (4,), NdarrayCodec()),
    ])


def _rows(n=ROWS, base=0):
    return [{"id": base + i, "vec": np.full(4, base + i, np.float32)}
            for i in range(n)]


@pytest.fixture()
def memfs():
    fs = fsspec.filesystem("memory")
    yield fs
    # the memory store is a process-global singleton: isolate tests
    fs.store.clear()


def test_memory_scheme_write_stamp_read(memfs):
    url = "memory://ds_a"
    write_dataset(url, _schema(), _rows(), row_group_size_rows=8)
    # resolution went through the fsspec fallback, not pyarrow-native
    fs, path = get_filesystem_and_path(url)
    import pyarrow.fs as pafs

    assert isinstance(fs, pafs.PyFileSystem)
    with make_reader(url, reader_pool_type="thread", workers_count=2,
                     num_epochs=1, shuffle_row_groups=False) as r:
        rows = list(r)
    # thread pools deliver in completion order: compare as a set, check pairs
    assert sorted(row.id for row in rows) == list(range(ROWS))
    by_id = {int(row.id): row.vec for row in rows}
    np.testing.assert_array_equal(by_id[5], np.full(4, 5, np.float32))


def test_memory_scheme_jax_feed(memfs):
    import jax

    from petastorm_tpu.jax import JaxDataLoader

    url = "memory://ds_feed"
    write_dataset(url, _schema(), _rows(), row_group_size_rows=8)
    with make_batch_reader(url, reader_pool_type="thread", num_epochs=1,
                           shuffle_row_groups=False) as r:
        with JaxDataLoader(r, batch_size=8) as loader:
            batches = list(loader)
    assert len(batches) == 4
    got = np.concatenate([np.asarray(b["id"]) for b in batches])
    assert sorted(got.tolist()) == list(range(ROWS))
    assert isinstance(batches[0]["vec"], jax.Array)


def test_memory_scheme_multi_url_expansion(memfs):
    """A list of dataset file URLs over a remote scheme reads as one dataset
    (reference get_filesystem_and_path_or_paths, fs_utils.py:199-228)."""
    url_a, url_b = "memory://multi/ds_a", "memory://multi/ds_b"
    files_a = write_dataset(url_a, _schema(), _rows(16, base=0),
                            row_group_size_rows=8)
    files_b = write_dataset(url_b, _schema(), _rows(16, base=16),
                            row_group_size_rows=8)
    urls = [f"memory://{p}" for p in files_a + files_b]
    with make_reader(urls, reader_pool_type="serial", num_epochs=1,
                     shuffle_row_groups=False) as r:
        rows = list(r)
    assert sorted(row.id for row in rows) == list(range(32))


def test_memory_scheme_mixed_authority_rejected(memfs):
    from petastorm_tpu.errors import PetastormTpuError

    with pytest.raises(PetastormTpuError, match="share scheme"):
        make_reader(["memory://x/a.parquet", "other://x/b.parquet"])


def test_dir_scheme_process_pool_factory_pickling(tmp_path):
    """The fsspec-fallback filesystem re-resolves from (url, storage_options)
    inside a SPAWNED worker process - the full FilesystemFactory contract."""
    backing = tmp_path / "backing"
    backing.mkdir()
    url = "dir://ds"
    opts = {"path": str(backing), "target_protocol": "file"}
    write_dataset(url, _schema(), _rows(), row_group_size_rows=8,
                  storage_options=opts)
    assert (backing / "ds" / "_common_metadata").exists()  # really remote-backed
    with make_reader(url, reader_pool_type="process", workers_count=2,
                     num_epochs=1, shuffle_row_groups=False,
                     storage_options=opts) as r:
        rows = list(r)
    assert sorted(row.id for row in rows) == list(range(ROWS))
