#!/usr/bin/env python
"""Compare two benchmark result files and gate CI on the delta.

Inputs: any two of

* a driver-captured ``BENCH_rNN.json`` (``{"tail": "<bench.py stdout>", ...}``)
* raw ``python bench.py`` stdout saved to a file (one JSON line per metric)
* a bare JSON object ``{"metric_name": value, ...}``

Metric lines recognized inside a tail/stdout::

    {"metric": "<name>", "value": <float>, ...}
    {"metric": "bench_summary", "metrics": {"<name>": [<value>, <vs_b>], ...}}

Usage::

    python tools/bench_compare.py BENCH_r04.json BENCH_r05.json
    python tools/bench_compare.py old.json new.json --fail-threshold 10
    python tools/bench_compare.py old.json new.json --json

``--fail-threshold PCT`` arms the gate: exit 1 when any shared metric
regresses by more than PCT percent (direction-aware - ``*_pct`` metrics
matching the lower-is-better markers fail on increase, everything else on
decrease).  Without it the comparison is report-only and always exits 0, so
the same command serves both a human diff and a CI gate on the bench
trajectory (RESULTS.md notes this host's rates drift +-30% between sessions;
pick thresholds accordingly).

Warm-cache metrics (BENCH_r07+, docs/operations.md "Warm cache"): the
``warm_cache_*`` family gates like any rate, but note the two ratio-shaped
members are SAME-SESSION anchored and therefore drift-immune - treat a
regression in ``warm_cache_epoch2_vs_epoch1_ratio`` (warm epoch over cold
epoch; the ISSUE 7 target is vs_baseline >= 1.0 against its 3.0x bar) or in
``warm_cache_cross_reader_hit_rate`` (fraction of reader B's first-epoch
items served from the tier; 1.0 = fully warm) as a code regression even in
a session whose absolute rates drifted.

Service metrics (BENCH_r08+, docs/operations.md "Disaggregated ingest
service"): ``service_ingest_samples_per_sec`` is the remote fleet's
delivery rate (dispatcher + 2 worker subprocesses) and drifts with the
host like any absolute rate; ``service_inprocess_anchor_samples_per_sec``
is the same read through the in-process thread pool in the same session;
their quotient ``service_vs_inprocess_ratio`` is the SAME-SESSION-anchored,
drift-immune member - it prices the wire-transport tax, so a drop in the
RATIO means the service plane itself regressed even when both absolute
rates moved with the host.  History: r08 captured 0.36x on pickled frames;
the ISSUE 12 binary wire plane carries an ABSOLUTE floor of 0.7x for the
remote client, and the ``service_colocated_vs_inprocess_ratio`` member
(shm-armed co-located fleet, emitted only where the arena plane is live -
python >= 3.12) carries 0.9x.

Determinism metrics (BENCH_r09+, docs/operations.md "Reproducibility"):
``determinism_vs_off_ratio`` prices the ``deterministic='seed'`` reorder
stage against completion-order delivery, same-session anchored.  It also
carries an ABSOLUTE floor (see ``ABSOLUTE_FLOORS``): any candidate below
0.85x fails an armed gate even if the baseline file was already below it -
the ISSUE 10 acceptance bar is absolute, not relative.

Sequence metrics (BENCH_r10+, docs/operations.md "Token pipelines"):
``sequence_packed_vs_padded_ratio`` prices packed ``(batch, seq_len)``
delivery against the naive pad-to-max baseline under a fixed simulated
step per block - SAME-SESSION anchored (drift-immune), absolute floor
1.5x.  ``sequence_packing_fill_rate`` (real tokens / emitted slots) is a
pure property of the packer + corpus shape and carries the 0.85 absolute
floor from the ISSUE 11 acceptance bar; the two absolute-rate members
(``sequence_packed_tokens_per_sec`` / ``..._padded_anchor_...``) drift
with the host like any rate.

Transform-cache / planner metrics (BENCH_r13+, docs/operations.md
"Transform caching & the pipeline planner"): ``transform_warm_vs_cold_ratio``
prices a warm epoch of a transform-DOMINATED pipeline with post-transform
output caching armed against its own cold epoch (same session, fresh tier
per round - drift-immune); its ``vs_baseline`` compares against the 13.5x
decode-only warm ratio of BENCH_r07, and the 3.0 absolute floor catches
output caching silently disarming.  ``transform_warm_vs_decode_only_warm_
ratio`` (floor 1.2) isolates what caching the transform's OUTPUT adds over
caching only the decode.  ``planner_cold_start_ratio`` (floor 1.2) is
explore-from-bad-knobs time-to-90%-of-steady over flight-profile-seeded
time-to-90% - the planner's cold-start win; ``planner_time_to_90pct_seconds``
is the seeded arm's absolute t90 (lower is better via the ``time_to``
marker).

Tracing metrics (ISSUE 19, docs/operations.md "Distributed tracing &
fleet view"): ``service_trace_armed_vs_untraced_ratio`` prices arming
per-item distributed tracing (``trace_items=8`` - 1-in-8 wire items carry
a trace context and collect per-hop monotonic stamps at dispatcher and
workers, merged client-side into spans + ``service.hop.*`` histograms)
against the identical untraced fleet read, interleaved in the same
session (drift-immune).  Absolute floor 0.98 = the <= 2% overhead
acceptance bar: tracing is meant to be cheap enough to leave sampled-on
in production, so a candidate below the floor fails an armed gate even
against a baseline that was already below it.  The two absolute-rate
members (``service_trace_armed_samples_per_sec`` /
``service_untraced_anchor_samples_per_sec``) drift with the host.

Autoscale metrics (BENCH_r12+, docs/operations.md "Fleet autoscaling &
QoS"): ``autoscale_vs_static_ratio`` prices the closed loop - an
undersized 1-worker fleet plus a live ``AutoscaleSupervisor`` over a
fleet statically sized right from the start, same session (drift-immune),
INCLUDING the loop's detect->spawn->register reaction window.  Absolute
floor 0.8x (the ISSUE 14 acceptance bar); the two absolute-rate members
(``autoscale_fleet_samples_per_sec`` /
``autoscale_static_anchor_samples_per_sec``) drift with the host.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

#: substrings marking a metric where SMALLER is better (idle/stall
#: percentages, latency ratios, time-to-threshold seconds); everything else
#: is treated as a rate
LOWER_IS_BETTER_MARKERS = ("idle_pct", "stall_pct", "latency",
                           "latent_vs_local", "time_to")

#: metric -> minimum acceptable value: an armed gate fails a candidate
#: BELOW the floor regardless of the baseline (absolute acceptance bars,
#: immune to a baseline that was itself captured in a bad session)
ABSOLUTE_FLOORS = {
    # ISSUE 10: deterministic-mode throughput >= 0.85x completion-order
    "determinism_vs_off_ratio": 0.85,
    # ISSUE 11: packed delivery >= 1.5x the pad-to-max baseline, and the
    # packer must fill >= 85% of emitted (batch, seq_len) slots
    "sequence_packed_vs_padded_ratio": 1.5,
    "sequence_packing_fill_rate": 0.85,
    # ISSUE 12: the binary wire plane must hold a remote service client at
    # >= 0.7x in-process (vs 0.35x on the old pickled frames), and an
    # shm-armed co-located client at >= 0.9x (metric emitted only on
    # runtimes where the arena plane is live, python >= 3.12)
    "service_vs_inprocess_ratio": 0.7,
    "service_colocated_vs_inprocess_ratio": 0.9,
    # ISSUE 14: a 1-worker fleet + the live autoscale supervisor must land
    # within 0.8x of a statically right-sized fleet on the same read -
    # the closed loop's detect->spawn->register latency is what's priced
    "autoscale_vs_static_ratio": 0.8,
    # ISSUE 15: a transform-dominated warm epoch with post-transform caching
    # must run >= 3x its cold epoch (the headline target is beating the
    # decode-only 13.5x - gated via vs_baseline in the note - but the
    # absolute floor catches output caching silently disarming), and output
    # caching must beat decode-only caching on the same warm epoch by 1.2x
    "transform_warm_vs_cold_ratio": 3.0,
    "transform_warm_vs_decode_only_warm_ratio": 1.2,
    # ISSUE 15: a flight-profile-seeded cold start must reach 90% of
    # steady-state delivery at least 1.2x sooner than the runtime loop
    # climbing from bad static knobs
    "planner_cold_start_ratio": 1.2,
    # ISSUE 19: arming per-item distributed tracing (trace_items=8) must
    # cost <= 2% of untraced fleet throughput in the same session
    "service_trace_armed_vs_untraced_ratio": 0.98,
}


def lower_is_better(name: str) -> bool:
    """True when a decrease in ``name`` is an improvement."""
    return any(m in name for m in LOWER_IS_BETTER_MARKERS)


def load_metrics(path: str, with_flags: bool = False):
    """Extract ``{metric: value}`` from a bench artifact (see module doc).

    ``with_flags=True`` returns ``(metrics, weather_flagged)`` where the
    second element is the set of metric names the capture stamped
    ``"weather": "degraded"`` (per-line, or via the summary line's
    ``weather_degraded`` list) - device-path numbers taken while the
    tunnel/runtime weather probe said the session was degraded.  The gate
    SKIPS those (a degraded session measures the weather, not the code)."""
    with open(path) as f:
        text = f.read()
    lines = text.splitlines()
    flagged = set()
    try:
        obj = json.loads(text)
    except ValueError:
        obj = None
    if isinstance(obj, dict):
        if "tail" in obj:            # driver-captured BENCH_rNN.json
            lines = str(obj["tail"]).splitlines()
        elif "metric" not in obj:    # bare {name: value} map
            metrics = {str(k): float(v if not isinstance(v, (list, tuple))
                                     else v[0])
                       for k, v in obj.items()
                       if isinstance(v, (int, float, list, tuple))}
            return (metrics, flagged) if with_flags else metrics
    metrics: Dict[str, float] = {}
    for line in lines:
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            entry = json.loads(line)
        except ValueError:
            continue
        if not isinstance(entry, dict):
            continue
        if entry.get("metric") == "bench_summary":
            for name, value in entry.get("metrics", {}).items():
                if isinstance(value, (list, tuple)):
                    value = value[0]
                metrics[str(name)] = float(value)
            flagged.update(str(n) for n in entry.get("weather_degraded", []))
        elif "metric" in entry and isinstance(entry.get("value"),
                                              (int, float)):
            metrics[str(entry["metric"])] = float(entry["value"])
            if entry.get("weather") == "degraded":
                flagged.add(str(entry["metric"]))
    if not metrics:
        raise SystemExit(f"{path}: no bench metrics found (expected bench.py"
                         " JSON lines, a BENCH_rNN.json capture, or a bare"
                         " metric map)")
    return (metrics, flagged) if with_flags else metrics


def compare(old: Dict[str, float], new: Dict[str, float]) -> List[Dict]:
    """Per-metric rows: value pair, signed delta pct, and the direction-aware
    ``regression_pct`` (how much WORSE the new value is; <= 0 = no worse).

    A baseline metric MISSING from the candidate is the worst possible
    regression (the bench stopped measuring it - e.g. it crashed mid-run),
    so it carries ``regression_pct = inf`` and trips any armed gate; a NEW
    metric absent from the baseline is not a regression.  A zero baseline
    admits no percentage, but a direction-worse move off zero still gates
    (``inf``)."""
    rows = []
    for name in sorted(set(old) | set(new)):
        a, b = old.get(name), new.get(name)
        row: Dict = {"metric": name, "old": a, "new": b,
                     "lower_is_better": lower_is_better(name)}
        if a is not None and b is None:
            row["regression_pct"] = float("inf")
        elif a is not None and b is not None and a != 0:
            delta_pct = (b - a) / abs(a) * 100.0
            row["delta_pct"] = delta_pct
            row["regression_pct"] = (delta_pct if row["lower_is_better"]
                                     else -delta_pct)
        elif a == 0 and b is not None and b != a:
            row["regression_pct"] = (float("inf")
                                     if (b > a) == row["lower_is_better"]
                                     else 0.0)
        rows.append(row)
    return rows


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="bench_compare",
        description="Print per-metric deltas between two bench result files;"
                    " optionally fail on regression (CI gate)")
    parser.add_argument("old", help="baseline bench file")
    parser.add_argument("new", help="candidate bench file")
    parser.add_argument("--fail-threshold", type=float, default=None,
                        metavar="PCT",
                        help="exit 1 when any shared metric regresses by more"
                             " than PCT percent (unset = report-only)")
    parser.add_argument("--metrics", nargs="+", default=None,
                        help="only compare these metric names")
    parser.add_argument("--json", action="store_true",
                        help="print one JSON object instead of a table")
    args = parser.parse_args(argv)

    old, old_flags = load_metrics(args.old, with_flags=True)
    new, new_flags = load_metrics(args.new, with_flags=True)
    weather_flagged = old_flags | new_flags
    if args.metrics:
        old = {k: v for k, v in old.items() if k in args.metrics}
        new = {k: v for k, v in new.items() if k in args.metrics}
    rows = compare(old, new)
    for r in rows:
        if r["metric"] in weather_flagged:
            r["weather"] = "degraded"
    # weather-flagged metrics report but never gate: a capture taken while
    # the tunnel/runtime weather probe said "degraded" measures the weather,
    # not the code (VERDICT r5) - skipping beats a false regression alarm
    for r in rows:
        floor = ABSOLUTE_FLOORS.get(r["metric"])
        if floor is not None and r["new"] is not None and r["new"] < floor:
            r["below_floor"] = floor
    failures = [r for r in rows
                if args.fail_threshold is not None
                and r["metric"] not in weather_flagged
                and (r.get("regression_pct", 0.0) > args.fail_threshold
                     or "below_floor" in r)]
    skipped = [r for r in rows
               if args.fail_threshold is not None
               and r["metric"] in weather_flagged
               and r.get("regression_pct", 0.0) > args.fail_threshold]

    if args.json:
        print(json.dumps({"rows": rows,
                          "fail_threshold": args.fail_threshold,
                          "failures": [r["metric"] for r in failures],
                          "weather_skipped": [r["metric"] for r in skipped]}))
    else:
        width = max([len(r["metric"]) for r in rows] + [6])
        print(f"{'metric':<{width}} {'old':>14} {'new':>14} {'delta%':>8}")
        for r in rows:
            old_s = f"{r['old']:.2f}" if r["old"] is not None else "-"
            new_s = f"{r['new']:.2f}" if r["new"] is not None else "-"
            delta = r.get("delta_pct")
            delta_s = f"{delta:+7.1f}%" if delta is not None else "       -"
            note = " (lower is better)" if r["lower_is_better"] else ""
            if r.get("weather"):
                note += " [degraded weather - gate skipped]"
            if "below_floor" in r:
                note += f" [below absolute floor {r['below_floor']:g}]"
            flag = "  << REGRESSION" if r in failures else ""
            print(f"{r['metric']:<{width}} {old_s:>14} {new_s:>14}"
                  f" {delta_s}{note}{flag}")
        if args.fail_threshold is not None:
            print(f"gate: {len(failures)} metric(s) regressed more than"
                  f" {args.fail_threshold:g}%"
                  + (f": {', '.join(r['metric'] for r in failures)}"
                     if failures else "")
                  + (f"; {len(skipped)} weather-flagged metric(s) skipped:"
                     f" {', '.join(r['metric'] for r in skipped)}"
                     if skipped else ""))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
