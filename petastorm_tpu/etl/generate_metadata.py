"""``petastorm-tpu-generate-metadata``: (re)stamp dataset metadata.

Reference parity: petastorm/etl/petastorm_generate_metadata.py (161 LoC,
console script at setup.py:94) - regenerate ``_common_metadata`` (schema +
per-file rowgroup counts) for a dataset whose metadata is missing or stale,
e.g. after files were added/rewritten by an external engine.

The schema source is, in order: an explicit ``--schema-from`` dataset, the
schema JSON embedded in the data files themselves, or (with ``--infer``)
inference from the arrow schema (scalar columns only, like make_batch_reader).
"""

from __future__ import annotations

import argparse
import logging
import sys
from typing import List, Optional

logger = logging.getLogger(__name__)


def generate_metadata(dataset_url: str,
                      schema_from: Optional[str] = None,
                      infer: bool = False,
                      storage_options: Optional[dict] = None) -> None:
    from petastorm_tpu.etl.metadata import open_dataset
    from petastorm_tpu.etl.writer import stamp_dataset_metadata

    schema = None
    if schema_from is not None:
        from petastorm_tpu.etl.metadata import infer_or_load_schema
        schema = infer_or_load_schema(
            open_dataset(schema_from, storage_options=storage_options,
                         require_stored_schema=True))
    elif infer:
        from petastorm_tpu.etl.metadata import infer_or_load_schema
        schema = infer_or_load_schema(
            open_dataset(dataset_url, storage_options=storage_options,
                         require_stored_schema=False))
    # schema=None -> stamp_dataset_metadata reads the schema JSON from file KV
    stamp_dataset_metadata(dataset_url, schema=schema,
                           storage_options=storage_options)
    logger.info("Stamped metadata for %s", dataset_url)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-generate-metadata",
        description="Regenerate _common_metadata (schema + rowgroup counts)"
                    " for a dataset")
    parser.add_argument("dataset_url")
    parser.add_argument("--schema-from", default=None,
                        help="borrow the stored schema from another dataset URL")
    parser.add_argument("--infer", action="store_true",
                        help="infer the schema from the parquet arrow schema"
                             " when no stored schema exists")
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    logging.basicConfig(level=logging.INFO)
    args = build_parser().parse_args(argv)
    generate_metadata(args.dataset_url, schema_from=args.schema_from,
                      infer=args.infer)
    print(f"metadata stamped: {args.dataset_url}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
