"""Disaggregated ingest service (petastorm_tpu.service): the v2 binary
wire (control codec, batch frames, robustness against corrupt/legacy
frames), dispatcher assignment/requeue/buffer-relay, client executor,
multi-client e2e with the shared warm tier, and chaos on the service plane
(worker SIGKILL, client connection drop, dispatcher loss)."""

import os
import pickle
import signal
import socket
import struct
import subprocess
import sys
import threading
import time
import uuid

import numpy as np
import pytest

from petastorm_tpu.batch import ColumnBatch
from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.etl.writer import write_dataset
from petastorm_tpu.pool import VentilatedItem, WorkerError
from petastorm_tpu.reader import make_batch_reader
from petastorm_tpu.retry import RetryPolicy
from petastorm_tpu.schema import Field, Schema
from petastorm_tpu.service import wire
from petastorm_tpu.service.client import (ServiceConnectionError,
                                          ServiceExecutor)
from petastorm_tpu.service.dispatcher import Dispatcher
from petastorm_tpu.service.protocol import (FrameClosedError, FrameSocket,
                                            LegacyPickleFrameError,
                                            PayloadDecoder, WireItem,
                                            connect_frames, encode_result,
                                            parse_address,
                                            shm_transport_available)
from petastorm_tpu.service.wire import WireFormatError
from petastorm_tpu.service.worker import ServiceWorker
from petastorm_tpu.telemetry import Telemetry

FAST_RECONNECT = RetryPolicy(max_attempts=3, initial_backoff_s=0.05,
                             backoff_multiplier=1.5, max_backoff_s=0.3)


class EchoFactory:
    """Module-level (ServiceExecutor pickles factories to ship them)."""

    def __call__(self):
        return lambda item: ("echo", item.item,
                             getattr(item, "ordinal", None))


class PlainEchoFactory:
    def __call__(self):
        return lambda item: item.item


class SleepForeverFactory:
    def __call__(self):
        def fn(item):  # noqa: ARG001 - pretends to work forever
            time.sleep(3600)

        return fn


class HangFirstAttemptFactory:
    """Wedges attempt 0 of every item; requeued attempts complete - the
    shape the assignment-deadline liveness backstop recovers from."""

    def __call__(self):
        def fn(item):
            if getattr(item, "attempt", 0) == 0:
                time.sleep(3600)
            return ("recovered", item.ordinal)

        return fn


class UnpicklableResultFactory:
    """Returns a result pickle cannot serialize (a thread lock) - the
    worker must answer with a failure frame, not die silently."""

    def __call__(self):
        return lambda item: threading.Lock()


# -- fixtures -----------------------------------------------------------------

@pytest.fixture
def int_dataset(tmp_path):
    """200 int rows in 20 rowgroups."""
    url = str(tmp_path / "ds")
    schema = Schema("SvcInts", [Field("x", np.int64)])
    write_dataset(url, schema, [{"x": i} for i in range(200)],
                  row_group_size_rows=10)
    return url


@pytest.fixture
def fleet(int_dataset):
    """A dispatcher + two in-process workers, stopped at teardown."""
    disp = Dispatcher(telemetry=Telemetry(), heartbeat_timeout_s=5.0).start()
    addr = f"127.0.0.1:{disp.port}"
    workers = [ServiceWorker(addr, capacity=2, name=f"w{i}")
               for i in range(2)]
    threads = [threading.Thread(target=w.run, daemon=True) for w in workers]
    for t in threads:
        t.start()
    _wait_for(lambda: len(disp.stats()["workers"]) == 2)
    try:
        yield disp, addr, workers
    finally:
        for w in workers:
            w.stop()
        disp.stop()
        disp.join()


def _wait_for(cond, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def _read_all(url, addr, **kwargs):
    tele = kwargs.pop("telemetry", None) or Telemetry()
    with make_batch_reader(url, service_address=addr,
                           shuffle_row_groups=False, telemetry=tele,
                           **kwargs) as reader:
        rows = sorted(x for b in reader.iter_batches()
                      for x in b.columns["x"])
        diag = reader.diagnostics
    return rows, diag, tele


# -- protocol -----------------------------------------------------------------

def test_frame_roundtrip_and_eof():
    a, b = socket.socketpair()
    fa, fb = FrameSocket(a), FrameSocket(b)
    msgs = [{"t": "x", "n": 1}, {"t": "y", "blob": os.urandom(1 << 16)},
            {"t": "item", "item": WireItem.encode(
                VentilatedItem(3, "work", attempt=1))}]
    for m in msgs:
        fa.send(m)
    got = [fb.recv(timeout=2.0) for _ in msgs]
    assert got[0] == msgs[0]
    assert got[1]["blob"] == msgs[1]["blob"]
    item = WireItem.from_wire(got[2]["item"])
    assert item.ordinal == 3 and item.attempt == 1
    assert pickle.loads(item.blob) == "work"  # opaque blob: worker-side only
    assert fb.bytes_received == fa.bytes_sent
    # timeout (no data) -> None, partial state preserved
    assert fb.recv(timeout=0.05) is None
    # EOF -> FrameClosedError
    fa.close()
    with pytest.raises(FrameClosedError):
        fb.recv(timeout=2.0)
    fb.close()


def _ctrl_frame(msg) -> bytes:
    """A raw v2 CTRL frame as it appears on the socket."""
    payload = bytes([wire.KIND_CTRL]) + wire.dumps(msg)
    return struct.pack("!I", len(payload)) + payload


def test_frame_partial_delivery_survives_timeouts():
    a, b = socket.socketpair()
    fb = FrameSocket(b)
    payload = FrameSocket(a)
    framed = _ctrl_frame({"t": "big", "blob": b"z" * 100_000})
    # dribble the frame in two halves with a gap: the first recv times out
    # mid-frame, the second completes it from the kept buffer
    a.sendall(framed[:50])
    assert fb.recv(timeout=0.05) is None
    a.sendall(framed[50:])
    msg = fb.recv(timeout=2.0)
    assert msg["t"] == "big" and len(msg["blob"]) == 100_000
    payload.close()
    fb.close()


def test_send_timeout_declares_peer_dead():
    """A peer that stops draining its buffer must fail the send within the
    bound (and close the socket - a partial frame cannot be resumed), not
    block the sending thread forever."""
    a, b = socket.socketpair()
    fa = FrameSocket(a, send_timeout_s=0.2)
    blob = os.urandom(1 << 20)
    t0 = time.monotonic()
    with pytest.raises(OSError, match="drain|closed"):
        for _ in range(256):  # peer never reads: the buffer eventually fills
            fa.send({"t": "big", "blob": blob})
    assert time.monotonic() - t0 < 5.0
    # the timed-out socket is dead for good (stream would be desynced)
    with pytest.raises(OSError):
        fa.send({"t": "ping"})
    # ...and a read loop polling it must see the FrameClosedError it
    # already handles (reconnect path), not a ValueError from select on
    # the closed fd (which would crash a service worker's main loop)
    with pytest.raises(FrameClosedError):
        fa.recv(timeout=0.1)
    b.close()


def test_send_timeout_rearms_on_progress():
    """The send timeout bounds a drain STALL: a peer draining slowly but
    steadily must never be declared dead mid-frame."""
    a, b = socket.socketpair()
    fa = FrameSocket(a, send_timeout_s=0.3)
    stop = threading.Event()

    def slow_drain():
        while not stop.is_set():
            time.sleep(0.1)  # stalls shorter than the timeout, repeatedly
            try:
                if not b.recv(1 << 16):
                    return
            except OSError:
                return

    t = threading.Thread(target=slow_drain, daemon=True)
    t.start()
    try:
        # several times the socketpair buffer: completes only if progress
        # re-arms the deadline (total transfer time >> send_timeout_s)
        fa.send({"t": "big", "blob": os.urandom(1 << 20)})
    finally:
        stop.set()
        fa.close()
        b.close()
        t.join(timeout=5.0)


def test_recv_timeout_is_total_not_per_fill():
    """One recv deadline covers header AND body: a frame stuck mid-body
    must not double the caller's wait."""
    a, b = socket.socketpair()
    fb = FrameSocket(b)
    framed = _ctrl_frame({"t": "x"})
    a.sendall(framed[:5])  # length prefix + 1 body byte
    t0 = time.monotonic()
    assert fb.recv(timeout=0.3) is None
    assert time.monotonic() - t0 < 0.55
    a.close()
    fb.close()


def test_auth_token_gates_every_hello():
    """A dispatcher with a handshake secret refuses untokened/wrong-token
    workers, clients, and stats probes - and serves matching ones."""
    disp = Dispatcher(telemetry=Telemetry(), auth_token="s3cret").start()
    addr = f"127.0.0.1:{disp.port}"
    try:
        # untokened worker: registration refused (exit code 1, no state)
        assert ServiceWorker(addr, capacity=1, auth_token=None).run() == 1
        assert disp.stats()["workers"] == {}
        # wrong-token client: hello refused
        ex = ServiceExecutor(addr, telemetry=Telemetry(), auth_token="nope")
        with pytest.raises(OSError, match="refused"):
            ex.start(EchoFactory())
        # untokened stats probe: error frame, no snapshot
        probe = connect_frames(parse_address(addr))
        probe.send({"t": "stats?"})
        assert probe.recv(timeout=5.0)["t"] == "error"
        probe.close()
        assert disp.stats()["counters"].get(
            "service.auth_rejected", 0) >= 3
        # matching tokens: full roundtrip works
        worker = ServiceWorker(addr, capacity=2, auth_token="s3cret")
        wt = threading.Thread(target=worker.run, daemon=True)
        wt.start()
        _wait_for(lambda: len(disp.stats()["workers"]) == 1)
        ex = ServiceExecutor(addr, telemetry=Telemetry(), window=4,
                             auth_token="s3cret")
        ex.start(EchoFactory())
        ex.put(VentilatedItem(0, "payload"))
        assert ex.get(timeout=10.0) == ("echo", "payload", 0)
        ex.stop()
        ex.join()
        worker.stop()
    finally:
        disp.stop()
        disp.join()


def test_auth_token_env_var(monkeypatch):
    """$PETASTORM_TPU_SERVICE_TOKEN is the zero-plumbing path: every party
    resolves it by default."""
    from petastorm_tpu.service.protocol import resolve_auth_token

    monkeypatch.delenv("PETASTORM_TPU_SERVICE_TOKEN", raising=False)
    assert resolve_auth_token(None) is None
    assert resolve_auth_token("x") == "x"
    monkeypatch.setenv("PETASTORM_TPU_SERVICE_TOKEN", "tok")
    assert resolve_auth_token(None) == "tok"
    assert resolve_auth_token("explicit") == "explicit"
    disp = Dispatcher(telemetry=Telemetry()).start()
    addr = f"127.0.0.1:{disp.port}"
    try:
        worker = ServiceWorker(addr, capacity=1)  # token from env
        wt = threading.Thread(target=worker.run, daemon=True)
        wt.start()
        _wait_for(lambda: len(disp.stats()["workers"]) == 1)
        worker.stop()
        # a party that missed the env var is refused
        monkeypatch.delenv("PETASTORM_TPU_SERVICE_TOKEN")
        assert ServiceWorker(addr, capacity=1).run() == 1
    finally:
        disp.stop()
        disp.join()


def test_pick_worker_affinity_is_deterministic():
    """Rowgroup affinity must survive hash randomization and load churn:
    the same rowgroup maps to the same worker independent of the momentary
    free list, falling back only when the affine worker is saturated."""
    import zlib

    import types

    rg = types.SimpleNamespace(path="/data/part-0.parquet", row_group=7)
    work = types.SimpleNamespace(row_group=rg)
    disp = Dispatcher(telemetry=Telemetry())  # never started: pure routing
    a, b = socket.socketpair()
    conn = FrameSocket(a)
    from petastorm_tpu.service.dispatcher import _WorkerState
    workers = {n: _WorkerState(n, conn, 2, "h") for n in ("w1", "w2", "w3")}
    disp._workers = workers
    item = VentilatedItem(0, work)
    key = zlib.crc32(b"/data/part-0.parquet:7")
    expected = workers[sorted(workers)[key % 3]]
    free = list(workers.values())
    for _ in range(5):  # stable across repeated picks and free-list orders
        assert disp._pick_worker(item, free) is expected
        free = free[1:] + free[:1]
    # the wire plane's structural affinity key routes IDENTICALLY to the
    # in-process object path (the dispatcher never opens the item blob)
    wire_item = WireItem(0, 0, b"opaque", ["/data/part-0.parquet", 7])
    assert disp._pick_worker(wire_item, list(workers.values())) is expected
    # saturated affine worker -> least-loaded fallback, not a re-route of
    # the whole mapping
    others = [w for w in workers.values() if w is not expected]
    others[0].inflight.add(("c", 1))
    assert disp._pick_worker(
        item, others) is others[1]
    conn.close()
    b.close()


def test_parse_address():
    assert parse_address("host:123") == ("host", 123)
    assert parse_address(("h", 9)) == ("h", 9)
    assert parse_address(":123") == ("127.0.0.1", 123)
    with pytest.raises(PetastormTpuError):
        parse_address("no-port")


def _result_msg(header, parts):
    """Round one encoded result through a socketpair, as the client's
    receiver would see it (BATCH frame -> header dict + '_body')."""
    a, b = socket.socketpair()
    fa, fb = FrameSocket(a), FrameSocket(b)
    try:
        fa.send_batch(dict(header, t="result"), parts)
        return fb.recv(timeout=2.0)
    finally:
        fa.close()
        fb.close()


def test_payload_binary_roundtrip():
    """ColumnBatch results travel as schema'd binary frames - zero pickle -
    and decode to WRITABLE numpy views over the received buffer."""
    batch = ColumnBatch({"x": np.arange(5), "img": np.arange(30, dtype=np.uint8)
                         .reshape(5, 3, 2)}, 5, ordinal=7)
    header, parts = encode_result(batch, arena=None)
    assert header["pk"] == "bin"
    msg = _result_msg(header, parts)
    out = PayloadDecoder().decode(msg)
    np.testing.assert_array_equal(out.columns["x"], np.arange(5))
    np.testing.assert_array_equal(out.columns["img"], batch.columns["img"])
    assert out.ordinal == 7
    # consumers mutate batches in place (torch normalize etc.): the
    # zero-copy views must be writable or every batch pays a copy downstream
    assert out.columns["x"].flags.writeable
    out.columns["x"][0] = 99


def test_payload_object_columns_ride_inline_binary():
    """Object-dtype columns (strings/bytes/ragged arrays) stay on the
    binary plane via the control codec's inline path."""
    strs = np.empty(3, dtype=object)
    strs[:] = ["a", "bb", "ccc"]
    ragged = np.empty(3, dtype=object)
    ragged[:] = [np.arange(i + 1) for i in range(3)]
    batch = ColumnBatch({"s": strs, "r": ragged, "x": np.arange(3)}, 3)
    header, parts = encode_result(batch)
    assert header["pk"] == "bin"
    out = PayloadDecoder().decode(_result_msg(header, parts))
    assert list(out.columns["s"]) == ["a", "bb", "ccc"]
    np.testing.assert_array_equal(out.columns["r"][2], np.arange(3))


def test_payload_pickle_fallback_is_counted_and_gated():
    """Results outside the wire domain fall back to pickle (pk='pickle');
    a client refusing pickle gets a classified WireFormatError, never an
    unpickle."""
    header, parts = encode_result(("echo", "payload", 3))
    assert header["pk"] == "pickle"
    msg = _result_msg(header, parts)
    assert PayloadDecoder().decode(msg) == ("echo", "payload", 3)
    with pytest.raises(WireFormatError, match="refuses"):
        PayloadDecoder(allow_pickle=False).decode(msg)


def test_payload_compression_roundtrip():
    """A zlib-coded batch body decodes identically (end-to-end: the
    dispatcher never touches it)."""
    batch = ColumnBatch({"x": np.zeros((64, 128), dtype=np.uint8)}, 64)
    header, parts = encode_result(batch, codec="zlib")
    assert header["pk"] == "bin" and header["codec"] == "zlib"
    assert sum(len(p) for p in parts) < batch.columns["x"].nbytes  # it DID
    out = PayloadDecoder().decode(_result_msg(header, parts))
    np.testing.assert_array_equal(out.columns["x"], batch.columns["x"])
    # a corrupted compressed body is a classified failure, not a zlib crash
    msg = _result_msg(header, [b"\x00garbage"])
    with pytest.raises(WireFormatError, match="corrupt|bytes"):
        PayloadDecoder().decode(msg)


# -- wire robustness: corrupt/hostile frames ----------------------------------

def test_wire_control_codec_roundtrip():
    values = [None, True, False, 0, -(2 ** 62), 3.5, "héllo", b"\x00\xff",
              [1, [2, [3]]], {"a": {"b": [None, "x"]}},
              np.arange(6, dtype=np.float32).reshape(2, 3)]
    for v in values:
        out = wire.loads(wire.dumps(v))
        if isinstance(v, np.ndarray):
            np.testing.assert_array_equal(out, v)
        else:
            assert out == v, (v, out)
    with pytest.raises(WireFormatError, match="not wire-encodable"):
        wire.dumps(object())
    with pytest.raises(WireFormatError, match="64-bit"):
        wire.dumps(2 ** 70)


def test_wire_rejects_truncated_and_trailing():
    blob = wire.dumps({"k": [1, 2, 3]})
    with pytest.raises(WireFormatError, match="truncated"):
        wire.loads(blob[:-2])
    with pytest.raises(WireFormatError, match="trailing"):
        wire.loads(blob + b"\x00")
    # a list claiming 2^20+ items on 4 bytes of input: bounds, not OOM
    bomb = struct.pack("!BI", 0x07, (1 << 20) + 1)
    with pytest.raises(WireFormatError, match="claims"):
        wire.loads(bomb)
    # an object array claiming 2^29 elements in a 6-byte frame must be
    # bounded BEFORE allocation (np.empty of the pointer array alone
    # would be 4GB - the allocation-bomb shape of the same attack)
    obj_bomb = struct.pack("!BBI", 0x0A, 1, 1 << 29)
    with pytest.raises(WireFormatError, match="claims"):
        wire.loads(obj_bomb)
    # deep nesting is cut off, not a RecursionError
    deep = b"\x07\x00\x00\x00\x01" * 64 + wire.dumps(None)
    with pytest.raises(WireFormatError, match="nests deeper"):
        wire.loads(deep)


def test_frame_socket_rejects_unknown_and_legacy_kinds():
    """Unknown frame kinds and v1 pickled frames are refused as classified
    errors - the pickled frame is DETECTED (first byte), never loaded."""
    a, b = socket.socketpair()
    fb = FrameSocket(b)
    # unknown kind byte
    a.sendall(struct.pack("!I", 1) + b"\x7f")
    with pytest.raises(WireFormatError, match="unknown frame kind"):
        fb.recv(timeout=2.0)
    # a legacy pickled frame: the payload would RCE if anyone loaded it;
    # detection must classify it without executing anything
    evil = pickle.dumps({"t": "client_hello"})
    assert evil[0] == wire.PICKLE_PROTO_BYTE
    a.sendall(struct.pack("!I", len(evil)) + evil)
    with pytest.raises(LegacyPickleFrameError, match="v1 pickled"):
        fb.recv(timeout=2.0)
    # the stream itself stays synced: a good frame after the bad ones parses
    a.sendall(_ctrl_frame({"t": "ok"}))
    assert fb.recv(timeout=2.0) == {"t": "ok"}
    a.close()
    fb.close()


def test_batch_frame_spec_validation():
    """Every header/buffer disagreement is a classified WireFormatError:
    wrong lengths, out-of-bounds offsets, object dtypes, oversize column
    tables - never a numpy crash or an unpickle."""
    body = bytearray(np.arange(10, dtype=np.int64).tobytes())

    def decode(cols, rows=10, blen=None):
        header = {"pk": "bin", "rows": rows, "cols": cols,
                  "blen": len(body) if blen is None else blen, "codec": ""}
        return wire.decode_batch_body(header, memoryview(body))

    ok = decode({"x": ["raw", "<i8", [10], 0, 80]})
    np.testing.assert_array_equal(ok.columns["x"], np.arange(10))
    with pytest.raises(WireFormatError, match="needs"):
        decode({"x": ["raw", "<i8", [10], 0, 64]})  # nbytes vs dtype*shape
    with pytest.raises(WireFormatError, match="outside"):
        decode({"x": ["raw", "<i8", [10], 64, 80]})  # overruns the body
    with pytest.raises(WireFormatError, match="object dtypes"):
        decode({"x": ["raw", "|O", [10], 0, 80]})  # unpickle in disguise
    with pytest.raises(WireFormatError, match="bad wire dtype"):
        decode({"x": ["raw", "not-a-dtype", [10], 0, 80]})
    with pytest.raises(WireFormatError, match="rows"):
        decode({"x": ["raw", "<i8", [10], 0, 80]}, rows=7)  # len disagreement
    with pytest.raises(WireFormatError, match="body is"):
        decode({"x": ["raw", "<i8", [10], 0, 80]}, blen=79)
    with pytest.raises(WireFormatError, match="implausibly large"):
        decode({"x": ["raw", "<i8", [1 << 30, 1 << 30], 0, 80]})
    with pytest.raises(WireFormatError, match="oversize"):
        decode({f"c{i}": ["inline", None] for i in range(5000)})
    with pytest.raises(WireFormatError, match="unknown spec kind"):
        decode({"x": ["mystery", 1]})


def test_legacy_v1_client_is_refused_loudly():
    """A v1 (pickled-wire) client hello gets a v1-READABLE error frame and
    a closed connection - a loud version mismatch, not a hang or desync."""
    disp = Dispatcher(telemetry=Telemetry()).start()
    try:
        sock = socket.create_connection(("127.0.0.1", disp.port), timeout=5)
        evil = pickle.dumps({"t": "client_hello", "protocol": 1})
        sock.sendall(struct.pack("!I", len(evil)) + evil)
        # the reply is a pickled error dict (the one format v1 peers read)
        (length,) = struct.unpack("!I", _recv_exact(sock, 4))
        reply = pickle.loads(_recv_exact(sock, length))
        assert reply["t"] == "error"
        assert "protocol version mismatch" in reply["error"]
        assert _recv_exact(sock, 1) == b""  # then EOF: connection closed
        sock.close()
    finally:
        disp.stop()
        disp.join()


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return buf
        buf += chunk
    return buf


def test_corrupt_result_is_classified_failure_not_desync(fleet):
    """A result the client cannot decode (here: the pickle fallback with
    pickle refused) surfaces as a classified WorkerError per ordinal while
    the stream keeps flowing - no desync, no unpickle attempt - and is
    still ACKED (a refused outcome must not pin the dispatcher's
    redelivery buffer / replay forever)."""
    disp, addr, _workers = fleet
    ex = ServiceExecutor(addr, telemetry=Telemetry(), window=4,
                         stop_on_failure=False, allow_pickle_results=False)
    ex.start(EchoFactory())
    for i in range(3):
        ex.put(VentilatedItem(i, f"p{i}"))
    failures = 0
    for _ in range(3):
        with pytest.raises(WorkerError, match="refuses"):
            ex.get(timeout=15.0)
        failures += 1
    assert failures == 3  # every ordinal individually classified
    _wait_for(lambda: all(c["unacked"] == 0
                          for c in disp.stats()["clients"].values()),
              what="refused results acked (redelivery buffer freed)")
    ex.stop()
    ex.join()


# -- wire negotiation / encoding mix ------------------------------------------

def test_negotiate_codec_policy():
    codecs = ("zlib",)
    # auto: compress only cross-host hops both ends support
    assert wire.negotiate_codec("auto", True, codecs, codecs) == ""
    assert wire.negotiate_codec("auto", False, codecs, codecs) == "zlib"
    assert wire.negotiate_codec("auto", False, (), codecs) == ""
    assert wire.negotiate_codec("auto", False, codecs, ()) == ""
    # off: never; forced: wherever both ends support it
    assert wire.negotiate_codec("off", False, codecs, codecs) == ""
    assert wire.negotiate_codec("zlib", True, codecs, codecs) == "zlib"
    assert wire.negotiate_codec("zlib", True, (), codecs) == ""


def test_binary_wire_counters_and_shm_diagnostics(int_dataset, fleet):
    """The e2e result path is pickle-free for real reads: every batch is a
    binary frame (client AND dispatcher meter the mix), the per-direction
    decode stage records, and the reader surfaces which shm transport path
    this runtime can negotiate - and why not."""
    disp, addr, _workers = fleet
    rows, diag, tele = _read_all(int_dataset, addr)
    assert rows == list(range(200))
    c = tele.snapshot()["counters"]
    assert c["service.frames_binary"] == 20
    assert c.get("service.frames_pickle_fallback", 0) == 0
    assert "stage.service.decode.busy_s" in c
    dc = disp.stats()["counters"]
    assert dc["service.frames_binary"] >= 20
    assert dc.get("service.frames_pickle_fallback", 0) == 0
    shm = diag["native"]["shm_transport"]
    assert shm["available"] == shm_transport_available()
    if not shm["available"]:
        # the dark fast path must name its reason (py<3.12, missing .so)
        assert shm["reason"]


def test_pickle_fallback_is_metered(fleet):
    """Non-ColumnBatch worker results (the echo factory's tuples) take the
    counted pickle fallback - visible, never silent."""
    _disp, addr, _workers = fleet
    tele = Telemetry()
    ex = ServiceExecutor(addr, telemetry=tele, window=4)
    ex.start(EchoFactory())
    for i in range(4):
        ex.put(VentilatedItem(i, f"p{i}"))
    got = sorted(ex.get(timeout=10.0) for _ in range(4))
    assert got == [("echo", f"p{i}", i) for i in range(4)]
    c = tele.snapshot()["counters"]
    assert c["service.frames_pickle_fallback"] == 4
    assert c.get("service.frames_binary", 0) == 0
    ex.stop()
    ex.join()


def test_forced_compression_end_to_end(int_dataset):
    """wire_codec='zlib' forces BATCH-body compression even on one host;
    the stream stays exact and the client meters compressed frames."""
    disp = Dispatcher(telemetry=Telemetry(), wire_codec="zlib").start()
    addr = f"127.0.0.1:{disp.port}"
    workers = [ServiceWorker(addr, capacity=2, name=f"wz{i}")
               for i in range(2)]
    for w in workers:
        threading.Thread(target=w.run, daemon=True).start()
    try:
        _wait_for(lambda: len(disp.stats()["workers"]) == 2,
                  what="worker registration")
        rows, _diag, tele = _read_all(int_dataset, addr)
        assert rows == list(range(200))
        c = tele.snapshot()["counters"]
        assert c["service.frames_binary"] == 20
        assert c["service.frames_compressed"] == 20
    finally:
        for w in workers:
            w.stop()
        disp.stop()
        disp.join()


def test_wire_codec_knob_validation():
    with pytest.raises(PetastormTpuError, match="wire_codec"):
        Dispatcher(telemetry=Telemetry(), wire_codec="snappy")


def test_client_hello_logs_negotiated_wire(fleet, caplog):
    """Satellite: the hello log states which data plane was negotiated and
    WHY the shm fast path is (un)available on this runtime."""
    import logging

    _disp, addr, _workers = fleet
    with caplog.at_level(logging.INFO, logger="petastorm_tpu.service.client"):
        ex = ServiceExecutor(addr, telemetry=Telemetry(), window=2)
        ex.start(EchoFactory())
    lines = [r.getMessage() for r in caplog.records
             if "service wire negotiated" in r.getMessage()]
    assert lines, caplog.records
    assert "binary v2 frames" in lines[0]
    if shm_transport_available():
        assert "shm fast path available" in lines[0]
    else:
        assert "unavailable (python" in lines[0] \
            or "unavailable (native" in lines[0]
    ex.stop()
    ex.join()


_SHM_DARK = not shm_transport_available()


@pytest.mark.skipif(
    _SHM_DARK and not os.environ.get("PETASTORM_TPU_REQUIRE_ARENA"),
    reason="shm transport plane unavailable (python >= 3.12 + native lib)")
def test_shm_fast_path_end_to_end(int_dataset):
    """Co-located client+worker negotiate the shm arena: batches cross the
    socket as descriptors only (pk='shm'), counted on both ends.

    Under PETASTORM_TPU_REQUIRE_ARENA=1 (the py3.12 CI jobs) this test
    RUNS unconditionally, so a silently-broken arena plane fails loudly
    instead of skipping - the fast path can never go dark unnoticed again.
    """
    disp = Dispatcher(telemetry=Telemetry()).start()
    addr = f"127.0.0.1:{disp.port}"
    workers = [ServiceWorker(addr, capacity=2, name=f"ws{i}",
                             shm_size_bytes=64 * 2 ** 20) for i in range(2)]
    for w in workers:
        threading.Thread(target=w.run, daemon=True).start()
    try:
        _wait_for(lambda: len(disp.stats()["workers"]) == 2,
                  what="worker registration")
        rows, diag, tele = _read_all(int_dataset, addr)
        assert rows == list(range(200))
        c = tele.snapshot()["counters"]
        assert c["service.frames_shm"] == 20, c
        assert c.get("service.frames_pickle_fallback", 0) == 0
        assert disp.stats()["counters"]["service.frames_shm"] >= 20
        assert diag["native"]["shm_transport"]["available"] is True
    finally:
        for w in workers:
            w.stop()
        disp.stop()
        disp.join()


# -- client executor unit behavior -------------------------------------------

def test_client_executor_requires_picklable_factory(fleet):
    _disp, addr, _workers = fleet
    ex = ServiceExecutor(addr, telemetry=Telemetry())
    with pytest.raises(PetastormTpuError, match="picklable"):
        ex.start(lambda: (lambda item: item))  # lambdas don't pickle
    ex.stop()
    ex.join()


def test_service_executor_roundtrip_plain(fleet):
    """The raw ExecutorBase protocol over the wire: put N, get N."""
    _disp, addr, _workers = fleet
    # window >= items: put and get run on one thread here (a real reader
    # ventilates from a separate thread, so the window can backpressure)
    ex = ServiceExecutor(addr, telemetry=Telemetry(), window=8)
    ex.start(EchoFactory())
    for i in range(8):
        ex.put(VentilatedItem(i, f"payload-{i}"))
    got = sorted(ex.get(timeout=10.0) for _ in range(8))
    assert got == [("echo", f"payload-{i}", i) for i in range(8)]
    ex.stop()
    ex.join()


# -- multi-client e2e ---------------------------------------------------------

def test_two_clients_exact_multisets(int_dataset, fleet):
    """Acceptance core: two make_reader(service_address=...) clients on one
    dataset each receive their exact expected row multiset."""
    _disp, addr, _workers = fleet
    out = {}

    def read(tag, epochs):
        out[tag] = _read_all(int_dataset, addr, num_epochs=epochs)[0]

    threads = [threading.Thread(target=read, args=("a", 1)),
               threading.Thread(target=read, args=("b", 2))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert out["a"] == list(range(200))
    assert out["b"] == sorted(list(range(200)) * 2)


def test_fleet_decodes_each_rowgroup_once_shared_tier(int_dataset, fleet,
                                                      tmp_path):
    """Decode-once sharing: with the host-wide warm tier, the second client
    is served entirely from cache - each rowgroup decoded exactly once
    fleet-wide (sequential clients make the accounting exact; concurrent
    clients can race a handful of duplicate decodes, by design)."""
    disp, addr, _workers = fleet
    loc = str(tmp_path / f"tier_{uuid.uuid4().hex[:8]}")
    rows_a, diag_a, _ = _read_all(int_dataset, addr, cache_type="shared",
                                  cache_location=loc)
    rows_b, diag_b, _ = _read_all(int_dataset, addr, cache_type="shared",
                                  cache_location=loc)
    assert rows_a == list(range(200))
    assert rows_b == list(range(200))
    stats = diag_b["cache"]
    # 20 rowgroups: every one decoded exactly once (client A's epoch),
    # client B fully tier-served (L1 hits, or L2 after an L1 eviction)
    assert stats["misses"] == 20, stats
    assert stats["hits"] + stats["l2_hits"] >= 20, stats
    # the fleet-side proof rides the dispatcher registry via worker
    # heartbeats: both clients' items were processed by the fleet
    _wait_for(lambda: disp.stats()["counters"].get(
        "service.fleet.worker.rowgroups_decoded", 0) >= 40,
        timeout=10.0, what="fleet heartbeat counters")


def test_shuffled_epochs_and_resume_cursor(int_dataset, fleet):
    """The deterministic plan plane is untouched by the service hop:
    shuffled epochs deliver exact multisets and the resume cursor restarts
    mid-stream exactly like a local pool's."""
    _disp, addr, _workers = fleet
    tele = Telemetry()
    with make_batch_reader(int_dataset, service_address=addr,
                           shuffle_row_groups=True, shuffle_seed=7,
                           telemetry=tele) as reader:
        it = reader.iter_batches()
        consumed = []
        for _ in range(6):
            consumed.extend(next(it).columns["x"])
        reader.quiesce()
        consumed.extend(x for b in it for x in b.columns["x"])
        state = reader.state_dict()
    assert state["ordinal_exact"]
    with make_batch_reader(int_dataset, service_address=addr,
                           shuffle_row_groups=True, shuffle_seed=7,
                           resume_from=state) as reader:
        rest = [x for b in reader.iter_batches() for x in b.columns["x"]]
    assert sorted(consumed + rest) == list(range(200))


def test_on_error_skip_quarantines_data_failures(int_dataset, fleet):
    """A poisoned rowgroup surfaces as a classified data failure across the
    wire and the reader's skip policy quarantines it - service and local
    pools share the on_error contract."""
    _disp, addr, _workers = fleet
    from petastorm_tpu.test_util.chaos import ChaosSpec

    rows, diag, tele = _read_all(int_dataset, addr, on_error="skip",
                                 chaos=ChaosSpec(decode_fail_ordinals=(3,)))
    assert rows == sorted(set(range(200)) - set(range(30, 40)))
    assert diag["skipped_rowgroups"] == 1
    assert diag["quarantined_rowgroups"][0]["ordinal"] == 3
    assert tele.snapshot()["counters"]["errors.skipped_rowgroups"] == 1


# -- chaos on the service plane ----------------------------------------------

def _spawn_worker_proc(addr, name, capacity=2):
    return subprocess.Popen(
        [sys.executable, "-m", "petastorm_tpu.service.cli", "worker",
         "--address", addr, "--capacity", str(capacity), "--name", name],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


@pytest.mark.slow
def test_chaos_worker_sigkill_mid_epoch(int_dataset):
    """Acceptance chaos: SIGKILL one remote worker holding in-flight items;
    both clients still see their exact row multiset and
    service.requeued_items accounts for the kill."""
    disp = Dispatcher(telemetry=Telemetry(), heartbeat_timeout_s=5.0).start()
    addr = f"127.0.0.1:{disp.port}"
    procs = [_spawn_worker_proc(addr, f"w{i}") for i in range(2)]
    try:
        _wait_for(lambda: len(disp.stats()["workers"]) == 2, timeout=30.0,
                  what="worker registration")
        out = {}

        def read(tag):
            out[tag] = _read_all(int_dataset, addr)[0:2]

        threads = [threading.Thread(target=read, args=(t,))
                   for t in ("a", "b")]
        for t in threads:
            t.start()
        _wait_for(lambda: disp.stats()["workers"].get(
            "w0", {}).get("inflight", 0) > 0, timeout=30.0,
            what="w0 holding in-flight work")
        os.kill(procs[0].pid, signal.SIGKILL)
        for t in threads:
            t.join(timeout=120)
        assert out["a"][0] == list(range(200))
        assert out["b"][0] == list(range(200))
        counters = disp.stats()["counters"]
        assert counters.get("service.requeued_items", 0) >= 1
        # the kill is visible client-side too (requeued notices)
        assert (out["a"][1]["requeued_items"]
                + out["b"][1]["requeued_items"]) >= 1
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        disp.stop()
        disp.join()


def test_chaos_client_connection_drop(int_dataset, fleet):
    """Yank the client's TCP connection mid-epoch: the executor reconnects
    with backoff, the dispatcher replays unacked results, the ledger dedups,
    and the epoch completes with the exact multiset."""
    _disp, addr, _workers = fleet
    tele = Telemetry()
    reader = make_batch_reader(int_dataset, service_address=addr,
                               shuffle_row_groups=False, telemetry=tele)
    reader._executor._reconnect_policy = FAST_RECONNECT
    rows = []
    for i, b in enumerate(reader.iter_batches()):
        rows.extend(b.columns["x"])
        if i == 4:
            reader._executor._conn._sock.shutdown(socket.SHUT_RDWR)
    diag = reader.diagnostics
    reader.stop()
    reader.join()
    assert sorted(rows) == list(range(200))
    assert diag["reconnects"] >= 1
    assert tele.snapshot()["counters"]["service.reconnects"] >= 1


def test_chaos_simulated_worker_kill_via_chaos_spec(int_dataset, fleet):
    """The chaos harness's kill injection rides the pickled factory to the
    fleet: in-process test workers treat it like a real death only when they
    are processes, so here we assert the dispatcher requeue path triggers
    via a dropped worker instead (worker.stop mid-epoch)."""
    disp, addr, workers = fleet
    tele = Telemetry()
    reader = make_batch_reader(int_dataset, service_address=addr,
                               shuffle_row_groups=False, telemetry=tele)
    rows = []
    stopped = False
    for b in reader.iter_batches():
        rows.extend(b.columns["x"])
        if not stopped and len(rows) >= 30:
            stopped = True
            workers[0].stop()  # drops its connection; in-flight requeues
    diag = reader.diagnostics
    reader.stop()
    reader.join()
    assert sorted(rows) == list(range(200))
    assert disp.stats()["counters"].get("service.requeued_items", 0) >= 0
    assert diag["consumed"] == 20


def test_assignment_deadline_drops_hung_worker(int_dataset):
    """Liveness backstop: a worker wedged inside user code (still
    heartbeating) is dropped once its assignment exceeds the deadline, and
    the requeued attempt completes on a fresh worker."""
    disp = Dispatcher(telemetry=Telemetry(), assignment_deadline_s=1.0).start()
    addr = f"127.0.0.1:{disp.port}"
    workers = [ServiceWorker(addr, capacity=1, name=f"w{i}")
               for i in range(2)]
    for w in workers:
        threading.Thread(target=w.run, daemon=True).start()
    _wait_for(lambda: len(disp.stats()["workers"]) == 2,
              what="worker registration")
    ex = ServiceExecutor(addr, telemetry=Telemetry(), window=2)
    ex.start(HangFirstAttemptFactory())
    try:
        ex.put(VentilatedItem(0, "wedge-me"))
        assert ex.get(timeout=30.0) == ("recovered", 0)
        counters = disp.stats()["counters"]
        assert counters.get("service.hung_workers_dropped", 0) >= 1, counters
        assert counters.get("service.requeued_items", 0) >= 1, counters
    finally:
        ex.stop()
        ex.join()
        for w in workers:
            w.stop()
        disp.stop()
        disp.join()


def test_unpicklable_result_surfaces_as_failure_not_hang(fleet):
    """A transform output pickle cannot serialize must come back as a
    classified data failure, not a silently-dead processor thread."""
    _disp, addr, _workers = fleet
    ex = ServiceExecutor(addr, telemetry=Telemetry(), window=2)
    ex.start(UnpicklableResultFactory())
    ex.put(VentilatedItem(0, "unpicklable"))
    with pytest.raises(WorkerError, match="TypeError|pickle|cannot"):
        ex.get(timeout=30.0)
    ex.join()


def test_dispatcher_loss_raises_classified_error(int_dataset):
    """Graceful client degrade: a lost dispatcher (reconnect window
    exhausted) raises a classified infrastructure WorkerError carrying
    .diagnostics instead of hanging the epoch."""
    disp = Dispatcher(telemetry=Telemetry()).start()
    addr = f"127.0.0.1:{disp.port}"
    worker = ServiceWorker(addr, capacity=2)
    threading.Thread(target=worker.run, daemon=True).start()
    reader = make_batch_reader(int_dataset, service_address=addr,
                               shuffle_row_groups=False)
    reader._executor._reconnect_policy = FAST_RECONNECT
    with pytest.raises(ServiceConnectionError) as info:
        for i, _b in enumerate(reader.iter_batches()):
            if i == 2:
                disp.stop()
    assert info.value.kind == "infra"
    assert info.value.diagnostics["service_address"] == addr
    assert info.value.diagnostics["connected"] is False
    reader.stop()
    reader.join()
    worker.stop()
    disp.join()


def test_requeue_budget_exhaustion_surfaces_worker_error(fleet, int_dataset):
    """An item whose every attempt lands on a dying worker exhausts the
    budget and surfaces the pool-shaped infra WorkerError."""
    disp, addr, _workers = fleet
    ex = ServiceExecutor(addr, telemetry=Telemetry(), window=2,
                         max_requeue_attempts=0)
    ex.start(SleepForeverFactory())
    ex.put(VentilatedItem(0, "doomed"))
    # kill whichever fleet worker holds it (in-process workers: stop both)
    _wait_for(lambda: sum(w["inflight"]
                          for w in disp.stats()["workers"].values()) > 0,
              what="item assigned")
    for w in _workers:
        w.stop()
    with pytest.raises(WorkerError, match="requeue budget exhausted"):
        ex.get(timeout=30.0)
    assert ex._stopped  # stop_on_failure honored
    ex.join()


# -- observability / scaling --------------------------------------------------

def test_service_stage_prerendered_and_watch_line(int_dataset, fleet):
    """Satellite: a just-started service pipeline renders 'service' as
    "(no samples yet)" in pipeline_report and the watch frame, then as live
    rates once results flow."""
    from petastorm_tpu.telemetry.report import render_pipeline_report
    from petastorm_tpu.tools.diagnose import render_watch_frame

    _disp, addr, _workers = fleet
    tele = Telemetry()
    reader = make_batch_reader(int_dataset, service_address=addr,
                               shuffle_row_groups=False, telemetry=tele,
                               sample_interval_s=0.05)
    try:
        report = render_pipeline_report(tele.snapshot())
        assert "service" in report  # registered before any result
        empty_frame = render_watch_frame(
            {"dt_s": 0.1, "rates": {}, "stages": {}, "gauges":
             {"service.connected": 1.0}, "counters": {}})
        assert "service: (no samples yet)" in empty_frame
        rows = [x for b in reader.iter_batches() for x in b.columns["x"]]
        assert sorted(rows) == list(range(200))
        reader.sampler.sample_now()
        point = reader.sampler.latest()
        frame = render_watch_frame(point, reader.diagnostics)
        service_line = frame.split("service:")[1].splitlines()[0]
        assert "(no samples yet)" not in service_line
        # the wire-encoding mix rides the line: all-binary here, zero
        # pickle fallback (the satellite observable of the v2 wire)
        assert "wire bin=20" in service_line, service_line
        assert "/pkl=0" in service_line, service_line
        report = render_pipeline_report(tele.snapshot())
        assert "service" in report
    finally:
        reader.stop()
        reader.join()


def test_dispatcher_scaling_signal(int_dataset):
    """The fleet-pressure signal: starved clients + queued work with no
    capacity -> grow; an idle fleet -> shrink eligibility; busy -> ok."""
    disp = Dispatcher(telemetry=Telemetry()).start()
    addr = f"127.0.0.1:{disp.port}"
    try:
        # no workers at all, a client with pending work and starvation
        ex = ServiceExecutor(addr, telemetry=Telemetry(), window=4)
        ex.start(PlainEchoFactory())
        ex.put(VentilatedItem(0, "queued"))
        # simulate the reader's starved-consumer report
        ex._starved_s = 5.0
        ex._stats_sent_at = 0.0
        ex._maybe_send_stats()
        _wait_for(lambda: disp.scaling_signal()["pressure"] > 0,
                  what="starved report folded")
        sig = disp.scaling_signal()
        assert sig["recommendation"] == "grow", sig
        assert sig["pressure"] > sig["starved_threshold"]
        # a worker joins and drains: pressure decays toward ok/shrink
        worker = ServiceWorker(addr, capacity=2)
        threading.Thread(target=worker.run, daemon=True).start()
        assert ex.get(timeout=15.0) == "queued"
        _wait_for(lambda: disp.scaling_signal()["recommendation"]
                  in ("ok", "shrink"), timeout=15.0,
                  what="pressure decay")
        ex.stop()
        ex.join()
        worker.stop()
    finally:
        disp.stop()
        disp.join()


def test_dispatcher_stats_and_cli_stats_roundtrip(fleet, int_dataset):
    """Dispatcher stats carry fleet membership + per-client progress, and
    the stats? frame (the CLI's probe) returns the same snapshot."""
    disp, addr, _workers = fleet
    rows, _diag, _tele = _read_all(int_dataset, addr)
    assert rows == list(range(200))
    stats = disp.stats()
    assert len(stats["workers"]) == 2
    assert stats["counters"]["service.completed_items"] >= 20
    assert stats["counters"]["service.client_rows"] >= 200
    conn = connect_frames(parse_address(addr))
    try:
        conn.send({"t": "stats?"})
        reply = conn.recv(timeout=10.0)
    finally:
        conn.close()
    assert reply["t"] == "stats"
    assert reply["stats"]["workers"].keys() == stats["workers"].keys()


def test_service_reader_validation(int_dataset, fleet):
    """service_address refuses process-local caches and quietly disables
    client-side liveness/autotune knobs."""
    _disp, addr, _workers = fleet
    with pytest.raises(PetastormTpuError, match="process-local"):
        make_batch_reader(int_dataset, service_address=addr,
                          cache_type="memory")
    # liveness knobs are dropped with a warning, not fatal
    rows, diag, _ = _read_all(int_dataset, addr, item_deadline_s=5.0,
                              hedge_after_s=2.0)
    assert rows == list(range(200))
    assert diag["connected"] is True  # diagnostics captured mid-read
