"""Column codecs: how a logical tensor field is stored in Parquet.

Reference parity: petastorm/codecs.py (261 LoC) defines DataframeColumnCodec with
per-cell encode/decode plus four codecs (CompressedImageCodec, NdarrayCodec,
CompressedNdarrayCodec, ScalarCodec) (codecs.py:36-238) and a shape-compliance
check with None wildcards (codecs.py:241-261).

Design differences (TPU-first):

* **Columnar decode is the primary API.** The reference decodes cell-by-cell inside a
  per-row dict loop (petastorm/utils.py:54-87) - its main CPU bottleneck.  Here
  ``decode_column`` takes a whole ``pyarrow.Array`` and returns one contiguous numpy
  array (n, *shape) when the field shape is fixed, ready for zero-copy device feed.
  Per-cell ``decode`` exists for variable-shape fields and tests.
* **JSON-serializable, not pickled.** The reference pickles codec instances into
  dataset metadata, so a class rename breaks old datasets (petastorm/codecs.py:20-21,
  etl/dataset_metadata.py:202-206).  Codecs here serialize to ``{"codec": name,
  **params}`` via a registry; the wire format is stable by construction.
* **Storage formats are kept petastorm-compatible** where cheap: NdarrayCodec uses
  ``np.save`` bytes, CompressedNdarrayCodec uses ``np.savez_compressed``, images are
  standard PNG/JPEG streams - so datasets written by the reference decode here.
* **Device placement hook.** Codecs declare whether their decode can run on-device
  (``device_decodable``); the JAX loader uses this to ship raw bytes + run the
  Pallas/XLA decode kernel instead of host decode (petastorm_tpu/ops/).
"""

from __future__ import annotations

import contextlib
import io
import threading
from abc import ABC, abstractmethod
from typing import Any, Dict, Optional, Tuple, Type

import numpy as np
import pyarrow as pa

from petastorm_tpu import dtypes
from petastorm_tpu.errors import CodecError

_CODEC_REGISTRY: Dict[str, Type["Codec"]] = {}

_DECODE_THREADS: Optional[int] = None


def _decode_threads() -> int:
    """PETASTORM_TPU_DECODE_THREADS: internal decode fan-out for serial consumers
    (e.g. the jax loader path) on multicore hosts; pool workers keep 1.  Parsed
    once; malformed values warn and fall back to 1."""
    global _DECODE_THREADS
    if _DECODE_THREADS is None:
        import logging
        import os

        raw = os.environ.get("PETASTORM_TPU_DECODE_THREADS", "1")
        try:
            _DECODE_THREADS = max(1, int(raw))
        except ValueError:
            logging.getLogger(__name__).warning(
                "Ignoring malformed PETASTORM_TPU_DECODE_THREADS=%r; using 1", raw)
            _DECODE_THREADS = 1
    return _DECODE_THREADS


# -- per-call decode options (set by the worker plane, read by codecs) --------

_DECODE_CTX = threading.local()


class DecodeOptions:
    """Options the rowgroup worker threads down to ``decode_column`` without
    widening every codec's signature:

    * ``nthreads`` - internal fan-out of the native batched decode (the
      worker sizes it to its share of the host's cores; overrides the
      ``PETASTORM_TPU_DECODE_THREADS`` default);
    * ``roi`` - ``(crop_ys, crop_xs, crop_h, crop_w)`` partial decode for
      image columns (``make_reader(decode_roi=...)``): only the kept window
      is decoded (native path) or sliced (fallback path) - output rows are
      ``(crop_h, crop_w[, C])``;
    * ``batch_slots`` - allow allocating the decode output from the active
      shm :class:`~petastorm_tpu.native.transport.SlotAllocator` so process
      pools ship it with zero further copies (the worker enables this only
      when no cache would retain the arena-backed array).
    """

    __slots__ = ("nthreads", "roi", "batch_slots")

    def __init__(self, nthreads: Optional[int] = None,
                 roi: Optional[Tuple] = None, batch_slots: bool = False):
        self.nthreads = nthreads
        self.roi = roi
        self.batch_slots = batch_slots


@contextlib.contextmanager
def decode_options(nthreads: Optional[int] = None,
                   roi: Optional[Tuple] = None, batch_slots: bool = False):
    """Install :class:`DecodeOptions` for decode calls on this thread."""
    prev = getattr(_DECODE_CTX, "opts", None)
    _DECODE_CTX.opts = DecodeOptions(nthreads=nthreads, roi=roi,
                                     batch_slots=batch_slots)
    try:
        yield
    finally:
        _DECODE_CTX.opts = prev


def _current_opts() -> DecodeOptions:
    opts = getattr(_DECODE_CTX, "opts", None)
    return opts if opts is not None else _DEFAULT_OPTS


_DEFAULT_OPTS = DecodeOptions()


def register_codec(cls: Type["Codec"]) -> Type["Codec"]:
    """Class decorator: make a Codec subclass JSON-round-trippable by name
    (datasets stamp ``{"codec": codec_name, **params}``; readers look the
    name up here).  User-defined codecs must register before reading."""
    _CODEC_REGISTRY[cls.codec_name] = cls
    return cls


def codec_from_json(obj: Dict[str, Any]) -> "Codec":
    obj = dict(obj)
    name = obj.pop("codec")
    if name not in _CODEC_REGISTRY:
        raise CodecError(f"Unknown codec {name!r}; known: {sorted(_CODEC_REGISTRY)}")
    return _CODEC_REGISTRY[name].from_json(obj)


def check_shape_compliance(field, value: np.ndarray) -> None:
    """Validate ndarray rank/dims against the field shape; None dims are wildcards.

    Reference: petastorm/codecs.py:241-261.
    """
    expected = field.shape
    if len(expected) != value.ndim:
        raise CodecError(
            f"field {field.name!r}: rank mismatch, schema {expected} vs value {value.shape}"
        )
    for want, got in zip(expected, value.shape):
        if want is not None and want != got:
            raise CodecError(
                f"field {field.name!r}: shape mismatch, schema {expected} vs value {value.shape}"
            )


class Codec(ABC):
    """Field storage codec.

    ``encode`` produces the python value handed to pyarrow for one cell;
    ``decode`` inverts it for one cell; ``decode_column`` inverts a whole column.
    """

    codec_name: str = ""
    #: True when petastorm_tpu.ops has an on-device decode kernel for this codec.
    device_decodable: bool = False
    #: True when encoded cells are already entropy-coded (PNG/JPEG/deflate):
    #: the writer then stores the column UNCOMPRESSED - parquet-level snappy
    #: over such bytes saves nothing and costs a decompress pass on every read
    precompressed: bool = False

    @abstractmethod
    def storage_type(self, field) -> pa.DataType:
        """Arrow type this codec stores the field as."""

    @abstractmethod
    def encode(self, field, value) -> Any:
        """One cell's python value -> the storage value handed to pyarrow."""

    @abstractmethod
    def decode(self, field, value) -> Any:
        """Invert :meth:`encode` for one stored cell."""

    def decode_column(self, field, column: pa.Array) -> np.ndarray:
        """Decode an arrow column -> stacked numpy array.

        Default: per-cell loop; fixed-shape fields are stacked contiguously,
        variable-shape fields come back as an object array.
        """
        cells = [None if v is None else self.decode(field, v) for v in column.to_pylist()]
        return _stack_cells(field, cells)

    # -- serialization --------------------------------------------------------

    def to_json(self) -> Dict[str, Any]:
        """JSON-native params dict stored in dataset metadata ({} by default);
        inverted by ``from_json`` via the codec registry."""
        return {"codec": self.codec_name}

    @classmethod
    def from_json(cls, obj: Dict[str, Any]) -> "Codec":
        return cls(**obj)

    def __eq__(self, other):
        return type(self) is type(other) and self.to_json() == other.to_json()

    def __hash__(self):
        return hash((type(self).__name__, tuple(sorted(self.to_json().items()))))

    def __repr__(self):
        params = {k: v for k, v in self.to_json().items() if k != "codec"}
        return f"{type(self).__name__}({', '.join(f'{k}={v!r}' for k, v in params.items())})"


def _slice_roi(decoded: np.ndarray, roi: Tuple) -> np.ndarray:
    """Fallback ROI: crop a fully-decoded stacked column to the ROI windows
    (same result as the native partial decode, minus the savings)."""
    ys, xs, crop_h, crop_w = roi
    n = len(decoded)
    ys = np.broadcast_to(np.asarray(ys, dtype=np.int64), (n,))
    xs = np.broadcast_to(np.asarray(xs, dtype=np.int64), (n,))
    if decoded.dtype == object:
        out = np.empty(n, dtype=object)
        for i in range(n):
            # nullable image columns decode None cells through this branch;
            # the null passes through uncropped rather than crashing
            out[i] = (None if decoded[i] is None else np.ascontiguousarray(
                decoded[i][ys[i]:ys[i] + crop_h, xs[i]:xs[i] + crop_w]))
        return out
    out = np.empty((n, crop_h, crop_w) + decoded.shape[3:], decoded.dtype)
    for i in range(n):
        out[i] = decoded[i, ys[i]:ys[i] + crop_h, xs[i]:xs[i] + crop_w]
    return out


def _stack_cells(field, cells) -> np.ndarray:
    if field.is_fixed_shape and not any(c is None for c in cells):
        if not cells:
            return np.empty((0,) + field.shape, dtype=field.dtype)
        return np.stack(cells)
    out = np.empty(len(cells), dtype=object)
    for i, c in enumerate(cells):
        out[i] = c
    return out


@register_codec
class ScalarCodec(Codec):
    """Plain scalar column; arrow-native storage.

    Reference: petastorm/codecs.py:189-238 (ScalarCodec over spark types).  Here the
    storage type derives from the field's numpy dtype; an optional ``store_dtype``
    overrides it (e.g. store int8 labels as int32 for ecosystem compatibility).
    """

    codec_name = "scalar"

    def __init__(self, store_dtype: Optional[str] = None):
        self._store_dtype = np.dtype(store_dtype) if store_dtype else None

    def storage_type(self, field) -> pa.DataType:
        return dtypes.numpy_to_arrow(self._store_dtype or field.dtype)

    def encode(self, field, value):
        if field.shape != ():
            raise CodecError(f"ScalarCodec on non-scalar field {field.name!r} {field.shape}")
        return dtypes.sanitize_value(value, self._store_dtype or field.dtype)

    def decode(self, field, value):
        if field.dtype.kind in ("U", "S", "O"):
            return value
        return field.dtype.type(value)

    def decode_column(self, field, column: pa.Array) -> np.ndarray:
        if column.null_count > 0:
            # arrow->numpy of an int column with nulls goes through float64+NaN and
            # astype would turn NaN into INT_MIN; preserve None via the object path
            return super().decode_column(field, column)
        arr = column.to_numpy(zero_copy_only=False)
        if field.dtype.kind not in ("U", "S", "O") and arr.dtype != field.dtype:
            arr = arr.astype(field.dtype)
        return arr

    def to_json(self):
        out = {"codec": self.codec_name}
        if self._store_dtype is not None:
            out["store_dtype"] = self._store_dtype.name
        return out


#: parsed-.npy-header cache: raw header bytes -> (dtype, shape).  A dataset has
#: a handful of distinct headers (one per field x shape), so this stays tiny; it
#: removes the per-cell ``ast`` parse that dominates ``np.load`` for small arrays.
_NPY_HEADER_CACHE: Dict[bytes, Tuple[np.dtype, Tuple[int, ...]]] = {}


def _fast_npy_decode(value: bytes) -> Optional[np.ndarray]:
    """Decode ``np.save`` bytes without BytesIO/np.load overhead.

    Returns None for anything unusual (fortran order, object dtype, version we
    don't recognize) so the caller can fall back to ``np.load``.
    """
    if not value.startswith(b"\x93NUMPY") or len(value) < 10:
        return None
    major = value[6]
    if major == 1:
        hlen, off = int.from_bytes(value[8:10], "little"), 10
    elif major in (2, 3):
        if len(value) < 12:
            return None
        hlen, off = int.from_bytes(value[8:12], "little"), 12
    else:
        return None
    header = value[off:off + hlen]
    parsed = _NPY_HEADER_CACHE.get(header)
    if parsed is None:
        import ast

        try:
            d = ast.literal_eval(header.decode("latin1"))
        except (ValueError, SyntaxError):
            return None
        if d.get("fortran_order"):
            return None
        dtype = np.dtype(d["descr"])
        if dtype.hasobject:
            return None
        parsed = (dtype, tuple(d["shape"]))
        # bound the cache: variable-shape fields embed each cell's shape in the
        # header, so distinct headers are unbounded over a long-running worker
        if len(_NPY_HEADER_CACHE) < 1024:
            _NPY_HEADER_CACHE[header] = parsed
    dtype, shape = parsed
    count = 1
    for dim in shape:
        count *= dim
    data = np.frombuffer(value, dtype=dtype, count=count, offset=off + hlen)
    # copy: frombuffer over bytes is read-only; callers expect writable arrays
    return data.reshape(shape).copy()


@register_codec
class NdarrayCodec(Codec):
    """ndarray <-> ``np.save`` bytes (petastorm-compatible storage format).

    Reference: petastorm/codecs.py:121-152.
    """

    codec_name = "ndarray"

    def storage_type(self, field) -> pa.DataType:
        return pa.binary()

    def encode(self, field, value) -> bytes:
        value = np.asarray(value)
        check_shape_compliance(field, value)
        if value.dtype != field.dtype:
            raise CodecError(
                f"field {field.name!r}: dtype mismatch {value.dtype} vs schema {field.dtype}"
            )
        buf = io.BytesIO()
        np.save(buf, value)
        return buf.getvalue()

    def decode(self, field, value: bytes) -> np.ndarray:
        arr = _fast_npy_decode(value)
        if arr is not None:
            return arr
        return np.load(io.BytesIO(value), allow_pickle=False)

    def decode_column(self, field, column: pa.Array) -> np.ndarray:
        """Fixed-shape fast path: the whole column decodes as ONE vectorized
        pass.  Equal-shape cells share identical npy headers, so the arrow
        data buffer is n equally-strided records; a (n, cell_bytes) uint8
        view + one slice/view/copy replaces the per-cell
        frombuffer+copy+stack loop."""
        batched = _batched_npy_decode(field, column)
        if batched is not None:
            return batched
        return super().decode_column(field, column)


def _batched_npy_decode(field, column: pa.Array) -> Optional[np.ndarray]:
    if not field.is_fixed_shape or column.null_count:
        return None
    typ = column.type
    if typ == pa.binary():
        off_dtype = np.dtype(np.int32)
    elif typ == pa.large_binary():
        off_dtype = np.dtype(np.int64)
    else:
        return None
    buffers = column.buffers()  # [validity, offsets, data]
    if len(buffers) != 3 or buffers[1] is None or buffers[2] is None:
        return None
    n = len(column)
    if n == 0:
        return np.empty((0,) + field.shape, dtype=field.dtype)
    offsets = np.frombuffer(buffers[1], dtype=off_dtype, count=n + 1,
                            offset=column.offset * off_dtype.itemsize)
    lens = np.diff(offsets)
    cell_len = int(lens[0])
    if cell_len == 0 or not (lens == cell_len).all():
        return None
    data = np.frombuffer(buffers[2], dtype=np.uint8, count=n * cell_len,
                         offset=int(offsets[0]))
    cells = data.reshape(n, cell_len)
    # one cached header parse tells us where the payload starts
    first = cells[0].tobytes()
    probe = _fast_npy_decode(first)
    if probe is None or probe.dtype != field.dtype or probe.shape != field.shape:
        return None
    hdr_len = cell_len - probe.nbytes
    if hdr_len <= 0:
        return None
    if n > 1 and not (cells[:, :hdr_len] == cells[0, :hdr_len]).all():
        return None  # differing headers despite equal length: per-cell path
    payload = cells[:, hdr_len:]
    out = payload.view(field.dtype).reshape((n,) + field.shape)
    # unconditional copy: the view aliases the arrow buffer (ascontiguousarray
    # would be a no-op for n==1 via relaxed strides, returning a read-only
    # alias that pins the rowgroup buffer); callers expect writable owners
    return out.copy()


@register_codec
class CompressedNdarrayCodec(Codec):
    """ndarray <-> ``np.savez_compressed`` bytes (petastorm-compatible).

    Reference: petastorm/codecs.py:155-186.
    """

    codec_name = "compressed_ndarray"
    precompressed = True

    def storage_type(self, field) -> pa.DataType:
        return pa.binary()

    def encode(self, field, value) -> bytes:
        value = np.asarray(value)
        check_shape_compliance(field, value)
        if value.dtype != field.dtype:
            raise CodecError(
                f"field {field.name!r}: dtype mismatch {value.dtype} vs schema {field.dtype}"
            )
        buf = io.BytesIO()
        np.savez_compressed(buf, arr=value)
        return buf.getvalue()

    def decode(self, field, value: bytes) -> np.ndarray:
        with np.load(io.BytesIO(value), allow_pickle=False) as npz:
            return npz["arr"]


@register_codec
class ScalarListCodec(Codec):
    """1-D variable-length list of scalars stored as an arrow list column.

    Used for inferred (non-petastorm) parquet stores where 1-D data lives in
    arrow list columns (reference handles these in arrow_reader_worker.py:39-87
    by vstacking lists at readout).
    """

    codec_name = "scalar_list"

    def storage_type(self, field) -> pa.DataType:
        return pa.list_(dtypes.numpy_to_arrow(field.dtype))

    def encode(self, field, value):
        arr = np.asarray(value)
        if arr.ndim != 1:
            raise CodecError(f"Field {field.name!r}: ScalarListCodec stores 1-D values")
        return arr.astype(field.dtype).tolist()

    def decode(self, field, value):
        return np.asarray(value, dtype=field.dtype)

    def decode_column(self, field, column: pa.Array) -> np.ndarray:
        # Fast path: fixed-width numeric lists reshape straight from the
        # arrow values buffer (one vectorized astype-copy, no per-element
        # python); ragged or nullable columns fall back per cell.
        n = len(column)
        if (n and column.null_count == 0
                and field.dtype.kind not in ("U", "S", "O")):
            try:
                lengths = np.unique(
                    pa.compute.list_value_length(column).to_numpy())
                if len(lengths) == 1:
                    arr = (column.combine_chunks()
                           if isinstance(column, pa.ChunkedArray) else column)
                    flat = arr.flatten().to_numpy(zero_copy_only=False)
                    # astype with copy=True: owning, writable, never aliasing
                    # the arrow buffer (reshape first so the copy is the
                    # final, base-less array)
                    return flat.reshape(n, int(lengths[0])).astype(
                        field.dtype, copy=True)
            except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
                pass
        pylist = column.to_pylist()
        lens = {len(v) for v in pylist if v is not None}
        if len(lens) == 1 and None not in pylist:
            return np.asarray(pylist, dtype=field.dtype)
        out = np.empty(len(pylist), dtype=object)
        for i, v in enumerate(pylist):
            out[i] = None if v is None else np.asarray(v, dtype=field.dtype)
        return out


@register_codec
class CompressedImageCodec(Codec):
    """Image <-> PNG/JPEG stream via OpenCV (PIL fallback).

    Reference: petastorm/codecs.py:53-118 - including the RGB<->BGR swap for
    3-channel images (cv2 is BGR-native) so stored streams are standard RGB files.

    TPU path (``device_decodable``): the JAX loader can fuse uint8->float
    normalize on-chip (petastorm_tpu/ops/normalize.py), and jpeg fields support
    full hybrid decode - ``make_reader(..., decode_placement={'field': 'device'})``
    ships raw streams, the host runs only entropy decode, and dequant + IDCT +
    upsample + color run on the TPU (petastorm_tpu/ops/jpeg.py; the
    BASELINE.json north star).
    """

    codec_name = "compressed_image"
    device_decodable = True
    precompressed = True

    def __init__(self, image_codec: str = "png", quality: int = 80):
        if image_codec not in ("png", "jpeg", "jpg"):
            raise CodecError(f"Unsupported image codec {image_codec!r}")
        self._format = "jpeg" if image_codec == "jpg" else image_codec
        self._quality = int(quality)

    @property
    def image_codec(self) -> str:
        """The stored image format: 'png' or 'jpeg'."""
        return self._format

    def storage_type(self, field) -> pa.DataType:
        return pa.binary()

    def _cv2(self):
        try:
            import cv2  # local import: heavy, optional

            return cv2
        except ImportError:
            return None

    def encode(self, field, value) -> bytes:
        value = np.asarray(value)
        check_shape_compliance(field, value)
        if value.dtype != field.dtype:
            raise CodecError(
                f"field {field.name!r}: dtype mismatch {value.dtype} vs schema {field.dtype}"
            )
        if value.dtype not in (np.dtype("uint8"), np.dtype("uint16")):
            raise CodecError("CompressedImageCodec supports uint8/uint16 images only")
        if self._format == "jpeg" and value.dtype != np.dtype("uint8"):
            raise CodecError("JPEG supports uint8 only")
        cv2 = self._cv2()
        if cv2 is not None:
            bgr = value[..., ::-1] if value.ndim == 3 and value.shape[2] == 3 else value
            if self._format == "jpeg":
                ok, enc = cv2.imencode(".jpeg", bgr, [int(cv2.IMWRITE_JPEG_QUALITY), self._quality])
            else:
                ok, enc = cv2.imencode(".png", bgr)
            if not ok:
                raise CodecError(f"cv2.imencode failed for field {field.name!r}")
            return enc.tobytes()
        return self._pil_encode(value)

    def decode(self, field, value: bytes) -> np.ndarray:
        # (h, w, 1) fields are grayscale streams; decode single-channel so the
        # result honors the declared shape (and matches the native batched path)
        single_channel = len(field.shape) == 3 and field.shape[2] == 1
        cv2 = self._cv2()
        if cv2 is not None:
            flags = cv2.IMREAD_UNCHANGED if field.dtype == np.dtype("uint16") else (
                cv2.IMREAD_COLOR if len(field.shape) == 3 and not single_channel
                else cv2.IMREAD_GRAYSCALE
            )
            img = cv2.imdecode(np.frombuffer(value, dtype=np.uint8), flags)
            if img is None:
                raise CodecError(f"cv2.imdecode failed for field {field.name!r}")
            if img.ndim == 3 and img.shape[2] == 3:
                # cvtColor instead of img[..., ::-1]: SIMD, contiguous output,
                # and releases the GIL so thread-pool decode scales
                img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
        else:
            img = self._pil_decode(field, value)
        if single_channel and img.ndim == 2:
            img = img[..., None]
        return np.ascontiguousarray(img.astype(field.dtype, copy=False))

    def decode_column(self, field, column: pa.Array) -> np.ndarray:
        # Hot path: batched multi-core native decode (libpng/libjpeg, GIL
        # released) into a preallocated contiguous array - no per-cell Python
        # at all.  The output array comes from the active shm SlotAllocator
        # when the worker armed one (process pools then ship the batch slot
        # itself: decode-into-slot, zero further copies); with a decode ROI
        # only the kept window is decoded.  Applies to fixed-shape uint8
        # images; everything else falls back to per-cell decode.
        opts = _current_opts()
        roi = opts.roi
        if (field.is_fixed_shape and field.dtype == np.dtype("uint8")
                and column.null_count == 0
                and (len(field.shape) == 2
                     or (len(field.shape) == 3 and field.shape[2] in (1, 3)))):
            from petastorm_tpu.native import image as native_image

            if native_image.available_or_warn():
                if roi is not None:
                    ys, xs, crop_h, crop_w = roi
                    shape = (len(column), crop_h, crop_w) + field.shape[2:]
                    native_roi = (ys, xs)
                    full_shape = field.shape[:2]
                else:
                    shape = (len(column),) + field.shape
                    native_roi = None
                    full_shape = None
                out = self._alloc_output(shape, opts)
                nthreads = (opts.nthreads if opts.nthreads is not None
                            else _decode_threads())
                if native_image.decode_column_native(column, out,
                                                     nthreads=nthreads,
                                                     roi=native_roi,
                                                     full_shape=full_shape):
                    return out
        decoded = super().decode_column(field, column)
        if roi is not None:
            decoded = _slice_roi(decoded, roi)
        return decoded

    @staticmethod
    def _alloc_output(shape, opts: DecodeOptions) -> np.ndarray:
        if opts.batch_slots:
            from petastorm_tpu.native.transport import current_slot_allocator

            allocator = current_slot_allocator()
            if allocator is not None:
                out = allocator.alloc(shape, np.uint8)
                if out is not None:
                    return out
        return np.empty(shape, dtype=np.uint8)

    def raw_column(self, column: pa.Array) -> np.ndarray:
        """Undecoded streams as an object array of bytes (for on-device decode)."""
        return np.asarray(column.to_pylist(), dtype=object)

    # -- PIL fallback ---------------------------------------------------------

    def _pil_encode(self, value: np.ndarray) -> bytes:
        from PIL import Image

        buf = io.BytesIO()
        Image.fromarray(value).save(buf, format="JPEG" if self._format == "jpeg" else "PNG",
                                    quality=self._quality)
        return buf.getvalue()

    def _pil_decode(self, field, value: bytes) -> np.ndarray:
        from PIL import Image

        img = Image.open(io.BytesIO(value))
        # match the cv2/native paths: color streams reduce to luma for 1-channel
        # fields, and 3-channel fields always get RGB (PIL's 'L' is the same
        # ITU-R 601 weighting cv2 uses, within 1 LSB)
        single_channel = len(field.shape) <= 2 or (
            len(field.shape) == 3 and field.shape[2] == 1)
        if single_channel and img.mode not in ("L", "I;16", "I"):
            img = img.convert("L")
        elif len(field.shape) == 3 and field.shape[2] == 3 and img.mode != "RGB":
            img = img.convert("RGB")
        return np.asarray(img).astype(field.dtype, copy=False)

    def to_json(self):
        return {"codec": self.codec_name, "image_codec": self._format, "quality": self._quality}
