"""TF delivery layer tests (reference: tests/test_tf_utils.py, tf.data path)."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from petastorm_tpu.codecs import NdarrayCodec  # noqa: E402
from petastorm_tpu.errors import PetastormTpuError  # noqa: E402
from petastorm_tpu.etl.writer import write_dataset  # noqa: E402
from petastorm_tpu.ngram import NGram  # noqa: E402
from petastorm_tpu.reader import make_reader  # noqa: E402
from petastorm_tpu.tf import make_petastorm_dataset  # noqa: E402
from petastorm_tpu.schema import Field, Schema  # noqa: E402


@pytest.fixture(scope="module")
def tf_dataset_url(tmp_path_factory):
    url = str(tmp_path_factory.mktemp("tf_ds") / "ds")
    schema = Schema("TfSchema", [
        Field("id", np.int64),
        Field("u16", np.uint16),
        Field("name", np.dtype("object")),
        Field("vec", np.float32, (3,), NdarrayCodec()),
    ])
    rows = [{"id": i, "u16": i * 2, "name": f"row_{i}",
             "vec": np.full(3, i, np.float32)} for i in range(20)]
    write_dataset(url, schema, rows, row_group_size_rows=5)
    return url


def test_round_trip_with_promotions_and_strings(tf_dataset_url):
    with make_reader(tf_dataset_url, reader_pool_type="serial",
                     shuffle_row_groups=False, num_epochs=1) as reader:
        ds = make_petastorm_dataset(reader)
        items = list(ds.as_numpy_iterator())
    assert len(items) == 20
    assert [int(x.id) for x in items] == list(range(20))
    assert items[3].u16 == 6 and items[3].u16.dtype == np.int32
    assert items[3].name == b"row_3"
    np.testing.assert_array_equal(items[3].vec, np.full(3, 3, np.float32))


def test_tf_data_pipeline_ops(tf_dataset_url):
    with make_reader(tf_dataset_url, reader_pool_type="serial",
                     shuffle_row_groups=False, num_epochs=1) as reader:
        ds = make_petastorm_dataset(reader)
        total = ds.map(lambda row: row.id).reduce(np.int64(0), lambda a, b: a + b)
        assert int(total) == sum(range(20))


def test_ngram_rejected(tf_dataset_url):
    ngram = NGram({0: ["vec"], 1: ["vec"]}, 1, "id")
    with make_reader(tf_dataset_url, ngram=ngram, num_epochs=1) as reader:
        with pytest.raises(PetastormTpuError, match="NGram"):
            make_petastorm_dataset(reader)
