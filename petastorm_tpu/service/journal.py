"""Dispatcher session journal: optional warm restarts that skip re-sends.

Dispatcher crash recovery does NOT need this file: a fresh dispatcher
reconstructs its sessions from its peers (clients re-hello with their job
blob and re-send unresolved ledger items; workers rejoin and report what
they are still executing - see :mod:`petastorm_tpu.service.dispatcher`).
The journal is the *warm* variant: with ``Dispatcher(journal_path=...)``
(CLI ``--journal``) the control-plane events that define a session -
client hellos, enqueued work items, acks, purges - are appended to a
length-prefixed :mod:`petastorm_tpu.service.wire` record file, and a
restarted dispatcher replays it into ready-to-serve client sessions before
it accepts a single connection.  A reconnecting client is then told (via
``hello_ok``'s ``known`` ordinal list) which of its ledger items the
dispatcher already holds, so its resync skips re-sending them - the
restart costs one reconnect handshake instead of a window's worth of
re-enqueues.

Only control-plane state is journaled.  Result *bodies* (the multi-MB
column payloads in the redelivery buffer) never touch the journal: a
journal-restored item that was delivered-but-unacked at crash time simply
re-executes, and the client's per-ordinal ledger drops the duplicate -
exactly the cold-recovery semantics, paid only for the ack-batch-sized
tail.  Requeue ``attempt`` counters restore from the *enqueued* value, so
a restart is slightly generous to items that were mid-requeue (documented,
deliberate: the budget is a safety valve, not an exactness invariant).

Durability is flush-per-record by default: a host power-loss can truncate
the tail, and :meth:`ServiceJournal.load` stops cleanly at the first
short/undecodable record (peer reconstruction covers whatever the tail
lost).  ``fsync=True`` (CLI ``--journal-fsync``) additionally fsyncs every
record - each append then pays a device round-trip (metered as
``service.journal_fsyncs``), in exchange for a tail no OS buffer can eat;
size that tradeoff against how much a hot-standby's re-fetch of a lost
tail costs (docs/operations.md "Dispatcher HA").  The file auto-compacts -
acked items are dropped and the journal rewritten - once the append log
outgrows its live state 4x.

Beyond the file, the journal doubles as the dispatcher's **live session
mirror** (``path=None`` keeps the mirror with no file at all), and
:meth:`attach_tail` exposes it as a stream: a subscriber receives a
state-reconstructing snapshot plus every subsequent logical record, in
order, regardless of file compaction (compaction rewrites bytes, never
live state - which is exactly why the tail is logical records, not file
offsets).  The hot-standby dispatcher tails this stream over the wire as
``journal_sync`` frames to keep warm (:mod:`petastorm_tpu.service.
dispatcher`).  A monotonic ``epoch`` record persists the split-brain
fencing epoch across restarts.
"""

from __future__ import annotations

import collections
import logging
import os
import struct
import threading
from typing import Any, Dict, Optional

from petastorm_tpu.service import wire
from petastorm_tpu.service.wire import WireFormatError

logger = logging.getLogger(__name__)

_LEN = struct.Struct("!I")
#: a single journal record larger than this is a corrupt length prefix
#: (records are hellos and work-item stubs, tens of KB at most)
_MAX_RECORD = 64 << 20
#: compact when the file exceeds this AND 4x the live-state size
_COMPACT_MIN_BYTES = 4 << 20


class _Session:
    """In-memory mirror of one client's journaled state (the compaction
    source and the restart payload)."""

    __slots__ = ("hello", "items")

    def __init__(self, hello: Dict[str, Any]):
        self.hello = hello
        #: ordinal -> work-item wire fields, insertion-ordered (the replay
        #: re-enqueues in the order the client ventilated)
        self.items: "collections.OrderedDict[int, Dict]" = \
            collections.OrderedDict()


class ServiceJournal:
    """Append-only session journal for one dispatcher (see module doc).

    Lifecycle: ``load()`` parses any existing file into session dicts (the
    dispatcher turns them into client states), then ``open()`` compacts and
    starts appending.  All methods are thread-safe; appends flush so an
    ordinary process death (the recovery scenario) loses nothing.
    """

    def __init__(self, path: Optional[str], *, fsync: bool = False,
                 fsync_counter=None):
        #: ``path=None`` is a pure in-memory mirror: ``load``/``open`` are
        #: no-ops and appends only update live state (what a journal-less
        #: dispatcher feeds its hot standby from, and what a standby
        #: accumulates before promotion).
        self._path = path
        self._fsync = bool(fsync)
        self._fsync_counter = fsync_counter
        self.fsyncs = 0
        self._lock = threading.Lock()
        self._fh = None
        self._bytes = 0
        self._sessions: Dict[str, _Session] = {}
        #: count of logical records applied (monotonic; tail stream position)
        self.seq = 0
        #: split-brain fencing epoch, 0 until a dispatcher stamps one
        self.epoch = 0
        self._tails = []

    # -- restart side ----------------------------------------------------------

    def load(self) -> Dict[str, _Session]:
        """Parse the journal (tolerating a truncated tail) into sessions;
        returns ``{client_id: _Session}``.  Call before :meth:`open`."""
        if self._path is None or not os.path.exists(self._path):
            return {}
        records = 0
        with open(self._path, "rb") as fh:
            while True:
                hdr = fh.read(_LEN.size)
                if len(hdr) < _LEN.size:
                    break
                (length,) = _LEN.unpack(hdr)
                if length > _MAX_RECORD:
                    logger.warning("journal %s: corrupt record length %d;"
                                   " stopping replay here", self._path, length)
                    break
                body = fh.read(length)
                if len(body) < length:
                    break  # crash-truncated tail: expected, not an error
                try:
                    rec = wire.loads(body)
                except WireFormatError:
                    logger.warning("journal %s: undecodable record after %d"
                                   " good one(s); stopping replay here",
                                   self._path, records)
                    break
                if isinstance(rec, dict):
                    self._apply(rec)
                    records += 1
        logger.info("journal %s: replayed %d record(s) into %d session(s),"
                    " %d unresolved item(s)", self._path, records,
                    len(self._sessions),
                    sum(len(s.items) for s in self._sessions.values()))
        return dict(self._sessions)

    def _apply(self, rec: Dict[str, Any]) -> None:
        kind, cid = rec.get("r"), rec.get("client")
        if kind == "epoch":
            value = rec.get("epoch")
            if isinstance(value, int) and value > self.epoch:
                self.epoch = value
            return
        if not isinstance(cid, str):
            return
        if kind == "hello":
            session = self._sessions.get(cid)
            if session is None:
                self._sessions[cid] = _Session(rec)
            else:
                session.hello = rec  # reconnects refresh the job blob
        elif kind == "enq":
            session = self._sessions.get(cid)
            item = rec.get("item")
            if session is not None and isinstance(item, dict) \
                    and isinstance(item.get("o"), int):
                self._sessions[cid].items[item["o"]] = item
        elif kind == "ack":
            session = self._sessions.get(cid)
            if session is not None:
                for ordinal in rec.get("ordinals") or ():
                    session.items.pop(ordinal, None)
        elif kind == "purge":
            self._sessions.pop(cid, None)

    # -- append side -----------------------------------------------------------

    def open(self) -> "ServiceJournal":
        """Compact-rewrite the loaded state and start appending (no-op for
        an in-memory mirror)."""
        with self._lock:
            if self._path is not None:
                self._rewrite_locked()
        return self

    def append_hello(self, cid: str, hello: Dict[str, Any]) -> None:
        self._append(dict(hello, r="hello", client=cid))

    def append_enqueue(self, cid: str, item: Dict[str, Any]) -> None:
        self._append({"r": "enq", "client": cid, "item": item})

    def append_ack(self, cid: str, ordinals) -> None:
        self._append({"r": "ack", "client": cid, "ordinals": list(ordinals)})

    def append_purge(self, cid: str) -> None:
        self._append({"r": "purge", "client": cid})

    def set_epoch(self, epoch: int) -> None:
        """Stamp (and persist, if file-backed) the fencing epoch."""
        self._append({"r": "epoch", "epoch": int(epoch)})

    def ingest(self, rec) -> None:
        """Apply one record received over the wire (standby sync path)."""
        if isinstance(rec, dict):
            self._append(rec)

    def _append(self, rec: Dict[str, Any]) -> None:
        encoded = None
        if self._fh is not None:
            try:
                encoded = wire.dumps(rec)
            except WireFormatError:
                # a hello with out-of-domain extras must not kill the
                # control plane; the session just won't warm-restart
                logger.warning("journal: unencodable record dropped (%r)",
                               rec.get("r"))
                return
        with self._lock:
            self._apply(rec)
            self.seq += 1
            if self._fh is not None:
                if encoded is None:
                    try:
                        encoded = wire.dumps(rec)
                    except WireFormatError:
                        encoded = None
                if encoded is not None:
                    self._fh.write(_LEN.pack(len(encoded)) + encoded)
                    self._fh.flush()
                    if self._fsync:
                        os.fsync(self._fh.fileno())
                        self.fsyncs += 1
                        if self._fsync_counter is not None:
                            self._fsync_counter.add(1)
                    self._bytes += _LEN.size + len(encoded)
                    if self._bytes > _COMPACT_MIN_BYTES \
                            and self._bytes > 4 * self._live_bytes_locked():
                        self._rewrite_locked()
            for fn in list(self._tails):
                try:
                    fn(self.seq, rec)
                except Exception:  # noqa: BLE001 - a broken tail must not
                    self._tails.remove(fn)  # stall the control plane

    # -- streaming tail (hot-standby sync) -------------------------------------

    def attach_tail(self, fn):
        """Subscribe ``fn(seq, rec)`` to every subsequent logical record.

        Returns ``(snapshot_records, seq)``: replaying the snapshot then the
        streamed records reconstructs this journal's live state exactly.
        ``fn`` runs under the journal lock and must never block (push to a
        bounded queue; a raising tail is detached).
        """
        with self._lock:
            records = self._snapshot_records_locked()
            self._tails.append(fn)
            return records, self.seq

    def detach_tail(self, fn) -> None:
        with self._lock:
            try:
                self._tails.remove(fn)
            except ValueError:
                pass

    def _snapshot_records_locked(self):
        records = []
        if self.epoch:
            records.append({"r": "epoch", "epoch": self.epoch})
        for cid, session in self._sessions.items():
            records.append(session.hello)
            records.extend({"r": "enq", "client": cid, "item": item}
                           for item in session.items.values())
        return records

    def sessions(self) -> Dict[str, _Session]:
        with self._lock:
            return dict(self._sessions)

    def reset(self) -> None:
        """Drop all mirrored state (a standby starting a fresh re-sync)."""
        with self._lock:
            self._sessions.clear()
            self.epoch = 0

    def _live_bytes_locked(self) -> int:
        total = 0
        for session in self._sessions.values():
            total += len(session.hello.get("factory") or b"") + 256
            for item in session.items.values():
                total += len(item.get("blob") or b"") + 64
        return total

    def _rewrite_locked(self) -> None:
        """Rewrite the file from the live mirror (compaction + open)."""
        if self._fh is not None:
            self._fh.close()
        tmp = self._path + ".tmp"
        with open(tmp, "wb") as fh:
            size = 0
            for rec in self._snapshot_records_locked():
                try:
                    encoded = wire.dumps(rec)
                except WireFormatError:
                    logger.warning("journal: unencodable record dropped in"
                                   " rewrite (%r)", rec.get("r"))
                    continue
                fh.write(_LEN.pack(len(encoded)) + encoded)
                size += _LEN.size + len(encoded)
            if self._fsync:
                fh.flush()
                os.fsync(fh.fileno())
        os.replace(tmp, self._path)
        self._fh = open(self._path, "ab")
        self._bytes = size

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
