"""``petastorm-tpu-diagnose``: one-command pipeline bottleneck diagnosis.

Runs a short telemetered read over a dataset (or a generated synthetic one)
and prints the ``pipeline_report()`` bottleneck summary - which stage
(ventilate / decode / transform) dominates, and whether queue time points at
the worker plane or the consumer.  Optionally exports the run's span
timeline as Chrome ``trace_event`` JSON for Perfetto.

``--watch`` switches to live mode: the read runs in the background while a
``top``-style view refreshes every ``--interval`` seconds from the reader's
metrics sampler - per-stage rates and interval p50/p99, queue depths,
queue-wait rates, faults/liveness interventions, and the interval's dominant
stage.  ``--duration S`` bounds the capture (the read stops cleanly after S
seconds); ``--metrics-port`` additionally serves the Prometheus endpoint for
the run's lifetime.

Examples::

    petastorm-tpu-diagnose file:///data/imagenet --pool thread --workers 4
    petastorm-tpu-diagnose --synthetic --trace-out /tmp/trace.json
    petastorm-tpu-diagnose file:///data/imagenet --watch --duration 30
    python -m petastorm_tpu.tools.diagnose --synthetic --json

Deliberately jax-free (reader + pool plane only): it runs anywhere the host
pipeline runs, TPU attached or not.  For the device feed path use
``petastorm-tpu-throughput --method jax --telemetry``.
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional

from petastorm_tpu.errors import ReaderClosedError
from petastorm_tpu.telemetry import Telemetry, dominant_stage
from petastorm_tpu.telemetry.report import STAGE_ORDER


def _positive_seconds(value: str) -> float:
    """argparse type for strictly-positive second values (an interval of 0
    would busy-spin the watch loop and disable the reader's sampler)."""
    parsed = float(value)
    if parsed <= 0:
        raise argparse.ArgumentTypeError(
            f"must be a positive number of seconds, got {value!r}")
    return parsed


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-diagnose",
        description="Run a short telemetered read and print the pipeline"
                    " bottleneck report")
    parser.add_argument("dataset_url", nargs="?", default=None,
                        help="dataset to read (omit with --synthetic)")
    parser.add_argument("--synthetic", action="store_true",
                        help="generate a small synthetic dataset in a temp"
                             " dir (default when no dataset_url is given)")
    parser.add_argument("--rows", type=int, default=200,
                        help="synthetic dataset size (--synthetic)")
    parser.add_argument("--row-group-size", type=int, default=20,
                        help="synthetic rowgroup size (--synthetic)")
    parser.add_argument("--method", default="batch", choices=("batch", "row"),
                        help="batch=make_batch_reader (columnar),"
                             " row=make_reader")
    parser.add_argument("-p", "--pool-type", default="thread",
                        choices=("thread", "process", "serial"))
    parser.add_argument("-w", "--workers-count", type=int, default=3)
    parser.add_argument("--num-epochs", type=int, default=1)
    parser.add_argument("--max-batches", type=int, default=0,
                        help="stop after N rowgroup batches (0 = read all)")
    parser.add_argument("--trace-out", metavar="PATH", default=None,
                        help="write the run's Chrome trace_event JSON here"
                             " (open in Perfetto / chrome://tracing)")
    parser.add_argument("--json", action="store_true",
                        help="print the raw telemetry snapshot as JSON"
                             " instead of the human-readable report")
    parser.add_argument("--chaos", metavar="SPEC", default=None,
                        help="diagnose under injected faults (same spec"
                             " syntax as petastorm-tpu-throughput --chaos,"
                             " e.g. 'decode_fail_rate=0.05,"
                             "fail_first_reads=3')")
    parser.add_argument("--on-error", default="raise",
                        choices=("raise", "skip"),
                        help="reader failure policy; 'skip' quarantines"
                             " failing rowgroups (listed in the report)")
    parser.add_argument("--item-deadline", type=float, default=None,
                        metavar="S",
                        help="liveness: SIGKILL+respawn (process pool) or"
                             " abandon (thread pool) a worker hung on one"
                             " item for S seconds; the item is requeued")
    from petastorm_tpu.pool import parse_hedge_after

    parser.add_argument("--hedge-after", default=None, metavar="S|auto",
                        type=parse_hedge_after,
                        help="liveness: speculatively re-issue an item"
                             " running longer than S seconds to an idle"
                             " worker ('auto' = 4x telemetry decode p99)")
    parser.add_argument("--watch", action="store_true",
                        help="live mode: refresh a top-style per-stage"
                             " rate/latency/queue view every --interval"
                             " seconds while the read runs (Ctrl-C stops)")
    parser.add_argument("--interval", type=_positive_seconds, default=1.0,
                        metavar="S",
                        help="sampling + refresh interval for --watch and"
                             " the reader's metrics sampler (default 1s;"
                             " must be > 0)")
    parser.add_argument("--duration", type=float, default=None, metavar="S",
                        help="stop the read cleanly after S seconds (bounded"
                             " capture; mostly with --watch)")
    parser.add_argument("--metrics-port", type=int, default=None, metavar="N",
                        help="serve the run's metrics in Prometheus text"
                             " format on localhost:N for the run's lifetime"
                             " (0 = ephemeral; the bound port is printed)")
    parser.add_argument("--flight-record", metavar="PATH", default=None,
                        help="on a terminal failure, dump the flight record"
                             " (sampled series + trace tail) to PATH as"
                             " JSONL")
    parser.add_argument("--autotune", action="store_true",
                        help="run the closed-loop knob tuner during the read"
                             " (petastorm_tpu.autotune): workers /"
                             " results-queue bound adapt to the live metrics"
                             " sampler; the report lists every decision and"
                             " --watch frames show the autotune.* counters")
    parser.add_argument("--cache-type", default="null",
                        choices=("null", "memory", "local-disk", "shared"),
                        help="decoded-rowgroup cache (docs/operations.md"
                             " 'Warm cache'); 'shared' = the host-wide warm"
                             " tier - --watch then renders a live cache:"
                             " hit/miss/hit-rate line, and re-running the"
                             " command shows the warm profile")
    parser.add_argument("--cache-location", default=None, metavar="PATH",
                        help="names the cache tier (same location = same"
                             " shared tier host-wide; also the disk"
                             " directory)")
    parser.add_argument("--seed", type=int, default=None, metavar="N",
                        help="shuffle the read with this seed (enables"
                             " shuffle_row_groups and, via"
                             " deterministic='auto', seed-stable delivery)")
    parser.add_argument("--deterministic", default="auto",
                        choices=("auto", "seed", "off"),
                        help="delivery-order mode (docs/operations.md"
                             " 'Reproducibility'): 'seed' releases batches"
                             " in plan order so the stream digest is"
                             " bit-identical across configurations")
    parser.add_argument("--service-address", default=None,
                        metavar="HOST:PORT",
                        help="read through the disaggregated ingest service"
                        " at this dispatcher instead of a local pool"
                        " (failover list 'a:p,b:p' accepted)")
    parser.add_argument("--trace-items", type=int, default=None, metavar="N",
                        help="arm per-item DISTRIBUTED tracing on the"
                        " service plane: every Nth item carries a trace"
                        " context through client/dispatcher/worker; the"
                        " merged cross-process timeline lands in"
                        " --trace-out and the service.hop.* decomposition"
                        " renders in --watch (needs --service-address;"
                        " default off)")
    parser.add_argument("--stream-digest", action="store_true",
                        help="print the run's stream certificate as a"
                             " machine-parseable 'stream_digest ...' line -"
                             " run twice (any worker count / pool / chaos)"
                             " and diff the lines to verify seed-stable"
                             " delivery; also under --json")
    return parser


def run_diagnosis(dataset_url: str, method: str = "batch",
                  pool_type: str = "thread", workers_count: int = 3,
                  num_epochs: Optional[int] = 1, max_batches: int = 0,
                  telemetry: Optional[Telemetry] = None,
                  chaos=None, on_error: str = "raise",
                  item_deadline_s: Optional[float] = None,
                  hedge_after_s=None,
                  duration_s: Optional[float] = None,
                  metrics_port: Optional[int] = None,
                  flight_record_path: Optional[str] = None,
                  sample_interval_s: Optional[float] = None,
                  autotune=False,
                  cache_type: str = "null",
                  cache_location: Optional[str] = None,
                  shuffle_seed: Optional[int] = None,
                  deterministic: str = "auto",
                  service_address: Optional[str] = None,
                  trace_items=None,
                  on_reader=None) -> dict:
    """Read ``dataset_url`` with telemetry enabled; returns a result dict
    with ``rows``, ``batches``, ``snapshot``, ``report``,
    ``dominant_stage``, the reader's fault ledger
    (``quarantined_rowgroups``) and a ``liveness`` verdict (hung-kill /
    hedge / circuit counts + slowest observed in-flight item age) - also
    the programmatic entry the tests use.

    ``duration_s`` bounds the read in wall-clock time (the iterator stops
    cleanly once elapsed - the ``--watch --duration`` capture).
    ``metrics_port``/``flight_record_path``/``sample_interval_s`` pass
    through to the reader (docs/operations.md "Live monitoring").
    ``on_reader`` is called with the live Reader right after construction -
    the watch loop uses it to poll ``reader.sampler`` and diagnostics."""
    from petastorm_tpu.reader import make_batch_reader, make_reader

    tele = telemetry or Telemetry()
    factory = make_batch_reader if method == "batch" else make_reader
    rows = 0
    batches = 0
    slowest_inflight = 0.0
    t_start = time.monotonic()
    with factory(dataset_url, reader_pool_type=pool_type,
                 workers_count=workers_count, num_epochs=num_epochs,
                 shuffle_row_groups=shuffle_seed is not None,
                 shuffle_seed=shuffle_seed, deterministic=deterministic,
                 telemetry=tele,
                 chaos=chaos, on_error=on_error,
                 item_deadline_s=item_deadline_s,
                 hedge_after_s=hedge_after_s,
                 metrics_port=metrics_port,
                 flight_record_path=flight_record_path,
                 sample_interval_s=sample_interval_s,
                 cache_type=cache_type, cache_location=cache_location,
                 service_address=service_address, trace_items=trace_items,
                 autotune=autotune or None) as reader:
        if on_reader is not None:
            on_reader(reader)

        def _sample_inflight() -> None:
            # slowest in-flight item age: the number a wedged production
            # pipeline is triaged by (whose item is old, and how old)
            nonlocal slowest_inflight
            for _i, _o, age in reader.diagnostics.get("workers_busy", []):
                slowest_inflight = max(slowest_inflight, age)

        def _out_of_time() -> bool:
            return (duration_s is not None
                    and time.monotonic() - t_start >= duration_s)

        if method == "batch":
            for batch in reader.iter_batches():
                rows += batch.num_rows
                batches += 1
                _sample_inflight()
                if max_batches and batches >= max_batches:
                    break
                if _out_of_time():
                    break
        else:
            for _ in reader:
                rows += 1
                if rows % 50 == 0:  # cheap, but not per-row
                    _sample_inflight()
                # the duration check IS per-row (one clock read, only when a
                # bound is set): a slow decode must not overshoot the
                # "bounded capture" contract by up to 50 rows
                if duration_s is not None and _out_of_time():
                    break
        _sample_inflight()
        quarantined = reader.quarantined_rowgroups
        final_diag = reader.diagnostics
        bound_port = (reader.metrics_server.port
                      if reader.metrics_server is not None else None)
    snapshot = tele.snapshot()
    counters = snapshot.get("counters", {})
    liveness = {
        "hung_workers_killed": final_diag.get("hung_workers_killed", 0),
        "hung_workers_abandoned": final_diag.get("hung_workers_abandoned", 0),
        "hedged_items": final_diag.get("hedged_items", 0),
        "hedge_wins": final_diag.get("hedge_wins", 0),
        "requeued_items": final_diag.get("requeued_items", 0),
        # parent-process view only: spawned process-pool workers hold their
        # own breaker copies and record opens into their own telemetry
        "circuit_opens": int(counters.get("liveness.circuit_opens", 0)),
        "circuit_breaker": final_diag.get("circuit_breaker"),
        # breaker signal that DOES cross the process boundary: rowgroups
        # quarantined because a worker-side circuit was failing fast
        "circuit_open_quarantines": sum(
            1 for e in quarantined if e.get("exc_type") == "CircuitOpenError"),
        "slowest_inflight_age_s": round(slowest_inflight, 3),
    }
    return {"rows": rows, "batches": batches, "snapshot": snapshot,
            "report": tele.pipeline_report(),
            "dominant_stage": dominant_stage(snapshot),
            "quarantined_rowgroups": quarantined,
            "liveness": liveness,
            # knob values + decision log when --autotune tuned the run
            "autotune": final_diag.get("autotune"),
            # the static planner's seed verdict (per-knob provenance:
            # profile / metadata / default / pinned) when it ran
            "planner": final_diag.get("planner"),
            # the run's stream certificate (docs/operations.md
            # "Reproducibility"); operators and the CI determinism smoke
            # share this one code path via --stream-digest
            "stream_digest": final_diag.get("stream_digest"),
            "deterministic": final_diag.get("deterministic"),
            "metrics_port": bound_port,
            "telemetry": tele}


#: watch-frame fault counters worth a line the moment they move (autotune
#: moves ride along so a watched run shows the tuner acting live)
_WATCH_FAULT_PREFIXES = ("errors.", "liveness.", "io.retries", "autotune.")

#: short watch labels per queue-wait counter; the counter LIST itself comes
#: from report._QUEUE_WAITS (one source of truth - a new queue-wait counter
#: added there shows up in watch frames automatically, with its report
#: meaning until a short label is added here)
_WATCH_QUEUE_LABELS = {
    "queue.input_full_wait_s": "ventilator blocked (workers saturated)",
    "queue.results_full_wait_s": "workers blocked (consumer-bound)",
    "queue.results_empty_wait_s": "consumer starved (worker-bound)",
}


def _watch_queue_waits():
    from petastorm_tpu.telemetry.report import _QUEUE_WAITS

    return [(name, _WATCH_QUEUE_LABELS.get(name, meaning))
            for name, meaning in _QUEUE_WAITS]


def render_watch_frame(point: Dict, diagnostics: Optional[Dict] = None,
                       elapsed_s: float = 0.0) -> str:
    """One ``--watch`` frame from a sampler point (+ optional live reader
    diagnostics): per-stage rate and interval p50/p99, queue depths and
    wait rates, fault/liveness counters, and the interval's dominant stage.
    Pure function of its inputs (tests render from canned points)."""
    lines = [f"== petastorm-tpu watch  t={elapsed_s:6.1f}s  "
             f"interval={point.get('dt_s', 0.0):.2f}s =="]
    rates = point.get("rates", {})
    rows_rate = rates.get("reader.rows_emitted", 0.0)
    batches_rate = rates.get("reader.batches_consumed", 0.0)
    lines.append(f"rows/s: {rows_rate:10.1f}    batches/s:"
                 f" {batches_rate:7.2f}    total rows:"
                 f" {point.get('counters', {}).get('reader.rows_emitted', 0):.0f}")
    stages = point.get("stages", {})
    if stages:
        ordered = [s for s in STAGE_ORDER if s in stages]
        ordered += sorted(set(stages) - set(STAGE_ORDER))
        lines.append(f"{'stage':<16} {'rate/s':>8} {'p50_ms':>8}"
                     f" {'p99_ms':>8} {'busy%':>7}")
        busiest, busiest_frac = "", 0.0
        for name in ordered:
            st = stages[name]
            if st["count"] == 0 and st["rate_per_s"] == 0.0:
                lines.append(f"{name:<16} {'-':>8} {'-':>8} {'-':>8} {'-':>7}"
                             "  (no samples yet)")
                continue
            p50 = (f"{st['p50_s'] * 1e3:>8.1f}"
                   if st.get("p50_s") is not None else f"{'-':>8}")
            p99 = (f"{st['p99_s'] * 1e3:>8.1f}"
                   if st.get("p99_s") is not None else f"{'-':>8}")
            frac = st.get("busy_frac", 0.0)
            lines.append(f"{name:<16} {st['rate_per_s']:>8.2f} {p50} {p99}"
                         f" {100.0 * frac:>6.1f}%")
            if frac > busiest_frac:
                busiest, busiest_frac = name, frac
        lines.append(f"dominant stage (this interval): "
                     f"{busiest or '(no samples yet)'}")
    waits = [(label, rates.get(name, 0.0))
             for name, label in _watch_queue_waits() if rates.get(name)]
    if waits:
        lines.append("queue wait (blocked-seconds/second):")
        lines.extend(f"  {rate:6.2f}  {label}" for label, rate in waits)
    gauges = point.get("gauges", {})
    depth_parts = [f"{name.split('.', 1)[1]}={gauges[name]:g}"
                   for name in sorted(gauges)
                   if "depth" in name or "queue" in name]
    if depth_parts:
        lines.append("queue depths: " + "  ".join(depth_parts))
    counters = point.get("counters", {})
    if counters.get("cache.hits") or counters.get("cache.misses") \
            or counters.get("cache.l2_hits"):
        # the shared warm tier's pulse: per-interval hit/miss rates, the
        # cumulative hit-rate gauge, resident L1 bytes and eviction total
        hit_rate = gauges.get("cache.hit_rate", 0.0)
        lines.append(
            f"cache: {rates.get('cache.hits', 0.0):6.1f} hit/s"
            f"  {rates.get('cache.misses', 0.0):6.1f} miss/s"
            f"  {rates.get('cache.l2_hits', 0.0):5.1f} l2hit/s"
            f"  hit-rate {100.0 * hit_rate:5.1f}%"
            f"  L1 {gauges.get('cache.bytes', 0.0) / 2 ** 20:.0f}MB"
            f"  evictions {counters.get('cache.evictions', 0):g}"
            # post-transform entries (decode AND transform skipped on a hit)
            f"  xform {counters.get('cache.transform_hits', 0):g}h"
            f"/{counters.get('cache.transform_stores', 0):g}s")
    if any(n.startswith("service.") for n in counters) \
            or any(n.startswith("service.") for n in gauges):
        # the disaggregated ingest plane's pulse (client-side series): a
        # just-started fleet with nothing delivered yet renders an explicit
        # "(no samples yet)" line instead of vanishing from the frame
        results_total = counters.get("service.results", 0)
        if results_total:
            lines.append(
                f"service: {rates.get('service.results', 0.0):6.1f} results/s"
                f"  {rates.get('service.frame_bytes_received', 0.0) / 2 ** 10:8.1f} KB/s in"
                # wire-encoding mix: a hot pickle fallback (pkl > 0 and
                # climbing) means the binary plane is NOT carrying the data
                # path - visible here, not just in a slow bench
                f"  wire bin={counters.get('service.frames_binary', 0):g}"
                f"/shm={counters.get('service.frames_shm', 0):g}"
                f"/pkl={counters.get('service.frames_pickle_fallback', 0):g}"
                f"  requeued {counters.get('service.requeued_items', 0):g}"
                f"  reconnects {counters.get('service.reconnects', 0):g}"
                f"  connected {gauges.get('service.connected', 0):g}")
        else:
            lines.append("service: (no samples yet)")
    hops = point.get("hops", {})
    if hops:
        # per-hop latency decomposition of traced service items, in wire
        # order (the seven legs telescope to the end-to-end 'total')
        hop_order = ("client_serialize", "dispatcher_queue", "relay",
                     "worker_queue", "worker_exec", "return_relay",
                     "client_deserialize", "total")
        ordered_hops = [h for h in hop_order if h in hops]
        ordered_hops += sorted(set(hops) - set(hop_order))
        parts = []
        for name in ordered_hops:
            h = hops[name]
            p50 = h.get("p50_s")
            parts.append(f"{name}={p50 * 1e3:.1f}ms"
                         if p50 is not None else f"{name}=-")
        lines.append("hops p50 (traced items): " + "  ".join(parts))
    faults = {n: v for n, v in counters.items()
              if n.startswith(_WATCH_FAULT_PREFIXES) and v}
    if faults:
        lines.append("faults/liveness (totals): " + "  ".join(
            f"{n}={v:g}" for n, v in sorted(faults.items())))
    if diagnostics:
        if diagnostics.get("planner"):
            # where this run's starting knobs came from (one compact line;
            # the full provenance rides the post-run report)
            lines.append(render_planner_verdict(diagnostics["planner"],
                                                compact=True))
        busy = diagnostics.get("workers_busy", [])
        if busy:
            oldest = max(age for _i, _o, age in busy)
            lines.append(f"in-flight: {len(busy)} worker(s) busy, oldest item"
                         f" {oldest:.1f}s (worker, item, age):"
                         f" {busy[:6]}")
        lines.append(
            f"consumed {diagnostics.get('consumed_items', 0)}"
            f"/{diagnostics.get('expected_items', '?')} items"
            f"  requeued={diagnostics.get('requeued_items', 0)}"
            f"  hedged={diagnostics.get('hedged_items', 0)}"
            f"  hung_killed={diagnostics.get('hung_workers_killed', 0)}"
            f"  skipped={diagnostics.get('skipped_rowgroups', 0)}")
    return "\n".join(lines)


def _watch(args, url: str, chaos) -> int:
    """Drive ``run_diagnosis`` in a background thread while rendering watch
    frames from the reader's sampler every ``--interval`` seconds."""
    tele = Telemetry()
    box: Dict = {}
    reader_box: Dict = {}
    num_epochs = args.num_epochs if args.num_epochs > 0 else None
    # completion is signaled via an Event, NOT Thread.join/is_alive: a
    # Thread.join(timeout) interrupted by Ctrl-C corrupts the thread state on
    # this interpreter (cpython bpo-45274: is_alive() then reports False
    # while the thread still runs), which silently dropped the final report
    done = threading.Event()

    def _run() -> None:
        try:
            box["result"] = run_diagnosis(
                url, method=args.method, pool_type=args.pool_type,
                workers_count=args.workers_count, num_epochs=num_epochs,
                max_batches=args.max_batches, telemetry=tele, chaos=chaos,
                on_error=args.on_error, item_deadline_s=args.item_deadline,
                hedge_after_s=args.hedge_after, duration_s=args.duration,
                metrics_port=args.metrics_port,
                flight_record_path=args.flight_record,
                sample_interval_s=args.interval,
                autotune=args.autotune,
                cache_type=args.cache_type,
                cache_location=args.cache_location,
                shuffle_seed=args.seed,
                deterministic=args.deterministic,
                service_address=args.service_address,
                trace_items=args.trace_items,
                on_reader=lambda r: reader_box.update(reader=r))
        except BaseException as exc:  # noqa: BLE001 - reported on main thread
            box["error"] = exc
        finally:
            done.set()

    thread = threading.Thread(target=_run, daemon=True,
                              name="petastorm-tpu-diagnose-read")
    thread.start()
    t0 = time.monotonic()
    interrupted = False
    clear = "\x1b[H\x1b[2J" if sys.stdout.isatty() else ""
    try:
        while not done.wait(timeout=args.interval):
            reader = reader_box.get("reader")
            sampler = getattr(reader, "sampler", None)
            point = sampler.latest() if sampler is not None else None
            if point is None:
                continue
            try:
                diag = reader.diagnostics
            except Exception:  # noqa: BLE001 - reader may be mid-teardown
                diag = None
            frame = render_watch_frame(point, diag,
                                       elapsed_s=time.monotonic() - t0)
            if reader is not None and reader.metrics_server is not None:
                frame += (f"\nmetrics: http://127.0.0.1:"
                          f"{reader.metrics_server.port}/metrics")
            print(f"{clear}{frame}" + ("" if clear else "\n"), flush=True)
    except KeyboardInterrupt:
        interrupted = True
        reader = reader_box.get("reader")
        if reader is not None:
            reader.stop()
        done.wait(timeout=10)
    if args.trace_out:
        tele.export_chrome_trace(args.trace_out)
        print(f"chrome trace written to {args.trace_out}"
              " (load in Perfetto / chrome://tracing)")
    if not box:
        # the read thread neither returned nor raised within the post-Ctrl-C
        # grace (a wedged pipeline the stop could not unwedge): that is a
        # failure - never a silent success exit
        print("watch aborted: the read did not stop within 10s of Ctrl-C"
              " (pipeline wedged?); state below is the last observed",
              file=sys.stderr)
        print(tele.pipeline_report())
        return 1
    error = box.get("error")
    if interrupted and isinstance(error, ReaderClosedError):
        # Ctrl-C is the documented way to END a watch, not a failure: our
        # own stop() is what raised ReaderClosedError in the read thread
        print("watch stopped")
        print(tele.pipeline_report())
        return 0
    if error is not None:
        print(f"read failed: {type(error).__name__}: {error}",
              file=sys.stderr)
        diag = getattr(error, "diagnostics", None)
        if isinstance(diag, dict) and diag.get("flight_recorder"):
            print(f"flight record captured"
                  f" ({len(diag['flight_recorder']['points'])} points"
                  + (f"; written to {args.flight_record}"
                     if args.flight_record else "") + ")",
                  file=sys.stderr)
        print(tele.pipeline_report())
        return 1
    result = box.get("result")
    if result is not None:
        # a Ctrl-C'd batch read ends CLEANLY (iter_batches absorbs the
        # stop), so it reaches here too - name it a stop, not a finish
        print(f"watch {'stopped' if interrupted else 'finished'}:"
              f" read {result['rows']} rows")
        print(result["report"])
        print(render_liveness_verdict(result["liveness"]))
        if result.get("planner"):
            print(render_planner_verdict(result["planner"]))
        if result.get("autotune"):
            print(render_autotune_verdict(result["autotune"]))
    return 0


def render_planner_verdict(planner: dict, compact: bool = False) -> str:
    """The static planner's seed verdict as text: every planned knob with
    its provenance, plus (non-compact) the flight profile it planned from.
    ``compact=True`` is the one-line ``--watch`` form."""
    knobs = planner.get("knobs", {})
    parts = [f"{name}={knob['value']}({knob['source']})"
             for name, knob in sorted(knobs.items())]
    line = "planner: " + ("  ".join(parts) if parts else "(no knobs planned)")
    if compact:
        return line
    lines = [line]
    for name, knob in sorted(knobs.items()):
        lines.append(f"  {name}={knob['value']} [{knob['source']}]"
                     f" {knob.get('why', '')}")
    profile = planner.get("profile")
    if profile:
        observed = profile.get("observed_rows_per_sec")
        lines.append(
            f"  flight profile: {planner.get('profile_path')}"
            + (f" (observed {observed:.0f} rows/s)"
               if isinstance(observed, (int, float)) else ""))
    else:
        lines.append(
            "  no flight profile yet (written at reader stop; next cold"
            f" start seeds from {planner.get('profile_path')})")
    return "\n".join(lines)


def render_autotune_verdict(autotune: dict) -> str:
    """Compact summary of what the tuner did: final knob values plus the
    per-decision trail (knob, move, rates, kept/reverted)."""
    knobs = "  ".join(f"{k}={v}" for k, v in
                      sorted(autotune.get("knobs", {}).items()))
    lines = [f"autotune: {autotune.get('moves_applied', 0)} move(s),"
             f" {autotune.get('moves_kept', 0)} kept,"
             f" {autotune.get('moves_reverted', 0)} reverted;"
             f" final knobs: {knobs or '(none)'}"]
    for d in autotune.get("decisions", []):
        rate = (f"{d['measured_rate']:.0f}/s"
                if d.get("measured_rate") is not None else "?")
        lines.append(f"  {d['action']} {d['knob']} {d['from']}->{d['to']}"
                     f" ({d['reason']}): {d['outcome']} @ {rate}")
    return "\n".join(lines)


def render_stream_digest(digest: Optional[dict],
                         deterministic: Optional[str] = None) -> str:
    """Machine-parseable one-liner for the run's stream certificate - the
    line the CI determinism smoke (and an operator diffing two runs) greps
    and compares (docs/operations.md "Reproducibility")."""
    if not digest:
        return "stream_digest unavailable"
    epochs = " ".join(f"e{e}={v}"
                      for e, v in sorted(digest.get("epochs", {}).items()))
    return ("stream_digest"
            + (f" mode={deterministic}" if deterministic else "")
            + f" combined={digest.get('combined')}"
            + f" batches={digest.get('batches')} rows={digest.get('rows')}"
            + (f" {epochs}" if epochs else ""))


def render_liveness_verdict(liveness: dict) -> str:
    """One-line liveness triage verdict from ``run_diagnosis``'s
    ``liveness`` dict - the answer to "is this pipeline wedged, and on
    what?" from one command."""
    interventions = []
    if liveness.get("hung_workers_killed"):
        interventions.append(
            f"{liveness['hung_workers_killed']} hung worker(s) killed+respawned")
    if liveness.get("hung_workers_abandoned"):
        interventions.append(
            f"{liveness['hung_workers_abandoned']} hung thread slot(s) abandoned")
    if liveness.get("hedged_items"):
        interventions.append(
            f"{liveness['hedged_items']} item(s) hedged"
            f" ({liveness.get('hedge_wins', 0)} hedge win(s))")
    if liveness.get("circuit_opens"):
        interventions.append(
            f"storage circuit opened {liveness['circuit_opens']}x")
    if liveness.get("circuit_open_quarantines"):
        # worker-side breaker activity: visible through the quarantine
        # ledger even when the breaker lives in spawned worker processes
        interventions.append(
            f"{liveness['circuit_open_quarantines']} rowgroup(s) quarantined"
            " on an open storage circuit")
    breaker = liveness.get("circuit_breaker")
    if breaker and breaker.get("state") != "closed":
        interventions.append(f"circuit breaker {breaker['state']}")
    verdict = ("liveness: " + ("; ".join(interventions) if interventions
                               else "OK (no hung-worker kills, no hedges,"
                                    " circuit closed)"))
    verdict += (f"; slowest in-flight item age observed:"
                f" {liveness.get('slowest_inflight_age_s', 0.0):.1f}s")
    return verdict


def main(argv: Optional[List[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.watch and args.json:
        # watch is a human-paced frame stream; silently printing the human
        # report under --json would break any script parsing stdout
        parser.error("--watch and --json are incompatible (watch renders"
                     " refreshing frames; use --watch with --metrics-port"
                     " for machine-readable live series)")
    if args.dataset_url is None and not args.synthetic:
        args.synthetic = True
    tmpdir = None
    url = args.dataset_url
    try:
        if url is None:
            from petastorm_tpu.test_util.synthetic import create_test_dataset

            tmpdir = tempfile.mkdtemp(prefix="petastorm_tpu_diagnose_")
            create_test_dataset(tmpdir, num_rows=args.rows,
                                row_group_size_rows=args.row_group_size)
            url = tmpdir
        chaos = None
        if args.chaos:
            from petastorm_tpu.test_util.chaos import ChaosSpec

            chaos = ChaosSpec.parse(args.chaos)
        if args.watch:
            return _watch(args, url, chaos)
        result = run_diagnosis(url, method=args.method,
                               pool_type=args.pool_type,
                               workers_count=args.workers_count,
                               num_epochs=args.num_epochs,
                               max_batches=args.max_batches,
                               chaos=chaos, on_error=args.on_error,
                               item_deadline_s=args.item_deadline,
                               hedge_after_s=args.hedge_after,
                               duration_s=args.duration,
                               metrics_port=args.metrics_port,
                               flight_record_path=args.flight_record,
                               sample_interval_s=args.interval,
                               autotune=args.autotune,
                               cache_type=args.cache_type,
                               cache_location=args.cache_location,
                               shuffle_seed=args.seed,
                               deterministic=args.deterministic,
                               service_address=args.service_address,
                               trace_items=args.trace_items)
        if args.trace_out:
            result["telemetry"].export_chrome_trace(args.trace_out)
        if args.json:
            print(json.dumps({"rows": result["rows"],
                              "batches": result["batches"],
                              "dominant_stage": result["dominant_stage"],
                              "quarantined_rowgroups":
                                  result["quarantined_rowgroups"],
                              "liveness": result["liveness"],
                              "autotune": result["autotune"],
                              "planner": result["planner"],
                              "stream_digest": result["stream_digest"],
                              "deterministic": result["deterministic"],
                              "snapshot": result["snapshot"]}))
        else:
            what = "synthetic dataset" if tmpdir else url
            print(f"read {result['rows']} rows"
                  + (f" in {result['batches']} rowgroup batches"
                     if args.method == "batch" else "")
                  + f" from {what}")
            print(result["report"])
            print(render_liveness_verdict(result["liveness"]))
            if result.get("planner"):
                print(render_planner_verdict(result["planner"]))
            if args.stream_digest:
                print(render_stream_digest(result["stream_digest"],
                                           result["deterministic"]))
            if result.get("autotune"):
                print(render_autotune_verdict(result["autotune"]))
            for entry in result["quarantined_rowgroups"]:
                print(f"quarantined: {entry['path']}#{entry['row_group']}"
                      f" (work item {entry['ordinal']}, {entry['kind']}"
                      f" error: {entry['error']})")
            if args.trace_out:
                print(f"chrome trace written to {args.trace_out}"
                      " (load in Perfetto / chrome://tracing)")
        return 0
    finally:
        if tmpdir is not None:
            shutil.rmtree(tmpdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
