"""Chaos-matrix determinism (ISSUE 10 / ROADMAP item 3): a (seed, epoch)
pair delivers a bit-identical stream - visitation order AND batch
composition - across worker counts, executor flavors, chaos kills, hangs,
hedges, mid-epoch resizes, the service transport, and a quiesce/resume
split.  Certified two ways per cell: the reader's StreamDigest and the
harness's independent crc over delivered column bytes
(test_util/matrix.py).
"""

import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from petastorm_tpu.etl.writer import write_dataset
from petastorm_tpu.reader import make_batch_reader
from petastorm_tpu.schema import Field, Schema
from petastorm_tpu.seeding import StreamDigest, derive_seed, seed_stream
from petastorm_tpu.test_util.matrix import (CellResult, MatrixCell,
                                            run_cell, run_sequence_cell,
                                            service_fleet)

SEED = 7
EPOCHS = 2


@pytest.fixture(scope="module")
def matrix_dataset(tmp_path_factory):
    """200 int rows in 20 rowgroups: small enough for many cells, enough
    rowgroups for real out-of-order completion."""
    url = str(tmp_path_factory.mktemp("det_matrix") / "ds")
    schema = Schema("DetMatrix", [Field("x", np.int64)])
    write_dataset(url, schema, [{"x": i} for i in range(200)],
                  row_group_size_rows=10)
    return url


@pytest.fixture(scope="module")
def baseline(matrix_dataset) -> CellResult:
    """The reference stream: 2 thread workers, no chaos."""
    return run_cell(matrix_dataset, SEED,
                    MatrixCell(workers=2, pool="thread"), num_epochs=EPOCHS)


def _assert_matches(result: CellResult, base: CellResult, label: str) -> None:
    assert result.rows == base.rows, label
    assert result.batch_rows == base.batch_rows, \
        f"{label}: batch boundaries differ"
    assert result.digest["combined"] == base.digest["combined"], \
        f"{label}: stream digest differs ({result.digest} vs {base.digest})"
    assert result.digest["epochs"] == base.digest["epochs"], label
    assert result.content_crc == base.content_crc, \
        f"{label}: delivered bytes differ despite equal digests"


# -- the matrix ---------------------------------------------------------------

LOCAL_CELLS = [
    MatrixCell(workers=1, pool="thread"),
    MatrixCell(workers=4, pool="thread"),
    MatrixCell(workers=2, pool="serial"),
    MatrixCell(workers=3, pool="thread", chaos="kill"),
    MatrixCell(workers=3, pool="thread", chaos="hang"),
    MatrixCell(workers=3, pool="thread", chaos="hedge"),
    MatrixCell(workers=2, pool="thread", resize=True),
    MatrixCell(workers=4, pool="thread", chaos="kill", resize=True),
    MatrixCell(workers=2, pool="thread", split="quiesce"),
    MatrixCell(workers=3, pool="thread", chaos="kill", split="quiesce"),
]


@pytest.mark.parametrize("cell", LOCAL_CELLS, ids=lambda c: c.label())
def test_local_cells_bit_identical(matrix_dataset, baseline, cell):
    """Every local-transport cell delivers the baseline's exact stream."""
    result = run_cell(matrix_dataset, SEED, cell, num_epochs=EPOCHS)
    _assert_matches(result, baseline, cell.label())


PROCESS_CELLS = [
    MatrixCell(workers=2, pool="process"),
    MatrixCell(workers=3, pool="process", chaos="kill"),
    MatrixCell(workers=2, pool="process", resize=True),
    MatrixCell(workers=2, pool="process", split="quiesce"),
]


@pytest.mark.slow
@pytest.mark.parametrize("cell", PROCESS_CELLS, ids=lambda c: c.label())
def test_process_cells_bit_identical(matrix_dataset, baseline, cell):
    """Process-pool cells (spawn cost makes these slow-marked): real
    worker processes, real os._exit kills - same stream."""
    result = run_cell(matrix_dataset, SEED, cell, num_epochs=EPOCHS)
    _assert_matches(result, baseline, cell.label())


def test_process_cell_smoke(matrix_dataset, baseline):
    """One process-pool cell stays in the tier-1 (not-slow) run: the
    cross-executor half of the invariant must not rot between slow runs."""
    result = run_cell(matrix_dataset, SEED,
                      MatrixCell(workers=2, pool="process"),
                      num_epochs=EPOCHS)
    _assert_matches(result, baseline, "2w-process")


def test_service_cells_bit_identical(matrix_dataset, baseline):
    """The service hop delivers the identical stream - plain, and across a
    mid-epoch quiesce/resume split (one fleet serves both cells)."""
    with service_fleet(n_workers=2) as (_disp, addr, _workers):
        plain = run_cell(matrix_dataset, SEED,
                         MatrixCell(transport="service"),
                         num_epochs=EPOCHS, service_address=addr)
        _assert_matches(plain, baseline, "service")
        split = run_cell(matrix_dataset, SEED,
                         MatrixCell(transport="service", split="quiesce"),
                         num_epochs=EPOCHS, service_address=addr)
        _assert_matches(split, baseline, "service-quiesce")


@pytest.mark.slow
def test_service_sigkill_quiesce_resume_digest(matrix_dataset, baseline):
    """Satellite: quiesce a service reader mid-epoch while a REAL worker
    subprocess is SIGKILLed, resume, and the combined stream digest equals
    an uninterrupted run's (the dispatcher requeues the killed worker's
    in-flight items; the reorder stage + digest chain absorb the rest)."""
    with service_fleet(n_workers=2, subprocess_workers=True) \
            as (disp, addr, procs):
        kwargs = dict(service_address=addr, shuffle_row_groups=True,
                      shuffle_seed=SEED, deterministic="seed",
                      num_epochs=EPOCHS)
        crc_rows = []
        with make_batch_reader(matrix_dataset, **kwargs) as reader:
            it = reader.iter_batches()
            for _ in range(4):
                crc_rows.extend(next(it).columns["x"])
            # kill a worker holding in-flight work, then quiesce mid-epoch
            procs[0].send_signal(signal.SIGKILL)
            for _ in range(2):
                crc_rows.extend(next(it).columns["x"])
            reader.quiesce()
            crc_rows.extend(x for b in it for x in b.columns["x"])
            state = reader.state_dict()
        assert state["ordinal_exact"]
        assert disp.stats()["counters"].get("service.requeued_items", 0) >= 0
        with make_batch_reader(matrix_dataset, resume_from=state,
                               **kwargs) as reader:
            crc_rows.extend(x for b in reader.iter_batches()
                            for x in b.columns["x"])
            resumed = reader.diagnostics["stream_digest"]
    assert resumed["combined"] == baseline.digest["combined"], \
        (resumed, baseline.digest)
    assert resumed["rows"] == baseline.rows


# -- disruption cells (ISSUE 13: dispatcher crash + network chaos) ------------

def test_dispatcher_restart_cell_bit_identical(matrix_dataset, baseline):
    """Dispatcher-SIGKILL+restart as a matrix cell: the dispatcher dies
    mid-epoch with in-flight work everywhere, peers reconstruct the
    session (client re-hello/resync, worker rejoin claims), and the
    delivered stream is bit-identical to the uninterrupted baseline."""
    from petastorm_tpu.test_util.matrix import recoverable_fleet

    cell = MatrixCell(transport="service",
                      disruption="dispatcher-restart")
    with recoverable_fleet(n_workers=2,
                           worker_reconnect_backoff_s=0.1) as fleet:
        result = run_cell(
            matrix_dataset, SEED, cell, num_epochs=EPOCHS,
            service_address=fleet.address,
            disruptor=lambda: fleet.restart_dispatcher(downtime_s=0.2))
        _assert_matches(result, baseline, cell.label())
        # the replacement dispatcher must have RECOVERED, not restarted
        # the epoch: session reconstructed from the client, workers back
        dc = fleet.dispatcher.stats()["counters"]
        assert dc.get("service.sessions_reconstructed", 0) >= 1, dc
        assert dc.get("service.worker_rejoins", 0) >= 1, dc
    assert fleet.restarts == 1


def test_netchaos_cell_bit_identical(matrix_dataset, baseline):
    """Seeded network chaos (duplicates, delays, a mid-frame cut) on the
    client<->dispatcher link: the ledger dedups, reconnect+resync absorb
    the cut - same stream, and the proxy proves the faults fired."""
    from petastorm_tpu.test_util.matrix import recoverable_fleet
    from petastorm_tpu.test_util.netchaos import NetChaosSpec

    spec = NetChaosSpec(seed=SEED, dup_rate=0.08, delay_rate=0.1,
                        delay_s=0.01, cut_frames=(23,))
    cell = MatrixCell(transport="service", disruption="netchaos")
    with recoverable_fleet(n_workers=2, net_spec=spec) as fleet:
        # the chaos is continuous (armed at the proxy); the cell's
        # mid-epoch action is a no-op marker
        result = run_cell(matrix_dataset, SEED, cell, num_epochs=EPOCHS,
                          service_address=fleet.address,
                          disruptor=lambda: None)
        _assert_matches(result, baseline, cell.label())
        stats = dict(fleet.proxy.stats)
    assert stats["cuts"] >= 1, stats
    assert stats["dups"] + stats["delays"] >= 1, stats


def test_netsplit_heal_cell_bit_identical(matrix_dataset, baseline):
    """Partition-then-heal as a matrix cell: the client link goes dark
    mid-epoch, reconnects are refused until the heal, then resync
    reconstructs - same stream."""
    from petastorm_tpu.test_util.matrix import recoverable_fleet
    from petastorm_tpu.test_util.netchaos import NetChaosSpec

    cell = MatrixCell(transport="service", disruption="netsplit")
    with recoverable_fleet(n_workers=2, net_spec=NetChaosSpec()) as fleet:
        result = run_cell(
            matrix_dataset, SEED, cell, num_epochs=EPOCHS,
            service_address=fleet.address,
            disruptor=lambda: fleet.netsplit(duration_s=0.4))
        _assert_matches(result, baseline, cell.label())
        stats = dict(fleet.proxy.stats)
    # the partition cut the live pipe; completing the read forced at
    # least one reconnect through the healed proxy
    assert stats["connections"] >= 2, stats


# -- failover cells (ISSUE 17: hot-standby HA must not move a byte) -----------

def test_failover_cell_bit_identical(matrix_dataset, baseline):
    """Hot-standby failover as a matrix cell: the primary dispatcher is
    killed mid-epoch with in-flight work everywhere, the warm standby
    promotes off its replicated journal mirror, peers roll over through
    the failover address list - and the delivered stream is bit-identical
    to the uninterrupted baseline."""
    from petastorm_tpu.test_util.matrix import ha_fleet

    cell = MatrixCell(transport="service", disruption="failover")
    with ha_fleet(n_workers=2) as fleet:
        result = run_cell(matrix_dataset, SEED, cell, num_epochs=EPOCHS,
                          service_address=fleet.address,
                          disruptor=fleet.failover)
        _assert_matches(result, baseline, cell.label())
        # the promoted standby is now the live dispatcher: a real
        # failover (counted once), a bumped epoch, and the warm session
        stats = fleet.dispatcher.stats()
        assert stats["counters"].get("service.failovers", 0) == 1, stats
        assert stats["epoch"] >= 2, stats
        assert stats["standby"]["promoted"], stats


def test_failover_partition_cell_fences_split_brain(matrix_dataset,
                                                    baseline):
    """Split-brain fencing as a matrix cell: the primary is PARTITIONED
    away (still alive!) mid-epoch, the standby promotes with a higher
    epoch, and the read completes bit-identically - no item is delivered
    twice even though two dispatchers believe they own the fleet.  After
    the heal, the deposed primary is refused by its own workers (stale
    epoch), so it can never double-assign."""
    from petastorm_tpu.errors import PetastormTpuError
    from petastorm_tpu.service.protocol import (connect_frames,
                                                parse_address)
    from petastorm_tpu.test_util.matrix import ha_fleet

    cell = MatrixCell(transport="service", disruption="failover")
    with ha_fleet(n_workers=2, partitionable=True) as fleet:

        def split_brain():
            fleet.partition_primary()
            fleet.wait_promoted()

        result = run_cell(matrix_dataset, SEED, cell, num_epochs=EPOCHS,
                          service_address=fleet.address,
                          disruptor=split_brain)
        # bit-identical == exactly-once: equal row multisets + crc leave
        # no room for a double delivery from the deposed side
        _assert_matches(result, baseline, cell.label())
        assert fleet.peer_proxy.stats["partition_refusals"] >= 1, \
            dict(fleet.peer_proxy.stats)
        assert fleet.dispatcher.stats()["epoch"] >= 2

        # fencing: a worker that served the promoted standby refuses the
        # healed (still alive, still epoch-1) primary outright
        fleet.heal_primary()
        worker = fleet.workers[0]
        deadline = 20.0
        import time as _time
        end = _time.monotonic() + deadline
        while worker._dispatcher_epoch < 2 and _time.monotonic() < end:
            _time.sleep(0.05)
        assert worker._dispatcher_epoch >= 2, worker._dispatcher_epoch
        conn = connect_frames(parse_address(fleet.primary_direct))
        try:
            with pytest.raises(PetastormTpuError, match="stale epoch"):
                worker._register(conn)
        finally:
            conn.close()
        refusals = worker.telemetry.snapshot()["counters"].get(
            "service.stale_epoch_refusals", 0)
        assert refusals >= 1
        # the deposed primary never promoted anything and never counted
        # a failover: one side of the split stayed fenced out
        assert fleet.primary.stats()["counters"].get(
            "service.failovers", 0) == 0


# -- elastic-fleet cells (ISSUE 14: autoscaling must not move a byte) ---------

def test_elastic_fleet_cell_bit_identical(matrix_dataset, baseline):
    """Elastic-fleet as a matrix cell: mid-epoch a NEW worker joins and an
    ORIGINAL worker (holding live assignments) gracefully drains out - the
    autoscale supervisor's grow + retire moves.  The delivered stream is
    bit-identical to the uninterrupted baseline, and the drain requeues
    NOTHING (graceful means finished, not rescheduled)."""
    from petastorm_tpu.test_util.matrix import recoverable_fleet

    cell = MatrixCell(transport="service", disruption="elastic-fleet")
    with recoverable_fleet(n_workers=2) as fleet:
        result = run_cell(matrix_dataset, SEED, cell, num_epochs=EPOCHS,
                          service_address=fleet.address,
                          disruptor=fleet.elastic_event)
        _assert_matches(result, baseline, cell.label())
        dc = fleet.dispatcher.stats()["counters"]
        assert dc.get("service.qos.workers_draining", 0) >= 1, dc
        # graceful = the drained worker FINISHED its items; nothing moved
        # through the requeue path
        assert dc.get("service.requeued_items", 0) == 0, dc
        assert len(fleet.dispatcher.stats()["workers"]) == 2  # 2+1-1


def test_autoscale_supervisor_cell_bit_identical(matrix_dataset, baseline):
    """The CLOSED LOOP as a matrix cell: an undersized fleet (1 worker) +
    a live AutoscaleSupervisor reacting to real client pressure.  The
    supervisor must scale up at least once mid-read, and the delivered
    stream must still be bit-identical to the baseline."""
    from petastorm_tpu.service.autoscale import (AutoscalePolicy,
                                                 AutoscaleSupervisor,
                                                 InProcessSpawner)
    from petastorm_tpu.test_util.chaos import ChaosSpec
    from petastorm_tpu.test_util.matrix import recoverable_fleet

    cell = MatrixCell(transport="service")
    with recoverable_fleet(n_workers=1, capacity=1) as fleet:
        policy = AutoscalePolicy(min_workers=0, max_workers=3,
                                 poll_interval_s=0.2, grow_windows=2,
                                 shrink_windows=50, settle_s=0.5,
                                 worker_capacity=1,
                                 starved_threshold=0.01,
                                 drain_timeout_s=20.0)
        supervisor = AutoscaleSupervisor(
            dispatcher=fleet.dispatcher, policy=policy,
            spawner=InProcessSpawner(fleet.address, capacity=1,
                                     heartbeat_interval_s=0.3)).start()
        try:
            # every item decodes 50ms slower: the 1-worker fleet starves
            # the client long enough for the loop to react (timing-only
            # chaos - content identical to the baseline by construction)
            result = run_cell(
                matrix_dataset, SEED, cell, num_epochs=EPOCHS,
                service_address=fleet.address,
                reader_kwargs={"chaos": ChaosSpec(slow_rate=1.0,
                                                  slow_s=0.05)})
        finally:
            supervisor.stop()
        _assert_matches(result, baseline, "autoscale-closed-loop")
        counters = supervisor.summary()["counters"]
        assert counters["workers_spawned"] >= 1, counters
        assert counters["workers_force_killed"] == 0, counters


# -- token-dataset cell family (ISSUE 11: the packed stream is certified) -----

@pytest.fixture(scope="module")
def token_corpora(tmp_path_factory):
    """Two small token corpora (lognormal doc lengths, 8 rowgroups each):
    enough items for real out-of-order completion and the (2, 7) kill
    ordinals, cheap enough for many cells."""
    from petastorm_tpu.test_util.synthetic import write_token_corpus

    base = tmp_path_factory.mktemp("det_tokens")
    urls = []
    for i in range(2):
        url = str(base / f"c{i}")
        write_token_corpus(url, n_docs=80, rows_per_rg=10, mean_len=24,
                           max_len=100, seed=40 + i)
        urls.append(url)
    return urls


@pytest.fixture(scope="module")
def token_baseline(token_corpora):
    """Reference packed stream: 2-corpus seeded mixture, 2 thread workers,
    no chaos."""
    return run_sequence_cell(token_corpora, SEED,
                             MatrixCell(workers=2, pool="thread"),
                             num_epochs=EPOCHS)


def _assert_sequence_matches(result, base, label: str) -> None:
    assert result.tokens == base.tokens, label
    assert result.rows == base.rows, f"{label}: packed row counts differ"
    assert result.packed_crc == base.packed_crc, \
        f"{label}: packed stream differs"
    assert result.mixture == base.mixture, \
        f"{label}: mixture certificate differs"


TOKEN_CELLS = [
    MatrixCell(workers=1, pool="thread"),
    MatrixCell(workers=4, pool="thread"),
    MatrixCell(workers=2, pool="serial"),
    MatrixCell(workers=3, pool="thread", chaos="kill"),
]


@pytest.mark.parametrize("cell", TOKEN_CELLS, ids=lambda c: c.label())
def test_token_cells_bit_identical(token_corpora, token_baseline, cell):
    """The PACKED 2-corpus mixture stream - tokens, segment boundaries,
    masks AND the mixture draw certificate - is bit-identical across
    worker counts, executor flavors and chaos kills."""
    result = run_sequence_cell(token_corpora, SEED, cell, num_epochs=EPOCHS)
    _assert_sequence_matches(result, token_baseline, cell.label())


@pytest.mark.slow
def test_token_process_cell_bit_identical(token_corpora, token_baseline):
    """Real spawned worker processes deliver the same packed stream (the
    variable-length token columns cross the process transport)."""
    result = run_sequence_cell(token_corpora, SEED,
                               MatrixCell(workers=2, pool="process"),
                               num_epochs=EPOCHS)
    _assert_sequence_matches(result, token_baseline, "2w-process-tokens")


def test_token_service_cell_bit_identical(token_corpora, token_baseline):
    """The service hop delivers the identical packed mixture stream (both
    corpus readers consume through one dispatcher + fleet)."""
    with service_fleet(n_workers=2) as (_disp, addr, _workers):
        result = run_sequence_cell(token_corpora, SEED,
                                   MatrixCell(transport="service"),
                                   num_epochs=EPOCHS, service_address=addr)
    _assert_sequence_matches(result, token_baseline, "service-tokens")


def test_token_different_seed_differs(token_corpora, token_baseline):
    """Seed sensitivity: a different mixture seed changes corpus plans AND
    the draw sequence - both certificates must move."""
    other = run_sequence_cell(token_corpora, SEED + 1, MatrixCell(),
                              num_epochs=EPOCHS)
    assert other.tokens == token_baseline.tokens  # same corpus, same mass
    assert other.packed_crc != token_baseline.packed_crc
    assert other.mixture["combined"] != token_baseline.mixture["combined"]
    assert other.mixture["draws"] != token_baseline.mixture["draws"]


def test_token_single_corpus_cells(token_corpora):
    """The single-corpus (no mixer) packed stream is equally seed-stable."""
    base = run_sequence_cell(token_corpora[0], SEED, MatrixCell(workers=2),
                             num_epochs=1)
    assert base.mixture is None
    kill = run_sequence_cell(token_corpora[0], SEED,
                             MatrixCell(workers=4, chaos="kill"),
                             num_epochs=1)
    assert kill.packed_crc == base.packed_crc
    assert base.fill_rate > 0.8  # lognormal corpus packs densely


def test_token_cell_refuses_quiesce_split(token_corpora):
    from petastorm_tpu.errors import PetastormTpuError

    with pytest.raises(PetastormTpuError, match="quiesce"):
        run_sequence_cell(token_corpora, SEED,
                          MatrixCell(split="quiesce"))


# -- seed sensitivity ---------------------------------------------------------

def test_different_seed_different_digest(matrix_dataset, baseline):
    """The certificate is seed-SENSITIVE: ordinals alone would collapse
    different plans to equal digests; item identity must not."""
    other = run_cell(matrix_dataset, SEED + 1, MatrixCell(), num_epochs=EPOCHS)
    assert other.rows == baseline.rows
    assert other.digest["combined"] != baseline.digest["combined"]
    assert other.content_crc != baseline.content_crc


def test_deterministic_off_still_certifies(matrix_dataset):
    """'off' keeps the digest as a per-run certificate (batch/row totals
    exact) without the ordering guarantee."""
    with make_batch_reader(matrix_dataset, workers_count=3,
                           shuffle_row_groups=True, shuffle_seed=SEED,
                           deterministic="off", num_epochs=1) as reader:
        rows = sum(b.num_rows for b in reader.iter_batches())
        dig = reader.diagnostics["stream_digest"]
        assert reader.deterministic == "off"
    assert rows == 200
    assert dig["batches"] == 20 and dig["rows"] == 200


def test_deterministic_auto_resolution(matrix_dataset):
    """'auto' = 'seed' exactly when a shuffle_seed is pinned."""
    with make_batch_reader(matrix_dataset, shuffle_seed=3,
                           num_epochs=1) as reader:
        assert reader.deterministic == "seed"
        list(reader.iter_batches())
    with make_batch_reader(matrix_dataset, num_epochs=1) as reader:
        assert reader.deterministic == "off"
        list(reader.iter_batches())


# -- PYTHONHASHSEED stability (satellite: centralized seed derivation) --------

_HASHSEED_SCRIPT = """
import sys
from petastorm_tpu.reader import make_batch_reader
from petastorm_tpu.seeding import derive_seed

with make_batch_reader(sys.argv[1], workers_count=2, shuffle_row_groups=True,
                       shuffle_seed=7, deterministic="seed",
                       num_epochs=1) as reader:
    rows = [int(x) for b in reader.iter_batches() for x in b.columns["x"]]
    dig = reader.diagnostics["stream_digest"]["combined"]
print(dig)
print(derive_seed(7, 0, "loader.shuffle_buffer"))
print(rows[:20])
"""


def test_digest_stable_across_pythonhashseed(matrix_dataset, tmp_path):
    """Seed derivation must never route through hash(): the same read under
    different PYTHONHASHSEED values produces identical digests, derived
    seeds and row streams (the exact failure mode that silently defeated
    cross-process cache sharing in PR 7)."""
    script = tmp_path / "hashseed_probe.py"
    script.write_text(_HASHSEED_SCRIPT)
    outputs = []
    for hashseed in ("0", "424242"):
        env = dict(os.environ, PYTHONHASHSEED=hashseed,
                   JAX_PLATFORMS="cpu")
        env["PYTHONPATH"] = os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
            + env.get("PYTHONPATH", "").split(os.pathsep))
        proc = subprocess.run(
            [sys.executable, str(script), matrix_dataset],
            capture_output=True, text=True, timeout=120, env=env)
        assert proc.returncode == 0, proc.stderr
        outputs.append(proc.stdout)
    assert outputs[0] == outputs[1], \
        f"PYTHONHASHSEED changed the stream:\n{outputs[0]}\nvs\n{outputs[1]}"


# -- seeding unit behavior ----------------------------------------------------

def test_seed_stream_properties():
    a = seed_stream(1, 0, "d").integers(0, 1 << 30, 8)
    assert (a == seed_stream(1, 0, "d").integers(0, 1 << 30, 8)).all()
    # seed, epoch, domain and extra parts all separate streams
    for other in (seed_stream(2, 0, "d"), seed_stream(1, 1, "d"),
                  seed_stream(1, 0, "e"), seed_stream(1, 0, "d", 1),
                  seed_stream(1, 0, "d", "x")):
        assert not (a == other.integers(0, 1 << 30, 8)).all()
    # None == 0 (deterministic default), int/str parts are type-tagged
    assert derive_seed(None, 0, "d") == derive_seed(0, 0, "d")
    assert derive_seed(0, 0, "d", 1) != derive_seed(0, 0, "d", "1")


def test_stream_digest_chain_and_state_roundtrip():
    a = StreamDigest()
    a.record_batch(0, 0, 5, 1, 0, 10, 10)
    a.record_skip(0, 1, 6, 2)
    a.record_batch(1, 2, 7, 0, 0, 10, 10)
    # state round-trip continues the chain exactly
    b = StreamDigest(state=a.state())
    c = StreamDigest()
    for d in (a, b):
        d.record_batch(1, 3, 8, 1, 0, 10, 10)
    c.record_batch(0, 0, 5, 1, 0, 10, 10)
    c.record_skip(0, 1, 6, 2)
    c.record_batch(1, 2, 7, 0, 0, 10, 10)
    c.record_batch(1, 3, 8, 1, 0, 10, 10)
    assert a.summary() == b.summary() == c.summary()
    assert set(a.summary()["epochs"]) == {0, 1}
    # order sensitivity
    d = StreamDigest()
    d.record_batch(1, 2, 7, 0, 0, 10, 10)
    d.record_batch(0, 0, 5, 1, 0, 10, 10)
    d.record_skip(0, 1, 6, 2)
    d.record_batch(1, 3, 8, 1, 0, 10, 10)
    assert d.summary()["combined"] != a.summary()["combined"]


def test_straggler_release_noop_under_deterministic(matrix_dataset, caplog):
    """Satellite: straggler_release_s is a timing-driven floor bypass; under
    deterministic='seed' the loader disarms it with one warning."""
    pytest.importorskip("jax")
    from petastorm_tpu.jax.loader import JaxDataLoader

    with make_batch_reader(matrix_dataset, workers_count=2,
                           shuffle_row_groups=True, shuffle_seed=SEED,
                           num_epochs=1) as reader:
        import logging

        with caplog.at_level(logging.WARNING,
                             logger="petastorm_tpu.jax.loader"):
            loader = JaxDataLoader(reader, batch_size=16,
                                   shuffling_queue_capacity=64,
                                   straggler_release_s=1.0)
        with loader:
            assert loader._straggler_s is None
            assert any("straggler_release_s" in r.message
                       for r in caplog.records)
            rows = 0
            for batch in loader:
                rows += int(np.asarray(batch["x"]).shape[0])
    assert rows == 192  # 200 rows, batch 16, drop_last


def test_loader_batches_bit_identical_across_workers(matrix_dataset):
    """End-to-end through the jax loader: shuffle-buffer composition is a
    pure function of the seed root - two worker counts deliver identical
    batch sequences (the 'batch composition' half of the invariant)."""
    pytest.importorskip("jax")
    from petastorm_tpu.jax.loader import JaxDataLoader

    def run(workers):
        out = []
        with make_batch_reader(matrix_dataset, workers_count=workers,
                               shuffle_row_groups=True, shuffle_seed=SEED,
                               num_epochs=1) as reader:
            with JaxDataLoader(reader, batch_size=16,
                               shuffling_queue_capacity=64) as loader:
                for batch in loader:
                    out.append(np.asarray(batch["x"]).tolist())
        return out

    assert run(1) == run(4)


def test_autotune_excludes_decode_split_when_deterministic():
    """The decode_split knob is content-changing and must never attach
    under a deterministic policy exclusion."""
    from petastorm_tpu.autotune import AutotuneController, AutotunePolicy
    from petastorm_tpu.telemetry import Telemetry

    class _FakeSampler:
        def series(self):
            return []

    tele = Telemetry()
    policy = AutotunePolicy(exclude_knobs=frozenset({"decode_split"}))
    controller = AutotuneController(object(), _FakeSampler(), tele,
                                    policy=policy)
    controller.attach_decode_split(get=lambda: 1, set_=lambda v: v)
    assert "decode_split" not in controller._knobs


def test_ordinal_less_batch_degrades_without_wedging(matrix_dataset):
    """A transport that drops a ventilation ordinal mid-stream must degrade
    to arrival order (one warning) and FLUSH the already-held batches - not
    wedge the epoch waiting on an ordinal that will never release."""
    import dataclasses
    import logging

    with make_batch_reader(matrix_dataset, workers_count=4,
                           shuffle_row_groups=True, shuffle_seed=SEED,
                           deterministic="seed", num_epochs=1) as reader:
        real_get = reader._executor.get
        stripped = {"n": 0}

        def stripping_get(timeout=None):
            batch = real_get(timeout=timeout)
            stripped["n"] += 1
            if stripped["n"] == 3:  # drop the THIRD arrival's ordinal
                return dataclasses.replace(batch, ordinal=None)
            return batch

        reader._executor.get = stripping_get
        logger = logging.getLogger("petastorm_tpu.reader")
        records = []
        handler = logging.Handler()
        handler.emit = records.append
        logger.addHandler(handler)
        try:
            rows = sorted(x for b in reader.iter_batches()
                          for x in b.columns["x"])
        finally:
            logger.removeHandler(handler)
        assert reader._det_warned_unordered
        assert not reader._det_held  # everything held was flushed
        # reset() restores full seed-stable delivery: the degrade flag
        # clears, and the reset run's digest equals a FRESH reader's
        reader._executor.get = real_get
        reader.reset()
        assert not reader._det_warned_unordered
        reset_rows = [int(x) for b in reader.iter_batches()
                      for x in b.columns["x"]]
        reset_digest = reader.diagnostics["stream_digest"]["combined"]
    with make_batch_reader(matrix_dataset, workers_count=2,
                           shuffle_row_groups=True, shuffle_seed=SEED,
                           deterministic="seed", num_epochs=1) as fresh:
        fresh_rows = [int(x) for b in fresh.iter_batches()
                      for x in b.columns["x"]]
        fresh_digest = fresh.diagnostics["stream_digest"]["combined"]
    assert reset_rows == fresh_rows
    assert reset_digest == fresh_digest
    assert rows == list(range(200))  # exact multiset despite the degrade
    assert sum("degraded" in r.getMessage() for r in records) == 1


def test_ventilator_release_window_paces_and_resumes():
    """The deterministic release window: ventilation pauses one window past
    the release point and resumes as releases advance - the bound that
    keeps the reorder stage's memory finite under a straggling rowgroup."""
    import threading
    import time as _time

    from petastorm_tpu.pool import Ventilator

    class _Plan:
        def epoch_items(self, epoch):
            return list(range(50))

        def total_items(self, n):
            return 50 * n

    class _RecordingExecutor:
        def __init__(self):
            self.puts = []

        def put(self, item, cancel_event=None):
            self.puts.append(item.ordinal)

    released = {"n": 0}
    ex = _RecordingExecutor()
    vent = Ventilator(ex, _Plan(), num_epochs=1, release_window=10,
                      release_progress=lambda: released["n"])
    vent.start()
    deadline = _time.monotonic() + 5
    while len(ex.puts) < 10 and _time.monotonic() < deadline:
        _time.sleep(0.01)
    _time.sleep(0.1)  # would overshoot here without the window
    assert len(ex.puts) == 10, ex.puts  # paused exactly one window ahead
    released["n"] = 25  # consumer released a prefix
    deadline = _time.monotonic() + 5
    while len(ex.puts) < 35 and _time.monotonic() < deadline:
        _time.sleep(0.01)
    assert len(ex.puts) == 35  # resumed up to the new window edge
    released["n"] = 50
    vent.join()
    assert ex.puts == list(range(50))
    assert threading.active_count() >= 1  # ventilator thread exited cleanly


def test_reorder_telemetry_counters(matrix_dataset):
    """Reordered deliveries are observable: the reader counts batches that
    arrived out of plan order and exposes the digest gauge."""
    from petastorm_tpu.telemetry import Telemetry

    tele = Telemetry()
    with make_batch_reader(matrix_dataset, workers_count=4,
                           shuffle_row_groups=True, shuffle_seed=SEED,
                           deterministic="seed", num_epochs=2,
                           telemetry=tele) as reader:
        rows = sum(b.num_rows for b in reader.iter_batches())
        expected = reader.diagnostics["stream_digest"]
    assert rows == 400
    snap = tele.snapshot()
    assert snap["gauges"]["stream.digest"] == int(expected["combined"], 16)
    assert "reader.reordered_batches" in snap["counters"]
