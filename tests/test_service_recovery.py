"""Dispatcher crash recovery + network-fault injection (ISSUE 13): the
fresh-dispatcher peer-reconstruction handshake (client re-hello/resync,
worker rejoin claims, orphan results), the optional session journal, the
bounded redelivery buffer, and the FrameSocket-boundary chaos transport
(mid-frame cuts, drops-with-cut, duplicates, delays, partitions)."""

import logging
import pickle
import socket
import struct
import threading
import time

import numpy as np
import pytest

from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.etl.writer import write_dataset
from petastorm_tpu.pool import VentilatedItem
from petastorm_tpu.reader import make_batch_reader
from petastorm_tpu.retry import RetryPolicy
from petastorm_tpu.schema import Field, Schema
from petastorm_tpu.service import wire
from petastorm_tpu.service.client import ServiceExecutor
from petastorm_tpu.service.dispatcher import Dispatcher
from petastorm_tpu.service.journal import ServiceJournal
from petastorm_tpu.service.protocol import FrameClosedError, FrameSocket
from petastorm_tpu.service.worker import ServiceWorker
from petastorm_tpu.telemetry import Telemetry
from petastorm_tpu.test_util.matrix import (MatrixCell, recoverable_fleet,
                                            run_cell)
from petastorm_tpu.test_util.netchaos import ChaosProxy, NetChaosSpec

FAST_RECONNECT = RetryPolicy(max_attempts=6, initial_backoff_s=0.05,
                             backoff_multiplier=1.5, max_backoff_s=0.4)

_EXECUTIONS: dict = {}
_EXECUTIONS_LOCK = threading.Lock()


class CountingSlowFactory:
    """Counts executions per ordinal (module-global: in-process fleet
    workers share this interpreter) - the double-assignment detector."""

    def __init__(self, sleep_s: float = 0.0, tag: str = "t"):
        self.sleep_s = sleep_s
        self.tag = tag

    def __call__(self):
        sleep_s, tag = self.sleep_s, self.tag

        def fn(item):
            with _EXECUTIONS_LOCK:
                _EXECUTIONS.setdefault(tag, []).append(item.ordinal)
            if sleep_s:
                time.sleep(sleep_s)
            return ("done", item.ordinal)

        return fn


class EchoFactory:
    def __call__(self):
        return lambda item: item.item


def _wait_for(cond, timeout=15.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.fixture
def int_dataset(tmp_path):
    url = str(tmp_path / "ds")
    schema = Schema("RecInts", [Field("x", np.int64)])
    write_dataset(url, schema, [{"x": i} for i in range(200)],
                  row_group_size_rows=10)
    return url


def _ctrl_frame(msg) -> bytes:
    payload = bytes([wire.KIND_CTRL]) + wire.dumps(msg)
    return struct.pack("!I", len(payload)) + payload


# -- NetChaosSpec / ChaosProxy units ------------------------------------------

def test_netchaos_spec_validation_and_determinism():
    with pytest.raises(PetastormTpuError, match="direction"):
        NetChaosSpec(direction="up")
    with pytest.raises(PetastormTpuError, match="dup_rate"):
        NetChaosSpec(dup_rate=1.5)
    spec = NetChaosSpec(seed=3, dup_rate=0.3, delay_rate=0.3, cut_frames=(7,))
    # pure function of (seed, kind, index): two evaluations agree
    decisions = [spec.decide("s2c", i) for i in range(64)]
    assert decisions == [spec.decide("s2c", i) for i in range(64)]
    assert decisions[7] == "cut"
    assert "dup" in decisions and "delay" in decisions
    # a different seed moves the faults
    other = NetChaosSpec(seed=4, dup_rate=0.3, delay_rate=0.3)
    assert [other.decide("s2c", i) for i in range(64)] \
        != [NetChaosSpec(seed=3, dup_rate=0.3, delay_rate=0.3).decide(
            "s2c", i) for i in range(64)]
    # direction gating
    one_way = NetChaosSpec(cut_frames=(0,), direction="c2s")
    assert one_way.decide("c2s", 0) == "cut"
    assert one_way.decide("s2c", 0) == "none"
    # int -> tuple coercion, chaos-spec style
    assert NetChaosSpec(cut_frames=5).cut_frames == (5,)


def _echo_server():
    """A tiny frame echo server; returns (thread, port, stop)."""
    listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    listener.bind(("127.0.0.1", 0))
    listener.listen(8)
    listener.settimeout(0.2)
    stop = threading.Event()

    def serve():
        conns = []
        while not stop.is_set():
            try:
                sock, _ = listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            fs = FrameSocket(sock)
            conns.append(fs)

            def pump(fs=fs):
                try:
                    while not stop.is_set():
                        msg = fs.recv(timeout=0.2)
                        if msg is not None:
                            fs.send(msg)
                except Exception:  # noqa: BLE001 - cut connections expected
                    pass

            threading.Thread(target=pump, daemon=True).start()
        for fs in conns:
            fs.close()
        listener.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    return t, listener.getsockname()[1], stop


def test_chaos_proxy_transparent_and_duplicating():
    t, port, stop = _echo_server()
    try:
        # transparent passthrough
        with ChaosProxy(("127.0.0.1", port)).start() as proxy:
            conn = FrameSocket(socket.create_connection(
                ("127.0.0.1", proxy.port)))
            for i in range(5):
                conn.send({"t": "ping", "n": i})
                assert conn.recv(timeout=5.0) == {"t": "ping", "n": i}
            conn.close()
            assert proxy.stats["frames"] >= 10  # both directions counted
            assert proxy.stats["cuts"] == proxy.stats["drops"] == 0
        # duplication: the echo comes back twice for the dup'd frame
        spec = NetChaosSpec(dup_frames=(0,), direction="c2s")
        with ChaosProxy(("127.0.0.1", port), spec).start() as proxy:
            conn = FrameSocket(socket.create_connection(
                ("127.0.0.1", proxy.port)))
            conn.send({"t": "once"})
            assert conn.recv(timeout=5.0) == {"t": "once"}
            assert conn.recv(timeout=5.0) == {"t": "once"}  # the duplicate
            assert proxy.stats["dups"] == 1
            conn.close()
    finally:
        stop.set()
        t.join(timeout=5)


def test_chaos_proxy_mid_frame_cut_and_partition_heal():
    t, port, stop = _echo_server()
    try:
        spec = NetChaosSpec(cut_frames=(1,), direction="c2s")
        with ChaosProxy(("127.0.0.1", port), spec).start() as proxy:
            conn = FrameSocket(socket.create_connection(
                ("127.0.0.1", proxy.port)))
            conn.send({"t": "ok", "blob": b"x" * 4096})
            assert conn.recv(timeout=5.0)["t"] == "ok"
            # frame 1 is cut mid-body: the server side dies mid-recv_into,
            # and this side's connection is killed -> FrameClosedError,
            # never garbage
            with pytest.raises((FrameClosedError, OSError)):
                conn.send({"t": "doomed", "blob": b"y" * 4096})
                conn.recv(timeout=5.0)
            assert proxy.stats["cuts"] == 1
            conn.close()
            # a FRESH connection through the same proxy resyncs cleanly
            conn2 = FrameSocket(socket.create_connection(
                ("127.0.0.1", proxy.port)))
            conn2.send({"t": "alive"})
            assert conn2.recv(timeout=5.0) == {"t": "alive"}
            # partition: live pipe cut, new connections refused...
            proxy.partition()
            with pytest.raises((FrameClosedError, OSError)):
                conn2.send({"t": "partitioned"})
                conn2.recv(timeout=5.0)
            conn2.close()
            # ...until heal
            proxy.heal()
            conn3 = FrameSocket(socket.create_connection(
                ("127.0.0.1", proxy.port)))
            conn3.send({"t": "healed"})
            assert conn3.recv(timeout=5.0) == {"t": "healed"}
            conn3.close()
    finally:
        stop.set()
        t.join(timeout=5)


# -- FrameSocket mid-frame cut fuzz (satellite 3) ------------------------------

def test_frame_socket_sender_dies_after_partial_body_write():
    """A peer dying after a PARTIAL body write must surface as the
    classified FrameClosedError - and a replacement connection must stream
    cleanly (resync), never inherit desync."""
    a, b = socket.socketpair()
    fb = FrameSocket(b)
    framed = _ctrl_frame({"t": "big", "blob": b"z" * 50_000})
    a.sendall(framed[: len(framed) // 2])
    assert fb.recv(timeout=0.05) is None  # partial frame held, no garbage
    a.close()  # sender dies mid-frame
    with pytest.raises(FrameClosedError):
        fb.recv(timeout=2.0)
    fb.close()
    # the "reconnect": a fresh socket pair streams fine
    a2, b2 = socket.socketpair()
    fa2, fb2 = FrameSocket(a2), FrameSocket(b2)
    fa2.send({"t": "resynced"})
    assert fb2.recv(timeout=2.0) == {"t": "resynced"}
    fa2.close()
    fb2.close()


@pytest.mark.parametrize("cut_at", [1, 3, 4, 5, 37, 4095])
def test_frame_socket_fuzz_cut_at_every_layer(cut_at):
    """Fuzz the cut point across the frame layout (mid-length-prefix,
    mid-kind-byte, mid-body, last byte): every cut classifies as
    FrameClosedError after the partial bytes, with NO message ever
    fabricated from the torn frame."""
    framed = _ctrl_frame({"t": "fuzz", "blob": b"q" * 4096})
    cut_at = min(cut_at, len(framed) - 1)
    a, b = socket.socketpair()
    fb = FrameSocket(b)
    a.sendall(framed[:cut_at])
    assert fb.recv(timeout=0.05) is None
    a.close()
    with pytest.raises(FrameClosedError):
        fb.recv(timeout=2.0)
    fb.close()


def test_receiver_cut_mid_recv_into_classifies():
    """The receiving side losing its socket DURING a body fill (another
    thread closes it, as the send-timeout death path does) maps to
    FrameClosedError, not a crash of the read loop."""
    a, b = socket.socketpair()
    fb = FrameSocket(b)
    framed = _ctrl_frame({"t": "big", "blob": b"z" * (1 << 20)})
    a.sendall(framed[:5000])

    def cut_later():
        time.sleep(0.2)
        fb.close()

    threading.Thread(target=cut_later, daemon=True).start()
    with pytest.raises(FrameClosedError):
        # blocks mid-body until the concurrent close lands
        fb.recv(timeout=10.0)
    a.close()


# -- dispatcher crash recovery -------------------------------------------------

def test_dispatcher_restart_mid_epoch_recovers(int_dataset):
    """TENTPOLE e2e: kill the dispatcher while the client holds in-flight
    work, start a fresh one on the same port, and the epoch completes with
    the exact row multiset - session reconstructed from peers, counted on
    both sides."""
    with recoverable_fleet(n_workers=2) as fleet:
        tele = Telemetry()
        reader = make_batch_reader(int_dataset, service_address=fleet.address,
                                   shuffle_row_groups=False, telemetry=tele)
        rows = []
        restarted = False
        for b in reader.iter_batches():
            rows.extend(int(x) for x in b.columns["x"])
            if not restarted and len(rows) >= 40:
                restarted = True
                fleet.restart_dispatcher(downtime_s=0.2)
        diag = reader.diagnostics
        reader.stop()
        reader.join()
        assert sorted(rows) == list(range(200))
        assert len(rows) == 200  # exactly once, no duplicates
        assert diag["dispatcher_restarts"] == 1
        assert diag["reconnects"] >= 1
        c = tele.snapshot()["counters"]
        assert c["service.dispatcher_restarts"] == 1
        # the NEW dispatcher saw the session reconstructed + workers rejoin
        dc = fleet.dispatcher.stats()["counters"]
        assert dc.get("service.sessions_reconstructed", 0) >= 1, dc
        assert dc.get("service.worker_rejoins", 0) >= 1, dc


def test_no_double_execution_through_restart():
    """Workers keep executing through the outage and the rejoin claims
    re-attach their in-flight items: nothing is executed twice despite the
    client re-sending its whole ledger."""
    tag = "restart-exactly-once"
    _EXECUTIONS.pop(tag, None)
    # sleep_s must comfortably cover downtime + the worker's rejoin backoff
    # so the first wave is STILL EXECUTING when the rejoin hello lands -
    # otherwise the items legitimately come back as orphans, not claims
    with recoverable_fleet(n_workers=1, capacity=2,
                           worker_reconnect_backoff_s=0.1) as fleet:
        ex = ServiceExecutor(fleet.address, telemetry=Telemetry(), window=8,
                             reconnect_policy=FAST_RECONNECT)
        ex.start(CountingSlowFactory(sleep_s=1.2, tag=tag))
        try:
            for i in range(6):
                ex.put(VentilatedItem(i, f"p{i}"))
            time.sleep(0.2)  # let the worker start executing
            fleet.restart_dispatcher(downtime_s=0.2)
            got = sorted(ex.get(timeout=30.0) for _ in range(6))
            assert got == [("done", i) for i in range(6)]
        finally:
            ex.stop()
            ex.join()
        executed = _EXECUTIONS.get(tag, [])
        assert sorted(executed) == list(range(6)), \
            f"double execution: {sorted(executed)}"
        dc = fleet.dispatcher.stats()["counters"]
        assert dc.get("service.recovered_assignments", 0) >= 1, dc


def test_orphan_result_buffered_until_client_reconnects():
    """A rejoined worker finishing an item BEFORE its client reconnects:
    the outcome is buffered as an orphan and replayed on the client's
    hello - not dropped as a duplicate."""
    tag = "orphan"
    _EXECUTIONS.pop(tag, None)
    slow_client = RetryPolicy(max_attempts=4, initial_backoff_s=2.0,
                              backoff_multiplier=1.0, max_backoff_s=2.0)
    with recoverable_fleet(n_workers=1, capacity=1,
                           worker_reconnect_backoff_s=0.1) as fleet:
        ex = ServiceExecutor(fleet.address, telemetry=Telemetry(), window=2,
                             reconnect_policy=slow_client)
        ex.start(CountingSlowFactory(sleep_s=1.2, tag=tag))
        try:
            ex.put(VentilatedItem(0, "slow"))
            time.sleep(0.3)  # executing now
            # dispatcher dies; worker rejoins in ~0.1s and finishes the item
            # LONG before the client's 2s reconnect backoff expires
            fleet.restart_dispatcher(downtime_s=0.05)
            assert ex.get(timeout=30.0) == ("done", 0)
            assert _EXECUTIONS.get(tag) == [0]  # executed exactly once
            dc = fleet.dispatcher.stats()["counters"]
            assert dc.get("service.orphan_results_buffered", 0) >= 1, dc
        finally:
            ex.stop()
            ex.join()


def test_journal_warm_restart_skips_resends(tmp_path, caplog):
    """--journal: a restarted dispatcher replays sessions from disk, tells
    the reconnecting client which ordinals it already holds, and the
    client's resync skips re-sending them."""
    journal = str(tmp_path / "svc.journal")
    tag = "journal"
    _EXECUTIONS.pop(tag, None)
    tele = Telemetry()
    disp = Dispatcher(telemetry=tele, heartbeat_timeout_s=5.0,
                      journal_path=journal).start()
    port = disp.port
    addr = f"127.0.0.1:{port}"
    worker = ServiceWorker(addr, capacity=1, name="jw",
                           reconnect_attempts=60, reconnect_backoff_s=0.1)
    threading.Thread(target=worker.run, daemon=True).start()
    _wait_for(lambda: len(disp.stats()["workers"]) == 1,
              what="worker registration")
    ex = ServiceExecutor(addr, telemetry=Telemetry(), window=8,
                         reconnect_policy=FAST_RECONNECT)
    ex.start(CountingSlowFactory(sleep_s=0.3, tag=tag))
    try:
        for i in range(6):
            ex.put(VentilatedItem(i, f"p{i}"))
        time.sleep(0.15)  # journaled + some assigned
        disp.stop()
        disp.join()
        # the warm restart: same port, same journal, EMPTY memory
        disp = Dispatcher(telemetry=tele, heartbeat_timeout_s=5.0, port=port,
                          journal_path=journal).start()
        with caplog.at_level(logging.INFO,
                             logger="petastorm_tpu.service.client"):
            got = sorted(ex.get(timeout=30.0) for _ in range(6))
        assert got == [("done", i) for i in range(6)]
        c = tele.snapshot()["counters"]
        assert c.get("service.journal_items_restored", 0) >= 1, c
        assert any("resync skipped" in r.getMessage()
                   for r in caplog.records), \
            "client did not skip any journal-known re-sends"
        # exactly-once execution held through the warm restart too (the
        # worker's rejoin claims cover journal-restored pending items)
        assert sorted(_EXECUTIONS.get(tag, [])) == list(range(6))
    finally:
        ex.stop()
        ex.join()
        worker.stop()
        disp.stop()
        disp.join()


def test_journal_tolerates_truncated_tail(tmp_path):
    """A crash mid-append leaves a torn record; load() replays the good
    prefix and stops cleanly."""
    path = str(tmp_path / "torn.journal")
    j = ServiceJournal(path)
    j.open()
    j.append_hello("c1", {"factory": b"fac", "hostname": "h",
                          "shm_ok": False, "max_requeue": 2, "codecs": []})
    j.append_enqueue("c1", {"o": 0, "a": 0, "blob": b"item0"})
    j.append_enqueue("c1", {"o": 1, "a": 0, "blob": b"item1"})
    j.append_ack("c1", [0])
    j.close()
    with open(path, "ab") as fh:
        fh.write(struct.pack("!I", 500) + b"torn")  # crash mid-record
    sessions = ServiceJournal(path).load()
    assert list(sessions) == ["c1"]
    assert list(sessions["c1"].items) == [1]  # 0 acked, tail tolerated
    assert sessions["c1"].hello["factory"] == b"fac"
    # purge removes the whole session
    j2 = ServiceJournal(path)
    j2.load()
    j2.open()
    j2.append_purge("c1")
    j2.close()
    assert ServiceJournal(path).load() == {}


# -- bounded redelivery buffer (satellite 1) -----------------------------------

def test_replay_buffer_cap_degrades_oldest_and_forces_refetch(int_dataset):
    """Unacked result bodies past replay_buffer_bytes degrade to
    header-only; on reconnect the client re-fetches exactly those items -
    every row still delivered exactly once, memory bounded."""
    tele = Telemetry()
    disp = Dispatcher(telemetry=tele, heartbeat_timeout_s=5.0,
                      replay_buffer_bytes=16_384).start()
    addr = f"127.0.0.1:{disp.port}"
    worker = ServiceWorker(addr, capacity=2, name="bw")
    threading.Thread(target=worker.run, daemon=True).start()
    _wait_for(lambda: len(disp.stats()["workers"]) == 1,
              what="worker registration")
    slow_reconnect = RetryPolicy(max_attempts=10, initial_backoff_s=0.8,
                                 backoff_multiplier=1.0, max_backoff_s=0.8)
    ex = ServiceExecutor(addr, telemetry=Telemetry(), window=16,
                         reconnect_policy=slow_reconnect)
    ex.start(EchoFactory())
    try:
        for i in range(12):
            ex.put(VentilatedItem(i, b"B" * 4096))  # ~4KB bodies, 16KB cap
        # cut the client link so every result lands in the redelivery
        # buffer instead of the wire; ~48KB of bodies vs a 16KB cap
        ex._conn._sock.shutdown(socket.SHUT_RDWR)
        _wait_for(lambda: tele.snapshot()["counters"].get(
            "service.replay_bodies_dropped", 0) >= 1,
            what="replay-cap degrade")
        gauge = tele.snapshot()["gauges"]["service.replay_buffer_bytes"]
        assert gauge <= 16_384 + 8_192, gauge  # newest entry may overhang
        # the receiver reconnects after its backoff; stale outcomes force
        # re-fetch, fresh ones replay - all 12 arrive exactly once
        got = sorted([ex.get(timeout=30.0) for _ in range(12)],
                     key=lambda v: 0)
        assert got == [b"B" * 4096] * 12
        c = tele.snapshot()["counters"]
        assert c.get("service.replay_bodies_dropped", 0) >= 1, c
        assert c.get("service.replay_refetches_forced", 0) >= 1, c
    finally:
        ex.stop()
        ex.join()
        worker.stop()
        disp.stop()
        disp.join()


# -- pickle-fallback warn-once (satellite 2) -----------------------------------

def test_pickle_fallback_warns_once_naming_refusal_knobs():
    disp = Dispatcher(telemetry=Telemetry(), heartbeat_timeout_s=5.0).start()
    addr = f"127.0.0.1:{disp.port}"
    worker = ServiceWorker(addr, capacity=2, name="pw")
    threading.Thread(target=worker.run, daemon=True).start()
    _wait_for(lambda: len(disp.stats()["workers"]) == 1,
              what="worker registration")
    logger = logging.getLogger("petastorm_tpu.service.client")
    records = []
    handler = logging.Handler()
    handler.emit = records.append
    logger.addHandler(handler)
    ex = ServiceExecutor(addr, telemetry=Telemetry(), window=4)
    try:
        ex.start(EchoFactory())
        for i in range(4):
            ex.put(VentilatedItem(i, f"p{i}"))
        got = sorted(ex.get(timeout=15.0) for _ in range(4))
        assert got == [f"p{i}" for i in range(4)]
        warnings = [r.getMessage() for r in records
                    if r.levelno == logging.WARNING
                    and "PICKLE" in r.getMessage()]
        assert len(warnings) == 1, warnings  # once, not per frame
        assert "allow_pickle_results=False" in warnings[0]
        assert "PETASTORM_TPU_SERVICE_ALLOW_PICKLE" in warnings[0]
    finally:
        logger.removeHandler(handler)
        ex.stop()
        ex.join()
        worker.stop()
        disp.stop()
        disp.join()


# -- reads through a hostile network ------------------------------------------

def test_service_read_survives_netchaos_on_client_link(int_dataset):
    """A full read through a duplicating/delaying/cutting proxy delivers
    the exact row multiset - and the chaos provably fired."""
    # the cut frame index must be comfortably inside what the read pushes
    # per direction (~20 enqueues / ~20 results); high indexes are reached
    # only on runs whose ack batching stays fine-grained
    spec = NetChaosSpec(seed=11, dup_rate=0.1, delay_rate=0.15,
                        delay_s=0.01, cut_frames=(12,))
    with recoverable_fleet(n_workers=2, net_spec=spec) as fleet:
        tele = Telemetry()
        reader = make_batch_reader(int_dataset, service_address=fleet.address,
                                   shuffle_row_groups=False, telemetry=tele)
        rows = sorted(int(x) for b in reader.iter_batches()
                      for x in b.columns["x"])
        reader.stop()
        reader.join()
        assert rows == list(range(200))
        stats = fleet.proxy.stats
        assert stats["dups"] >= 1, stats
        assert stats["cuts"] >= 1, stats
        assert tele.snapshot()["counters"]["service.reconnects"] >= 1


def test_matrix_cell_rejects_local_disruption():
    with pytest.raises(PetastormTpuError, match="service"):
        MatrixCell(disruption="dispatcher-restart")
    with pytest.raises(PetastormTpuError, match="disruption"):
        MatrixCell(transport="service", disruption="meteor")
    with pytest.raises(PetastormTpuError, match="disruptor"):
        run_cell("unused", 7, MatrixCell(transport="service",
                                         disruption="netsplit"),
                 service_address="127.0.0.1:1")


def test_worker_rejoin_hello_reports_held_state():
    """Unit: a rejoining worker's hello carries its executing assignments
    and held jobs (what the dispatcher turns into claims)."""
    worker = ServiceWorker("127.0.0.1:1", capacity=1,
                           reconnect_attempts=1)
    worker.worker_name = "w0"  # registered once already
    worker._jobs["cid"] = {"factory": b"f", "shm_ok": False, "codec": ""}
    worker._held[("cid", 5)] = 1

    sent = {}

    class _FakeConn:
        def send(self, msg):
            sent.update(msg)

        def recv(self, timeout=None):
            return {"t": "hello_ok", "worker": "w0"}

    worker._register(_FakeConn())
    assert sent["resume"] is True
    assert sent["assignments"] == [["cid", 5, 1]]
    assert sent["jobs"] == ["cid"]
    # pre-registration hello is a plain one
    fresh = ServiceWorker("127.0.0.1:1", capacity=1)
    sent.clear()
    fresh._register(_FakeConn())
    assert sent["resume"] is False
    assert sent["assignments"] == []


# -- hot-standby dispatcher HA (ISSUE 17) --------------------------------------

def test_journal_load_survives_foreign_and_corrupt_records(tmp_path):
    """Journal fuzz (ISSUE 17 satellite): decodable-but-foreign records
    (a future journal version's kinds, wrong field types, a bogus epoch
    stamp) apply as no-ops, and an UNDECODABLE record stops replay at the
    good prefix - never a crash, never a poisoned session."""
    path = str(tmp_path / "fuzz.journal")
    j = ServiceJournal(path)
    j.open()
    j.append_hello("c1", {"factory": b"fac", "hostname": "h",
                          "shm_ok": False, "max_requeue": 2, "codecs": []})
    j.append_enqueue("c1", {"o": 0, "a": 0, "blob": b"item0"})
    # interleaved foreign-version records: unknown kind, enq with a
    # non-int ordinal, a hello for a non-string client, a non-int epoch
    j.ingest({"r": "v99-frobnicate", "client": "c1", "payload": b"x"})
    j.ingest({"r": "enq", "client": "c1", "item": {"o": "NaN"}})
    j.ingest({"r": "hello", "client": 7})
    j.ingest({"r": "epoch", "epoch": "seven"})
    j.append_enqueue("c1", {"o": 1, "a": 0, "blob": b"item1"})
    j.close()
    j2 = ServiceJournal(path)
    sessions = j2.load()
    assert sorted(sessions["c1"].items) == [0, 1]
    assert j2.epoch == 0  # the bogus stamp never applied
    # corrupt length prefix: an absurd length stops replay cleanly
    with open(path, "ab") as fh:
        fh.write(struct.pack("!I", 1 << 30) + b"junk")
    assert sorted(ServiceJournal(path).load()["c1"].items) == [0, 1]
    # undecodable body under a VALID length prefix: same degrade
    with open(path, "ab") as fh:
        fh.write(struct.pack("!I", 5) + b"\xff\xfe\xfd\xfc\xfb")
    j3 = ServiceJournal(path)
    sessions3 = j3.load()
    assert sorted(sessions3["c1"].items) == [0, 1]
    # and the journal stays appendable after the fuzzed load
    j3.open()
    j3.append_ack("c1", [0])
    j3.close()
    assert sorted(ServiceJournal(path).load()["c1"].items) == [1]


def test_journal_fsync_knob_meters(tmp_path):
    """--journal-fsync (ISSUE 17 satellite): off by default (no fsyncs),
    on it fsyncs per append and meters service.journal_fsyncs."""
    hello = {"factory": b"f", "hostname": "h", "shm_ok": False,
             "max_requeue": 2, "codecs": []}
    j_off = ServiceJournal(str(tmp_path / "off.journal"))
    j_off.open()
    j_off.append_hello("c1", hello)
    j_off.close()
    assert j_off.fsyncs == 0
    tele = Telemetry()
    j_on = ServiceJournal(str(tmp_path / "on.journal"), fsync=True,
                          fsync_counter=tele.counter(
                              "service.journal_fsyncs"))
    j_on.open()
    j_on.append_hello("c1", hello)
    j_on.append_enqueue("c1", {"o": 0, "a": 0, "blob": b"i"})
    j_on.close()
    assert j_on.fsyncs == 2
    assert tele.snapshot()["counters"]["service.journal_fsyncs"] == 2
    # end-to-end: a dispatcher with the knob meters its own appends
    dtele = Telemetry()
    disp = Dispatcher(telemetry=dtele, heartbeat_timeout_s=5.0,
                      journal_path=str(tmp_path / "svc.journal"),
                      journal_fsync=True).start()
    ex = ServiceExecutor(f"127.0.0.1:{disp.port}", telemetry=Telemetry(),
                         window=4, reconnect_policy=FAST_RECONNECT)
    try:
        ex.start(EchoFactory())
        ex.put(VentilatedItem(0, "x"))
        _wait_for(lambda: dtele.snapshot()["counters"].get(
            "service.journal_fsyncs", 0) >= 2,
            what="dispatcher journal fsyncs")
    finally:
        ex.stop()
        ex.join()
        disp.stop()
        disp.join()


def test_standby_degrades_once_on_undecodable_sync_stream(caplog):
    """ISSUE 17 satellite: a journal_sync stream that turns to garbage
    mid-flight (valid frame envelope, undecodable body) degrades the
    standby to a cold re-snapshot with ONE warning - never a crash,
    never a silently-desynced warm mirror."""
    subs = []
    stop = threading.Event()
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(8)
    lsock.settimeout(0.5)
    port = lsock.getsockname()[1]

    def serve():  # a fake primary speaking just enough of the sync wire
        while not stop.is_set():
            try:
                sock, _ = lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            fs = FrameSocket(sock)
            try:
                hello = fs.recv(timeout=5.0)
                if not isinstance(hello, dict) \
                        or hello.get("t") != "standby_hello":
                    fs.close()
                    continue
                subs.append(time.monotonic())
                fs.send({"t": "standby_ok", "epoch": 3, "boot": "fake"})
                fs.send({"t": "journal_sync", "k": "snap", "seq": 1,
                         "recs": [{"r": "hello", "client": "c1",
                                   "factory": b"f", "hostname": "h",
                                   "shm_ok": False, "max_requeue": 2,
                                   "codecs": []}]})
                if len(subs) == 1:
                    # the stream turns to garbage: a well-framed but
                    # undecodable body
                    payload = bytes([wire.KIND_CTRL]) + b"\xff\xfe\xfd\xfb"
                    sock.sendall(struct.pack("!I", len(payload)) + payload)
                    time.sleep(0.3)
                    fs.close()
                else:
                    fs.send({"t": "journal_sync", "k": "snap_end",
                             "seq": 1})
                    while not stop.is_set():
                        fs.send({"t": "journal_sync", "k": "ping",
                                 "seq": 1})
                        time.sleep(0.2)
            except (OSError, FrameClosedError):
                pass

    threading.Thread(target=serve, daemon=True).start()
    standby = None
    try:
        with caplog.at_level(logging.WARNING,
                             logger="petastorm_tpu.service.dispatcher"):
            standby = Dispatcher(telemetry=Telemetry(),
                                 standby_of=f"127.0.0.1:{port}").start()
            _wait_for(lambda: len(subs) >= 2,
                      what="standby re-subscription after garbage")
            _wait_for(lambda: standby.stats()["standby"]
                      ["synced_records"] >= 1,
                      what="clean re-snapshot")
        st = standby.stats()["standby"]
        assert st["primary_epoch"] == 3, st
        assert not st["promoted"], st
        assert not standby.standby_promoted.is_set()
        degrades = [r for r in caplog.records
                    if "re-snapshotting" in r.getMessage()]
        assert len(degrades) == 1, [r.getMessage() for r in degrades]
    finally:
        stop.set()
        lsock.close()
        if standby is not None:
            standby.stop()
            standby.join()


def test_standby_survives_mid_stream_sync_cut_then_promotes():
    """ISSUE 17 satellite: a mid-frame cut on the journal_sync link kills
    the session cleanly (FrameClosedError, not garbage), the standby
    re-snapshots through the healed link, and a later primary death still
    promotes it warm."""
    tele = Telemetry()
    primary = Dispatcher(telemetry=Telemetry(),
                         heartbeat_timeout_s=5.0).start()
    proxy = ChaosProxy(f"127.0.0.1:{primary.port}",
                       NetChaosSpec(cut_frames=(2,),
                                    direction="s2c")).start()
    standby = Dispatcher(telemetry=tele, heartbeat_timeout_s=5.0,
                         standby_of=proxy.address).start()
    try:
        _wait_for(lambda: proxy.stats["cuts"] >= 1,
                  what="mid-frame sync cut")
        _wait_for(lambda: standby.stats()["standby"]["synced_records"] >= 1
                  and standby.stats()["standby"]["lag_items"] == 0,
                  what="re-snapshot after the cut")
        assert not standby.standby_promoted.is_set()
        primary.stop()
        primary.join()
        _wait_for(lambda: standby.standby_promoted.is_set(), timeout=20.0,
                  what="promotion after primary death")
        assert standby.stats()["epoch"] >= 2
        assert tele.snapshot()["counters"].get("service.failovers", 0) == 1
    finally:
        proxy.stop()
        standby.stop()
        standby.join()
        primary.stop()
        primary.join()


def test_epoch_fencing_refuses_deposed_dispatcher(tmp_path):
    """Split-brain fencing units: a worker and a client that have seen
    epoch N refuse a dispatcher advertising epoch < N (the deposed
    primary that came back), metering service.stale_epoch_refusals."""
    from petastorm_tpu.service.protocol import connect_frames

    # d_new restores epoch 5 from a pre-stamped journal; d_old is a plain
    # epoch-1 dispatcher playing the deposed primary
    stamped = str(tmp_path / "stamped.journal")
    j = ServiceJournal(stamped)
    j.open()
    j.set_epoch(5)
    j.close()
    d_new = Dispatcher(telemetry=Telemetry(), heartbeat_timeout_s=5.0,
                       journal_path=stamped).start()
    d_old = Dispatcher(telemetry=Telemetry(),
                       heartbeat_timeout_s=5.0).start()
    ex = None
    try:
        assert d_new.stats()["epoch"] == 5
        assert d_old.stats()["epoch"] == 1
        # worker side
        worker = ServiceWorker(f"127.0.0.1:{d_new.port}", capacity=1)
        conn = connect_frames(("127.0.0.1", d_new.port))
        worker._register(conn)
        conn.close()
        assert worker._dispatcher_epoch == 5
        conn = connect_frames(("127.0.0.1", d_old.port))
        try:
            with pytest.raises(PetastormTpuError, match="stale epoch"):
                worker._register(conn)
        finally:
            conn.close()
        assert worker.telemetry.snapshot()["counters"][
            "service.stale_epoch_refusals"] == 1
        # client side: learns epoch 5, then its only failover target is
        # the deposed epoch-1 dispatcher - every rotation refuses it and
        # the reconnect budget expires rather than resyncing into it
        ctele = Telemetry()
        ex = ServiceExecutor(
            f"127.0.0.1:{d_new.port},127.0.0.1:{d_old.port}",
            telemetry=ctele, window=4, reconnect_policy=FAST_RECONNECT)
        ex.start(EchoFactory())
        assert ex.diagnostics["dispatcher_epoch"] == 5
        d_new.stop()
        d_new.join()
        ex.put(VentilatedItem(0, "x"))
        import queue as _queue
        with pytest.raises(PetastormTpuError, match="epoch cannot"
                           "|session lost|dispatcher"):
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                try:  # surfaced once the fenced reconnect budget dies
                    ex.get(timeout=1.0)
                except _queue.Empty:
                    continue
        assert ctele.snapshot()["counters"].get(
            "service.stale_epoch_refusals", 0) >= 1
    finally:
        if ex is not None:
            ex.stop()
            ex.join()
        d_old.stop()
        d_old.join()
        d_new.stop()
        d_new.join()


def test_hot_standby_warm_failover_exactly_once():
    """End-to-end failover off the replicated journal: the standby has
    lag 0 before the kill, promotes warm (journal-restored items), the
    client's resync skips known items, and nothing executes twice."""
    from petastorm_tpu.test_util.matrix import ha_fleet

    tag = "ha-warm"
    _EXECUTIONS.pop(tag, None)
    with ha_fleet(n_workers=1, capacity=2) as fleet:
        # a standby refuses work hellos until promoted (peers rotate)
        from petastorm_tpu.service.protocol import (PROTOCOL_VERSION,
                                                    connect_frames)
        probe = connect_frames(("127.0.0.1", fleet.standby.port))
        probe.send({"t": "worker_hello", "protocol": PROTOCOL_VERSION,
                    "token": None, "capacity": 1, "resume": False,
                    "assignments": [], "jobs": []})
        refusal = probe.recv(timeout=5.0)
        probe.close()
        assert refusal["t"] == "error" and "standby" in refusal["error"]
        # rides out the ~1.5s promotion window (3 missed sync probes)
        patient = RetryPolicy(max_attempts=20, initial_backoff_s=0.1,
                              backoff_multiplier=1.5, max_backoff_s=0.5)
        ex = ServiceExecutor(fleet.address, telemetry=Telemetry(),
                             window=8, reconnect_policy=patient)
        ex.start(CountingSlowFactory(sleep_s=0.3, tag=tag))
        try:
            for i in range(6):
                ex.put(VentilatedItem(i, f"p{i}"))
            # every enqueue must be MIRRORED before the kill: epoch +
            # hello + 6 enqueues and zero lag
            _wait_for(lambda: fleet.standby.stats()["standby"]
                      ["synced_records"] >= 8
                      and fleet.standby.stats()["standby"]
                      ["lag_items"] == 0,
                      what="standby caught up pre-kill")
            fleet.failover()
            got = sorted(ex.get(timeout=30.0) for _ in range(6))
            assert got == [("done", i) for i in range(6)]
            stats = fleet.dispatcher.stats()
            assert stats["counters"].get("service.failovers", 0) == 1
            assert stats["counters"].get(
                "service.journal_items_restored", 0) >= 1, stats["counters"]
            assert stats["epoch"] >= 2
            # exactly-once through the promotion: worker rejoin claims
            # cover the mirrored pending items
            assert sorted(_EXECUTIONS.get(tag, [])) == list(range(6))
        finally:
            ex.stop()
            ex.join()


def test_drain_handshake_is_structural():
    """ISSUE 17 satellite: graceful retirement ends with the drained?/
    drain_ok handshake - the worker says bye only after the dispatcher
    structurally confirms zero recorded in-flight, and any straggler
    assignment voids a stale confirmation."""
    worker = ServiceWorker("127.0.0.1:1", capacity=1)
    sent = []

    class _FakeConn:
        def send(self, msg):
            sent.append(msg)

        def close(self):
            pass

    worker._conn = _FakeConn()
    worker._connected.set()
    now = time.monotonic()
    # retire not acked yet: no probe, no bye
    assert worker._check_drained(now) is False
    assert sent == []
    worker._retire_acked.set()
    # locally empty -> probe the dispatcher, do NOT bye yet
    assert worker._check_drained(now) is False
    assert sent[-1] == {"t": "drained?"}
    # a straggler assignment lands: even a granted confirmation is void
    worker._drain_confirmed.set()
    worker._held[("cid", 1)] = 0
    assert worker._check_drained(now) is False
    assert not worker._drain_confirmed.is_set()
    worker._held.clear()
    # probe again; the dispatcher's structural drain_ok closes the loop
    assert worker._check_drained(now) is False
    worker._drain_confirmed.set()
    assert worker._check_drained(now) is True
    assert sent[-1] == {"t": "bye"}
    assert worker.retired_gracefully
