"""Shared warm-cache tier tests (ISSUE 7): the content-addressed
host-wide L1 (shm arena + shm index) / L2 (disk) cache, its cache-key
correctness contract (changing transform / ROI / placement / schema
selection / file content must change the key), the cross-reader e2e
(reader B's first epoch hits entries reader A decoded, with ZERO additional
rowgroup decodes), L2 survival of an L1 wipe, slot-decode composition,
telemetry publishing, the autotune cache-memory knob, and the hardened
LocalDiskCache under concurrent multi-process eviction."""

import os
import pickle
import tempfile
import threading
import time

import numpy as np
import pytest

from petastorm_tpu.batch import ColumnBatch
from petastorm_tpu.cache import (InMemoryCache, LocalDiskCache, NullCache,
                                 _MISSING, make_cache)
from petastorm_tpu.cache_shared import (DEFAULT_SLOTS, SharedWarmCache,
                                        STALE_PIN_S)
from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
from petastorm_tpu.etl.writer import write_dataset
from petastorm_tpu.schema import Field, Schema
from petastorm_tpu.telemetry import Telemetry
from petastorm_tpu.test_util.synthetic import synthetic_rgb_image
from petastorm_tpu.transform import TransformSpec, transform_signature


def _arena_ok() -> bool:
    from petastorm_tpu.native import allocator_available

    return allocator_available()


needs_arena = pytest.mark.skipif(
    not _arena_ok() and not os.environ.get("PETASTORM_TPU_REQUIRE_ARENA"),
    reason="native shm_arena library unavailable")


@pytest.fixture
def tier(tmp_path):
    cache = SharedWarmCache(location=str(tmp_path / "tier"),
                            l1_bytes=16 * 2 ** 20)
    yield cache
    cache.cleanup()


def _batch(n=64, seed=0, extra=None):
    rng = np.random.default_rng(seed)
    cols = {"x": np.arange(n, dtype=np.int64),
            "img": rng.integers(0, 255, (n, 8, 8, 3), dtype=np.uint8)}
    s = np.empty(n, dtype=object)
    s[:] = [f"row{i}" for i in range(n)]
    cols["s"] = s
    if extra:
        cols.update(extra)
    return ColumnBatch(cols, n)


# -- L1 roundtrip -------------------------------------------------------------

@needs_arena
def test_roundtrip_hit_preserves_types_and_isolation(tier):
    batch = _batch()
    calls = []
    v1 = tier.get("k", lambda: calls.append(1) or batch)
    v2 = tier.get("k", lambda: calls.append(1) or batch)
    assert calls == [1]
    assert v1 is batch                      # the fill's value passes through
    np.testing.assert_array_equal(v2.columns["x"], batch.columns["x"])
    np.testing.assert_array_equal(v2.columns["img"], batch.columns["img"])
    assert list(v2.columns["s"]) == list(batch.columns["s"])
    assert v2.columns["s"].dtype == object
    # served arrays are private: a consumer mutating them in place must not
    # corrupt the tier
    v2.columns["img"][:] = 0
    v3 = tier.get("k", lambda: calls.append(1) or batch)
    np.testing.assert_array_equal(v3.columns["img"], batch.columns["img"])
    assert calls == [1]
    stats = tier.stats()
    assert stats["hits"] == 2 and stats["misses"] == 1
    assert stats["entries"] == 1 and stats["bytes"] > 0


@needs_arena
def test_non_columnbatch_values_roundtrip(tier):
    value = {"arbitrary": [1, 2, 3]}
    assert tier.get("v", lambda: value) == value
    assert tier.get("v", lambda: pytest.fail("should hit")) == value


@needs_arena
def test_cross_instance_hit_same_namespace(tier, tmp_path):
    batch = _batch()
    tier.get("shared-key", lambda: batch)
    other = SharedWarmCache(location=str(tmp_path / "tier"))
    try:
        got = other.get("shared-key", lambda: pytest.fail("should hit"))
        np.testing.assert_array_equal(got.columns["img"],
                                      batch.columns["img"])
    finally:
        other.close()


@needs_arena
def test_pickled_copy_reattaches_and_hits(tier):
    batch = _batch()
    tier.get("p", lambda: batch)
    clone = pickle.loads(pickle.dumps(tier))
    try:
        got = clone.get("p", lambda: pytest.fail("should hit"))
        np.testing.assert_array_equal(got.columns["x"], batch.columns["x"])
    finally:
        clone.close()


# -- eviction / pinning -------------------------------------------------------

@needs_arena
def test_lru_eviction_under_pressure(tmp_path):
    cache = SharedWarmCache(location=str(tmp_path / "small"),
                            l1_bytes=4 * 2 ** 20, l2_enabled=False)
    try:
        big = _batch(n=256, seed=1)   # ~50KB payload each
        for i in range(200):
            cache.get(f"k{i}", lambda: big)
        stats = cache.stats()
        assert stats["evictions"] > 0
        assert stats["bytes"] <= stats["target_bytes"]
        # the NEWEST entry survived; the oldest was evicted (LRU order)
        assert cache._l1_lookup("k199") is not _MISSING  # noqa: SLF001
        assert cache._l1_lookup("k0") is _MISSING        # noqa: SLF001
    finally:
        cache.cleanup()


@needs_arena
def test_pinned_entries_survive_eviction_stale_pins_do_not(tmp_path):
    cache = SharedWarmCache(location=str(tmp_path / "pins"),
                            l1_bytes=4 * 2 ** 20, l2_enabled=False)
    try:
        cache.get("pinned", lambda: _batch(n=256))
        s = cache._slots_arr  # noqa: SLF001 - white-box pin surgery
        i = cache._find(*__import__("petastorm_tpu.cache_shared",
                                    fromlist=["_digest_pair"])
                        ._digest_pair("pinned"))  # noqa: SLF001
        s["pins"][i] = 1
        s["pin_wall"][i] = time.time()       # live pin
        big = _batch(n=256, seed=2)
        for j in range(200):
            cache.get(f"f{j}", lambda: big)
        assert cache.stats()["evictions"] > 0
        assert cache._l1_lookup("pinned") is not _MISSING  # noqa: SLF001
        # age the pin past the crash threshold: now evictable
        j = cache._find(*__import__("petastorm_tpu.cache_shared",
                                    fromlist=["_digest_pair"])
                        ._digest_pair("pinned"))  # noqa: SLF001
        s["pins"][j] = 1
        s["pin_wall"][j] = time.time() - STALE_PIN_S - 1
        for j in range(200, 400):
            cache.get(f"f{j}", lambda: big)
        assert cache._l1_lookup("pinned") is _MISSING  # noqa: SLF001
    finally:
        del s  # release the test's view so the segment can unmap cleanly
        cache.cleanup()


@needs_arena
def test_set_target_bytes_shrinks_residency(tmp_path):
    cache = SharedWarmCache(location=str(tmp_path / "target"),
                            l1_bytes=8 * 2 ** 20, l2_enabled=False)
    try:
        for i in range(20):
            cache.get(f"k{i}", lambda: _batch(n=256, seed=i))
        before = cache.stats()["bytes"]
        assert before > 2 ** 20
        clamped = cache.set_target_bytes(2 ** 20)
        assert clamped == 2 ** 20
        assert cache.stats()["bytes"] <= 2 ** 20
        # clamp floor and ceiling
        assert cache.set_target_bytes(1) == 2 ** 20
        assert cache.set_target_bytes(2 ** 60) <= int(0.8 * 8 * 2 ** 20)
    finally:
        cache.cleanup()


@needs_arena
def test_oversize_entry_rejected_not_stored(tmp_path):
    cache = SharedWarmCache(location=str(tmp_path / "oversize"),
                            l1_bytes=2 * 2 ** 20, l2_enabled=False)
    try:
        huge = ColumnBatch(
            {"b": np.zeros((4, 2 ** 20), dtype=np.uint8)}, 4)  # 4MB > arena/2
        calls = []
        cache.get("huge", lambda: calls.append(1) or huge)
        cache.get("huge", lambda: calls.append(1) or huge)
        assert calls == [1, 1]              # served uncached, both times
        assert cache.stats()["rejected_stores"] >= 1
    finally:
        cache.cleanup()


# -- L2 tier ------------------------------------------------------------------

@needs_arena
def test_l2_survives_l1_wipe_and_promotes_back(tmp_path):
    loc = str(tmp_path / "t2")
    cache = SharedWarmCache(location=loc, l1_bytes=16 * 2 ** 20)
    batch = _batch()
    cache.get("persist", lambda: batch)
    # simulate a host losing its shared memory (reboot / segment purge)
    # while the disk tier survives
    from petastorm_tpu.native import attach_shared_memory

    cache.close()
    for name in (cache._arena_name, cache._index_name):  # noqa: SLF001
        seg = attach_shared_memory(name)
        seg.unlink()
        seg.close()
    fresh = SharedWarmCache(location=loc, l1_bytes=16 * 2 ** 20)
    try:
        got = fresh.get("persist", lambda: pytest.fail("L2 must hit"))
        np.testing.assert_array_equal(got.columns["img"],
                                      batch.columns["img"])
        stats = fresh.stats()
        assert stats["l2_hits"] == 1
        # the L2 hit was PROMOTED into L1: the next get is an L1 hit
        fresh.get("persist", lambda: pytest.fail("should hit"))
        assert fresh.stats()["hits"] == 1
    finally:
        fresh.cleanup()


@needs_arena
def test_orphaned_uninitialized_index_is_adopted(tmp_path):
    """A creator dying between index-create and magic-set must not
    permanently poison the namespace: the next attacher (holding the init
    lock with no magic visible) adopts and initializes the orphan."""
    from multiprocessing import shared_memory

    from petastorm_tpu.cache_shared import (_HEADER_DTYPE, _SLOT_DTYPE,
                                            SharedWarmCache)

    probe = SharedWarmCache(location=str(tmp_path / "orph"), l2_enabled=False)
    index_name = probe._index_name  # noqa: SLF001
    probe.cleanup()
    size = _HEADER_DTYPE.itemsize + DEFAULT_SLOTS * _SLOT_DTYPE.itemsize
    orphan = shared_memory.SharedMemory(name=index_name, create=True,
                                        size=size)  # zeroed: no magic
    try:
        cache = SharedWarmCache(location=str(tmp_path / "orph"),
                                l2_enabled=False)
        try:
            assert cache.l1_enabled
            batch = _batch()
            cache.get("k", lambda: batch)
            got = cache.get("k", lambda: pytest.fail("should hit"))
            np.testing.assert_array_equal(got.columns["x"],
                                          batch.columns["x"])
        finally:
            cache.cleanup()
    finally:
        try:
            orphan.close()
        except BufferError:
            pass


def test_l2_only_degrade_when_arena_unavailable(tmp_path, monkeypatch):
    import petastorm_tpu.native as native

    monkeypatch.setattr(native, "allocator_available", lambda: False)
    cache = SharedWarmCache(location=str(tmp_path / "deg"))
    try:
        assert not cache.l1_enabled
        assert cache.stats() == {"l1_enabled": False, "l2_enabled": True,
                                 "location": str(tmp_path / "deg")}
        batch = _batch()
        calls = []
        cache.get("k", lambda: calls.append(1) or batch)
        got = cache.get("k", lambda: calls.append(1) or batch)
        assert calls == [1]                 # disk tier still serves
        np.testing.assert_array_equal(got.columns["x"], batch.columns["x"])
    finally:
        cache.cleanup()


# -- cache-key correctness (no stale-hit cross-contamination) -----------------

def _key_worker(tmp_path, cache, **kwargs):
    from petastorm_tpu.worker import RowGroupDecoderWorker

    class _Factory:
        url = "file:///ds"

        def __call__(self):
            raise AssertionError("never opened in key tests")

    schema = Schema("K", [Field("x", np.int64, (), ScalarCodec()),
                          Field("image", np.uint8, (32, 32, 3),
                                CompressedImageCodec("jpeg"))])
    defaults = dict(read_fields=["x", "image"])
    defaults.update(kwargs)
    return RowGroupDecoderWorker(_Factory(), schema, cache=cache, **defaults)


def _item(path="/ds/part0.parquet"):
    from petastorm_tpu.etl.metadata import RowGroupRef
    from petastorm_tpu.plan import WorkItem

    return WorkItem(RowGroupRef(path=path, row_group=0, num_rows=10,
                                global_index=0))


def test_cache_key_changes_with_every_signature_dimension(tmp_path):
    cache = InMemoryCache()
    base = _key_worker(tmp_path, cache)
    key = base._cache_key(_item(), (0, 10))  # noqa: SLF001

    # identical settings -> identical key (two readers SHARE)
    again = _key_worker(tmp_path, cache)
    assert again._cache_key(_item(), (0, 10)) == key  # noqa: SLF001

    variants = {
        "schema selection": _key_worker(tmp_path, cache,
                                        read_fields=["image"]),
        "transform": _key_worker(
            tmp_path, cache,
            transform=TransformSpec(lambda c: {k: v * 2
                                               for k, v in c.items()})),
        "decode_roi": _key_worker(tmp_path, cache,
                                  decode_roi={"image": (0, 0, 16, 16)}),
        "decode_placement": _key_worker(tmp_path, cache,
                                        raw_fields=["image"]),
        "mixed placement": _key_worker(tmp_path, cache,
                                       mixed_raw_fields=["image"]),
    }
    keys = {name: w._cache_key(_item(), (0, 10))  # noqa: SLF001
            for name, w in variants.items()}
    for name, k in keys.items():
        assert k != key, f"{name} did not change the cache key"
    assert len(set(keys.values())) == len(keys), "variant keys collide"
    # row span is in the key (ngram lookahead contract)
    assert base._cache_key(_item(), (0, 12)) != key  # noqa: SLF001


def test_transform_signature_stable_across_interpreters(tmp_path):
    """The signature must hash code CONTENT, not reprs embedding memory
    addresses / hash-randomized set ordering: a per-process digest would
    silently defeat cross-job and restart sharing for any transform with a
    nested lambda/comprehension (every worker derives a different key)."""
    import subprocess
    import sys as _sys

    script = tmp_path / "sig.py"
    script.write_text(
        "import sys\n"
        f"sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})\n"
        "from petastorm_tpu.transform import TransformSpec, transform_signature\n"
        "def tf(cols):\n"
        "    inner = lambda v: {k for k in ('a', 'b')} and v * 2\n"
        "    return {k: inner(v) for k, v in cols.items()}\n"
        "print(transform_signature(TransformSpec(tf)))\n")
    out = set()
    for seed in ("0", "7"):
        env = dict(os.environ, PYTHONHASHSEED=seed)
        out.add(subprocess.run([_sys.executable, str(script)], env=env,
                               stdout=subprocess.PIPE, text=True,
                               check=True).stdout.strip())
    assert len(out) == 1, f"signature differs across interpreters: {out}"


def test_cache_key_transform_signature_tracks_function_body():
    def f1(cols):
        return cols

    def f2(cols):
        return {k: v * 2 for k, v in cols.items()}

    s1 = transform_signature(TransformSpec(f1))
    s2 = transform_signature(TransformSpec(f2))
    assert s1 != s2
    assert transform_signature(TransformSpec(f1)) == s1   # deterministic
    assert transform_signature(None) == "-"
    # schema edits alone also change it
    s3 = transform_signature(TransformSpec(f1, removed_fields=["x"]))
    assert s3 != s1


def test_cache_key_file_fingerprint_tracks_rewrites(tmp_path):
    import pyarrow.fs as pafs

    cache = InMemoryCache()
    worker = _key_worker(tmp_path, cache)
    fs = pafs.LocalFileSystem()
    path = str(tmp_path / "data.parquet")
    with open(path, "wb") as f:
        f.write(b"v1")
    k1 = worker._cache_key(_item(path), (0, 10), fs)  # noqa: SLF001
    # rewrite in place: a NEW worker (fresh memo) must derive a NEW key
    time.sleep(0.01)
    with open(path, "wb") as f:
        f.write(b"v2-longer")
    worker2 = _key_worker(tmp_path, cache)
    k2 = worker2._cache_key(_item(path), (0, 10), fs)  # noqa: SLF001
    assert k1 != k2
    # NullCache readers skip the stat entirely
    nullw = _key_worker(tmp_path, NullCache())
    assert nullw._cache_key(_item(path), (0, 10), fs).endswith(":-")  # noqa: SLF001


# -- slot-decode composition --------------------------------------------------

def test_batch_slot_decode_stays_armed_for_copying_caches(tmp_path):
    probes = {
        NullCache(): True,
        InMemoryCache(): True,
        LocalDiskCache(str(tmp_path / "d")): True,
    }
    for cache, expect in probes.items():
        worker = _key_worker(tmp_path, cache)
        assert worker._allow_batch_slots is expect, type(cache)  # noqa: SLF001

    class UnknownCache(NullCache):
        retains_value_references = True  # third-party: conservative default

    assert not _key_worker(tmp_path, UnknownCache())._allow_batch_slots  # noqa: SLF001


@needs_arena
def test_shared_tier_keeps_slots_armed(tier, tmp_path):
    worker = _key_worker(tmp_path, tier)
    assert worker._allow_batch_slots  # noqa: SLF001
    assert SharedWarmCache.retains_value_references is False


@needs_arena
def test_hit_materializes_into_armed_transport_slot(tier):
    """A warm hit under the process pool copies straight into an arena batch
    slot (one shm->shm memcpy) so encode_batch ships it zero-copy."""
    from petastorm_tpu.native import SharedArena
    from petastorm_tpu.native.transport import SlotAllocator, _slot_scope

    tier.get("slot-key", lambda: _batch())
    arena = SharedArena.create(8 * 2 ** 20)
    try:
        allocator = SlotAllocator(arena)
        with _slot_scope(allocator):
            got = tier.get("slot-key", lambda: pytest.fail("should hit"))
        # fixed-shape columns were allocated FROM the transport slots
        assert allocator.claim(got.columns["img"]) is not None
        assert allocator.claim(got.columns["x"]) is not None
        allocator.rollback_claims()
        allocator.finalize(None)
    finally:
        del got, allocator  # release slot views so the arena unmaps cleanly
        arena.close()


# -- telemetry ----------------------------------------------------------------

@needs_arena
def test_publish_telemetry_folds_deltas_once(tmp_path):
    tele = Telemetry()
    cache = SharedWarmCache(location=str(tmp_path / "pub"), telemetry=tele)
    try:
        batch = _batch()
        cache.get("a", lambda: batch)
        cache.get("a", lambda: batch)
        cache.publish_telemetry()
        c = tele.snapshot()["counters"]
        assert c["cache.hits"] == 1 and c["cache.misses"] == 1
        assert c["cache.stores"] == 1
        g = tele.snapshot()["gauges"]
        assert g["cache.bytes"] > 0
        assert g["cache.hit_rate"] == pytest.approx(0.5)
        # idempotent: republishing without activity adds nothing
        cache.publish_telemetry()
        assert tele.snapshot()["counters"]["cache.hits"] == 1

        # the series ride the Prometheus exposition mechanically
        from petastorm_tpu.telemetry.export import render_prometheus

        body = render_prometheus(tele.snapshot())
        assert "petastorm_tpu_cache_hits_total 1" in body
        assert "petastorm_tpu_cache_misses_total 1" in body
        assert "petastorm_tpu_cache_hit_rate 0.5" in body
        assert "petastorm_tpu_cache_bytes" in body

        # a SECOND instance (another reader) baselines at attach: it only
        # publishes activity it observed, so nothing double-counts
        tele2 = Telemetry()
        other = SharedWarmCache(location=str(tmp_path / "pub"),
                                telemetry=tele2)
        try:
            other.get("a", lambda: pytest.fail("should hit"))
            other.publish_telemetry()
            c2 = tele2.snapshot()["counters"]
            assert c2["cache.hits"] == 1
            assert "cache.misses" not in c2
        finally:
            other.close()
    finally:
        cache.cleanup()


def test_watch_frame_renders_cache_line():
    from petastorm_tpu.tools.diagnose import render_watch_frame

    point = {"dt_s": 1.0,
             "rates": {"reader.rows_emitted": 100.0, "cache.hits": 12.0,
                       "cache.misses": 3.0},
             "counters": {"reader.rows_emitted": 100, "cache.hits": 12,
                          "cache.misses": 3, "cache.evictions": 2},
             "gauges": {"cache.hit_rate": 0.8,
                        "cache.bytes": 64 * 2 ** 20},
             "stages": {}}
    frame = render_watch_frame(point)
    assert "cache:" in frame
    assert "hit-rate  80.0%" in frame
    assert "L1 64MB" in frame
    assert "evictions 2" in frame
    # no cache activity -> no cache line
    assert "cache:" not in render_watch_frame(
        {"dt_s": 1.0, "rates": {}, "counters": {}, "gauges": {},
         "stages": {}})


# -- autotune knob ------------------------------------------------------------

def test_autotune_cache_memory_knob_moves_on_signals():
    from petastorm_tpu.autotune import AutotuneController, AutotunePolicy

    class _Clock:
        t = 1000.0

        def __call__(self):
            return self.t

    class _Sampler:
        def __init__(self):
            self.points = []

        def series(self):
            return list(self.points)

    def _point(rate, starved=0.0, blocked=0.0):
        return {"dt_s": 1.0,
                "rates": {"reader.rows_emitted": rate,
                          "queue.results_empty_wait_s": starved,
                          "queue.results_full_wait_s": blocked},
                "gauges": {}, "counters": {}, "stages": {}}

    tele = Telemetry()
    sampler = _Sampler()
    clock = _Clock()
    # bare executor: no worker/results knobs, so cache_mem is the only
    # candidate and the signal routing is unambiguous
    ctl = AutotuneController(object(), sampler, tele,
                             policy=AutotunePolicy(settle_s=1.0,
                                                   eval_points=2,
                                                   cooldown_s=0.0,
                                                   explore=False),
                             clock=clock)
    state = {"mb": 256}
    ctl.attach_cache_memory(get=lambda: state["mb"],
                            set_=lambda n: state.__setitem__("mb", n) or n,
                            lo_mb=16, hi_mb=1024)
    sampler.points.extend([_point(100, starved=0.9)] * 2)
    entry = ctl.step()
    assert entry is not None
    assert (entry["knob"], entry["action"]) == ("cache_mem", "grow")
    assert state["mb"] == 512               # mul step: doubled
    clock.t += 1.01
    assert ctl.step() is None               # settle over, eval anchored
    sampler.points.extend([_point(150)] * 2)
    done = ctl.step()
    assert done["outcome"] == "kept"
    assert tele.snapshot()["gauges"]["autotune.cache_mem"] == 512

    # consumer-bound: shrink
    sampler.points.extend([_point(100, blocked=0.9)] * 2)
    entry = ctl.step()
    assert (entry["knob"], entry["action"]) == ("cache_mem", "shrink")
    assert state["mb"] == 256


@needs_arena
def test_reader_attaches_cache_memory_knob(tmp_path):
    from petastorm_tpu.autotune import AutotunePolicy
    from petastorm_tpu.reader import make_batch_reader

    ds = str(tmp_path / "ds")
    schema = Schema("T", [Field("x", np.int64)])
    write_dataset(ds, schema, [{"x": i} for i in range(40)],
                  row_group_size_rows=10)
    loc = str(tmp_path / "tier")
    with make_batch_reader(ds, reader_pool_type="thread", workers_count=1,
                           shuffle_row_groups=False, cache_type="shared",
                           cache_location=loc,
                           autotune=AutotunePolicy(warmup_s=60),
                           sample_interval_s=0.2) as r:
        assert r.warm_cache is not None
        assert "cache_mem" in r.autotune.knobs()
        list(r.iter_batches())
    SharedWarmCache(location=loc).cleanup()


# -- e2e: two readers, one tier ----------------------------------------------

def _image_dataset(tmp_path, rows=48, rg=8):
    ds = str(tmp_path / "imgds")
    schema = Schema("Img", [
        Field("label", np.int64, (), ScalarCodec()),
        Field("image", np.uint8, (48, 48, 3),
              CompressedImageCodec("jpeg", quality=90)),
    ])
    write_dataset(ds, schema,
                  [{"label": i, "image": synthetic_rgb_image(i, 48, 48)}
                   for i in range(rows)], row_group_size_rows=rg)
    return ds


@needs_arena
def test_two_readers_share_tier_zero_extra_decodes(tmp_path):
    """The acceptance shape: reader A decodes cold; reader B over the SAME
    tier delivers identical rows from its FIRST epoch with cache hits and
    ZERO additional rowgroup decodes (decode.batch_calls delta == 0)."""
    from petastorm_tpu.reader import make_batch_reader

    ds = _image_dataset(tmp_path)
    loc = str(tmp_path / "tier")

    def read(tele):
        with make_batch_reader(ds, reader_pool_type="thread",
                               workers_count=2, shuffle_row_groups=False,
                               cache_type="shared", cache_location=loc,
                               telemetry=tele) as r:
            return sorted(int(x) for b in r.iter_batches()
                          for x in b.columns["label"])

    tele_a, tele_b = Telemetry(), Telemetry()
    rows_a = read(tele_a)
    rows_b = read(tele_b)
    assert rows_a == rows_b == list(range(48))
    ca = tele_a.snapshot()["counters"]
    cb = tele_b.snapshot()["counters"]
    assert ca["cache.misses"] == 6
    assert cb["cache.hits"] >= 6
    assert "cache.misses" not in cb
    from petastorm_tpu.native import image as native_image

    if native_image.available():
        # the decode-counter proof (decode.batch_* only move when the
        # native batched decode actually ran): cold epoch decoded every
        # rowgroup, the warm re-read decoded NOTHING
        assert ca["decode.batch_calls"] >= 6
        assert cb.get("decode.batch_calls", 0) == 0
    SharedWarmCache(location=loc).cleanup()


@needs_arena
def test_readers_with_different_transforms_do_not_cross_contaminate(tmp_path):
    from petastorm_tpu.reader import make_batch_reader

    ds = _image_dataset(tmp_path, rows=16, rg=8)
    loc = str(tmp_path / "tier")

    def read(transform):
        with make_batch_reader(ds, reader_pool_type="thread",
                               workers_count=1, shuffle_row_groups=False,
                               cache_type="shared", cache_location=loc,
                               transform_spec=transform) as r:
            return {n: np.concatenate([b.columns[n]
                                       for b in r.iter_batches()])
                    for n in ("label",)}

    plain = read(None)
    shifted = read(TransformSpec(
        lambda cols: {**cols, "label": cols["label"] + 1000}))
    # a stale cross-transform hit would leak UNSHIFTED labels into the
    # transformed reader (the cached value is the pre-transform decode, but
    # the key still separates the tiers' namespaces)
    np.testing.assert_array_equal(plain["label"], np.arange(16))
    np.testing.assert_array_equal(shifted["label"], np.arange(16) + 1000)
    SharedWarmCache(location=loc).cleanup()


@needs_arena
def test_concurrent_readers_cross_hit_live(tmp_path):
    """Two readers running AT THE SAME TIME over one tier: B records hits
    during its first epoch (cross-job sharing, not just epoch-2 reuse)."""
    from petastorm_tpu.reader import make_batch_reader

    ds = _image_dataset(tmp_path, rows=64, rg=8)
    loc = str(tmp_path / "tier")
    teles = [Telemetry(), Telemetry()]
    rows = [None, None]

    def read(i, epochs):
        with make_batch_reader(ds, reader_pool_type="thread",
                               workers_count=2, shuffle_row_groups=False,
                               cache_type="shared", cache_location=loc,
                               num_epochs=epochs, telemetry=teles[i]) as r:
            rows[i] = sorted(int(x) for b in r.iter_batches()
                             for x in b.columns["label"])

    a = threading.Thread(target=read, args=(0, 2))
    a.start()
    time.sleep(0.3)                      # let A warm part of the tier
    read(1, 1)
    a.join()
    assert rows[0] == sorted(list(range(64)) * 2)
    assert rows[1] == list(range(64))
    cb = teles[1].snapshot()["counters"]
    assert cb.get("cache.hits", 0) > 0, cb
    SharedWarmCache(location=loc).cleanup()


# -- LocalDiskCache hardening (satellite 1) -----------------------------------

def test_disk_cache_lookup_store_halves(tmp_path):
    cache = LocalDiskCache(str(tmp_path / "d"), size_limit_bytes=2 ** 20)
    assert cache.lookup("nope") is _MISSING
    cache.store("k", {"v": 1})
    assert cache.lookup("k") == {"v": 1}
    assert cache.get("k", lambda: pytest.fail("should hit")) == {"v": 1}


def test_disk_cache_tolerates_partner_deleted_entry(tmp_path):
    cache = LocalDiskCache(str(tmp_path / "d"))
    cache.store("k", "value")
    path = cache._entry_path("k")  # noqa: SLF001
    real_utime = os.utime

    def racing_utime(p, *a, **kw):
        # a concurrent evictor removes the entry between our open and touch
        os.remove(path)
        return real_utime(p, *a, **kw)

    import unittest.mock as mock

    with mock.patch("os.utime", racing_utime):
        assert cache.lookup("k") == "value"   # value already read: a hit
    assert cache.lookup("k") is _MISSING      # and the entry is gone


def test_disk_cache_eviction_spares_live_tmp_sweeps_orphans(tmp_path):
    cache = LocalDiskCache(str(tmp_path / "d"), size_limit_bytes=100)
    live_tmp = os.path.join(cache._dir, "writer.tmp")  # noqa: SLF001
    with open(live_tmp, "wb") as f:
        f.write(b"x" * 400)
    orphan_tmp = os.path.join(cache._dir, "orphan.tmp")  # noqa: SLF001
    with open(orphan_tmp, "wb") as f:
        f.write(b"x" * 400)
    old = time.time() - LocalDiskCache.ORPHAN_TMP_S - 10
    os.utime(orphan_tmp, (old, old))
    cache.store("k", "v" * 200)
    cache._maybe_evict()  # noqa: SLF001 - sweeps are amortized (SWEEP_EVERY)
    assert os.path.exists(live_tmp), "live writer temp was evicted"
    assert not os.path.exists(orphan_tmp), "crashed-writer orphan leaked"


def test_disk_cache_sweep_is_amortized(tmp_path):
    cache = LocalDiskCache(str(tmp_path / "d"), size_limit_bytes=10)
    for i in range(LocalDiskCache.SWEEP_EVERY - 1):
        cache.store(f"k{i}", "v" * 100)
    # over the cap, but no sweep yet: entries survive between sweeps
    assert len(os.listdir(cache._dir)) == LocalDiskCache.SWEEP_EVERY - 1  # noqa: SLF001
    cache.store("trigger", "v" * 100)         # SWEEP_EVERY-th store sweeps
    assert len(os.listdir(cache._dir)) <= 1  # noqa: SLF001


@pytest.mark.slow
def test_disk_cache_multiprocess_eviction_stress(tmp_path):
    """Concurrent writers + evictors from threads AND processes over one
    tiny directory: every get returns the correct value and nothing
    raises (the satellite-1 race contract)."""
    import multiprocessing as mp

    d = str(tmp_path / "stress")
    errs = mp.get_context("spawn").Queue()
    procs = [mp.get_context("spawn").Process(
        target=_stress_worker, args=(d, seed, errs)) for seed in range(3)]
    for p in procs:
        p.start()
    threads = [threading.Thread(target=_stress_worker, args=(d, 100 + s, errs))
               for s in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for p in procs:
        p.join(60)
        assert p.exitcode == 0
    assert errs.empty(), errs.get()


def _stress_worker(d, seed, errs):
    try:
        cache = LocalDiskCache(d, size_limit_bytes=64 * 1024)
        rng = np.random.default_rng(seed)
        for i in range(150):
            k = f"key{rng.integers(0, 40)}"
            expected = f"value-{k}" * 50
            got = cache.get(k, lambda: expected)
            assert got == expected, (k, got[:40])
    except Exception as exc:  # noqa: BLE001 - reported to the parent
        errs.put(f"worker {seed}: {type(exc).__name__}: {exc}")
        raise


# -- make_cache ---------------------------------------------------------------

@needs_arena
def test_make_cache_shared(tmp_path):
    cache = make_cache("shared", str(tmp_path / "mc"), 8 * 2 ** 20)
    try:
        assert isinstance(cache, SharedWarmCache)
        assert cache.l1_size_bytes == 8 * 2 ** 20
        assert cache.l1_enabled
    finally:
        cache.cleanup()


@needs_arena
def test_index_slot_capacity_constant():
    # layout regression guard: the shared index is a fixed binary format
    # other PROCESSES map - dtype drift corrupts every attached job
    from petastorm_tpu.cache_shared import _HEADER_DTYPE, _SLOT_DTYPE

    assert _HEADER_DTYPE.itemsize == 128
    assert _SLOT_DTYPE.itemsize == 64
    assert DEFAULT_SLOTS == 4096
