"""TF delivery layer tests (reference: tests/test_tf_utils.py, tf.data path)."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from petastorm_tpu.codecs import NdarrayCodec  # noqa: E402
from petastorm_tpu.errors import PetastormTpuError  # noqa: E402
from petastorm_tpu.etl.writer import write_dataset  # noqa: E402
from petastorm_tpu.ngram import NGram  # noqa: E402
from petastorm_tpu.reader import make_reader  # noqa: E402
from petastorm_tpu.tf import make_petastorm_dataset  # noqa: E402
from petastorm_tpu.schema import Field, Schema  # noqa: E402


@pytest.fixture(scope="module")
def tf_dataset_url(tmp_path_factory):
    url = str(tmp_path_factory.mktemp("tf_ds") / "ds")
    schema = Schema("TfSchema", [
        Field("id", np.int64),
        Field("u16", np.uint16),
        Field("name", np.dtype("object")),
        Field("vec", np.float32, (3,), NdarrayCodec()),
    ])
    rows = [{"id": i, "u16": i * 2, "name": f"row_{i}",
             "vec": np.full(3, i, np.float32)} for i in range(20)]
    write_dataset(url, schema, rows, row_group_size_rows=5)
    return url


def test_round_trip_with_promotions_and_strings(tf_dataset_url):
    with make_reader(tf_dataset_url, reader_pool_type="serial",
                     shuffle_row_groups=False, num_epochs=1) as reader:
        ds = make_petastorm_dataset(reader)
        items = list(ds.as_numpy_iterator())
    assert len(items) == 20
    assert [int(x.id) for x in items] == list(range(20))
    assert items[3].u16 == 6 and items[3].u16.dtype == np.int32
    assert items[3].name == b"row_3"
    np.testing.assert_array_equal(items[3].vec, np.full(3, 3, np.float32))


def test_tf_data_pipeline_ops(tf_dataset_url):
    with make_reader(tf_dataset_url, reader_pool_type="serial",
                     shuffle_row_groups=False, num_epochs=1) as reader:
        ds = make_petastorm_dataset(reader)
        total = ds.map(lambda row: row.id).reduce(np.int64(0), lambda a, b: a + b)
        assert int(total) == sum(range(20))


def test_ngram_rejected(tf_dataset_url):
    ngram = NGram({0: ["vec"], 1: ["vec"]}, 1, "id")
    with make_reader(tf_dataset_url, ngram=ngram, num_epochs=1) as reader:
        with pytest.raises(PetastormTpuError, match="NGram"):
            make_petastorm_dataset(reader)


# ---------------------------------------------------------------------------
# tf_tensors: TF1 graph-mode API (reference tf_utils.py:202-319)
# ---------------------------------------------------------------------------

def test_tf_tensors_graph_mode(tf_dataset_url):
    from petastorm_tpu.tf import tf_tensors

    graph = tf.Graph()
    with graph.as_default():
        with make_reader(tf_dataset_url, reader_pool_type="serial",
                         shuffle_row_groups=False, num_epochs=1) as reader:
            row_tensors = tf_tensors(reader)
            assert row_tensors.vec.get_shape().as_list() == [3]
            with tf.compat.v1.Session() as sess:
                rows = [sess.run(row_tensors) for _ in range(20)]
    assert [int(r.id) for r in rows] == list(range(20))
    assert rows[5].name == b"row_5"
    assert rows[5].u16 == 10 and rows[5].u16.dtype == np.int32
    np.testing.assert_array_equal(rows[7].vec, np.full(3, 7, np.float32))


def test_tf_tensors_with_shuffling_queue(tf_dataset_url):
    from petastorm_tpu.tf import RANDOM_SHUFFLING_QUEUE_SIZE, tf_tensors

    graph = tf.Graph()
    with graph.as_default():
        with make_reader(tf_dataset_url, reader_pool_type="serial",
                         shuffle_row_groups=False, num_epochs=None) as reader:
            row_tensors = tf_tensors(reader, shuffling_queue_capacity=10,
                                     min_after_dequeue=2)
            # the queue-size diagnostic node exists under the well-known name
            size_op = graph.get_operation_by_name(RANDOM_SHUFFLING_QUEUE_SIZE)
            assert size_op is not None
            with tf.compat.v1.Session() as sess:
                coord = tf.compat.v1.train.Coordinator()
                threads = tf.compat.v1.train.start_queue_runners(sess, coord)
                ids = [int(sess.run(row_tensors).id) for _ in range(40)]
                coord.request_stop()
                coord.join(threads, stop_grace_period_secs=5)
    # infinite-epoch shuffled stream: all values legal, not a straight replay
    assert set(ids) <= set(range(20)) and len(ids) == 40
    assert ids[:20] != list(range(20))


def test_tf_tensors_ngram(tf_dataset_url):
    from petastorm_tpu.tf import tf_tensors

    ngram = NGram({0: ["id", "vec"], 1: ["id"]}, 1, "id")
    graph = tf.Graph()
    with graph.as_default():
        with make_reader(tf_dataset_url, reader_pool_type="serial",
                         shuffle_row_groups=False, num_epochs=1,
                         ngram=ngram) as reader:
            window = tf_tensors(reader)
            assert sorted(window) == [0, 1]
            with tf.compat.v1.Session() as sess:
                w = sess.run(window)
    assert int(w[1].id) == int(w[0].id) + 1
    np.testing.assert_array_equal(w[0].vec, np.full(3, int(w[0].id), np.float32))
    assert not hasattr(w[1], "vec")


def test_tf_tensors_rejects_eager(tf_dataset_url):
    from petastorm_tpu.tf import tf_tensors

    with make_reader(tf_dataset_url, num_epochs=1) as reader:
        with pytest.raises(PetastormTpuError, match="graph"):
            tf_tensors(reader)


def test_tf_tensors_single_field_shuffling_queue(tf_dataset_url):
    """1-component queues dequeue a bare Tensor; must still build and run."""
    from petastorm_tpu.tf import tf_tensors

    graph = tf.Graph()
    with graph.as_default():
        with make_reader(tf_dataset_url, schema_fields=["id"],
                         reader_pool_type="serial", shuffle_row_groups=False,
                         num_epochs=None) as reader:
            row_tensors = tf_tensors(reader, shuffling_queue_capacity=8,
                                     min_after_dequeue=2)
            with tf.compat.v1.Session() as sess:
                coord = tf.compat.v1.train.Coordinator()
                threads = tf.compat.v1.train.start_queue_runners(sess, coord)
                vals = [int(sess.run(row_tensors).id) for _ in range(10)]
                coord.request_stop()
                coord.join(threads, stop_grace_period_secs=5)
    assert set(vals) <= set(range(20))


def test_tf_tensors_batched_shuffling_rejected(tf_dataset_url):
    from petastorm_tpu.reader import make_batch_reader
    from petastorm_tpu.tf import tf_tensors

    graph = tf.Graph()
    with graph.as_default():
        with make_batch_reader(tf_dataset_url, num_epochs=1) as reader:
            with pytest.raises(PetastormTpuError, match="rowgroup batches"):
                tf_tensors(reader, shuffling_queue_capacity=100)


def test_tf_function_autograph_consumption(tf_dataset_url):
    """The dataset feeds a @tf.function training step (graph-compiled
    iteration, reference tests/test_tf_autograph.py): reductions over our
    generator-backed dataset must trace and run."""
    with make_reader(tf_dataset_url, reader_pool_type="serial",
                     shuffle_row_groups=False, num_epochs=1) as reader:
        ds = make_petastorm_dataset(reader).map(
            lambda row: {"id": row.id, "vec": row.vec}).batch(5)

        @tf.function
        def epoch_sum(dataset):
            total = tf.constant(0, tf.int64)
            vec_sum = tf.zeros((3,), tf.float32)
            for batch in dataset:
                total += tf.reduce_sum(batch["id"])
                vec_sum += tf.reduce_sum(batch["vec"], axis=0)
            return total, vec_sum

        total, vec_sum = epoch_sum(ds)
    assert int(total) == sum(range(20))
    np.testing.assert_allclose(vec_sum.numpy(), np.full(3, sum(range(20)),
                                                        np.float32))
