"""Span tracing with thread/worker attribution; Chrome trace_event export.

Spans are complete events ("ph": "X" in the Chrome trace format): one append
per finished span carrying (name, category, thread, start, duration, args).
The buffer is bounded - a run that records more spans than ``max_events``
drops the excess and counts them, so an unbounded soak cannot grow host
memory without bound.

``chrome_trace()`` renders the JSON object format
(https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU)
loadable directly in Perfetto / chrome://tracing: every event has ``ph``,
``ts``/``dur`` (microseconds), ``pid``/``tid``, ``name``, ``cat``, ``args``,
plus ``thread_name`` metadata events so worker threads show up by name.

Cross-process spans: ``add(..., pid=..., proc=...)`` attributes a span to a
*synthetic* process row (e.g. the dispatcher or a remote worker whose hop
stamps were returned over the wire).  ``chrome_trace()`` emits a
``process_name`` metadata event per synthetic pid, so a merged trace of one
item's life across client -> dispatcher -> worker -> client renders as
separate named process tracks in a single Perfetto file.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional


class TraceBuffer:
    """Bounded in-memory span store (one tuple per finished span)."""

    def __init__(self, max_events: int = 200_000):
        self._lock = threading.Lock()
        #: (name, cat, tid, start_ns, dur_ns, args-or-None, pid-or-None)
        self._events: List[tuple] = []
        self._max_events = max_events
        self._dropped = 0
        self._thread_names: Dict[int, str] = {}
        #: synthetic pid -> process name for cross-process spans
        self._proc_names: Dict[int, str] = {}
        #: perf_counter_ns at buffer creation - trace timestamps are relative
        #: to this origin so they stay small and runs align at ts=0
        self._origin_ns = time.perf_counter_ns()

    def add(self, name: str, cat: str, start_ns: int, dur_ns: int,
            args: Optional[Dict] = None, pid: Optional[int] = None,
            proc: Optional[str] = None, tid: Optional[int] = None) -> None:
        """Append one finished span (attributed to the CALLING thread, so
        call from the thread that did the work).  ``pid``/``proc`` attribute
        the span to a synthetic remote process instead (the merged-trace
        path); ``start_ns`` must then already be mapped into this buffer's
        clock domain by the caller."""
        if tid is None:
            tid = threading.get_ident()
        with self._lock:
            if len(self._events) >= self._max_events:
                self._dropped += 1
                return
            if pid is None and tid not in self._thread_names:
                self._thread_names[tid] = threading.current_thread().name
            if pid is not None and proc and pid not in self._proc_names:
                self._proc_names[pid] = proc
            self._events.append((name, cat, tid, start_ns, dur_ns, args, pid))

    def __len__(self) -> int:
        return len(self._events)

    @property
    def dropped(self) -> int:
        """Spans discarded because the buffer hit ``max_events``."""
        return self._dropped

    def tail(self, n: int = 200) -> List[Dict]:
        """The last ``n`` finished spans as plain dicts, oldest first:
        ``{name, cat, thread, ts_ms, dur_ms[, args]}`` (milliseconds relative
        to the buffer origin, same clock as ``chrome_trace`` timestamps) -
        the flight recorder's trace payload."""
        if n <= 0:
            return []
        with self._lock:
            events = self._events[-n:]
            names = dict(self._thread_names)
            procs = dict(self._proc_names)
        origin = self._origin_ns
        out = []
        for name, cat, tid, start_ns, dur_ns, args, pid in events:
            ev = {"name": name, "cat": cat,
                  "thread": names.get(tid, str(tid)),
                  "ts_ms": (start_ns - origin) / 1e6,
                  "dur_ms": dur_ns / 1e6}
            if pid is not None:
                ev["proc"] = procs.get(pid, str(pid))
            if args:
                ev["args"] = args
            out.append(ev)
        return out

    def chrome_trace(self) -> Dict:
        """The buffered spans as a Chrome ``trace_event`` JSON object."""
        pid = os.getpid()
        with self._lock:
            events = list(self._events)
            names = dict(self._thread_names)
            procs = dict(self._proc_names)
        origin = self._origin_ns
        out = [{"ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
                "args": {"name": tname}} for tid, tname in names.items()]
        out.append({"ph": "M", "pid": pid, "name": "process_name",
                    "args": {"name": "petastorm-tpu"}})
        for spid, pname in procs.items():
            out.append({"ph": "M", "pid": spid, "name": "process_name",
                        "args": {"name": pname}})
        for name, cat, tid, start_ns, dur_ns, args, epid in events:
            ev = {"ph": "X", "pid": pid if epid is None else epid, "tid": tid,
                  "name": name, "cat": cat,
                  "ts": (start_ns - origin) / 1e3,   # microseconds
                  "dur": dur_ns / 1e3}
            if args:
                ev["args"] = args
            out.append(ev)
        return {"traceEvents": out, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str) -> str:
        """Write ``chrome_trace()`` JSON to ``path``; returns the path."""
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path
