"""On-demand native build: compiles the C++ sources into cached .so files.

No pip/pybind11 in this environment, so bindings are a plain C ABI loaded via
ctypes; g++ is invoked directly the first time a library is needed and the
result is cached next to the source, keyed by a source hash.
"""

from __future__ import annotations

import hashlib
import logging
import os
import subprocess
import tempfile
import threading
from typing import Optional

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_DIR = os.path.join(_DIR, "_lib")

#: name -> (source file, extra link flags)
_LIBS = {
    "shm_arena": ("shm_arena.cpp", []),
    "image_decode": ("image_decode.cpp", ["-lpng16", "-ljpeg"]),
}


def _source_tag(src: str) -> str:
    with open(src, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()[:16]


def lib_path(name: str = "shm_arena") -> str:
    src, _ = _LIBS[name]
    return os.path.join(_LIB_DIR, f"lib{name}-{_source_tag(os.path.join(_DIR, src))}.so")


_load_lock = threading.Lock()
_loaded: dict = {}


def load_library(name: str, configure) -> Optional["ctypes.CDLL"]:
    """Build (if needed) + ``ctypes.CDLL``-load + one-time ``configure(lib)``,
    cached per name; returns None (and remembers the failure) when the
    toolchain is missing or the .so fails to load.  Shared by every native
    binding so availability/error behavior stays consistent."""
    import ctypes

    with _load_lock:
        if name in _loaded:
            return _loaded[name]
        path = build(name)
        lib = None
        if path is not None:
            try:
                lib = ctypes.CDLL(path)
                configure(lib)
            except OSError as exc:
                logger.warning("loading native %s failed: %s", name, exc)
                lib = None
        _loaded[name] = lib
        return lib


def build(name: str = "shm_arena", force: bool = False) -> Optional[str]:
    """Compile (if needed) and return the .so path, or None if no toolchain."""
    src, link_flags = _LIBS[name]
    src = os.path.join(_DIR, src)
    path = lib_path(name)
    if os.path.exists(path) and not force:
        return path
    os.makedirs(_LIB_DIR, exist_ok=True)
    # build to a temp name then rename: concurrent builders race benignly
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_LIB_DIR)
    os.close(fd)
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread",
           src, "-o", tmp] + link_flags
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except FileNotFoundError:
        logger.warning("g++ not found; native %s unavailable", name)
        os.unlink(tmp)
        return None
    except subprocess.CalledProcessError as exc:
        logger.warning("native build of %s failed:\n%s", name, exc.stderr)
        os.unlink(tmp)
        return None
    os.replace(tmp, path)
    return path
